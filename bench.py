#!/usr/bin/env python
"""Benchmark entry — run by the driver on real TPU hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload: TPC-H Q1 at SF1 (6M lineitem rows) — the reference's own headline
scan benchmark (presto-orc results.txt:19: Aria selective reader runs the
Q1 scan kernel over SF1 lineitem in 0.79 s ≈ 7.6M rows/s; the stock batch
reader takes 3.99 s ≈ 1.5M rows/s). We run the FULL Q1 (scan + filter +
aggregate + sort), not just the scan, and report engine rows/s.
vs_baseline = our rows/s ÷ the Aria selective reader's rows/s.
"""

import json
import sys
import time

SF = float(sys.argv[1]) if len(sys.argv) > 1 else 1.0

Q1 = """
select l_returnflag, l_linestatus,
       sum(l_quantity) as sum_qty,
       sum(l_extendedprice) as sum_base_price,
       sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
       sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
       avg(l_quantity) as avg_qty,
       avg(l_extendedprice) as avg_price,
       avg(l_discount) as avg_disc,
       count(*) as count_order
from lineitem
where l_shipdate <= date '1998-12-01' - interval '90' day
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus
"""

# reference: Aria selective reader, TPC-H Q1 scan kernel, SF1 lineitem
# (presto-orc/src/main/java/com/facebook/presto/orc/results.txt:19)
_REF_SECONDS_SF1 = 0.79
_SF1_ROWS = 6_001_215


def main():
    from presto_tpu.catalog.tpch import tpch_catalog
    from presto_tpu.exec import ExecConfig, LocalRunner

    cat = tpch_catalog(SF)
    conn = cat.connectors["tpch"]
    conn._ensure("lineitem")  # generation outside the timed region
    nrows = conn.tables["lineitem"].num_rows

    runner = LocalRunner(cat, ExecConfig(batch_rows=1 << 20, agg_capacity=1 << 10))

    # warm-up: compile caches (Presto also excludes codegen from steady-state)
    runner.run_batch(Q1)

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = runner.run_batch(Q1)
        out.num_live()  # block on device completion
        times.append(time.perf_counter() - t0)
    best = min(times)

    rows_per_s = nrows / best
    ref_rows_per_s = _SF1_ROWS / _REF_SECONDS_SF1
    print(
        json.dumps(
            {
                "metric": f"tpch_q1_sf{SF:g}_rows_per_sec",
                "value": round(rows_per_s, 1),
                "unit": "rows/s",
                "vs_baseline": round(rows_per_s / ref_rows_per_s, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
