#!/usr/bin/env python
"""Benchmark entry — run by the driver on real TPU hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.

Covers the five BASELINE.json configs:
  q1_sf1    TPC-H Q1  SF1   — hash aggregation over lineitem
  q6_sf10   TPC-H Q6  SF10  — scan-filter-aggregate
  q3_sf10   TPC-H Q3  SF10  — 3-way join
  q9_sf100  TPC-H Q9  SF100 — multi-join + partitioned aggregation
  q64_sf100 TPC-DS Q64 SF100 — wide star-join (tpcds connector)

The headline metric stays TPC-H Q1 rows/s vs the reference fork's own
published number (presto-orc results.txt:19: Aria selective reader runs the
Q1 scan kernel over SF1 lineitem in 0.79 s = 7.6M rows/s; stock batch reader
3.99 s). We run the FULL Q1 (scan + filter + aggregate + sort), not just the
scan. vs_baseline = our rows/s / the Aria reader's rows/s. Q6 likewise has a
published scan-kernel number (results.txt:18: 0.54 s at SF1 = 11.1M rows/s).
Q3/Q9/Q64 have no published reference numbers; their vs_baseline is null and
the raw rows/s + seconds are recorded for cross-round tracking.

Per-config stage timings (generate / warmup-compile / best-of-N run) go to
stderr so the bottleneck is measurable without polluting the JSON line.

Env knobs:
  BENCH_CONFIGS   comma list (default: all five)
  BENCH_BUDGET_S  wall budget; remaining configs are skipped once exceeded
                  (default 2400)
  BENCH_SF_Q9 / BENCH_SF_Q64  override the big scale factors (default 100)
"""

import json
import os
import sys
import time

_T0 = time.time()


def _log(msg: str):
    print(f"[bench +{time.time() - _T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


Q1 = """
select l_returnflag, l_linestatus,
       sum(l_quantity) as sum_qty,
       sum(l_extendedprice) as sum_base_price,
       sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
       sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
       avg(l_quantity) as avg_qty,
       avg(l_extendedprice) as avg_price,
       avg(l_discount) as avg_disc,
       count(*) as count_order
from lineitem
where l_shipdate <= date '1998-12-01' - interval '90' day
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus
"""

Q6 = """
select sum(l_extendedprice * l_discount) as revenue
from lineitem
where l_shipdate >= date '1994-01-01'
  and l_shipdate < date '1995-01-01'
  and l_discount between 0.05 and 0.07
  and l_quantity < 24
"""

Q3 = """
select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
       o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
  and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate limit 10
"""

Q9 = """
select nation, o_year, sum(amount) as sum_profit
from (
  select n_name as nation,
         extract(year from o_orderdate) as o_year,
         l_extendedprice * (1 - l_discount) - ps_supplycost * l_quantity as amount
  from part, supplier, lineitem, partsupp, orders, nation
  where s_suppkey = l_suppkey and ps_suppkey = l_suppkey
    and ps_partkey = l_partkey and p_partkey = l_partkey
    and o_orderkey = l_orderkey and s_nationkey = n_nationkey
    and p_name like '%green%'
) profit
group by nation, o_year
order by nation, o_year desc
"""

# TPC-DS Q64-shaped star join over the tpcds connector (full Q64 is a
# two-instance CTE self-join; this is the inner star: store_sales joined to
# its dimensions with a grouped rollup — the config's multi-join shape).
Q64 = """
select i_product_name, s_store_name, d_year,
       count(*) as cnt,
       sum(ss_wholesale_cost) as s1,
       sum(ss_list_price) as s2,
       sum(ss_coupon_amt) as s3
from store_sales, date_dim, store, customer, item
where ss_sold_date_sk = d_date_sk
  and ss_store_sk = s_store_sk
  and ss_customer_sk = c_customer_sk
  and ss_item_sk = i_item_sk
  and i_current_price between 35 and 44
group by i_product_name, s_store_name, d_year
order by s1 limit 100
"""

# reference: Aria selective reader scan kernels over SF1 lineitem
# (presto-orc/src/main/java/com/facebook/presto/orc/results.txt:18-19)
_SF1_ROWS = 6_001_215
_REF = {
    "q1": _SF1_ROWS / 0.79,   # rows/s
    "q6": _SF1_ROWS / 0.54,
}


def _bench(name, sql, sf, catalog_factory, connector_name, tables,
           driving_table, batch_rows=1 << 20, agg_capacity=1 << 10, runs=3):
    """Generate → warm up (compile) → best-of-N timed runs, with per-stage
    timings on stderr."""
    from presto_tpu.exec import ExecConfig, LocalRunner

    t0 = time.time()
    cat = catalog_factory(sf)
    conn = cat.connectors[connector_name]
    for t in tables:
        conn._ensure(t)
    nrows = conn.tables[driving_table].num_rows
    _log(f"{name}: generated sf={sf:g} ({nrows} {driving_table} rows) "
         f"in {time.time() - t0:.1f}s")
    runner = LocalRunner(cat, ExecConfig(batch_rows=batch_rows,
                                         agg_capacity=agg_capacity))
    t0 = time.time()
    runner.run_batch(sql)  # warm-up: compile caches
    _log(f"{name}: warmup (compile) {time.time() - t0:.1f}s")
    times = []
    for _ in range(runs):
        t0 = time.perf_counter()
        out = runner.run_batch(sql)
        out.num_live()  # block on device completion
        times.append(time.perf_counter() - t0)
    best = min(times)
    _log(f"{name}: best {best:.3f}s of {sorted(round(t, 3) for t in times)}")
    return {"seconds": round(best, 4), "rows": nrows,
            "rows_per_sec": round(nrows / best, 1)}


def bench_tpch(name, sql, sf, tables, driving_table, runs=3):
    from presto_tpu.catalog.tpch import tpch_catalog

    return _bench(name, sql, sf, tpch_catalog, "tpch", tables, driving_table,
                  runs=runs)


def bench_tpcds(name, sql, sf, runs=3):
    from presto_tpu.catalog.tpcds import tpcds_catalog

    return _bench(name, sql, sf, tpcds_catalog, "tpcds",
                  ("store_sales", "date_dim", "store", "customer", "item"),
                  "store_sales", agg_capacity=1 << 12, runs=runs)


def main():
    budget = float(os.environ.get("BENCH_BUDGET_S", "2400"))
    sf_q9 = float(os.environ.get("BENCH_SF_Q9", "100"))
    sf_q64 = float(os.environ.get("BENCH_SF_Q64", "100"))
    wanted = os.environ.get(
        "BENCH_CONFIGS", "q1_sf1,q6_sf10,q3_sf10,q9_sf100,q64_sf100"
    ).split(",")

    configs = {
        "q1_sf1": lambda: bench_tpch("q1_sf1", Q1, 1.0, ["lineitem"],
                                     "lineitem"),
        "q6_sf10": lambda: bench_tpch("q6_sf10", Q6, 10.0, ["lineitem"],
                                      "lineitem"),
        "q3_sf10": lambda: bench_tpch("q3_sf10", Q3, 10.0,
                                      ["customer", "orders", "lineitem"],
                                      "lineitem"),
        "q9_sf100": lambda: bench_tpch(
            "q9_sf100", Q9, sf_q9,
            ["part", "supplier", "lineitem", "partsupp", "orders", "nation"],
            "lineitem", runs=2),
        "q64_sf100": lambda: bench_tpcds("q64_sf100", Q64, sf_q64, runs=2),
    }

    extra = {}
    for name in wanted:
        name = name.strip()
        if name not in configs:
            _log(f"{name}: UNKNOWN config (valid: {','.join(configs)})")
            extra[name] = {"error": "unknown config"}
            continue
        if time.time() - _T0 > budget:
            _log(f"{name}: SKIPPED (budget {budget:.0f}s exceeded)")
            extra[name] = {"skipped": "budget"}
            continue
        try:
            extra[name] = configs[name]()
        except Exception as e:  # record, keep benching the rest
            _log(f"{name}: FAILED {type(e).__name__}: {e}")
            extra[name] = {"error": f"{type(e).__name__}: {e}"}

    q1 = extra.get("q1_sf1", {})
    value = q1.get("rows_per_sec", 0.0)
    for name, ref in (("q1_sf1", _REF["q1"]), ("q6_sf10", _REF["q6"])):
        if name in extra and "rows_per_sec" in extra[name]:
            extra[name]["vs_baseline"] = round(
                extra[name]["rows_per_sec"] / ref, 3)
    print(json.dumps({
        "metric": "tpch_q1_sf1_rows_per_sec",
        "value": value,
        "unit": "rows/s",
        "vs_baseline": round(value / _REF["q1"], 3) if value else 0.0,
        "extra": extra,
    }))


if __name__ == "__main__":
    main()
