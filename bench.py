#!/usr/bin/env python
"""Benchmark entry — run by the driver on real TPU hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.

Covers the five BASELINE.json configs:
  q1_sf1    TPC-H Q1  SF1   — hash aggregation over lineitem
  q6_sf10   TPC-H Q6  SF10  — scan-filter-aggregate
  q3_sf10   TPC-H Q3  SF10  — 3-way join
  q9_sf100  TPC-H Q9  SF100 — multi-join + partitioned aggregation
  q64_sf100 TPC-DS Q64 SF100 — wide star-join (tpcds connector)

Data path: every config reads parquet through ParquetConnector (the real
storage layer — row groups, column pruning, dictionary-preserving decode).
Datasets generate ONCE into BENCH_DATA_DIR (default .bench_data/) with the
chunked exporters and are reused across configs AND rounds; re-runs only
pay parquet decode (host-cached) + host→device staging (device-cached for
working sets under the HBM budget). XLA executables persist across rounds
via the compilation cache (presto_tpu.__init__), so warm-up is ~seconds
after the first round.

The headline metric stays TPC-H Q1 rows/s vs the reference fork's own
published number (presto-orc results.txt:19: Aria selective reader runs the
Q1 scan kernel over SF1 lineitem in 0.79 s = 7.6M rows/s). We run the FULL
Q1 (scan + filter + aggregate + sort), not just the scan. Q6 likewise
(results.txt:18). Q3/Q9/Q64 have no published reference numbers; their
vs_baseline is null and raw rows/s + seconds are recorded for cross-round
tracking.

Env knobs:
  BENCH_CONFIGS   comma list (default: all five)
  BENCH_BUDGET_S  wall budget; remaining configs are skipped once exceeded
                  (default 2400)
  BENCH_DATA_DIR  dataset directory (default <repo>/.bench_data)
  BENCH_SF_Q9 / BENCH_SF_Q64  override the big scale factors (default 100)
"""

import json
import os
import sys
import time

_T0 = time.time()
_HERE = os.path.dirname(os.path.abspath(__file__))
DATA_DIR = os.environ.get("BENCH_DATA_DIR", os.path.join(_HERE, ".bench_data"))


def _log(msg: str):
    print(f"[bench +{time.time() - _T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


Q1 = """
select l_returnflag, l_linestatus,
       sum(l_quantity) as sum_qty,
       sum(l_extendedprice) as sum_base_price,
       sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
       sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
       avg(l_quantity) as avg_qty,
       avg(l_extendedprice) as avg_price,
       avg(l_discount) as avg_disc,
       count(*) as count_order
from lineitem
where l_shipdate <= date '1998-12-01' - interval '90' day
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus
"""

Q6 = """
select sum(l_extendedprice * l_discount) as revenue
from lineitem
where l_shipdate >= date '1994-01-01'
  and l_shipdate < date '1995-01-01'
  and l_discount between 0.05 and 0.07
  and l_quantity < 24
"""

Q3 = """
select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
       o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
  and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate limit 10
"""

Q9 = """
select nation, o_year, sum(amount) as sum_profit
from (
  select n_name as nation,
         extract(year from o_orderdate) as o_year,
         l_extendedprice * (1 - l_discount) - ps_supplycost * l_quantity as amount
  from part, supplier, lineitem, partsupp, orders, nation
  where s_suppkey = l_suppkey and ps_suppkey = l_suppkey
    and ps_partkey = l_partkey and p_partkey = l_partkey
    and o_orderkey = l_orderkey and s_nationkey = n_nationkey
    and p_name like '%green%'
) profit
group by nation, o_year
order by nation, o_year desc
"""

# TPC-DS Q64-shaped star join over the tpcds connector (full Q64 is a
# two-instance CTE self-join; this is the inner star: store_sales joined to
# its dimensions with a grouped rollup — the config's multi-join shape).
Q64 = """
select i_product_name, s_store_name, d_year,
       count(*) as cnt,
       sum(ss_wholesale_cost) as s1,
       sum(ss_list_price) as s2,
       sum(ss_coupon_amt) as s3
from store_sales, date_dim, store, customer, item
where ss_sold_date_sk = d_date_sk
  and ss_store_sk = s_store_sk
  and ss_customer_sk = c_customer_sk
  and ss_item_sk = i_item_sk
  and i_current_price between 35 and 44
group by i_product_name, s_store_name, d_year
order by s1 limit 100
"""

# reference: Aria selective reader scan kernels over SF1 lineitem
# (presto-orc/src/main/java/com/facebook/presto/orc/results.txt:18-19)
_SF1_ROWS = 6_001_215
_REF = {
    "q1": _SF1_ROWS / 0.79,   # rows/s
    "q6": _SF1_ROWS / 0.54,
}

_CATALOGS = {}  # (kind, sf) -> Catalog, shared across configs


def _dataset(kind: str, sf: float):
    """Generate-once parquet dataset + catalog over it (cached per proc)."""
    key = (kind, sf)
    if key in _CATALOGS:
        return _CATALOGS[key]
    from presto_tpu.catalog.parquet import (
        ParquetConnector, export_tpch_chunked, export_tpcds_chunked,
    )
    from presto_tpu.connector import Catalog

    d = os.path.join(DATA_DIR, f"{kind}_sf{sf:g}")
    t0 = time.time()
    if kind == "tpch":
        export_tpch_chunked(d, sf, log=_log)
    else:
        export_tpcds_chunked(d, sf, log=_log)
    dt = time.time() - t0
    if dt > 1:
        _log(f"{kind} sf={sf:g}: dataset ensured in {dt:.1f}s -> {d}")
    conn = ParquetConnector(d, name=kind)
    cat = Catalog()
    cat.register(kind, conn, default=True)
    _CATALOGS[key] = cat
    return cat


def _dataset_ready(kind: str, sf: float) -> bool:
    marker = "lineitem" if kind == "tpch" else "store_sales"
    return os.path.exists(
        os.path.join(DATA_DIR, f"{kind}_sf{sf:g}", f"{marker}.parquet"))


def _resolve_sf(kind: str, sf: float, budget: float) -> float:
    """Downscale a config's SF when its dataset is absent AND generating
    it cannot fit the remaining wall budget (SF100 generation is hours;
    the driver's bench window is not). Prefers the largest already-
    cached dataset, else the largest affordable one."""
    if _dataset_ready(kind, sf):
        return sf
    est_per_sf = 60.0  # measured ~55 s/SF for the chunked tpch exporter
    remaining = budget - (time.time() - _T0)
    if sf * est_per_sf < remaining * 0.5:
        return sf
    for cand in (10.0, 1.0, 0.1):
        if cand >= sf:
            continue
        if _dataset_ready(kind, cand) or cand * est_per_sf < remaining * 0.4:
            _log(f"{kind} sf={sf:g}: dataset absent and generation won't "
                 f"fit the budget — downscaling to sf={cand:g}")
            return cand
    return 0.1


def _bench(name, sql, kind, sf, driving_table,
           batch_rows=1 << 20, agg_capacity=1 << 10, runs=3):
    """Ensure dataset → warm up (compile + cache fill) → best-of-N timed
    runs, with per-stage timings on stderr."""
    from presto_tpu.exec import ExecConfig, LocalRunner

    cat = _dataset(kind, sf)
    conn = cat.connectors[kind]
    nrows = int(conn.get_table(driving_table).row_count)
    runner = LocalRunner(cat, ExecConfig(batch_rows=batch_rows,
                                         agg_capacity=agg_capacity))
    t0 = time.time()
    runner.run_batch(sql)  # warm-up: compiles + host/device caches
    _log(f"{name}: warmup (compile + cache fill) {time.time() - t0:.1f}s")
    times = []
    for _ in range(runs):
        t0 = time.perf_counter()
        out = runner.run_batch(sql)
        out.num_live()  # block on device completion
        times.append(time.perf_counter() - t0)
    best = min(times)
    _log(f"{name}: best {best:.3f}s of {sorted(round(t, 3) for t in times)} "
         f"({nrows} {driving_table} rows)")
    return {"seconds": round(best, 4), "rows": nrows, "sf": sf,
            "rows_per_sec": round(nrows / best, 1)}


def _probe_device() -> bool:
    """The axon TPU tunnel can wedge (observed: jax.devices() blocks
    forever). Probe it in a SUBPROCESS with a timeout before this process
    touches jax; on failure fall back to CPU so the driver records a
    (clearly labeled) number instead of a bench timeout."""
    import subprocess

    try:
        p = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.devices(); print('ok')"],
            timeout=150, capture_output=True)
        return p.returncode == 0 and b"ok" in p.stdout
    except subprocess.TimeoutExpired:
        return False


def main():
    budget = float(os.environ.get("BENCH_BUDGET_S", "2400"))
    device_ok = _probe_device()
    if not device_ok:
        _log("DEVICE PROBE FAILED (axon tunnel unresponsive) — "
             "falling back to CPU; numbers are NOT tpu numbers")
        import jax

        jax.config.update("jax_platforms", "cpu")
    sf_q9 = float(os.environ.get("BENCH_SF_Q9", "100"))
    sf_q64 = float(os.environ.get("BENCH_SF_Q64", "100"))
    wanted = os.environ.get(
        "BENCH_CONFIGS", "q1_sf1,q6_sf10,q3_sf10,q9_sf100,q64_sf100"
    ).split(",")

    configs = {
        "q1_sf1": lambda: _bench("q1_sf1", Q1, "tpch", 1.0, "lineitem"),
        "q6_sf10": lambda: _bench(
            "q6_sf10", Q6, "tpch", _resolve_sf("tpch", 10.0, budget),
            "lineitem"),
        "q3_sf10": lambda: _bench(
            "q3_sf10", Q3, "tpch", _resolve_sf("tpch", 10.0, budget),
            "lineitem", agg_capacity=1 << 21),
        "q9_sf100": lambda: _bench(
            "q9_sf100", Q9, "tpch", _resolve_sf("tpch", sf_q9, budget),
            "lineitem", agg_capacity=1 << 10, runs=2),
        "q64_sf100": lambda: _bench(
            "q64_sf100", Q64, "tpcds", _resolve_sf("tpcds", sf_q64, budget),
            "store_sales", agg_capacity=1 << 14, runs=2),
    }

    extra = {}
    for name in wanted:
        name = name.strip()
        if name not in configs:
            _log(f"{name}: UNKNOWN config (valid: {','.join(configs)})")
            extra[name] = {"error": "unknown config"}
            continue
        if time.time() - _T0 > budget:
            _log(f"{name}: SKIPPED (budget {budget:.0f}s exceeded)")
            extra[name] = {"skipped": "budget"}
            continue
        try:
            extra[name] = configs[name]()
        except Exception as e:  # record, keep benching the rest
            _log(f"{name}: FAILED {type(e).__name__}: {e}")
            extra[name] = {"error": f"{type(e).__name__}: {e}"}

    q1 = extra.get("q1_sf1", {})
    value = q1.get("rows_per_sec", 0.0)
    for name, ref in (("q1_sf1", _REF["q1"]), ("q6_sf10", _REF["q6"])):
        if name in extra and "rows_per_sec" in extra[name]:
            extra[name]["vs_baseline"] = round(
                extra[name]["rows_per_sec"] / ref, 3)
    if not device_ok:
        extra["device"] = "cpu-fallback (tpu tunnel unresponsive)"
    print(json.dumps({
        "metric": "tpch_q1_sf1_rows_per_sec",
        "value": value,
        "unit": "rows/s",
        "vs_baseline": round(value / _REF["q1"], 3) if value else 0.0,
        "extra": extra,
    }))


if __name__ == "__main__":
    main()
