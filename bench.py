#!/usr/bin/env python
"""Benchmark entry — run by the driver on real TPU hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.

Covers the five BASELINE.json configs:
  q1_sf1    TPC-H Q1  SF1   — hash aggregation over lineitem
  q6_sf10   TPC-H Q6  SF10  — scan-filter-aggregate
  q3_sf10   TPC-H Q3  SF10  — 3-way join
  q9        TPC-H Q9  — multi-join + partitioned aggregation
            (scale from BENCH_SF_Q9, default 100; may budget-downscale)
  q64       TPC-DS Q64 — wide star-join (tpcds connector; BENCH_SF_Q64)

Result keys record the sf that ACTUALLY ran (e.g. q9_sf10) and every
record carries "sf_actual" — no config key may claim a scale it didn't run.

Crash-safety architecture (round-4 redesign): the parent process NEVER
imports jax — each config runs in a subprocess with its own wall-clock
cap, so a pathological compile or a wedged TPU tunnel can only burn one
config's budget, not the whole driver window. Results accumulate in the
parent after every config (also mirrored to BENCH_partial.json), and a
SIGTERM/SIGINT handler emits the final JSON line immediately — an
external `timeout` kill still leaves driver-parseable evidence.

Data path: every config reads parquet through ParquetConnector (the real
storage layer — row groups, column pruning, dictionary-preserving decode).
Datasets generate ONCE into BENCH_DATA_DIR (default .bench_data/) and are
reused across configs AND rounds. XLA executables persist across rounds
via the compilation cache (presto_tpu.__init__).

The headline metric stays TPC-H Q1 rows/s vs the reference fork's own
published number (presto-orc results.txt:19: Aria selective reader runs
the Q1 scan kernel over SF1 lineitem in 0.79 s = 7.6M rows/s). We run the
FULL Q1 (scan + filter + aggregate + sort), not just the scan. Q6 likewise
(results.txt:18). Q3/Q9/Q64 have no published reference numbers; raw
rows/s + seconds are recorded for cross-round tracking.

Env knobs:
  BENCH_CONFIGS   comma list (default: all five)
  BENCH_BUDGET_S  total wall budget (default 2400)
  BENCH_DATA_DIR  dataset directory (default <repo>/.bench_data)
  BENCH_SF_Q9 / BENCH_SF_Q64  override the big scale factors (default 100)
  BENCH_SF_MESH   scale factor for the mesh_scaling sweep (default 0.1)
  BENCH_SF_SERVING / BENCH_SERVING_CLIENTS / BENCH_SERVING_QUERIES
                  serving_slo closed-loop knobs (default 0.1 / 8 / 4)
  BENCH_PALLAS=1  run aggregation configs with the Pallas MXU kernel
  BENCH_SPILL_ROWS  build-side rows for the spill_skew config (default 400000)
  BENCH_SF_MULTIWAY  scale factor for the multiway_ab join-chain A/B
                  (default 0.1)
  BENCH_ADAPTIVE_ROWS  rows for the adaptive_ab mis-estimated group-by
                  (default 16000)
"""

import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

_T0 = time.time()
_HERE = os.path.dirname(os.path.abspath(__file__))
DATA_DIR = os.environ.get("BENCH_DATA_DIR", os.path.join(_HERE, ".bench_data"))


def _log(msg: str):
    print(f"[bench +{time.time() - _T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


Q1 = """
select l_returnflag, l_linestatus,
       sum(l_quantity) as sum_qty,
       sum(l_extendedprice) as sum_base_price,
       sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
       sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
       avg(l_quantity) as avg_qty,
       avg(l_extendedprice) as avg_price,
       avg(l_discount) as avg_disc,
       count(*) as count_order
from lineitem
where l_shipdate <= date '1998-12-01' - interval '90' day
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus
"""

Q6 = """
select sum(l_extendedprice * l_discount) as revenue
from lineitem
where l_shipdate >= date '1994-01-01'
  and l_shipdate < date '1995-01-01'
  and l_discount between 0.05 and 0.07
  and l_quantity < 24
"""

Q3 = """
select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
       o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
  and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate limit 10
"""

# q3-shaped probe/build microbench: the lineitem→orders join + group-by
# that dominates q3, without the customer dimension — isolates the
# pipeline-breaker cost the radix partitioning targets
JOIN_SF1 = """
select o_orderpriority, count(*) as c,
       sum(l_extendedprice * (1 - l_discount)) as revenue
from lineitem join orders on l_orderkey = o_orderkey
where o_orderdate < date '1995-03-15' and l_shipdate > date '1995-03-15'
group by o_orderpriority
order by o_orderpriority
"""

Q9 = """
select nation, o_year, sum(amount) as sum_profit
from (
  select n_name as nation,
         extract(year from o_orderdate) as o_year,
         l_extendedprice * (1 - l_discount) - ps_supplycost * l_quantity as amount
  from part, supplier, lineitem, partsupp, orders, nation
  where s_suppkey = l_suppkey and ps_suppkey = l_suppkey
    and ps_partkey = l_partkey and p_partkey = l_partkey
    and o_orderkey = l_orderkey and s_nationkey = n_nationkey
    and p_name like '%green%'
) profit
group by nation, o_year
order by nation, o_year desc
"""

# TPC-DS Q64 (spec shape): two-instance CTE over the cross-channel star
# join, self-joined on item across consecutive years. The heavy lifting —
# store_sales ⋈ store_returns ⋈ catalog_sales + five dimension joins —
# matches the spec text; cs_ui / cross-year predicates included.
Q64 = """
with cross_sales as (
  select i_product_name as product_name, i_item_sk as item_sk,
         s_store_name as store_name, s_zip as store_zip,
         d_year as syear,
         count(*) as cnt,
         sum(ss_wholesale_cost) as s1,
         sum(ss_list_price) as s2,
         sum(ss_coupon_amt) as s3
  from store_sales, store_returns, date_dim, store, item, customer
  where ss_item_sk = i_item_sk
    and ss_item_sk = sr_item_sk and ss_ticket_number = sr_ticket_number
    and ss_sold_date_sk = d_date_sk
    and ss_store_sk = s_store_sk
    and ss_customer_sk = c_customer_sk
    and i_current_price between 35 and 44
    and i_product_name is not null
  group by i_product_name, i_item_sk, s_store_name, s_zip, d_year
)
select cs1.product_name, cs1.store_name, cs1.store_zip,
       cs1.syear, cs1.cnt, cs1.s1, cs1.s2, cs1.s3,
       cs2.s1 as s1_2, cs2.s2 as s2_2, cs2.s3 as s3_2, cs2.syear as syear_2,
       cs2.cnt as cnt_2
from cross_sales cs1, cross_sales cs2
where cs1.item_sk = cs2.item_sk
  and cs1.syear = 2000 and cs2.syear = 2001
  and cs2.cnt <= cs1.cnt
  and cs1.store_name = cs2.store_name and cs1.store_zip = cs2.store_zip
order by cs1.product_name, cs1.store_name, cs2.cnt limit 100
"""

# reference: Aria selective reader scan kernels over SF1 lineitem
# (presto-orc/src/main/java/com/facebook/presto/orc/results.txt:18-19)
_SF1_ROWS = 6_001_215
_REF = {
    "q1": _SF1_ROWS / 0.79,   # rows/s
    "q6": _SF1_ROWS / 0.54,
}

# name -> (sql, dataset kind, nominal sf, driving table, exec overrides).
# q9/q64 carry NO sf in their key: their scale comes from BENCH_SF_Q9/Q64
# with budget-driven downscaling, and a key like "q9_sf100" that silently
# ran SF10 poisoned cross-round comparisons. Every result record carries
# "sf_actual" — the scale that really ran.
_CONFIGS = {
    "q1_sf1": (Q1, "tpch", 1.0, "lineitem", {}),
    # fragment-fusion A/B: the same Q1 with the fused lax.scan ingest
    # disabled — the per-batch dispatch loop this round removes. The
    # rows/s delta between q1_sf1 and this key IS the dispatch-collapse
    # win (on CPU it mostly measures dispatch overhead; on a tunneled TPU
    # it measures the RTT budget — see BENCH_NOTES.md)
    "q1_nofuse_sf1": (Q1, "tpch", 1.0, "lineitem",
                      {"fragment_fusion": False}),
    "q6_sf10": (Q6, "tpch", 10.0, "lineitem", {}),
    "q3_sf10": (Q3, "tpch", 10.0, "lineitem", {}),
    "join_sf1": (JOIN_SF1, "tpch", 1.0, "lineitem",
                 {"radix_partitions": 8}),
    # breaker-engine A/B: the same keyed aggregation forced through the
    # Pallas linear-probing hash engine vs the sort/segment engine. The
    # rows/s delta between the pair IS the hash-engine win on a
    # high-duplication group-by (on TPU the hash path replaces the
    # O(n log n) sort with one MXU-free probe pass; the CBO picks it
    # when est. duplication x4+ — plan/stats.choose_breaker_engine)
    "groupby_engine_ab_sf1": (Q1, "tpch", 1.0, "lineitem",
                              {"breaker_engine": "hash"}),
    "groupby_engine_ab_sort_sf1": (Q1, "tpch", 1.0, "lineitem",
                                   {"breaker_engine": "sort"}),
    "q9": (Q9, "tpch", None, "lineitem", {"runs": 2}),
    "q64": (Q64, "tpcds", None, "store_sales",
            {"agg_capacity": 1 << 16, "runs": 2}),
}

# legacy config names (pre-rename BENCH_CONFIGS env values keep working)
_ALIASES = {"q9_sf100": "q9", "q64_sf100": "q64"}

# Per-config wall caps (seconds): one slow compile can only burn this much.
_CAPS = {"q1_sf1": 420, "q1_nofuse_sf1": 420, "q6_sf10": 420,
         "q3_sf10": 600, "join_sf1": 420, "q9": 900, "q64": 900,
         "groupby_engine_ab_sf1": 420, "groupby_engine_ab_sort_sf1": 420}


def _dataset_ready(kind: str, sf: float) -> bool:
    marker = "lineitem" if kind == "tpch" else "store_sales"
    d = os.path.join(DATA_DIR, f"{kind}_sf{sf:g}")
    return (os.path.exists(os.path.join(d, f"{marker}.parquet"))
            or os.path.exists(os.path.join(d, f"{marker}.parts")))


def _resolve_sf(kind: str, sf: float, remaining: float) -> float:
    """Downscale a config's SF when its dataset is absent AND generating it
    cannot fit the remaining wall budget (SF100 generation is hours)."""
    if _dataset_ready(kind, sf):
        return sf
    est_per_sf = 60.0  # measured ~55 s/SF for the chunked tpch exporter
    if sf * est_per_sf < remaining * 0.5:
        return sf
    for cand in (10.0, 1.0, 0.1):
        if cand >= sf:
            continue
        if _dataset_ready(kind, cand) or cand * est_per_sf < remaining * 0.4:
            _log(f"{kind} sf={sf:g}: dataset absent and generation won't "
                 f"fit the budget — downscaling to sf={cand:g}")
            return cand
    return 0.1


# ---------------------------------------------------------------- child ----

def _child(name: str, sf: float, cap_s: float = 0.0):
    """Run ONE config in this process; print a single JSON result line.
    `cap_s` is the parent's kill deadline: once one timed run landed,
    further runs are skipped if they might not fit — ONE number inside
    the cap beats the best of three outside it."""
    sql, kind, _, driving_table, over = _CONFIGS[name]
    if os.environ.get("BENCH_FORCE_CPU"):
        import jax

        jax.config.update("jax_platforms", "cpu")
    from presto_tpu.catalog.parquet import (
        ParquetConnector, export_tpch_chunked, export_tpcds_chunked,
    )
    from presto_tpu.connector import Catalog
    from presto_tpu.exec import ExecConfig, LocalRunner

    d = os.path.join(DATA_DIR, f"{kind}_sf{sf:g}")
    t0 = time.time()
    if kind == "tpch":
        export_tpch_chunked(d, sf, log=_log)
    else:
        export_tpcds_chunked(d, sf, log=_log)
    gen_s = round(time.time() - t0, 1)
    if gen_s > 1:
        _log(f"{kind} sf={sf:g}: dataset ensured in {gen_s}s -> {d}")
    cat = Catalog()
    conn = ParquetConnector(d, name=kind)
    cat.register(kind, conn, default=True)
    nrows = int(conn.get_table(driving_table).row_count)

    runs = over.get("runs", 3)
    cfg = {k: v for k, v in over.items() if k != "runs"}
    # ahead-of-stream precompilation on by default: chain programs trace
    # on a side pool while the scan decodes, shrinking warmup_s
    cfg.setdefault("precompile_workers", 2)
    # device cost/HBM accounting on for bench children: the roofline block
    # below needs XLA's per-program analysis; its cost lands in warmup
    cfg.setdefault("devprof", "on")
    runner = LocalRunner(cat, ExecConfig(batch_rows=1 << 20, **cfg))
    from presto_tpu.exec import programs
    snap0 = programs.snapshot()
    t0 = time.time()
    runner.run_batch(sql)  # warm-up: compiles + host/device caches
    warm_s = round(time.time() - t0, 1)
    snap1 = programs.snapshot()
    _log(f"{name}: warmup (compile + cache fill) {warm_s}s "
         f"({snap1['compiles'] - snap0['compiles']} compiles, "
         f"{snap1['trace_wall_s'] - snap0['trace_wall_s']:.1f}s trace wall)")
    times = []
    for _ in range(runs):
        if times and cap_s and (
                time.time() - _T0 + max(times) > cap_s * 0.85):
            _log(f"{name}: skipping remaining runs (cap {cap_s:.0f}s)")
            break
        t0 = time.perf_counter()
        out = runner.run_batch(sql)
        out.num_live()  # block on device completion
        times.append(time.perf_counter() - t0)
    best = min(times)
    _log(f"{name}: best {best:.3f}s of {sorted(round(t, 3) for t in times)} "
         f"({nrows} {driving_table} rows)")
    snap2 = programs.snapshot()
    lookups = snap2["hits"] + snap2["misses"]
    # dispatch-collapse accounting (exec/fragment_jit.py): how many fused
    # window dispatches vs per-batch step dispatches the LAST timed run
    # issued — the counters EXPLAIN ANALYZE and /v1/metrics also expose
    st = getattr(runner, "last_stats", {}) or {}
    print(json.dumps({
        "seconds": round(best, 4), "rows": nrows, "sf": sf, "sf_actual": sf,
        "rows_per_sec": round(nrows / best, 1), "warmup_s": warm_s,
        "fragment": {
            "fused_dispatches": st.get("fragment.dispatches", 0),
            "fused_batches": st.get("fragment.fused_batches", 0),
            "batch_dispatches": st.get("fragment.batch_dispatches", 0),
        },
        "compile": {
            "warm_compiles": snap1["compiles"] - snap0["compiles"],
            "post_warm_compiles": snap2["compiles"] - snap1["compiles"],
            "cache_hits": snap2["hits"],
            "cache_misses": snap2["misses"],
            "hit_rate": round(snap2["hits"] / lookups, 3) if lookups else 0.0,
            "trace_wall_s": round(snap2["trace_wall_s"], 2),
        },
        "hbo": _hbo_snapshot(st),
        "roofline": _roofline_snapshot(best),
    }), flush=True)


def _roofline_snapshot(wall_s):
    """Device cost/HBM accounting for a bench child record: call-weighted
    FLOPs and bytes the timed run dispatched, achieved rates over the best
    wall time, and the honest device label — on CPU the device block says
    available=false, so readers know the numbers are XLA static analysis
    over real wall time, not hardware counters."""
    from presto_tpu.obs import devprof

    s = devprof.summary(wall_s=wall_s)
    return {
        "programs_analyzed": s["programs"],
        "total_flops": round(s["total_flops"], 1),
        "total_bytes_accessed": round(s["total_bytes_accessed"], 1),
        "arithmetic_intensity": (round(s["arithmetic_intensity"], 4)
                                 if s["arithmetic_intensity"] else None),
        "achieved_flops_per_s": round(s.get("achieved_flops_per_s", 0.0), 1),
        "achieved_bytes_per_s": round(s.get("achieved_bytes_per_s", 0.0), 1),
        "peak_program_footprint_bytes": s["peak_program_footprint_bytes"],
        "device": s["device"],
    }


def _hbo_snapshot(st):
    """Runtime-statistics feedback accounting for a bench child record:
    replay waves paid this query + the process HBO counters."""
    from presto_tpu.obs import runstats
    snap = runstats.snapshot()
    return {
        "replay_waves": st.get("breaker.replay_waves", 0),
        "observations": sum(snap["observations"].values()),
        "would_flip": sum(snap["would_flip"].values()),
        "corrections": sum(snap["corrections"].values()),
        "history_entries": len(snap["history"]),
    }


def _mesh_child(n_dev: int, sf: float):
    """One mesh_scaling point: Q3 over an n_dev-device mesh. The PARENT
    sets XLA_FLAGS=--xla_force_host_platform_device_count before this
    process imports jax — device count is an import-time decision."""
    from presto_tpu.catalog.parquet import ParquetConnector, export_tpch_chunked
    from presto_tpu.connector import Catalog
    from presto_tpu.exec import ExecConfig
    from presto_tpu.parallel.mesh import make_mesh
    from presto_tpu.parallel.mesh_exec import MeshExecutor

    d = os.path.join(DATA_DIR, f"tpch_sf{sf:g}")
    export_tpch_chunked(d, sf, log=_log)
    cat = Catalog()
    conn = ParquetConnector(d, name="tpch")
    cat.register("tpch", conn, default=True)
    nrows = int(conn.get_table("lineitem").row_count)
    mx = MeshExecutor(cat, make_mesh(n_dev),
                      ExecConfig(batch_rows=1 << 18))
    t0 = time.time()
    mx.run_batch(Q3)  # warm-up: trace + compile + staging caches
    warm_s = round(time.time() - t0, 1)
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = mx.run_batch(Q3)
        out.num_live()
        times.append(time.perf_counter() - t0)
    best = min(times)
    lr = mx.last_run or {"retries": 0, "attempts": [{"exchanges": []}]}
    ex = lr["attempts"][-1]["exchanges"]
    used = sum(e["lanes_used"] for e in ex)
    total = sum(e["lanes_total"] for e in ex)
    print(json.dumps({
        "n_dev": n_dev, "seconds": round(best, 4), "rows": nrows,
        "rows_per_sec": round(nrows / best, 1), "warmup_s": warm_s,
        "a2a_bytes": sum(e["bytes"] for e in ex),
        "a2a_collectives": sum(e["a2a"] for e in ex),
        "exchanges": len(ex),
        "fused_exchanges": sum(1 for e in ex if e["fused"]),
        "lanes_used": used, "lanes_total": total,
        "lane_util": round(used / total, 4) if total else 0.0,
        "overflow_retries": lr["retries"],
    }), flush=True)


def _histogram_quantile(body: str, family: str, q: float):
    """Quantile from a Prometheus log-bucket histogram exposition, summed
    over every label set of the family (cumulative counts add across
    groups at equal `le` edges). Linear interpolation inside the bucket;
    None when the family has no samples."""
    import re

    pat = re.compile(rf"^{family}_bucket{{(.*)}} (\S+)$")
    buckets = {}
    for ln in body.splitlines():
        m = pat.match(ln)
        if not m:
            continue
        le = None
        for part in m.group(1).split(","):
            k, _, v = part.partition("=")
            if k.strip() == "le":
                le = float("inf") if v.strip('"') == "+Inf" else float(
                    v.strip('"'))
        if le is not None:
            buckets[le] = buckets.get(le, 0.0) + float(m.group(2))
    if not buckets:
        return None
    edges = sorted(buckets)
    total = buckets[edges[-1]]
    if total <= 0:
        return None
    target = q * total
    prev_edge, prev_count = 0.0, 0.0
    for e in edges:
        c = buckets[e]
        if c >= target:
            if e == float("inf"):
                return prev_edge
            span = c - prev_count
            frac = (target - prev_count) / span if span > 0 else 1.0
            return prev_edge + frac * (e - prev_edge)
        prev_edge, prev_count = e, c
    return edges[-2] if len(edges) > 1 else edges[-1]


def _serving_child(sf: float, n_clients: int, per_client: int):
    """One closed-loop serving run: boot an in-process cluster over the
    parquet dataset, drive n_clients concurrent client threads through a
    mixed TPC-H workload over the real statement protocol, then read
    p50/p99 queue-wait and e2e off the lifecycle SLO histograms the
    coordinator scraped up (/v1/metrics — the same numbers an operator's
    dashboard would chart)."""
    import threading
    import urllib.request

    if os.environ.get("BENCH_FORCE_CPU"):
        import jax

        jax.config.update("jax_platforms", "cpu")
    from presto_tpu.catalog.parquet import ParquetConnector, export_tpch_chunked
    from presto_tpu.connector import Catalog
    from presto_tpu.server.coordinator import DistributedRunner

    d = os.path.join(DATA_DIR, f"tpch_sf{sf:g}")
    export_tpch_chunked(d, sf, log=_log)
    cat = Catalog()
    conn = ParquetConnector(d, name="tpch")
    cat.register("tpch", conn, default=True)
    nrows = int(conn.get_table("lineitem").row_count)
    dr = DistributedRunner(cat, n_workers=2)
    base = dr.coordinator.url
    mix = [Q1, Q6, JOIN_SF1]
    errors = []
    client_walls = []
    lock = threading.Lock()

    def client(cid: int):
        for i in range(per_client):
            sql = mix[(cid + i) % len(mix)]
            t0 = time.perf_counter()
            try:
                req = urllib.request.Request(
                    base + "/v1/statement", data=sql.encode(),
                    headers={"X-Presto-User": f"bench-{cid}",
                             "Content-Type": "text/plain"})
                doc = json.loads(urllib.request.urlopen(
                    req, timeout=600).read())
                while doc.get("nextUri"):
                    doc = json.loads(urllib.request.urlopen(
                        doc["nextUri"], timeout=600).read())
                if doc.get("error"):
                    raise RuntimeError(doc["error"].get("message"))
                with lock:
                    client_walls.append(time.perf_counter() - t0)
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")
                return

    t0 = time.time()
    threads = [threading.Thread(target=client, args=(c,))
               for c in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.time() - t0
    body = urllib.request.urlopen(
        base + "/v1/metrics", timeout=30).read().decode()
    dr.close()
    rec = {
        "clients": n_clients, "queries": len(client_walls),
        "errors": errors[:5], "sf": sf, "sf_actual": sf, "rows": nrows,
        "wall_s": round(wall, 2),
        "queries_per_sec": round(len(client_walls) / wall, 3) if wall else 0,
    }
    for seg, fam in (("queue_wait", "presto_tpu_query_queue_wait_seconds"),
                     ("e2e", "presto_tpu_query_e2e_seconds")):
        for q, label in ((0.5, "p50"), (0.99, "p99")):
            v = _histogram_quantile(body, fam, q)
            rec[f"{seg}_{label}_s"] = round(v, 4) if v is not None else None
    print(json.dumps(rec), flush=True)


def _serving_cached_child(sf: float):
    """Result-cache economics: the same mixed workload served twice over
    the statement protocol with ``result_cache=query`` on the session.
    Round 1 (cold) pays plan+compile+execute; rounds 2-3 (warm) must be
    served out of the fingerprint-keyed result cache — the record carries
    cold/warm p50, the hit rate, and the bytes the cache holds for it."""
    import statistics
    import urllib.request

    if os.environ.get("BENCH_FORCE_CPU"):
        import jax

        jax.config.update("jax_platforms", "cpu")
    from presto_tpu.catalog.parquet import ParquetConnector, export_tpch_chunked
    from presto_tpu.connector import Catalog
    from presto_tpu.server.coordinator import DistributedRunner

    d = os.path.join(DATA_DIR, f"tpch_sf{sf:g}")
    export_tpch_chunked(d, sf, log=_log)
    cat = Catalog()
    conn = ParquetConnector(d, name="tpch")
    cat.register("tpch", conn, default=True)
    dr = DistributedRunner(cat, n_workers=2)
    base = dr.coordinator.url
    mix = [Q1, Q6, JOIN_SF1]

    def run_one(sql):
        t0 = time.perf_counter()
        req = urllib.request.Request(
            base + "/v1/statement", data=sql.encode(),
            headers={"X-Presto-User": "bench-cached",
                     "X-Presto-Session": "result_cache=query",
                     "Content-Type": "text/plain"})
        doc = json.loads(urllib.request.urlopen(req, timeout=600).read())
        while doc.get("nextUri"):
            doc = json.loads(urllib.request.urlopen(
                doc["nextUri"], timeout=600).read())
        if doc.get("error"):
            raise RuntimeError(doc["error"].get("message"))
        return time.perf_counter() - t0

    cold = [run_one(sql) for sql in mix]
    warm = [run_one(sql) for _ in range(2) for sql in mix]
    body = urllib.request.urlopen(
        base + "/v1/metrics", timeout=30).read().decode()
    dr.close()

    def _gauge(name):
        for line in body.splitlines():
            if line.startswith(name + "{") or line.startswith(name + " "):
                try:
                    return float(line.rsplit(" ", 1)[1])
                except ValueError:
                    pass
        return 0.0

    hits = _gauge("presto_tpu_result_cache_hits_total")
    misses = _gauge("presto_tpu_result_cache_misses_total")
    cold_p50 = statistics.median(cold)
    warm_p50 = statistics.median(warm)
    rec = {
        "sf": sf, "queries": len(mix),
        "cold_p50_s": round(cold_p50, 4), "warm_p50_s": round(warm_p50, 4),
        "speedup": round(cold_p50 / warm_p50, 1) if warm_p50 else None,
        "cache_hits": int(hits), "cache_misses": int(misses),
        "hit_rate": round(hits / (hits + misses), 3) if hits + misses else 0,
        "cache_bytes": int(_gauge("presto_tpu_result_cache_bytes")),
    }
    print(json.dumps(rec), flush=True)


def _spill_child(n_rows: int):
    """Skew-adversarial spilled join: 90% one-hot build keys joined under a
    memory pool ~40x smaller than the build side, vs the same join
    unconstrained. The slowdown factor is the price of graceful degradation
    under memory pressure; the stat block records how the dynamic hybrid
    hash converged (partition leaves, next-bit repartitions, role
    reversals) and the checksum proves the degraded path stayed correct."""
    if os.environ.get("BENCH_FORCE_CPU"):
        import jax

        jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import pandas as pd

    from presto_tpu.catalog.memory import MemoryConnector
    from presto_tpu.connector import Catalog
    from presto_tpu.exec import ExecConfig, LocalRunner
    from presto_tpu.exec.runtime import ExecContext, run_plan
    from presto_tpu.verifier import result_checksum

    rng = np.random.default_rng(47)
    bk = np.where(rng.random(n_rows) < 0.9, 7,
                  rng.integers(0, 50_000, n_rows)).astype(np.int64)
    conn = MemoryConnector()
    conn.add_table("build", pd.DataFrame({
        "bk": bk, "w": rng.normal(size=n_rows)}))
    n_probe = n_rows // 2
    conn.add_table("probe", pd.DataFrame({
        "k": rng.integers(0, 50_000, n_probe).astype(np.int64),
        "v": rng.normal(size=n_probe)}))
    cat = Catalog()
    cat.register("m", conn, default=True)
    sql = ("select probe.v, build.w from probe join build "
           "on probe.k = build.bk")

    base = LocalRunner(cat, ExecConfig(batch_rows=1 << 15))
    base.run_batch(sql)  # warm-up: compiles
    t0 = time.perf_counter()
    ref = base.run_batch(sql)
    ref.num_live()
    base_s = time.perf_counter() - t0

    pool = max(1 << 17, (n_rows * 16) // 40)
    lim = LocalRunner(cat, ExecConfig(
        batch_rows=1 << 15, memory_pool_bytes=pool, spill_partitions=8,
        spill_max_depth=4))
    times, last = [], None
    for i in range(3):  # first iteration doubles as spill-path warm-up
        qp = lim.plan(sql)
        ctx = ExecContext(cat, lim.config)
        t0 = time.perf_counter()
        out = run_plan(qp, ctx)
        out.num_live()
        if i > 0:
            times.append(time.perf_counter() - t0)
        last = (ctx, out)
    ctx, out = last
    best = min(times)
    print(json.dumps({
        "rows": n_rows + n_probe, "seconds": round(best, 4),
        "rows_per_sec": round((n_rows + n_probe) / best, 1),
        "unconstrained_seconds": round(base_s, 4),
        "degradation_factor": round(best / base_s, 2) if base_s else None,
        "pool_bytes": pool,
        "spilled_bytes": ctx.spill_manager.total_spilled_bytes,
        "spill_partitions": ctx.stats.get("spill.partitions", 0),
        "spill_repartitions": ctx.stats.get("spill.repartitions", 0),
        "spill_role_reversals": ctx.stats.get("spill.role_reversals", 0),
        "spill_revocations": ctx.stats.get("spill.revocations", 0),
        "checksum_equal": result_checksum(out) == result_checksum(ref),
    }), flush=True)


def _multiway_child(sf: float):
    """Star-chain join A/B (PR18 multiway engine): q3/q9/q64-shaped
    chains run binary (join_mode=off — the pre-collapse path) vs forced
    multiway in one process. Per mode: best wall, compiled-program count
    (process cache reset between modes so each pays its own compiles),
    and for the q3 shape a 2-worker distributed leg counting exchanged
    bytes (OutputBuffer page lengths) and plan fragments. The checksum
    ties the A and B legs to the same answer."""
    if os.environ.get("BENCH_FORCE_CPU"):
        import jax

        jax.config.update("jax_platforms", "cpu")
    from presto_tpu.catalog.tpch import tpch_catalog
    from presto_tpu.exec import ExecConfig, LocalRunner, programs
    from presto_tpu.verifier import result_checksum

    cat = tpch_catalog(sf)
    queries = {
        "q3_shape": (
            "select o.o_orderkey, sum(l.l_extendedprice) rev "
            "from lineitem l "
            "join orders o on l.l_orderkey = o.o_orderkey "
            "join customer c on o.o_custkey = c.c_custkey "
            "where c.c_mktsegment = 'BUILDING' "
            "group by o.o_orderkey"),
        "q9_shape": (
            "select s.s_nationkey, count(*) c, "
            "sum(l.l_extendedprice * (1 - l.l_discount)) v "
            "from lineitem l "
            "join supplier s on l.l_suppkey = s.s_suppkey "
            "join part p on l.l_partkey = p.p_partkey "
            "join orders o on l.l_orderkey = o.o_orderkey "
            "group by s.s_nationkey"),
        "q64_shape": (
            "select n.n_name, count(*) c "
            "from orders o "
            "join customer c on o.o_custkey = c.c_custkey "
            "left join nation n on c.c_nationkey = n.n_nationkey "
            "join lineitem l on o.o_orderkey = l.l_orderkey "
            "group by n.n_name"),
    }
    rec = {"sf_actual": sf}
    for name, sql in queries.items():
        entry = {}
        sums = {}
        for mode in ("binary", "multiway"):
            jm = "off" if mode == "binary" else "multiway"
            r = LocalRunner(cat, ExecConfig(batch_rows=1 << 15,
                                            join_mode=jm))
            programs.reset(counters_only=False)
            r.run_batch(sql)  # warm-up pays compiles
            compiles = programs.snapshot()["compiles"]
            times = []
            for _ in range(2):
                t0 = time.perf_counter()
                out = r.run_batch(sql)
                out.num_live()
                times.append(time.perf_counter() - t0)
            sums[mode] = result_checksum(out)
            entry[mode] = {"wall_s": round(min(times), 4),
                           "programs": int(compiles)}
        entry["checksum_equal"] = sums["binary"] == sums["multiway"]
        b, m = entry["binary"], entry["multiway"]
        entry["speedup"] = (round(b["wall_s"] / m["wall_s"], 2)
                            if m["wall_s"] else None)
        rec[name] = entry

    # distributed leg (q3 shape, small fixed sf): exchanged bytes +
    # fragment count, with broadcast suppressed so the binary chain pays
    # its per-join partitioned exchanges
    from presto_tpu.server import buffers
    from presto_tpu.server.coordinator import DistributedRunner

    dcat = cat if sf <= 0.1 else tpch_catalog(0.05)
    counter = {"bytes": 0, "pages": 0}
    orig = buffers.OutputBuffer.enqueue

    def counted(self, partition, page):
        counter["bytes"] += len(page)
        counter["pages"] += 1
        return orig(self, partition, page)

    buffers.OutputBuffer.enqueue = counted
    try:
        dist = {}
        for mode in ("binary", "multiway"):
            jm = "off" if mode == "binary" else "multiway"
            counter["bytes"] = counter["pages"] = 0
            with DistributedRunner(
                    dcat, n_workers=2,
                    config=ExecConfig(batch_rows=1 << 15, join_mode=jm),
                    broadcast_threshold_rows=0) as dr:
                dplan = dr.plan_distributed(queries["q3_shape"])
                dr.run(queries["q3_shape"])
            dist[mode] = {"exchange_bytes": counter["bytes"],
                          "exchange_pages": counter["pages"],
                          "fragments": len(dplan.fragments)}
        rec["q3_distributed"] = dist
    finally:
        buffers.OutputBuffer.enqueue = orig
    print(json.dumps(rec), flush=True)


def _adaptive_child(n_rows: int):
    """Mis-estimated group-by A/B for in-run adaptation (PR20): grouping
    through `k % 100000` blinds NDV estimation (est = rows*0.1, actual =
    full key NDV), so adaptive=off picks the hash engine, overflows, and
    pays replay waves; adaptive=on flips engines / presizes from the
    wave's OBSERVED group count. Per mode: best wall of two runs, replay
    waves, and acted action counts; the checksum proves adaptation
    changed the schedule, never the answer."""
    if os.environ.get("BENCH_FORCE_CPU"):
        import jax

        jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import pandas as pd

    from presto_tpu.catalog.memory import MemoryConnector
    from presto_tpu.connector import Catalog
    from presto_tpu.exec import ExecConfig, LocalRunner
    from presto_tpu.exec import adaptive as _adaptive
    from presto_tpu.obs import runstats

    conn = MemoryConnector()
    conn.add_table("t", pd.DataFrame({
        "k": np.arange(n_rows, dtype=np.int64),
        "v": np.ones(n_rows, dtype=np.int64)}))
    cat = Catalog()
    cat.register("m", conn, default=True)
    sql = "select k % 100000 as g, sum(v) as s from m.t group by 1"

    rec = {"rows": n_rows}
    frames = {}
    for mode in ("off", "on"):
        times, df, r = [], None, None
        for _ in range(2):  # first run doubles as this mode's compile
            runstats.reset()  # every run is a cold-HBO run with a fresh
            _adaptive.reset()  # plan (flip-at-most-once pins the node)
            r = LocalRunner(cat, ExecConfig(adaptive=mode))
            t0 = time.perf_counter()
            df = r.run(sql)
            times.append(time.perf_counter() - t0)
        frames[mode] = df.sort_values("g", ignore_index=True)
        m = {"wall_s": round(min(times), 4),
             "waves": int(r.last_stats.get("breaker.replay_waves", 0)),
             "engine_flips": int(
                 r.last_stats.get("breaker.engine_flips", 0))}
        if mode == "on":
            acts = {}
            for a in _adaptive.recent_decisions():
                if a.get("acted"):
                    acts[a["kind"]] = acts.get(a["kind"], 0) + 1
            m["actions"] = acts
        rec[mode] = m
    rec["checksum_equal"] = bool(frames["on"].equals(frames["off"]))
    rec["wave_reduction"] = rec["off"]["waves"] - rec["on"]["waves"]
    print(json.dumps(rec), flush=True)


def _compile_tail_child(mode: str):
    """One serving boot + first-seen-query measurement (PR16 compile
    farm A/B). The parent sequences four of these against one cache dir:
    cold (no farm), record (corpus + artifacts), converge (boot #1 — the
    HBO-informed plan fingerprints settle and their programs persist),
    armed (boot #2 — every artifact prewarmed, first query should pay
    neither trace nor backend compile)."""
    if os.environ.get("BENCH_FORCE_CPU"):
        import jax

        jax.config.update("jax_platforms", "cpu")
    import urllib.request

    from presto_tpu.catalog.tpch import tpch_catalog
    from presto_tpu.exec import farm, programs
    from presto_tpu.server.coordinator import DistributedRunner

    agg = ("select l_returnflag as f, sum(l_quantity) as q, count(*) as c "
           "from lineitem where l_discount > 0.02 "
           "group by l_returnflag order by f")
    join = ("select o_orderpriority as p, count(*) as c from lineitem "
            "join orders on l_orderkey = o_orderkey "
            "group by o_orderpriority order by p")

    cat = tpch_catalog(0.01)
    t0 = time.perf_counter()
    dr = DistributedRunner(cat, n_workers=2)
    boot_s = time.perf_counter() - t0
    base = dr.coordinator.url

    def run_sql(s):
        req = urllib.request.Request(
            base + "/v1/statement", data=s.encode(),
            headers={"X-Presto-User": "bench",
                     "Content-Type": "text/plain"})
        doc = json.load(urllib.request.urlopen(req, timeout=300))
        while doc.get("nextUri"):
            doc = json.load(urllib.request.urlopen(doc["nextUri"],
                                                   timeout=300))

    t0 = time.perf_counter()
    run_sql(agg)
    first_agg_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    run_sql(join)
    first_join_s = time.perf_counter() - t0
    if mode in ("record", "converge"):
        farm.drain()  # async artifact persists must land before exit
    snap = programs.snapshot()
    armed = getattr(dr.coordinator, "_farm_armed", 0)
    dr.close()
    print(json.dumps({
        "mode": mode, "boot_s": round(boot_s, 3),
        "first_agg_s": round(first_agg_s, 3),
        "first_join_s": round(first_join_s, 3),
        "compiles": int(snap["compiles"]),
        "restored": int(snap["restored"]),
        "prewarmed": int(snap["prewarmed"]), "armed": int(armed),
    }), flush=True)


def _run_compile_tail(extra: dict, remaining: float):
    """Cold-boot vs farm-armed-boot A/B (BENCH_NOTES round 16): serving
    warmup_s and first-query e2e, four child processes, one cache dir."""
    d = tempfile.mkdtemp(prefix="bench_farm_")
    rec = {}
    try:
        for mode in ("cold", "record", "converge", "armed"):
            env = dict(os.environ)
            for k in ("PRESTO_TPU_FARM", "PRESTO_TPU_PROGRAM_PERSIST",
                      "PRESTO_TPU_CACHE_DIR"):
                env.pop(k, None)
            if mode != "cold":
                env.update(PRESTO_TPU_CACHE_DIR=d, PRESTO_TPU_FARM="1",
                           PRESTO_TPU_PROGRAM_PERSIST="1")
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--compile-tail-child", mode],
                env=env, stdout=subprocess.PIPE,
                timeout=min(900, max(180, remaining - 15)))
            lines = p.stdout.decode().strip().splitlines()
            if p.returncode != 0 or not lines:
                rec[mode] = {"error": f"child rc={p.returncode}"}
                continue
            rec[mode] = json.loads(lines[-1])
        cold, armed = rec.get("cold", {}), rec.get("armed", {})
        if "first_agg_s" in cold and "first_agg_s" in armed:
            rec["first_query_speedup"] = round(
                cold["first_agg_s"] / max(armed["first_agg_s"], 1e-9), 2)
            rec["armed_onpath_compiles"] = armed["compiles"]
            _log(f"compile_tail: first query {cold['first_agg_s']}s cold "
                 f"vs {armed['first_agg_s']}s farm-armed "
                 f"({rec['first_query_speedup']}x; armed boot "
                 f"{armed['boot_s']}s prewarmed {armed['prewarmed']} "
                 f"artifacts, {armed['compiles']} on-path compiles)")
        extra["compile_tail"] = rec
    except subprocess.TimeoutExpired:
        extra["compile_tail"] = {"error": "timeout", **rec}
    except Exception as e:
        extra["compile_tail"] = {"error": f"{type(e).__name__}: {e}"}
    finally:
        shutil.rmtree(d, ignore_errors=True)


def _run_spill_skew(extra: dict, remaining: float):
    """Skew-adversarial spill bench (see BENCH_NOTES.md round 15): the
    graceful-degradation price of a join that cannot fit memory."""
    n_rows = int(os.environ.get("BENCH_SPILL_ROWS", "400000"))
    env = dict(os.environ)
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--spill-child",
             str(n_rows)],
            env=env, stdout=subprocess.PIPE,
            timeout=min(600, max(120, remaining - 15)))
        lines = p.stdout.decode().strip().splitlines()
        if p.returncode == 0 and lines:
            rec = json.loads(lines[-1])
            _log(f"spill_skew: {rec['seconds']}s spilled vs "
                 f"{rec['unconstrained_seconds']}s unconstrained "
                 f"({rec['degradation_factor']}x, "
                 f"{rec['spilled_bytes']}B spilled, "
                 f"{rec['spill_repartitions']} repartitions, "
                 f"{rec['spill_role_reversals']} reversals, "
                 f"checksum_equal={rec['checksum_equal']})")
            extra["spill_skew"] = rec
        else:
            extra["spill_skew"] = {"error": f"child rc={p.returncode}"}
    except subprocess.TimeoutExpired:
        extra["spill_skew"] = {"error": "timeout"}
    except Exception as e:  # noqa: BLE001
        extra["spill_skew"] = {"error": f"{type(e).__name__}: {e}"}


def _run_multiway_ab(extra: dict, remaining: float):
    """Binary-vs-multiway join chain A/B (see BENCH_NOTES.md round 18):
    wall, compiled-program count, and distributed exchange bytes for the
    q3/q9/q64 star-chain shapes."""
    sf = float(os.environ.get("BENCH_SF_MULTIWAY", "0.1"))
    env = dict(os.environ)
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--multiway-child",
             str(sf)],
            env=env, stdout=subprocess.PIPE,
            timeout=min(600, max(120, remaining - 15)))
        lines = p.stdout.decode().strip().splitlines()
        if p.returncode == 0 and lines:
            rec = json.loads(lines[-1])
            q3 = rec.get("q3_shape", {})
            d = rec.get("q3_distributed", {})
            _log(f"multiway_ab: q3 {q3.get('speedup')}x "
                 f"(programs {q3.get('binary', {}).get('programs')}"
                 f"->{q3.get('multiway', {}).get('programs')}, "
                 f"exchange "
                 f"{d.get('binary', {}).get('exchange_bytes')}"
                 f"->{d.get('multiway', {}).get('exchange_bytes')}B, "
                 f"checksum_equal={q3.get('checksum_equal')})")
            extra["multiway_ab"] = rec
        else:
            extra["multiway_ab"] = {"error": f"child rc={p.returncode}"}
    except subprocess.TimeoutExpired:
        extra["multiway_ab"] = {"error": "timeout"}
    except Exception as e:  # noqa: BLE001
        extra["multiway_ab"] = {"error": f"{type(e).__name__}: {e}"}


def _run_adaptive_ab(extra: dict, remaining: float):
    """In-run adaptation A/B (see BENCH_NOTES.md round 20): replay waves,
    wall, and acted adaptive-action counts for adaptive=off vs on on the
    10x-mis-estimated group-by."""
    n_rows = int(os.environ.get("BENCH_ADAPTIVE_ROWS", "16000"))
    env = dict(os.environ)
    if env.get("BENCH_FORCE_CPU"):
        # match the test topology so the flip-vs-replay accounting is the
        # same shape it would have on an 8-device slice
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            " --xla_force_host_platform_device_count=8")
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--adaptive-child",
             str(n_rows)],
            env=env, stdout=subprocess.PIPE,
            timeout=min(600, max(120, remaining - 15)))
        lines = p.stdout.decode().strip().splitlines()
        if p.returncode == 0 and lines:
            rec = json.loads(lines[-1])
            off, on = rec.get("off", {}), rec.get("on", {})
            _log(f"adaptive_ab: waves {off.get('waves')}->{on.get('waves')} "
                 f"({off.get('wall_s')}s->{on.get('wall_s')}s, "
                 f"actions={on.get('actions')}, "
                 f"checksum_equal={rec.get('checksum_equal')})")
            extra["adaptive_ab"] = rec
        else:
            extra["adaptive_ab"] = {"error": f"child rc={p.returncode}"}
    except subprocess.TimeoutExpired:
        extra["adaptive_ab"] = {"error": "timeout"}
    except Exception as e:  # noqa: BLE001
        extra["adaptive_ab"] = {"error": f"{type(e).__name__}: {e}"}


def _run_serving_slo_cached(extra: dict, remaining: float):
    """Warm-over-cold serving comparison for the semantic result cache
    (the perf claim: an identical repeat never re-plans, re-compiles, or
    re-executes — see BENCH_NOTES.md for how to read the record)."""
    sf = float(os.environ.get("BENCH_SF_SERVING", "0.1"))
    env = dict(os.environ)
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--serving-cached-child", str(sf)],
            env=env, stdout=subprocess.PIPE,
            timeout=min(900, max(120, remaining - 15)))
        lines = p.stdout.decode().strip().splitlines()
        if p.returncode == 0 and lines:
            rec = json.loads(lines[-1])
            _log(f"serving_slo_cached: cold p50={rec['cold_p50_s']}s "
                 f"warm p50={rec['warm_p50_s']}s "
                 f"({rec['speedup']}x, hit rate {rec['hit_rate']}, "
                 f"{rec['cache_bytes']}B held)")
            extra["serving_slo_cached"] = rec
        else:
            extra["serving_slo_cached"] = {"error": f"child rc={p.returncode}"}
    except subprocess.TimeoutExpired:
        extra["serving_slo_cached"] = {"error": "timeout"}
    except Exception as e:  # noqa: BLE001
        extra["serving_slo_cached"] = {"error": f"{type(e).__name__}: {e}"}


def _run_serving_slo(extra: dict, remaining: float):
    """Closed-loop serving-SLO bench: N concurrent protocol clients over a
    mixed TPC-H workload, latencies read from the per-group lifecycle
    histograms (log buckets, so the p99 is bucket-interpolated — same
    fidelity a Prometheus `histogram_quantile` would report)."""
    sf = float(os.environ.get("BENCH_SF_SERVING", "0.1"))
    n_clients = int(os.environ.get("BENCH_SERVING_CLIENTS", "8"))
    per_client = int(os.environ.get("BENCH_SERVING_QUERIES", "4"))
    env = dict(os.environ)
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--serving-child",
             str(sf), str(n_clients), str(per_client)],
            env=env, stdout=subprocess.PIPE,
            timeout=min(900, max(120, remaining - 15)))
        lines = p.stdout.decode().strip().splitlines()
        if p.returncode == 0 and lines:
            rec = json.loads(lines[-1])
            _log(f"serving_slo: {rec['queries']} queries from "
                 f"{rec['clients']} clients, e2e p50={rec['e2e_p50_s']}s "
                 f"p99={rec['e2e_p99_s']}s, queue p99="
                 f"{rec['queue_wait_p99_s']}s")
            extra["serving_slo"] = rec
        else:
            extra["serving_slo"] = {"error": f"child rc={p.returncode}"}
    except subprocess.TimeoutExpired:
        extra["serving_slo"] = {"error": "timeout"}
    except Exception as e:  # noqa: BLE001
        extra["serving_slo"] = {"error": f"{type(e).__name__}: {e}"}


def _run_mesh_scaling(extra: dict, remaining: float):
    """ICI exchange scaling sweep: Q3 at n_dev ∈ {1,2,4,8} on the host
    platform (deterministic on any machine; on a real slice the same
    sweep measures ICI). Each point is its own subprocess because the
    device count is fixed at jax import."""
    sf = float(os.environ.get("BENCH_SF_MESH", "0.1"))
    deadline = time.time() + remaining
    points = {}
    for n_dev in (1, 2, 4, 8):
        if time.time() > deadline - 60:
            points[f"n{n_dev}"] = {"skipped": "budget"}
            continue
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            f" --xla_force_host_platform_device_count={n_dev}")
        try:
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--mesh-child", str(n_dev), str(sf)],
                env=env, stdout=subprocess.PIPE, timeout=600)
            lines = p.stdout.decode().strip().splitlines()
            if p.returncode == 0 and lines:
                rec = json.loads(lines[-1])
                _log(f"mesh_scaling n_dev={n_dev}: {rec['seconds']}s, "
                     f"{rec['a2a_bytes']} a2a bytes, "
                     f"{100 * rec['lane_util']:.1f}% lane util")
                points[f"n{n_dev}"] = rec
            else:
                points[f"n{n_dev}"] = {"error": f"child rc={p.returncode}"}
        except subprocess.TimeoutExpired:
            points[f"n{n_dev}"] = {"error": "timeout"}
        except Exception as e:
            points[f"n{n_dev}"] = {"error": f"{type(e).__name__}: {e}"}
    extra["mesh_scaling"] = {"sf": sf, "query": "q3", **points}


# --------------------------------------------------------------- parent ----

_STATE = {"extra": {}, "emitted": False, "child": None}


def _emit():
    if _STATE["emitted"]:
        return
    _STATE["emitted"] = True
    extra = _STATE["extra"]

    def by_prefix(prefix, exact):
        # results are keyed by the sf ACTUALLY run; a downscaled run lands
        # under e.g. q1_sf0.1 — still surface it (vs_baseline only applies
        # at the nominal sf)
        r = extra.get(exact)
        if isinstance(r, dict):
            return r, True
        for k, v in extra.items():
            if k.startswith(prefix) and isinstance(v, dict):
                return v, False
        return {}, False

    for prefix, exact, ref in (("q1_sf", "q1_sf1", _REF["q1"]),
                               ("q6_sf", "q6_sf10", _REF["q6"])):
        r, nominal = by_prefix(prefix, exact)
        if nominal and "rows_per_sec" in r:
            r["vs_baseline"] = round(r["rows_per_sec"] / ref, 3)
    q1, q1_nominal = by_prefix("q1_sf", "q1_sf1")
    value = q1.get("rows_per_sec", 0.0)
    print(json.dumps({
        "metric": "tpch_q1_sf1_rows_per_sec",
        "value": value,
        "unit": "rows/s",
        "vs_baseline": (round(value / _REF["q1"], 3)
                        if value and q1_nominal else 0.0),
        "extra": extra,
    }), flush=True)


def _checkpoint():
    try:
        with open(os.path.join(_HERE, "BENCH_partial.json"), "w") as f:
            json.dump(_STATE["extra"], f, indent=1)
    except OSError:
        pass


def _on_term(signum, frame):
    _log(f"received signal {signum} — emitting partial results")
    _STATE["extra"].setdefault("note", f"killed by signal {signum}")
    child = _STATE.get("child")
    if child is not None and child.poll() is None:
        child.kill()
    _checkpoint()
    _emit()
    sys.exit(0)


def _probe_device() -> bool:
    """The axon TPU tunnel can wedge (observed: jax.devices() blocks
    forever). Probe it in a SUBPROCESS with a timeout; on failure fall
    back to CPU so the driver records a (clearly labeled) number instead
    of a bench timeout."""
    try:
        p = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices(); print('ok')"],
            timeout=150, capture_output=True)
        return p.returncode == 0 and b"ok" in p.stdout
    except subprocess.TimeoutExpired:
        return False


def main():
    if len(sys.argv) >= 4 and sys.argv[1] == "--child":
        _child(sys.argv[2], float(sys.argv[3]),
               float(sys.argv[4]) if len(sys.argv) > 4 else 0.0)
        return
    if len(sys.argv) >= 4 and sys.argv[1] == "--mesh-child":
        _mesh_child(int(sys.argv[2]), float(sys.argv[3]))
        return
    if len(sys.argv) >= 5 and sys.argv[1] == "--serving-child":
        _serving_child(float(sys.argv[2]), int(sys.argv[3]),
                       int(sys.argv[4]))
        return
    if len(sys.argv) >= 3 and sys.argv[1] == "--serving-cached-child":
        _serving_cached_child(float(sys.argv[2]))
        return
    if len(sys.argv) >= 3 and sys.argv[1] == "--spill-child":
        _spill_child(int(sys.argv[2]))
        return
    if len(sys.argv) >= 3 and sys.argv[1] == "--multiway-child":
        _multiway_child(float(sys.argv[2]))
        return
    if len(sys.argv) >= 3 and sys.argv[1] == "--compile-tail-child":
        _compile_tail_child(sys.argv[2])
        return
    if len(sys.argv) >= 3 and sys.argv[1] == "--adaptive-child":
        _adaptive_child(int(sys.argv[2]))
        return

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    budget = float(os.environ.get("BENCH_BUDGET_S", "2400"))
    extra = _STATE["extra"]

    device_ok = _probe_device()
    if not device_ok:
        _log("DEVICE PROBE FAILED (axon tunnel unresponsive) — "
             "falling back to CPU; numbers are NOT tpu numbers")
        extra["device"] = "cpu-fallback (tpu tunnel unresponsive)"

    sf_over = {"q9": float(os.environ.get("BENCH_SF_Q9", "100")),
               "q64": float(os.environ.get("BENCH_SF_Q64", "100"))}
    wanted = os.environ.get(
        "BENCH_CONFIGS", "q1_sf1,q1_nofuse_sf1,q6_sf10,q3_sf10,join_sf1,"
        "groupby_engine_ab_sf1,groupby_engine_ab_sort_sf1,mesh_scaling,"
        "serving_slo,serving_slo_cached,spill_skew,compile_tail,"
        "multiway_ab,adaptive_ab,q9,q64"
    ).split(",")

    for name in (w.strip() for w in wanted):
        if not name:
            continue
        name = _ALIASES.get(name, name)
        if name == "mesh_scaling":
            remaining = budget - (time.time() - _T0)
            if remaining < 60:
                _log("mesh_scaling: SKIPPED (budget exhausted)")
                extra["mesh_scaling"] = {"skipped": "budget"}
            else:
                _run_mesh_scaling(extra, remaining)
            _checkpoint()
            continue
        if name == "serving_slo":
            remaining = budget - (time.time() - _T0)
            if remaining < 60:
                _log("serving_slo: SKIPPED (budget exhausted)")
                extra["serving_slo"] = {"skipped": "budget"}
            else:
                if not device_ok:
                    os.environ["BENCH_FORCE_CPU"] = "1"
                _run_serving_slo(extra, remaining)
            _checkpoint()
            continue
        if name == "serving_slo_cached":
            remaining = budget - (time.time() - _T0)
            if remaining < 60:
                _log("serving_slo_cached: SKIPPED (budget exhausted)")
                extra["serving_slo_cached"] = {"skipped": "budget"}
            else:
                if not device_ok:
                    os.environ["BENCH_FORCE_CPU"] = "1"
                _run_serving_slo_cached(extra, remaining)
            _checkpoint()
            continue
        if name == "multiway_ab":
            remaining = budget - (time.time() - _T0)
            if remaining < 60:
                _log("multiway_ab: SKIPPED (budget exhausted)")
                extra["multiway_ab"] = {"skipped": "budget"}
            else:
                if not device_ok:
                    os.environ["BENCH_FORCE_CPU"] = "1"
                _run_multiway_ab(extra, remaining)
            _checkpoint()
            continue
        if name == "adaptive_ab":
            remaining = budget - (time.time() - _T0)
            if remaining < 60:
                _log("adaptive_ab: SKIPPED (budget exhausted)")
                extra["adaptive_ab"] = {"skipped": "budget"}
            else:
                if not device_ok:
                    os.environ["BENCH_FORCE_CPU"] = "1"
                _run_adaptive_ab(extra, remaining)
            _checkpoint()
            continue
        if name == "spill_skew":
            remaining = budget - (time.time() - _T0)
            if remaining < 60:
                _log("spill_skew: SKIPPED (budget exhausted)")
                extra["spill_skew"] = {"skipped": "budget"}
            else:
                if not device_ok:
                    os.environ["BENCH_FORCE_CPU"] = "1"
                _run_spill_skew(extra, remaining)
            _checkpoint()
            continue
        if name == "compile_tail":
            remaining = budget - (time.time() - _T0)
            if remaining < 240:
                _log("compile_tail: SKIPPED (budget exhausted)")
                extra["compile_tail"] = {"skipped": "budget"}
            else:
                if not device_ok:
                    os.environ["BENCH_FORCE_CPU"] = "1"
                _run_compile_tail(extra, remaining)
            _checkpoint()
            continue
        if name not in _CONFIGS:
            _log(f"{name}: UNKNOWN config (valid: {','.join(_CONFIGS)})")
            extra[name] = {"error": "unknown config"}
            continue
        remaining = budget - (time.time() - _T0)
        if remaining < 60:
            _log(f"{name}: SKIPPED (budget {budget:.0f}s exhausted)")
            extra[name] = {"skipped": "budget"}
            _checkpoint()
            continue
        _, kind, sf, _, _ = _CONFIGS[name]
        sf = sf_over.get(name, sf) if sf is None else sf
        sf = _resolve_sf(kind, sf, remaining)
        # the artifact key must record the sf ACTUALLY run, not the
        # config's nominal one (env override / budget downscale)
        label = f"{name.rsplit('_sf', 1)[0]}_sf{sf:g}"
        cap = _CAPS.get(name, 600)
        if not _dataset_ready(kind, sf):
            # cold cache: the child pays dataset generation (~60 s/SF for
            # the chunked exporters) before the measured run — the cap
            # must cover it or the child is killed mid-generation
            cap += sf * 70.0
        cap = min(cap, remaining - 15)
        env = dict(os.environ)
        if not device_ok:
            env["BENCH_FORCE_CPU"] = "1"
        if os.environ.get("BENCH_PALLAS"):
            env["PRESTO_TPU_PALLAS"] = "1"
        _log(f"{name}: starting (sf={sf:g}, cap={cap:.0f}s)")
        try:
            p = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__),
                 "--child", name, str(sf), str(cap)],
                env=env, stdout=subprocess.PIPE, stderr=None)
            _STATE["child"] = p
            try:
                out, _ = p.communicate(timeout=cap)
            except subprocess.TimeoutExpired:
                p.kill()
                p.communicate()
                raise
            lines = out.decode().strip().splitlines()
            if p.returncode == 0 and lines:
                rec = json.loads(lines[-1])
                rec.setdefault("sf_actual", sf)
                extra[label] = rec
            else:
                extra[label] = {"error": f"child rc={p.returncode}",
                               "sf": sf, "sf_actual": sf}
        except subprocess.TimeoutExpired:
            _log(f"{name}: TIMEOUT after {cap:.0f}s cap — moving on")
            extra[label] = {"error": f"timeout after {cap:.0f}s cap",
                           "sf": sf, "sf_actual": sf}
        except Exception as e:
            _log(f"{name}: FAILED {type(e).__name__}: {e}")
            extra[label] = {"error": f"{type(e).__name__}: {e}",
                           "sf": sf, "sf_actual": sf}
        finally:
            _STATE["child"] = None
        _checkpoint()

    _checkpoint()
    _emit()


if __name__ == "__main__":
    main()
