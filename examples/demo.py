#!/usr/bin/env python
"""presto-tpu demo: boot an in-process cluster and run the SQL surface.

    python examples/demo.py            # uses the real device if available
    python examples/demo.py --cpu     # force CPU

Shows: TPC-H queries, structural types + lambdas, grouping sets, window
frames, prepared statements, CTAS, and EXPLAIN ANALYZE with the
per-task stats rollup.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--sf", type=float, default=0.01)
    args = ap.parse_args()
    if not args.cpu:
        # the device tunnel can wedge indefinitely — reuse bench.py's
        # subprocess probe (one timeout policy for demo and bench) and
        # fall back to CPU instead of hanging the demo
        from bench import _probe_device

        if not _probe_device():
            print("device probe failed — falling back to CPU")
            args.cpu = True
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from presto_tpu.catalog.tpch import tpch_catalog
    from presto_tpu.exec import ExecConfig
    from presto_tpu.server.coordinator import DistributedRunner

    print(f"booting a 2-worker cluster over TPC-H sf={args.sf} ...")
    r = DistributedRunner(tpch_catalog(args.sf), n_workers=2,
                          config=ExecConfig(batch_rows=1 << 15))
    try:
        run = r.run
        print("\n-- TPC-H Q1 --")
        print(run("""
            select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
                   sum(l_extendedprice * (1 - l_discount)) as revenue,
                   count(*) as n
            from lineitem where l_shipdate <= date '1998-09-02'
            group by l_returnflag, l_linestatus
            order by l_returnflag, l_linestatus"""))

        print("\n-- structural types + lambdas --")
        print(run("""
            select o_orderpriority,
                   array_agg(o_orderkey) as keys
            from orders where o_orderkey < 40
            group by o_orderpriority order by o_orderpriority"""))
        print(run("select transform(sequence(1, 5), x -> x * x) as squares"))

        print("\n-- grouping sets --")
        print(run("""
            select o_orderstatus, o_orderpriority, count(*) as n,
                   grouping(o_orderstatus, o_orderpriority) as gid
            from orders group by rollup (o_orderstatus, o_orderpriority)
            order by gid, o_orderstatus, o_orderpriority limit 12"""))

        print("\n-- window frames --")
        print(run("""
            select o_custkey, o_totalprice,
                   avg(o_totalprice) over (partition by o_custkey
                       order by o_orderdate
                       rows between 2 preceding and current row) as mavg
            from orders where o_custkey < 5
            order by o_custkey limit 8"""))

        print("\n-- prepared statements --")
        from presto_tpu.client import execute

        url = r.coordinator.url
        execute(url, "prepare top_nations from "
                     "select n_name from nation where n_regionkey = ? "
                     "order by n_name limit ?")
        _, rows = execute(url, "execute top_nations using 2, 3")
        print([x[0] for x in rows])

        print("\n-- EXPLAIN ANALYZE (distributed stats rollup) --")
        out = r.coordinator.explain_analyze_distributed(
            "select count(*) as n from lineitem")
        print(out[out.index("-- task execution profile --"):])
    finally:
        r.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
