"""DBAPI connector — federate any PEP-249 database (sqlite3 built in).

Reference: presto-base-jdbc (BaseJdbcClient) + the mysql/postgresql/
sqlserver connectors built on it. Python's PEP-249 is the JDBC analog:
one connector class serves any driver, with the same pushdown surface —
column pruning becomes the SELECT list and engine scan constraints
become a WHERE clause (JdbcRecordSetProvider applying TupleDomain).

Rows fetched from the remote database decode straight into engine-native
columns (strings dictionary-encoded); results then flow through the
ordinary device pipeline like any other connector's batches.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from presto_tpu.batch import Batch, round_up_capacity
from presto_tpu.catalog.memory import DeviceSplitCache
from presto_tpu.connector import ColumnInfo, Connector, Split, TableHandle
from presto_tpu.dictionary import Dictionary
from presto_tpu.types import (
    BIGINT,
    DATE,
    DOUBLE,
    Type,
    VARCHAR,
)


def _quote(ident: str) -> str:
    return '"' + ident.replace('"', '""') + '"'


class DbapiConnector(DeviceSplitCache, Connector):
    """`connect_fn` returns a NEW DBAPI connection per call (drivers are
    rarely thread-safe; worker task threads each open their own)."""

    def __init__(self, connect_fn: Callable[[], object], name: str = "jdbc",
                 list_tables_sql: Optional[str] = None,
                 index_keys: Optional[Dict[str, List[List[str]]]] = None):
        self.name = name
        self._connect_fn = connect_fn
        # default works for sqlite; other drivers pass their dialect's
        # catalog query (e.g. information_schema.tables)
        self._list_tables_sql = list_tables_sql or (
            "select name from sqlite_master where type = 'table' "
            "order by name")
        self._handles: Dict[str, TableHandle] = {}
        self._dicts: Dict[str, Dict[str, Dictionary]] = {}
        # table -> declared keyed-lookup column sets (ConnectorIndex SPI;
        # remote databases index these, so WHERE key IN (...) is cheap)
        self._index_keys = {t: [list(k) for k in ks]
                            for t, ks in (index_keys or {}).items()}
        self._lock = threading.Lock()
        self._local = threading.local()
        self._init_split_cache()

    def get_index(self, handle, key_columns):
        ks = self._index_keys.get(handle.name, [])
        if any(set(key_columns) == set(k) for k in ks):
            return _DbapiIndex(self, handle.name, list(key_columns))
        return None

    def _conn(self):
        c = getattr(self._local, "conn", None)
        if c is None:
            c = self._local.conn = self._connect_fn()
        return c

    def table_names(self) -> List[str]:
        cur = self._conn().cursor()
        cur.execute(self._list_tables_sql)
        return [r[0] for r in cur.fetchall()]

    @staticmethod
    def _infer(values) -> Type:
        for v in values:
            if v is None:
                continue
            if isinstance(v, bool):
                return BIGINT
            if isinstance(v, int):
                return BIGINT
            if isinstance(v, float):
                return DOUBLE
            return VARCHAR
        return VARCHAR

    def get_table(self, name: str) -> TableHandle:
        with self._lock:
            h = self._handles.get(name)
            if h is not None:
                return h
        cur = self._conn().cursor()
        cur.execute(f"select * from {_quote(name)} limit 1000")
        col_names = [d[0] for d in cur.description]
        sample = cur.fetchall()
        types = [
            self._infer([row[i] for row in sample])
            for i in range(len(col_names))
        ]
        cur.execute(f"select count(*) from {_quote(name)}")
        nrows = cur.fetchone()[0]
        cols = [ColumnInfo(c, t, None) for c, t in zip(col_names, types)]
        h = TableHandle(self.name, name, cols, row_count=float(nrows))
        with self._lock:
            # the remote schema probe above runs outside the lock by
            # design; racing probes produce equivalent handles and the
            # insert is idempotent (last writer wins)
            self._handles[name] = h  # lint: allow(check-then-act)
        return h

    def splits(self, handle: TableHandle, desired: int = 1) -> List[Split]:
        # one remote cursor per table (the reference's JDBC splits are
        # also single unless the table exposes partitioning)
        return [Split(handle.name, 0, 1)]

    def _constraint_sql(self, constraints: Dict[str, tuple]) -> str:
        """Engine scan constraints → WHERE clause (TupleDomain pushdown)."""
        parts = []
        for col, (lo, hi) in (constraints or {}).items():
            if lo is not None:
                parts.append(f"{_quote(col)} >= {float(lo)!r}")
            if hi is not None:
                parts.append(f"{_quote(col)} <= {float(hi)!r}")
        return (" where " + " and ".join(parts)) if parts else ""

    def read_table_sql(self, table: str, columns: Sequence[str],
                       constraints=None) -> str:
        sel = ", ".join(_quote(c) for c in columns)
        return (f"select {sel} from {_quote(table)}"
                + self._constraint_sql(constraints))

    def _read_split_uncached(self, split: Split, columns: Sequence[str],
                             capacity: Optional[int] = None) -> Batch:
        cur = self._conn().cursor()
        sql = self.read_table_sql(split.table, columns)
        cur.execute(sql)
        return self._rows_to_batch(split.table, columns, cur.fetchall(),
                                   capacity)

    def read_split_constrained(self, split: Split, columns: Sequence[str],
                               capacity: Optional[int] = None,
                               constraints=None) -> Batch:
        """Range constraints become the remote WHERE clause
        (JdbcRecordSetProvider applying TupleDomain); bypasses the split
        cache, whose keys don't carry constraints. Non-numeric bounds stay
        engine-side (the filter above the scan re-applies everything)."""
        num = {c: (lo, hi) for c, (lo, hi) in (constraints or {}).items()
               if all(v is None or isinstance(v, (int, float))
                      for v in (lo, hi))}
        cur = self._conn().cursor()
        cur.execute(self.read_table_sql(split.table, columns, num))
        return self._rows_to_batch(split.table, columns, cur.fetchall(),
                                   capacity)

    def _rows_to_batch(self, table: str, columns: Sequence[str], rows,
                       capacity: Optional[int] = None) -> Batch:
        import jax.numpy as jnp

        from presto_tpu.batch import Column

        h = self.get_table(table)
        col_types = {c.name: c.type for c in h.columns}
        n = len(rows)
        # a single remote cursor may return more rows than the engine's
        # batch capacity hint — size the batch to the actual result
        cap = max(capacity or 0, round_up_capacity(max(n, 1)))
        names, types, cols = [], [], []
        dicts = {}
        live = np.zeros(cap, bool)
        live[:n] = True
        for i, cname in enumerate(columns):
            t = col_types[cname]
            raw = [r[i] for r in rows]
            valid = np.array([v is not None for v in raw])
            vcol = None
            if t.is_string:
                with self._lock:
                    d = self._dicts.setdefault(table, {}).get(cname)
                    vocab = sorted({str(v) for v in raw if v is not None})
                    nd = Dictionary(np.asarray(vocab, dtype=str))
                    if d is not None:
                        nd = Dictionary.merge(d, nd)
                    self._dicts[table][cname] = nd
                codes = np.array(
                    [nd.code_of(str(v)) if v is not None else -1
                     for v in raw], np.int32)
                buf = np.full(cap, -1, np.int32)
                buf[:n] = codes
                dicts[cname] = nd
                if not valid.all():
                    vb = np.zeros(cap, bool)
                    vb[:n] = valid
                    vcol = jnp.asarray(vb)
            else:
                arr = np.array(
                    [v if v is not None else 0 for v in raw],
                    dtype=t.dtype)
                buf = np.zeros(cap, dtype=t.dtype)
                buf[:n] = arr
                if not valid.all():
                    vb = np.zeros(cap, bool)
                    vb[:n] = valid
                    vcol = jnp.asarray(vb)
            names.append(cname)
            types.append(t)
            cols.append(Column(jnp.asarray(buf), vcol))
        return Batch(names, types, cols, jnp.asarray(live), dicts)


def sqlite_connector(path: str, name: str = "sqlite") -> DbapiConnector:
    """Convenience factory for a sqlite database file (or ':memory:' is
    NOT shareable across threads — use a file path)."""
    import sqlite3

    return DbapiConnector(
        lambda: sqlite3.connect(path, check_same_thread=False), name=name)


class _DbapiIndex:
    """ConnectorIndex over a remote table: probe keys become chunked
    `WHERE key IN (...)` / OR-group queries — the remote database's own
    index does the lookup (reference: the thrift/jdbc index shape of
    spi ConnectorIndex; presto-base-jdbc has no index support, so this
    EXCEEDS the reference's JDBC surface)."""

    def __init__(self, conn: DbapiConnector, table: str, key_columns):
        self.c = conn
        self.table = table
        self.keys = key_columns

    def lookup(self, keys, columns, capacity=None) -> Batch:
        arrs = [np.asarray(keys[c]) for c in self.keys]
        seen = set()
        tuples = []
        for row in zip(*arrs):
            t = tuple(x.item() if hasattr(x, "item") else x for x in row)
            if t not in seen:
                seen.add(t)
                tuples.append(t)
        sel = ", ".join(_quote(c) for c in columns)
        rows: list = []
        cur = self.c._conn().cursor()
        # stay under driver parameter limits (sqlite: 999) — the budget is
        # BOUND PARAMETERS, and multi-key groups bind len(keys) each
        CHUNK = max(1, 400 // len(self.keys))
        for i in range(0, len(tuples), CHUNK):
            chunk = tuples[i:i + CHUNK]
            if len(self.keys) == 1:
                ph = ",".join("?" * len(chunk))
                sql = (f"select {sel} from {_quote(self.table)} "
                       f"where {_quote(self.keys[0])} in ({ph})")
                params = [t[0] for t in chunk]
            else:
                grp = ("(" + " and ".join(f"{_quote(c)} = ?"
                                          for c in self.keys) + ")")
                sql = (f"select {sel} from {_quote(self.table)} where "
                       + " or ".join([grp] * len(chunk)))
                params = [x for t in chunk for x in t]
            cur.execute(sql, params)
            rows.extend(cur.fetchall())
        return self.c._rows_to_batch(self.table, columns, rows, capacity)
