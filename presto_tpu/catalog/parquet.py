"""Parquet storage connector — the persistent-format layer.

Reference analog: presto-hive + presto-orc/presto-parquet. Where the Aria
work makes the ORC reader *selective* (filter pushdown into the decode loop,
OrcSelectiveRecordReader.java:54, TupleDomainFilter.java:92), the TPU-native
equivalents are:

- row-group pruning with parquet min/max statistics (coarse TupleDomain
  filtering before any IO),
- column pruning (only referenced columns are decoded — driven by the
  planner's column pruning, SURVEY §2a PushdownSubfields analog),
- dictionary-preserving reads: parquet dictionary-encoded string columns map
  straight onto the engine's Dictionary codes without materializing strings.

Splits are row-group ranges; batches decode straight into fixed-capacity
device arrays.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from presto_tpu.batch import Batch, round_up_capacity
from presto_tpu.connector import ColumnInfo, Connector, Split, TableHandle
from presto_tpu.dictionary import Dictionary
from presto_tpu.types import (
    BIGINT,
    BOOLEAN,
    DATE,
    DOUBLE,
    DecimalType,
    INTEGER,
    REAL,
    Type,
    VARCHAR,
)


_DECIMAL_META = b"presto_tpu.decimal"


def _arrow_to_sql(field: pa.Field) -> Type:
    t = field.type
    if field.metadata and _DECIMAL_META in field.metadata:
        p, s = map(int, field.metadata[_DECIMAL_META].decode().split(","))
        return DecimalType(p, s)
    if pa.types.is_boolean(t):
        return BOOLEAN
    if pa.types.is_int8(t) or pa.types.is_int16(t) or pa.types.is_int32(t):
        return INTEGER
    if pa.types.is_int64(t):
        return BIGINT
    if pa.types.is_float32(t):
        return REAL
    if pa.types.is_float64(t):
        return DOUBLE
    if pa.types.is_date32(t):
        return DATE
    if pa.types.is_decimal(t):
        if t.precision <= 18:
            return DecimalType(t.precision, t.scale)
        raise NotImplementedError("decimal precision > 18")
    if pa.types.is_string(t) or pa.types.is_large_string(t) or (
        pa.types.is_dictionary(t)
    ):
        return VARCHAR
    raise NotImplementedError(f"arrow type {t}")


def _sql_to_arrow(t: Type):
    if t is BOOLEAN:
        return pa.bool_()
    if t is INTEGER:
        return pa.int32()
    if t is BIGINT:
        return pa.int64()
    if t is REAL:
        return pa.float32()
    if t is DOUBLE:
        return pa.float64()
    if t is DATE:
        return pa.date32()
    if isinstance(t, DecimalType):
        # unscaled int64 physical storage; the SQL type travels in field
        # metadata (fast zero-copy IO; readers see plain int64)
        return pa.int64()
    if t.is_string:
        return pa.dictionary(pa.int32(), pa.string())
    raise NotImplementedError(str(t))


def write_table(path: str, data: Dict[str, np.ndarray], types: Dict[str, Type],
                dicts: Optional[Dict[str, Dictionary]] = None,
                row_group_rows: int = 1 << 20):
    """Write engine-native columns (dict codes, unscaled decimals, day ints)
    to a parquet file."""
    arrays = []
    fields = []
    for name, arr in data.items():
        t = types[name]
        at = _sql_to_arrow(t)
        meta = None
        if t.is_string:
            d = (dicts or {})[name]
            idx = pa.array(arr.astype(np.int32), pa.int32())
            vocab = pa.array([str(v) for v in d.values], pa.string())
            a = pa.DictionaryArray.from_arrays(idx, vocab)
        elif isinstance(t, DecimalType):
            a = pa.array(arr.astype(np.int64), pa.int64())
            meta = {_DECIMAL_META: f"{t.precision},{t.scale}".encode()}
        elif t is DATE:
            a = pa.array(arr.astype(np.int32), pa.int32()).cast(pa.date32())
        else:
            a = pa.array(arr, at)
        arrays.append(a)
        fields.append(pa.field(name, at, metadata=meta))
    table = pa.Table.from_arrays(arrays, schema=pa.schema(fields))
    pq.write_table(table, path, row_group_size=row_group_rows,
                   use_dictionary=True, compression="zstd")


@dataclasses.dataclass
class _PqTable:
    path: str
    handle: TableHandle
    dicts: Dict[str, Dictionary]
    num_rows: int
    num_row_groups: int


class ParquetConnector(Connector):
    """Directory-of-parquet-files connector: each file <table>.parquet."""

    def __init__(self, directory: str, name: str = "parquet"):
        self.name = name
        self.directory = directory
        self._tables: Dict[str, _PqTable] = {}

    def table_names(self) -> List[str]:
        out = []
        for f in os.listdir(self.directory):
            if f.endswith(".parquet"):
                out.append(f[: -len(".parquet")])
        return sorted(out)

    def _load(self, name: str) -> _PqTable:
        if name in self._tables:
            return self._tables[name]
        path = os.path.join(self.directory, f"{name}.parquet")
        if not os.path.exists(path):
            raise KeyError(f"table not found: {name}")
        f = pq.ParquetFile(path)
        schema = f.schema_arrow
        cols = []
        dicts: Dict[str, Dictionary] = {}
        for field in schema:
            t = _arrow_to_sql(field)
            if t.is_string:
                # global per-column dictionary: union of per-row-group
                # dictionaries, built once at open (order-preserving)
                vocab = set()
                for rg in range(f.num_row_groups):
                    col = f.read_row_group(rg, columns=[field.name]).column(0)
                    for chunk in col.chunks:
                        if pa.types.is_dictionary(chunk.type):
                            vocab.update(chunk.dictionary.to_pylist())
                        else:
                            vocab.update(chunk.to_pylist())
                d = Dictionary(np.array(sorted(v for v in vocab if v is not None)))
                dicts[field.name] = d
                cols.append(ColumnInfo(field.name, t, d))
            else:
                cols.append(ColumnInfo(field.name, t))
        handle = TableHandle(self.name, name, cols, row_count=float(f.metadata.num_rows))
        t = _PqTable(path, handle, dicts, f.metadata.num_rows, f.num_row_groups)
        self._tables[name] = t
        return t

    def get_table(self, name: str) -> TableHandle:
        return self._load(name).handle

    def splits(self, handle: TableHandle, desired: int = 1) -> List[Split]:
        """Scan-parallelism units: row groups (like ORC stripes), subdivided
        when the engine wants finer batches than a row group. Split.part is
        (row_group, sub_index, sub_count)."""
        t = self._load(handle.name)
        f = pq.ParquetFile(t.path)
        target = max(1, -(-t.num_rows // max(desired, 1)))
        out = []
        for rg in range(t.num_row_groups):
            rg_rows = f.metadata.row_group(rg).num_rows
            subs = max(1, -(-rg_rows // target))
            for s in range(subs):
                out.append(Split(handle.name, (rg, s, subs), t.num_row_groups))
        return out

    def prune_splits(self, handle: TableHandle, splits: Sequence[Split],
                     min_max: Dict[str, Tuple[object, object]]) -> List[Split]:
        """Row-group pruning with column min/max constraints (the coarse
        TupleDomain pushdown of the selective reader)."""
        t = self._load(handle.name)
        f = pq.ParquetFile(t.path)
        keep = []
        name_to_idx = {f.schema_arrow.field(i).name: i for i in range(len(f.schema_arrow.names))}
        for s in splits:
            rg_idx = s.part[0] if isinstance(s.part, tuple) else s.part
            rg = f.metadata.row_group(rg_idx)
            ok = True
            for col, (lo, hi) in min_max.items():
                if col not in name_to_idx:
                    continue
                st = rg.column(name_to_idx[col]).statistics
                if st is None or not st.has_min_max:
                    continue
                if lo is not None and st.max is not None and st.max < lo:
                    ok = False
                    break
                if hi is not None and st.min is not None and st.min > hi:
                    ok = False
                    break
            if ok:
                keep.append(s)
        return keep

    def read_split(self, split: Split, columns: Sequence[str],
                   capacity: Optional[int] = None) -> Batch:
        t = self._load(split.table)
        f = pq.ParquetFile(t.path)
        if isinstance(split.part, tuple):
            rg, sub, sub_count = split.part
        else:
            rg, sub, sub_count = split.part, 0, 1
        tbl = f.read_row_group(rg, columns=list(columns))
        if sub_count > 1:
            per = -(-tbl.num_rows // sub_count)
            tbl = tbl.slice(sub * per, per)
        n = tbl.num_rows
        cap = capacity or round_up_capacity(max(n, 1))
        data = {}
        types = {}
        import jax.numpy as jnp

        from presto_tpu.batch import Column

        names, typelist, cols = [], [], []
        live = np.zeros(cap, bool)
        live[:n] = True
        validity_map = {}
        for name in columns:
            col = tbl.column(name)
            info = t.handle.column(name)
            st = info.type
            arr, valid = _decode_column(col, st, t.dicts.get(name))
            buf = np.zeros(cap, dtype=st.dtype)
            buf[:n] = arr
            if valid is not None:
                vb = np.zeros(cap, bool)
                vb[:n] = valid
                validity_map[name] = jnp.asarray(vb)
            names.append(name)
            typelist.append(st)
            cols.append(Column(jnp.asarray(buf), validity_map.get(name)))
        return Batch(
            names, typelist, cols, jnp.asarray(live),
            {c: t.dicts[c] for c in columns if c in t.dicts},
        )


def _decode_column(col: pa.ChunkedArray, t: Type, d: Optional[Dictionary]):
    """Arrow column → engine-native numpy (codes / unscaled / day ints)."""
    combined = col.combine_chunks() if col.num_chunks > 1 else (
        col.chunk(0) if col.num_chunks == 1 else pa.array([], col.type)
    )
    valid = None
    if combined.null_count:
        valid = np.asarray(combined.is_valid())
    if t.is_string:
        if pa.types.is_dictionary(combined.type):
            # remap this row group's dictionary codes into the table-global
            # dictionary (pure integer gather — no string materialization)
            local_vocab = np.asarray(combined.dictionary.to_pylist(), dtype=object)
            remap = np.searchsorted(d.values, local_vocab.astype(str))
            idx = combined.indices.to_numpy(zero_copy_only=False)
            idx = np.where(idx < 0, 0, idx)
            arr = remap[idx].astype(np.int32)
        else:
            strs = np.asarray(combined.to_pylist(), dtype=object)
            arr = np.array([d.code_of(s) if s is not None else -1 for s in strs], np.int32)
        if valid is not None:
            arr = np.where(valid, arr, -1)
        return arr, valid
    if isinstance(t, DecimalType):
        if pa.types.is_decimal(combined.type):
            arr = combined.cast(pa.decimal128(38, t.scale)).cast(pa.int64(), safe=False)
        else:
            arr = combined  # unscaled int64 storage
        return arr.to_numpy(zero_copy_only=False), valid
    if t is DATE:
        return combined.cast(pa.int32()).to_numpy(zero_copy_only=False), valid
    return combined.to_numpy(zero_copy_only=False), valid


def export_tpch(directory: str, sf: float = 1.0):
    """Materialize the TPC-H dataset to parquet (the dbgen→warehouse path)."""
    from presto_tpu.catalog.tpch import TpchConnector

    os.makedirs(directory, exist_ok=True)
    conn = TpchConnector(sf)
    for tname in conn.table_names():
        conn._ensure(tname)
        mt = conn.tables[tname]
        write_table(
            os.path.join(directory, f"{tname}.parquet"),
            mt.arrays,
            mt.types,
            mt.dicts,
        )
