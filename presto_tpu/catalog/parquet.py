"""Parquet storage connector — the persistent-format layer.

Reference analog: presto-hive + presto-orc/presto-parquet. Where the Aria
work makes the ORC reader *selective* (filter pushdown into the decode loop,
OrcSelectiveRecordReader.java:54, TupleDomainFilter.java:92), the TPU-native
equivalents are:

- row-group pruning with parquet min/max statistics (coarse TupleDomain
  filtering before any IO),
- column pruning (only referenced columns are decoded — driven by the
  planner's column pruning, SURVEY §2a PushdownSubfields analog),
- dictionary-preserving reads: parquet dictionary-encoded string columns map
  straight onto the engine's Dictionary codes without materializing strings.

Splits are row-group ranges; batches decode straight into fixed-capacity
device arrays.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from presto_tpu.batch import Batch, round_up_capacity
from presto_tpu.catalog.memory import DeviceSplitCache
from presto_tpu.connector import ColumnInfo, Connector, Split, TableHandle
from presto_tpu.dictionary import Dictionary
from presto_tpu.types import (
    BIGINT,
    BOOLEAN,
    DATE,
    DOUBLE,
    DecimalType,
    INTEGER,
    REAL,
    Type,
    VARCHAR,
)


_DECIMAL_META = b"presto_tpu.decimal"


def _arrow_to_sql(field: pa.Field) -> Type:
    t = field.type
    if field.metadata and _DECIMAL_META in field.metadata:
        p, s = map(int, field.metadata[_DECIMAL_META].decode().split(","))
        return DecimalType(p, s)
    if pa.types.is_boolean(t):
        return BOOLEAN
    if pa.types.is_int8(t) or pa.types.is_int16(t) or pa.types.is_int32(t):
        return INTEGER
    if pa.types.is_int64(t):
        return BIGINT
    if pa.types.is_float32(t):
        return REAL
    if pa.types.is_float64(t):
        return DOUBLE
    if pa.types.is_date32(t):
        return DATE
    if pa.types.is_decimal(t):
        if t.precision > 38:
            raise NotImplementedError(f"decimal precision {t.precision} > 38")
        return DecimalType(t.precision, t.scale)
    if pa.types.is_string(t) or pa.types.is_large_string(t) or (
        pa.types.is_dictionary(t)
    ):
        return VARCHAR
    raise NotImplementedError(f"arrow type {t}")


def _sql_to_arrow(t: Type):
    if t is BOOLEAN:
        return pa.bool_()
    if t is INTEGER:
        return pa.int32()
    if t is BIGINT:
        return pa.int64()
    if t is REAL:
        return pa.float32()
    if t is DOUBLE:
        return pa.float64()
    if t is DATE:
        return pa.date32()
    if isinstance(t, DecimalType):
        # unscaled int64 physical storage; the SQL type travels in field
        # metadata (fast zero-copy IO; readers see plain int64)
        return pa.int64()
    if t.is_string:
        return pa.dictionary(pa.int32(), pa.string())
    raise NotImplementedError(str(t))


def write_table(path: str, data: Dict[str, np.ndarray], types: Dict[str, Type],
                dicts: Optional[Dict[str, Dictionary]] = None,
                row_group_rows: int = 1 << 20,
                validity: Optional[Dict[str, np.ndarray]] = None):
    """Write engine-native columns (dict codes, unscaled decimals, day ints)
    to a parquet file. `validity` maps column → bool mask (False = NULL)."""
    arrays, schema = _to_arrow_columns(data, types, dicts or {}, validity)
    table = pa.Table.from_arrays(arrays, schema=schema)
    pq.write_table(table, path, row_group_size=row_group_rows,
                   use_dictionary=True, compression="zstd")


def write_bucketed_table(directory: str, name: str,
                         data: Dict[str, np.ndarray],
                         types: Dict[str, Type],
                         by: Sequence[str], count: int,
                         dicts: Optional[Dict[str, Dictionary]] = None,
                         validity: Optional[Dict[str, np.ndarray]] = None,
                         row_group_rows: int = 1 << 20):
    """Write a BUCKETED table: rows hash-partition by content hash of the
    `by` columns (np_bucket_ids — the SAME hash the spiller and colocated
    split placement use) into `<name>.buckets/b<i>.parquet` + a
    _bucketing.json spec. Reference: hive bucketed tables
    (HiveBucketing.getHiveBucket + ConnectorNodePartitioningProvider) —
    equal-bucketed joins on the bucket keys skip the shuffle."""
    import shutil

    from presto_tpu.spiller import np_bucket_ids

    dicts = dicts or {}
    validity = validity or {}
    d = os.path.join(directory, f"{name}.buckets")
    tmp = d + ".tmp"
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)
    pid = np_bucket_ids(
        [(np.asarray(data[k]), dicts.get(k), validity.get(k)) for k in by],
        count)
    for b in range(count):
        mask = pid == b
        bdata = {c: np.ascontiguousarray(np.asarray(v)[mask])
                 for c, v in data.items()}
        bvalid = {c: np.asarray(v)[mask] for c, v in validity.items()
                  if v is not None}
        arrays, schema = _to_arrow_columns(bdata, types, dicts, bvalid)
        pq.write_table(pa.Table.from_arrays(arrays, schema=schema),
                       os.path.join(tmp, f"b{b:05d}.parquet"),
                       row_group_size=row_group_rows,
                       use_dictionary=True, compression="zstd")
    with open(os.path.join(tmp, "_bucketing.json"), "w") as f:
        json.dump({"by": list(by), "count": int(count)}, f)
    shutil.rmtree(d, ignore_errors=True)
    os.replace(tmp, d)


def _footer_stats(f: "pq.ParquetFile", col_idx: int, t: Type,
                  ndv=None) -> Optional["ColumnStats"]:
    """CBO column stats from parquet footer metadata: min/max and null
    counts aggregated over row groups, NDV from the global dictionary when
    present (the reference's HiveMetastore-supplied table statistics analog;
    here the file footer IS the metastore)."""
    from presto_tpu.connector import ColumnStats

    mn = mx = None
    nulls = 0
    rows = max(f.metadata.num_rows, 1)
    for rg in range(f.num_row_groups):
        st = f.metadata.row_group(rg).column(col_idx).statistics
        if st is None:
            return ColumnStats(ndv=ndv) if ndv else None
        if st.null_count is not None:
            nulls += st.null_count
        if st.has_min_max and not t.is_string:
            try:
                lo, hi = float(st.min), float(st.max)
            except (TypeError, ValueError):
                try:  # date32 statistics arrive as datetime.date
                    lo = float(st.min.toordinal() - 719163)
                    hi = float(st.max.toordinal() - 719163)
                except Exception:
                    lo = hi = None
            if lo is not None:
                mn = lo if mn is None else min(mn, lo)
                mx = hi if mx is None else max(mx, hi)
    return ColumnStats(ndv=ndv, null_fraction=nulls / rows,
                       min_value=mn, max_value=mx)


@dataclasses.dataclass
class _PqTable:
    path: str
    handle: TableHandle
    dicts: Dict[str, Dictionary]
    num_rows: int
    num_row_groups: int
    # file version at load: (mtime_ns, size). A rewrite (INSERT/CTAS
    # replace) changes it; every process watching the same directory
    # revalidates on access, so multi-process workers see DDL from the
    # coordinator without an invalidation RPC
    version: tuple = (0, 0)
    # flattened ROW leaves: dotted column name -> (struct column, field)
    nested: Dict[str, tuple] = dataclasses.field(default_factory=dict)
    # scaled-writer part tables: virtual row-group index -> (file, rg)
    part_map: Optional[list] = None
    # hive-partitioned tables: {"pcols": [(name, Type)], "pvals": [tuple]}
    # where pvals[i] aligns with part_map[i] (engine-native values, None
    # for the NULL partition)
    hive: Optional[dict] = None
    # bucketed tables (ConnectorNodePartitioningProvider analog):
    # (key column names, bucket count); bucket_map[vrg] = bucket id
    bucketing: Optional[tuple] = None
    bucket_map: Optional[list] = None


class ParquetConnector(DeviceSplitCache, Connector):
    """Directory-of-parquet-files connector: each file <table>.parquet.

    Two cache tiers over the raw file (the warm-path analog of the
    reference's OS page cache + in-heap data cache):
    - device-resident split LRU (DeviceSplitCache mixin, HBM budget)
    - host-RAM decoded-column LRU (`host_cache_bytes`): parquet decode is
      single-threaded and dominates re-scans of tables too big for HBM
      (SF100 lineitem); decoded engine-native numpy columns are kept so
      re-runs pay only host→device transfer."""

    host_cache_bytes: int = 48 << 30
    # staging dirs untouched this long are reclaimable (SIGKILL'd writer)
    stale_staging_s: float = 3600.0

    def __init__(self, directory: str, name: str = "parquet"):
        import threading
        from collections import OrderedDict

        self.name = name
        self.directory = directory
        self._tables: Dict[str, _PqTable] = {}
        self._init_split_cache()
        self._host_cache: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._host_cache_used = 0
        self._host_cache_lock = threading.Lock()

    def table_names(self) -> List[str]:
        out = []
        for f in os.listdir(self.directory):
            if f.endswith(".parquet"):
                out.append(f[: -len(".parquet")])
            elif f.endswith(".parts") and os.path.isdir(
                    os.path.join(self.directory, f)):
                out.append(f[: -len(".parts")])
            elif f.endswith(".hive") and os.path.isdir(
                    os.path.join(self.directory, f)):
                out.append(f[: -len(".hive")])
        return sorted(out)

    @staticmethod
    def _file_version(path: str) -> tuple:
        st = os.stat(path)
        return (st.st_mtime_ns, st.st_size)

    def _check_fresh(self, name: str):
        """Drop cached metadata/pages when the backing file changed (the
        cross-process DDL-visibility path — see _PqTable.version)."""
        t = self._tables.get(name)
        if t is None:
            return
        try:
            if t.hive is not None:
                st = os.stat(t.path)  # the partition-root directory
                nfiles = sum(1 for _, _, fs in os.walk(t.path)
                             for f in fs if f.endswith(".parquet"))
                if (st.st_mtime_ns, nfiles) != t.version:
                    self._invalidate_table(name)
                return
            if t.part_map is not None:
                st = os.stat(t.path)  # the parts directory
                nparts = len([f for f in os.listdir(t.path)
                              if f.endswith(".parquet")])
                if (st.st_mtime_ns, nparts) != t.version:
                    self._invalidate_table(name)
                return
            if self._file_version(t.path) != t.version:
                self._invalidate_table(name)
        except OSError:
            self._invalidate_table(name)

    # -- scaled writers (SCALED_WRITER_DISTRIBUTION analog) ---------------
    # A table is either one <name>.parquet file or a <name>.parts/
    # directory of part-*.parquet files written concurrently by writer
    # tasks; readers treat every (file, row group) as a split.

    def supports_scaled_writes(self) -> bool:
        return True

    def parts_dir(self, name: str, staging: bool = False) -> str:
        return os.path.join(self.directory,
                            f"{name}.parts.tmp" if staging else f"{name}.parts")

    def begin_scaled_create(self, name: str, if_not_exists: bool = False):
        if self._table_exists(name):
            if if_not_exists:
                return False
            raise ValueError(f"table already exists: {name}")
        staging = self.parts_dir(name, staging=True)
        for attempt in (0, 1):
            try:
                # EXCLUSIVE create: two racing CTAS must not share a
                # staging dir (the loser would interleave its parts into
                # the winner's commit). mkdir is the atomic mutual-
                # exclusion primitive — the metadata-transaction role of
                # TransactionManager + HiveMetadata begin/finishCreate.
                os.makedirs(staging, exist_ok=False)
                return True
            except FileExistsError:
                # staleness recovery: a SIGKILL'd writer never aborts its
                # staging — reclaim when nothing has written to it for a
                # while, else a dead CTAS blocks the name forever
                try:
                    newest = max(
                        (os.path.getmtime(os.path.join(staging, f))
                         for f in os.listdir(staging)),
                        default=os.path.getmtime(staging))
                except OSError:
                    continue  # lost a race with a finishing writer
                import time as _time

                if attempt == 0 and _time.time() - newest > self.stale_staging_s:
                    import shutil

                    shutil.rmtree(staging, ignore_errors=True)
                    continue
                raise ValueError(
                    f"table {name!r} is being created concurrently"
                ) from None
        return True

    def write_part(self, name: str, part_id: str, batches,
                   staging: bool = True) -> int:
        from presto_tpu.catalog.memory import _batches_to_host

        d = self.parts_dir(name, staging=staging)
        names, types, data = _batches_to_host(batches)
        from presto_tpu.types import ArrayType, MapType

        if any(isinstance(t, (ArrayType, MapType)) for t in types):
            raise NotImplementedError(
                "parquet writer does not support ARRAY/MAP columns yet")
        plain = {c: v[0] for c, v in data.items()}
        validity = {c: v[1] for c, v in data.items() if v[1] is not None}
        his = {c: v[2] for c, v in data.items() if v[2] is not None}
        dicts = {c: v[3] for c, v in data.items() if v[3] is not None}
        arrays, schema = _to_arrow_columns(plain, dict(zip(names, types)),
                                           dicts, validity, his)
        tbl = pa.Table.from_arrays(arrays, schema=schema)
        path = os.path.join(d, f"part-{part_id}.parquet")
        pq.write_table(tbl, path + ".tmp", row_group_size=1 << 20,
                       use_dictionary=True, compression="zstd")
        os.replace(path + ".tmp", path)
        return int(tbl.num_rows)

    def finish_scaled_create(self, name: str):
        """Commit: staging dir renames into place atomically."""
        os.replace(self.parts_dir(name, staging=True),
                   self.parts_dir(name))
        self._invalidate_table(name)

    def abort_scaled_create(self, name: str):
        import shutil

        shutil.rmtree(self.parts_dir(name, staging=True),
                      ignore_errors=True)

    def _table_exists(self, name: str) -> bool:
        return (os.path.exists(os.path.join(self.directory,
                                            f"{name}.parquet"))
                or os.path.isdir(self.parts_dir(name))
                or os.path.isdir(self.hive_dir(name))
                or os.path.isdir(self.buckets_dir(name)))

    def _part_files(self, name: str):
        d = self.parts_dir(name)
        if not os.path.isdir(d):
            return None
        return sorted(os.path.join(d, f) for f in os.listdir(d)
                      if f.endswith(".parquet"))

    def _scan_part_files(self, paths):
        """Union schema/row-groups/string-vocab over a list of parquet
        files (shared by the parts-directory and hive loaders).

        Schema drift across parts is REJECTED (every file must match the
        first file's arrow schema) instead of silently reading later files
        through the first schema. Per-file vocab is cached by
        (path, mtime), so an INSERT-triggered invalidation only scans the
        new part files, and string columns are read dictionary-encoded so
        the union walks unique values, not full columns."""
        schema = None
        str_cols: list = []
        num_rows = 0
        rgs = []  # (path, num_row_groups)
        vocab: Dict[str, set] = {}
        cache = self.__dict__.setdefault("_vocab_cache", {})
        for p in paths:
            f = pq.ParquetFile(p)
            if schema is None:
                schema = f.schema_arrow
                str_cols = [fl.name for fl in schema
                            if _arrow_to_sql(fl).is_string]
            elif not f.schema_arrow.equals(schema):
                raise ValueError(
                    f"schema drift in parts table: {p} has schema "
                    f"{f.schema_arrow} != first part's {schema}")
            num_rows += f.metadata.num_rows
            rgs.append((p, f.num_row_groups))
            if not str_cols:
                continue
            ckey = (p, os.stat(p).st_mtime_ns)
            fvocab = cache.get(ckey)
            if fvocab is None:
                fvocab = {c: set() for c in str_cols}
                fd = pq.ParquetFile(p, read_dictionary=str_cols)
                for rg in range(fd.num_row_groups):
                    t = fd.read_row_group(rg, columns=str_cols)
                    for c in str_cols:
                        for chunk in t.column(c).chunks:
                            if pa.types.is_dictionary(chunk.type):
                                fvocab[c].update(
                                    chunk.dictionary.to_pylist())
                            else:
                                fvocab[c].update(chunk.to_pylist())
                cache[ckey] = fvocab
            for c, vs in fvocab.items():
                vocab.setdefault(c, set()).update(vs)
        # evict superseded generations (same path, older mtime) and entries
        # whose file was deleted (compaction/table rewrite) — stale vocab
        # sets would otherwise leak for the connector's lifetime. Other
        # tables share this cache; their live files are untouched.
        scanned = set(paths)
        live_keys = {(p, os.stat(p).st_mtime_ns) for p in paths
                     if os.path.exists(p)}
        for k in list(cache):
            if (k[0] in scanned and k not in live_keys) \
                    or not os.path.exists(k[0]):
                del cache[k]
        return schema, num_rows, rgs, vocab

    @staticmethod
    def _cols_from_schema(schema, vocab):
        """ColumnInfo + global Dictionary list from a unioned schema."""
        cols, dicts = [], {}
        for field in schema:
            t = _arrow_to_sql(field)
            if t.is_string:
                d = Dictionary(np.array(sorted(
                    v for v in vocab.get(field.name, ()) if v is not None)))
                dicts[field.name] = d
                cols.append(ColumnInfo(field.name, t, d))
            else:
                cols.append(ColumnInfo(field.name, t, None))
        return cols, dicts

    def _load_parts(self, name: str, parts: list) -> _PqTable:
        """Part-directory table: (file, row group) pairs become the
        virtual row-group space; schema/dictionaries union over parts."""
        schema, num_rows, rgs, vocab = self._scan_part_files(parts)
        part_map = [(p, rg) for p, n_rg in rgs for rg in range(n_rg)]
        cols, dicts = self._cols_from_schema(schema, vocab)
        handle = TableHandle(self.name, name, cols, row_count=float(num_rows))
        d = self.parts_dir(name)
        st = os.stat(d)
        t = _PqTable(d, handle, dicts, num_rows, len(part_map),
                     version=(st.st_mtime_ns, len(parts)),
                     part_map=part_map)
        self._tables[name] = t
        return t

    def buckets_dir(self, name: str) -> str:
        return os.path.join(self.directory, f"{name}.buckets")

    def _load_buckets(self, name: str) -> _PqTable:
        """Bucketed table: bucket files in id order become the virtual
        row-group space, each vrg tagged with its bucket (splits carry it
        as the lifespan id). The handle exposes the bucketing spec so the
        fragmenter can plan colocated joins."""
        d = self.buckets_dir(name)
        with open(os.path.join(d, "_bucketing.json")) as f:
            spec = json.load(f)
        count = int(spec["count"])
        files = [os.path.join(d, f"b{b:05d}.parquet") for b in range(count)]
        schema, num_rows, rgs, vocab = self._scan_part_files(files)
        part_map, bucket_map = [], []
        for b, (p, n_rg) in enumerate(rgs):
            for rg in range(n_rg):
                part_map.append((p, rg))
                bucket_map.append(b)
        cols, dicts = self._cols_from_schema(schema, vocab)
        handle = TableHandle(self.name, name, cols,
                             row_count=float(num_rows),
                             bucketing=(tuple(spec["by"]), count))
        st = os.stat(d)
        t = _PqTable(d, handle, dicts, num_rows, len(part_map),
                     version=(st.st_mtime_ns, count),
                     part_map=part_map,
                     bucketing=(tuple(spec["by"]), count),
                     bucket_map=bucket_map)
        self._tables[name] = t
        return t

    # -- hive-style partitioned tables (reference: presto-hive partitions:
    # HiveTableProperties.PARTITIONED_BY_PROPERTY, HivePartitionManager
    # partition pruning, directory layout <table>/<col>=<value>/part-*) ----

    _HIVE_NULL = "__HIVE_DEFAULT_PARTITION__"

    def hive_dir(self, name: str, staging: bool = False) -> str:
        return os.path.join(self.directory,
                            f"{name}.hive.tmp" if staging else f"{name}.hive")

    @staticmethod
    def _pval_to_path(v) -> str:
        import urllib.parse

        if v is None:
            return ParquetConnector._HIVE_NULL
        if isinstance(v, bool):
            return "true" if v else "false"
        if isinstance(v, str):
            return urllib.parse.quote(v, safe="")
        return str(int(v))

    @staticmethod
    def _pval_from_path(s: str, t: Type):
        import urllib.parse

        if s == ParquetConnector._HIVE_NULL:
            return None
        if t is BOOLEAN:
            return s == "true"
        if t.is_string:
            return urllib.parse.unquote(s)
        return int(s)

    def _hive_files(self, name: str):
        """[(fpath, pvals_by_name)] for every part file, sorted; None when
        the table is not hive-partitioned."""
        import json

        root = self.hive_dir(name)
        meta_path = os.path.join(root, "_meta.json")
        if not os.path.isfile(meta_path):
            return None
        from presto_tpu.types import parse_type

        meta = json.load(open(meta_path))
        pcols = [(c, parse_type(ts)) for c, ts in meta["partitioned_by"]]
        out: list = []
        for dirpath, _dirs, files in sorted(os.walk(root)):
            pq_files = sorted(f for f in files if f.endswith(".parquet"))
            if not pq_files:
                continue
            rel = os.path.relpath(dirpath, root)
            comps = [] if rel == "." else rel.split(os.sep)
            if len(comps) != len(pcols):
                continue  # stray depth: not a partition leaf
            pvals = {}
            for comp, (c, t) in zip(comps, pcols):
                cname, _, raw = comp.partition("=")
                if cname != c:
                    raise ValueError(
                        f"malformed partition directory {rel!r} in {name}")
                pvals[c] = self._pval_from_path(raw, t)
            for f in pq_files:
                out.append((os.path.join(dirpath, f), pvals))
        return pcols, out, meta

    def _load_hive(self, name: str) -> _PqTable:
        """Partitioned table: partition values come from directory names,
        data columns from the files; partition columns append to the
        schema (hive convention: partition keys are the trailing
        columns)."""
        root = self.hive_dir(name)
        pcols, files, meta = self._hive_files(name)
        schema, num_rows, rgs, vocab = self._scan_part_files(
            [fp for fp, _ in files])
        pvals_by_file = dict(files)
        part_map, pvals_list = [], []
        for fp, n_rg in rgs:
            for rg in range(n_rg):
                part_map.append((fp, rg))
                pvals_list.append(tuple(pvals_by_file[fp][c]
                                        for c, _ in pcols))
        if schema is not None:
            cols, dicts = self._cols_from_schema(schema, vocab)
        else:
            # zero-row table: the data-column schema survives in _meta.json
            from presto_tpu.types import parse_type

            cols, dicts = [], {}
            pset = {c for c, _ in pcols}
            for c, ts in meta.get("columns", []):
                if c in pset:
                    continue
                t = parse_type(ts)
                if t.is_string:
                    d = Dictionary(np.array([], dtype=object))
                    dicts[c] = d
                    cols.append(ColumnInfo(c, t, d))
                else:
                    cols.append(ColumnInfo(c, t, None))
        from presto_tpu.connector import ColumnStats

        for i, (c, t) in enumerate(pcols):
            vals = sorted({pv[i] for pv in pvals_list if pv[i] is not None})
            if t.is_string:
                d = Dictionary(np.array(vals, dtype=object))
                dicts[c] = d
                cols.append(ColumnInfo(c, t, d,
                                       ColumnStats(ndv=float(len(vals)))))
            else:
                cols.append(ColumnInfo(c, t, None, ColumnStats(
                    ndv=float(len(vals)),
                    min_value=(float(vals[0]) if vals else None),
                    max_value=(float(vals[-1]) if vals else None))))
        handle = TableHandle(self.name, name, cols, row_count=float(num_rows))
        st = os.stat(root)
        t = _PqTable(root, handle, dicts, num_rows, len(part_map),
                     version=(st.st_mtime_ns, len(files)),
                     part_map=part_map,
                     hive={"pcols": pcols, "pvals": pvals_list})
        self._tables[name] = t
        return t

    def _hive_group_rows(self, pnames, data):
        """Group host rows by partition tuple: [(pvals_tuple, row_idx)]
        with engine-native values (strings decoded, None for NULL)."""
        combined = None
        reprs = []
        for c in pnames:
            vals, valid, hi, d = data[c]
            if hi is not None:
                raise ValueError(
                    f"partition column {c} has an unsupported wide type")
            is_bool = np.asarray(vals).dtype == np.bool_
            arr = np.asarray(vals).astype(np.int64)
            null_mark = (np.asarray(~np.asarray(valid))
                         if valid is not None else np.zeros(len(arr), bool))
            reprs.append((arr, null_mark, d, is_bool))
            # group code: 0 = the NULL partition, else 1 + value ordinal
            # (a separate null axis — a real value of -1 must not merge
            # with NULLs)
            _, inv = np.unique(arr, return_inverse=True)
            code = np.where(null_mark, 0, inv + 1)
            width = int(code.max()) + 1 if len(code) else 1
            combined = (code if combined is None
                        else combined * width + code)
        u_comb, inv = np.unique(combined, return_inverse=True)
        groups = []
        for gi in range(len(u_comb)):
            idx = np.nonzero(inv == gi)[0]
            row0 = int(idx[0])
            pvals = []
            for arr, null_mark, d, is_bool in reprs:
                if null_mark[row0]:
                    pvals.append(None)
                elif d is not None:
                    pvals.append(str(d.decode(arr[row0:row0 + 1])[0]))
                elif is_bool:
                    pvals.append(bool(arr[row0]))
                else:
                    pvals.append(int(arr[row0]))
            groups.append((tuple(pvals), idx))
        return groups

    def _hive_validate(self, pnames, names, types):
        tmap = dict(zip(names, types))
        for c in pnames:
            if c not in tmap:
                raise ValueError(f"partition column {c} not in table schema")
            t = tmap[c]
            ok = (t.is_string or t is BOOLEAN or t is DATE
                  or (not t.is_string and t.dtype in ("int64", "int32")
                      and not isinstance(t, DecimalType)))
            if not ok:
                raise ValueError(
                    f"partition column {c} must be integer, varchar, "
                    f"boolean or date, got {t}")
        if list(names[-len(pnames):]) != list(pnames):
            raise ValueError(
                "partitioned_by columns must be the trailing table "
                "columns (hive convention)")

    def _hive_write_groups(self, root, pnames, names, types, data, groups,
                           file_tag: str):
        """Write one parquet file per partition group under
        root/<c>=<v>/..., data columns only."""
        dnames = [c for c in names if c not in set(pnames)]
        tmap = dict(zip(names, types))
        rows = 0
        for pvals, idx in groups:
            comps = [f"{c}={self._pval_to_path(v)}"
                     for c, v in zip(pnames, pvals)]
            d = os.path.join(root, *comps)
            os.makedirs(d, exist_ok=True)
            plain = {c: np.asarray(data[c][0])[idx] for c in dnames}
            validity = {c: np.asarray(data[c][1])[idx]
                        for c in dnames if data[c][1] is not None}
            his = {c: np.asarray(data[c][2])[idx]
                   for c in dnames if data[c][2] is not None}
            dicts = {c: data[c][3] for c in dnames if data[c][3] is not None}
            arrays, schema = _to_arrow_columns(
                plain, {c: tmap[c] for c in dnames}, dicts, validity, his)
            tbl = pa.Table.from_arrays(arrays, schema=schema)
            pq.write_table(tbl, os.path.join(d, f"part-{file_tag}.parquet"),
                           row_group_size=1 << 20, use_dictionary=True,
                           compression="zstd")
            rows += int(tbl.num_rows)
        return rows

    def _hive_create(self, name: str, batches, pnames,
                     if_not_exists: bool = False) -> int:
        import json
        import shutil

        from presto_tpu.catalog.memory import _batches_to_host
        from presto_tpu.types import ArrayType, MapType

        if self._table_exists(name):
            if if_not_exists:
                return 0
            raise ValueError(f"table already exists: {name}")
        names, types, data = _batches_to_host(batches)
        if any(isinstance(t, (ArrayType, MapType)) for t in types):
            raise NotImplementedError(
                "parquet writer does not support ARRAY/MAP columns yet")
        self._hive_validate(pnames, names, types)
        staging = self.hive_dir(name, staging=True)
        shutil.rmtree(staging, ignore_errors=True)
        os.makedirs(staging)
        groups = self._hive_group_rows(pnames, data)
        rows = self._hive_write_groups(staging, pnames, names, types, data,
                                       groups, "0")
        tmap = dict(zip(names, types))
        with open(os.path.join(staging, "_meta.json"), "w") as f:
            json.dump({"partitioned_by": [[c, tmap[c].name] for c in pnames],
                       # full schema: survives a zero-row CTAS (no files)
                       "columns": [[c, tmap[c].name] for c in names]}, f)
        os.rename(staging, self.hive_dir(name))
        self._invalidate_table(name)
        return rows

    def _hive_insert(self, name: str, batches) -> int:
        import uuid

        from presto_tpu.catalog.memory import _batches_to_host

        t = self._load(name)
        pnames = [c for c, _ in t.hive["pcols"]]
        names, types, data = _batches_to_host(batches)
        existing = [(c.name, c.type.name) for c in t.handle.columns]
        if [(c, tt.name) for c, tt in zip(names, types)] != existing:
            raise ValueError(
                f"INSERT schema mismatch for partitioned table {name}: "
                f"{[(c, tt.name) for c, tt in zip(names, types)]} vs "
                f"{existing}")
        groups = self._hive_group_rows(pnames, data)
        rows = self._hive_write_groups(self.hive_dir(name), pnames, names,
                                       types, data, groups, uuid.uuid4().hex)
        os.utime(self.hive_dir(name))  # bust _check_fresh versions
        self._invalidate_table(name)
        return rows

    def _load(self, name: str) -> _PqTable:
        self._check_fresh(name)
        if name in self._tables:
            return self._tables[name]
        path = os.path.join(self.directory, f"{name}.parquet")
        if not os.path.exists(path):
            if os.path.isdir(self.hive_dir(name)):
                return self._load_hive(name)
            if os.path.isdir(self.buckets_dir(name)):
                return self._load_buckets(name)
            parts = self._part_files(name)
            if parts:
                return self._load_parts(name, parts)
            raise KeyError(f"table not found: {name}")
        f = pq.ParquetFile(path)
        schema = f.schema_arrow
        cols = []
        dicts: Dict[str, Dictionary] = {}
        nested: Dict[str, tuple] = {}  # dotted name -> (parent, leaf)
        name_to_idx = {schema.field(i).name: i for i in range(len(schema.names))}
        for field in schema:
            if pa.types.is_struct(field.type):
                # ROW columns flatten to dotted leaf columns — the
                # spi/type/RowType surface over parquet structs (analysis
                # resolves r.f to the flattened name; see Scope.resolve)
                for sub in field.type:
                    leaf_name = f"{field.name}.{sub.name}"
                    st = _arrow_to_sql(sub)
                    nested[leaf_name] = (field.name, sub.name)
                    if st.is_string:
                        vocab = set()
                        for rg in range(f.num_row_groups):
                            col = f.read_row_group(
                                rg, columns=[field.name]).column(0)
                            vals = col.combine_chunks().field(sub.name)
                            vocab.update(vals.to_pylist())
                        d = Dictionary(np.array(
                            sorted(v for v in vocab if v is not None)))
                        dicts[leaf_name] = d
                        cols.append(ColumnInfo(leaf_name, st, d))
                    else:
                        cols.append(ColumnInfo(leaf_name, st, None))
                continue
            t = _arrow_to_sql(field)
            if t.is_string:
                # global per-column dictionary: union of per-row-group
                # dictionaries, built once at open (order-preserving)
                vocab = set()
                for rg in range(f.num_row_groups):
                    col = f.read_row_group(rg, columns=[field.name]).column(0)
                    for chunk in col.chunks:
                        if pa.types.is_dictionary(chunk.type):
                            vocab.update(chunk.dictionary.to_pylist())
                        else:
                            vocab.update(chunk.to_pylist())
                d = Dictionary(np.array(sorted(v for v in vocab if v is not None)))
                dicts[field.name] = d
                cols.append(ColumnInfo(
                    field.name, t, d,
                    _footer_stats(f, name_to_idx[field.name], t,
                                  ndv=float(len(d)))))
            else:
                cols.append(ColumnInfo(
                    field.name, t, None,
                    _footer_stats(f, name_to_idx[field.name], t)))
        handle = TableHandle(self.name, name, cols, row_count=float(f.metadata.num_rows))
        t = _PqTable(path, handle, dicts, f.metadata.num_rows, f.num_row_groups,
                     version=self._file_version(path), nested=nested)
        self._tables[name] = t
        return t

    def get_table(self, name: str) -> TableHandle:
        return self._load(name).handle

    def splits(self, handle: TableHandle, desired: int = 1) -> List[Split]:
        """Scan-parallelism units: row groups (like ORC stripes), subdivided
        when the engine wants finer batches than a row group. Split.part is
        (row_group, sub_index, sub_count)."""
        t = self._load(handle.name)
        target = max(1, -(-t.num_rows // max(desired, 1)))
        out = []
        if t.part_map is not None:
            meta_cache: Dict[str, object] = {}
            for vrg, (fpath, rg) in enumerate(t.part_map):
                md = meta_cache.get(fpath)
                if md is None:
                    md = meta_cache[fpath] = pq.ParquetFile(fpath).metadata
                rg_rows = md.row_group(rg).num_rows
                subs = max(1, -(-rg_rows // target))
                bucket = (t.bucket_map[vrg] if t.bucket_map is not None
                          else None)
                for s in range(subs):
                    out.append(Split(handle.name, (vrg, s, subs),
                                     t.num_row_groups, bucket=bucket))
            return out
        f = pq.ParquetFile(t.path)
        for rg in range(t.num_row_groups):
            rg_rows = f.metadata.row_group(rg).num_rows
            subs = max(1, -(-rg_rows // target))
            for s in range(subs):
                out.append(Split(handle.name, (rg, s, subs), t.num_row_groups))
        return out

    def prune_splits(self, handle: TableHandle, splits: Sequence[Split],
                     min_max: Dict[str, Tuple[object, object]]) -> List[Split]:
        """Row-group pruning with column min/max constraints (the coarse
        TupleDomain pushdown of the selective reader)."""
        t = self._load(handle.name)
        files: Dict[str, object] = {}

        def rg_meta(rg_idx: int):
            if t.part_map is not None:
                fpath, rg = t.part_map[rg_idx]
            else:
                fpath, rg = t.path, rg_idx
            f = files.get(fpath)
            if f is None:
                f = files[fpath] = pq.ParquetFile(fpath)
            return f, f.metadata.row_group(rg)

        f0, _ = rg_meta(0) if (t.num_row_groups or t.part_map) else (None, None)
        if f0 is None:
            return list(splits)
        keep = []
        name_to_idx = {f0.schema_arrow.field(i).name: i
                       for i in range(len(f0.schema_arrow.names))}
        pidx = ({c: i for i, (c, _) in enumerate(t.hive["pcols"])}
                if t.hive is not None else {})

        def partition_pruned(rg_idx) -> bool:
            """Hive partition pruning: directory values against the
            constraint, zero file IO (HivePartitionManager analog).
            Constraint values arrive in the storage domain (dates as
            datetime.date) — convert the stored engine value to match."""
            import datetime

            pvals = t.hive["pvals"][rg_idx]
            for col, (lo, hi) in min_max.items():
                i = pidx.get(col)
                if i is None:
                    continue
                v = pvals[i]
                if v is None:
                    # NULL partition never matches a range constraint
                    return lo is not None or hi is not None
                if t.hive["pcols"][i][1] is DATE:
                    v = datetime.date.fromordinal(719163 + int(v))
                if lo is not None and v < lo:
                    return True
                if hi is not None and v > hi:
                    return True
            return False

        for s in splits:
            rg_idx = s.part[0] if isinstance(s.part, tuple) else s.part
            if pidx and partition_pruned(rg_idx):
                continue
            _, rg = rg_meta(rg_idx)
            ok = True
            for col, (lo, hi) in min_max.items():
                if col not in name_to_idx:
                    continue
                st = rg.column(name_to_idx[col]).statistics
                if st is None or not st.has_min_max:
                    continue
                try:
                    if lo is not None and st.max is not None and st.max < lo:
                        ok = False
                        break
                    if hi is not None and st.min is not None and st.min > hi:
                        ok = False
                        break
                except TypeError:
                    # constraint/statistic domain mismatch (e.g. a string
                    # bound against numeric stats) — keep the split
                    continue
            if ok:
                keep.append(s)
        return keep

    def split_stats(self, handle: TableHandle, split: Split):
        """Row-group statistics as a storage-domain SplitStats (the
        generic face of the footer stats `prune_splits` reads natively —
        used by tests and cross-connector tooling)."""
        from presto_tpu.scan.pruning import SplitStats

        t = self._load(handle.name)
        rg_idx = split.part[0] if isinstance(split.part, tuple) else split.part
        if t.part_map is not None:
            fpath, rg = t.part_map[rg_idx]
        elif t.num_row_groups:
            fpath, rg = t.path, rg_idx
        else:
            return None
        md = pq.ParquetFile(fpath).metadata.row_group(rg)
        cols = {}
        for i in range(md.num_columns):
            cmeta = md.column(i)
            st = cmeta.statistics
            if st is None:
                continue
            mn, mx = ((st.min, st.max) if st.has_min_max else (None, None))
            cols[cmeta.path_in_schema] = (mn, mx, st.null_count)
        return SplitStats(md.num_rows, cols)

    def read_split_selective(self, split: Split, columns: Sequence[str],
                             filters, capacity: Optional[int] = None,
                             adaptive=None, counters=None) -> Batch:
        """Predicate-during-decode read: filter columns decode first, the
        cascade shrinks the selection vector, payload columns decode (and
        upload) only for survivors. Bypasses the device split cache —
        output depends on the filter set, like read_split_constrained."""
        from presto_tpu.scan.selective import selective_read

        self._check_fresh(split.table)
        t = self._load(split.table)
        if isinstance(split.part, tuple):
            rg, sub, sub_count = split.part
        else:
            rg, sub, sub_count = split.part, 0, 1

        def _decode(cols):
            return self._decoded_columns(t, rg, sub, sub_count, cols)

        return selective_read(_decode, t.handle, columns, filters,
                              capacity=capacity, dicts=t.dicts,
                              adaptive=adaptive, counters=counters)

    # -- write path (reference: HivePageSink writing ORC/parquet files;
    # CTAS = CreateTableTask + TableWriter chain) -------------------------

    def _invalidate_table(self, name: str):
        self._tables.pop(name, None)
        self.invalidate_cache(name)
        with self._host_cache_lock:
            # t.path is the single file OR the parts/hive directory
            paths = {os.path.join(self.directory, f"{name}.parquet"),
                     self.parts_dir(name), self.hive_dir(name)}
            for k in [k for k in self._host_cache if k[0] in paths]:
                _, nbytes = self._host_cache.pop(k)
                self._host_cache_used -= nbytes

    def create_table_from(self, name: str, batches, if_not_exists: bool = False,
                          properties: Optional[dict] = None) -> int:
        from presto_tpu.catalog.memory import _batches_to_host

        if properties:
            props = dict(properties)
            pby = props.pop("partitioned_by", None)
            if props:
                raise ValueError(
                    f"unknown table properties: {sorted(props)}")
            if pby:
                if isinstance(pby, str):
                    pby = [pby]
                return self._hive_create(name, batches, list(pby),
                                         if_not_exists=if_not_exists)
        path = os.path.join(self.directory, f"{name}.parquet")
        if os.path.exists(path):
            if if_not_exists:
                return 0
            raise ValueError(f"table already exists: {name}")
        names, types, data = _batches_to_host(batches)
        from presto_tpu.types import ArrayType, MapType

        if any(isinstance(t, (ArrayType, MapType)) for t in types):
            raise NotImplementedError(
                "parquet writer does not support ARRAY/MAP columns yet; "
                "CTAS structural results into the memory connector")
        plain = {c: v[0] for c, v in data.items()}
        validity = {c: v[1] for c, v in data.items() if v[1] is not None}
        his = {c: v[2] for c, v in data.items() if v[2] is not None}
        dicts = {c: v[3] for c, v in data.items() if v[3] is not None}
        arrays, schema = _to_arrow_columns(plain, dict(zip(names, types)),
                                           dicts, validity, his)
        tbl = pa.Table.from_arrays(arrays, schema=schema)
        try:
            pq.write_table(tbl, path + ".tmp", row_group_size=1 << 20,
                           use_dictionary=True, compression="zstd")
            os.replace(path + ".tmp", path)
        except BaseException:
            # all-or-nothing: a failed write must not leave staging junk
            try:
                os.remove(path + ".tmp")
            except OSError:
                pass
            raise
        self._invalidate_table(name)
        return int(tbl.num_rows)

    def insert_into(self, name: str, batches) -> int:
        """Append. Part-directory tables append a NEW part (no rewrite);
        single-file tables rewrite existing rows + new rows into a fresh
        file (parquet files are immutable)."""
        path = os.path.join(self.directory, f"{name}.parquet")
        if not os.path.exists(path):
            if os.path.isdir(self.hive_dir(name)):
                return self._hive_insert(name, batches)
            if os.path.isdir(self.parts_dir(name)):
                import uuid

                t = self._load(name)
                # schema check against the existing handle
                from presto_tpu.catalog.memory import _batches_to_host

                names, types, _ = _batches_to_host(batches)
                existing = [c.type.name for c in t.handle.columns]
                if [tt.name for tt in types] != existing:
                    raise ValueError(
                        f"INSERT schema mismatch: {[str(t) for t in types]}"
                        f" vs {existing}")
                n = self.write_part(name, f"ins-{uuid.uuid4().hex[:8]}",
                                    batches, staging=False)
                self._invalidate_table(name)
                return n
            raise KeyError(f"table not found: {name}")
        from presto_tpu.catalog.memory import _batches_to_host

        names, types, data = _batches_to_host(batches)
        from presto_tpu.types import ArrayType, MapType

        if any(isinstance(t, (ArrayType, MapType)) for t in types):
            raise NotImplementedError(
                "parquet writer does not support ARRAY/MAP columns yet")
        existing = pq.read_table(path)
        target_names = list(existing.schema.names)
        if len(target_names) != len(names):
            raise ValueError(
                f"INSERT arity mismatch: {len(names)} columns vs "
                f"{len(target_names)} in {name}")
        # positional matching (INSERT ... SELECT semantics): i-th source
        # column feeds the i-th target column, logical types must agree
        for field, t in zip(existing.schema, types):
            et = _arrow_to_sql(field)
            if et.name != t.name:
                raise ValueError(
                    f"INSERT column {field.name} type mismatch: "
                    f"{t} vs {et}")
        plain, validity, his, dicts = {}, {}, {}, {}
        for src, tgt in zip(names, target_names):
            vals, valid, hi, d = data[src]
            plain[tgt] = vals
            if valid is not None:
                validity[tgt] = valid
            if hi is not None:
                his[tgt] = hi
            if d is not None:
                dicts[tgt] = d
        arrays, schema = _to_arrow_columns(plain, dict(zip(target_names, types)),
                                           dicts, validity, his)
        new_tbl = pa.Table.from_arrays(arrays, schema=schema)
        # unify schemas (dictionary value types etc.) then concatenate
        new_tbl = new_tbl.cast(existing.schema)
        merged = pa.concat_tables([existing, new_tbl])
        pq.write_table(merged, path + ".tmp", row_group_size=1 << 20,
                       use_dictionary=True, compression="zstd")
        os.replace(path + ".tmp", path)
        self._invalidate_table(name)
        return int(new_tbl.num_rows)

    def drop_table(self, name: str, if_exists: bool = False) -> None:
        path = os.path.join(self.directory, f"{name}.parquet")
        if not os.path.exists(path):
            for d in (self.parts_dir(name), self.hive_dir(name)):
                if os.path.isdir(d):
                    import shutil

                    shutil.rmtree(d)
                    self._invalidate_table(name)
                    return
            if if_exists:
                return
            raise KeyError(f"table not found: {name}")
        os.remove(path)
        self._invalidate_table(name)

    def create_empty(self, name: str, cols, if_not_exists: bool = False):
        """CREATE TABLE name (schema): a zero-row file carrying the
        schema (decimal SQL types ride field metadata as usual)."""
        path = os.path.join(self.directory, f"{name}.parquet")
        if os.path.exists(path):
            if if_not_exists:
                return
            raise ValueError(f"table already exists: {name}")
        data = {c: np.zeros(0, dtype=t.dtype) for c, t in cols}
        arrays, schema = _to_arrow_columns(data, dict(cols), {})
        pq.write_table(pa.Table.from_arrays(arrays, schema=schema),
                       path + ".tmp")
        os.replace(path + ".tmp", path)
        self._invalidate_table(name)

    def truncate_table(self, name: str):
        t = self._load(name)
        if t.hive is not None:
            raise NotImplementedError(
                "TRUNCATE on hive-partitioned tables is not supported")
        cols = [(c.name, c.type) for c in t.handle.columns]
        self.drop_table(name)
        self.create_empty(name, cols)

    def replace_table_from(self, name: str, batches) -> int:
        t = self._load(name)  # existence check
        if t.hive is not None:
            raise NotImplementedError(
                "DELETE rewrite on hive-partitioned tables is not supported")
        self.drop_table(name)
        return self.create_table_from(name, batches)

    def read_split(self, split: Split, columns: Sequence[str],
                   capacity: Optional[int] = None) -> Batch:
        self._check_fresh(split.table)
        return super().read_split(split, columns, capacity)

    def _decoded_columns(self, t: _PqTable, rg: int, sub: int, sub_count: int,
                         columns: Sequence[str]):
        """Decode (or fetch from the host LRU) one split's engine-native
        numpy columns: {name: (values, validity_or_None)} plus row count."""
        key = (t.path, rg, sub, sub_count, tuple(columns))
        with self._host_cache_lock:
            hit = self._host_cache.get(key)
            if hit is not None:
                self._host_cache.move_to_end(key)
                return hit[0]
        vrg = rg
        if t.part_map is not None:
            # part-directory table: the virtual row-group index resolves
            # to (part file, row group within it)
            fpath, rg = t.part_map[rg]
            f = pq.ParquetFile(fpath)
        else:
            f = pq.ParquetFile(t.path)
        pset = ({c for c, _ in t.hive["pcols"]} if t.hive is not None
                else set())
        plain = [c for c in columns if c not in t.nested and c not in pset]
        parents = sorted({t.nested[c][0] for c in columns if c in t.nested})
        tbl = f.read_row_group(rg, columns=plain + parents)
        if t.nested:
            # flatten requested ROW leaves out of their struct columns
            arrays, fields = [], []
            for c in columns:
                if c in t.nested:
                    parent, leaf = t.nested[c]
                    sc = tbl.column(parent)
                    arr = (sc.combine_chunks() if isinstance(
                        sc, pa.ChunkedArray) else sc)
                    if isinstance(arr, pa.ChunkedArray):
                        arr = arr.combine_chunks()
                    arrays.append(arr.field(leaf))
                    fields.append(pa.field(c, arrays[-1].type))
                else:
                    arrays.append(tbl.column(c))
                    fields.append(pa.field(c, tbl.column(c).type))
            tbl = pa.Table.from_arrays(arrays, schema=pa.schema(fields))
        rg_rows = f.metadata.row_group(rg).num_rows
        if sub_count > 1:
            per = -(-rg_rows // sub_count)
            tbl = tbl.slice(sub * per, per)
            n = max(0, min(per, rg_rows - sub * per))
        else:
            n = rg_rows
        out = {}
        nbytes = 0
        for name in columns:
            st = t.handle.column(name).type
            if name in pset:
                arr, valid, hi = self._hive_constant(t, vrg, name, st, n)
            else:
                arr, valid, hi = _decode_column(tbl.column(name), st,
                                                t.dicts.get(name))
            arr = np.ascontiguousarray(np.asarray(arr))
            out[name] = (arr, valid, hi)
            nbytes += arr.nbytes + (valid.nbytes if valid is not None else 0)
            nbytes += hi.nbytes if hi is not None else 0
        result = (out, n)
        if nbytes <= self.host_cache_bytes:
            with self._host_cache_lock:
                if key not in self._host_cache:
                    self._host_cache[key] = (result, nbytes)
                    self._host_cache_used += nbytes
                    while self._host_cache_used > self.host_cache_bytes:
                        _, (_, freed) = self._host_cache.popitem(last=False)
                        self._host_cache_used -= freed
        return result

    def _hive_constant(self, t: _PqTable, vrg: int, name: str, st: Type,
                       n: int):
        """Partition column for one split: a constant engine-native array
        from the directory value (HivePartitionKey → constant block)."""
        i = next(j for j, (c, _) in enumerate(t.hive["pcols"]) if c == name)
        v = t.hive["pvals"][vrg][i]
        if v is None:
            return (np.zeros(n, dtype=st.dtype), np.zeros(n, bool), None)
        if st.is_string:
            code = t.dicts[name].code_of(v)
            return (np.full(n, code, dtype=st.dtype), None, None)
        return (np.full(n, v, dtype=st.dtype), None, None)

    def _read_split_uncached(self, split: Split, columns: Sequence[str],
                             capacity: Optional[int] = None) -> Batch:
        t = self._load(split.table)
        if isinstance(split.part, tuple):
            rg, sub, sub_count = split.part
        else:
            rg, sub, sub_count = split.part, 0, 1
        decoded, n = self._decoded_columns(t, rg, sub, sub_count, columns)
        cap = capacity or round_up_capacity(max(n, 1))
        import jax.numpy as jnp

        from presto_tpu.batch import Column

        names, typelist, cols = [], [], []
        live = np.zeros(cap, bool)
        live[:n] = True
        for name in columns:
            st = t.handle.column(name).type
            arr, valid, hi = decoded[name]
            buf = np.zeros(cap, dtype=st.dtype)
            buf[:n] = arr
            vcol = None
            if valid is not None:
                vb = np.zeros(cap, bool)
                vb[:n] = valid
                vcol = jnp.asarray(vb)
            hcol = None
            if hi is not None:
                hb = np.zeros(cap, np.int64)
                hb[:n] = hi
                hcol = jnp.asarray(hb)
            names.append(name)
            typelist.append(st)
            cols.append(Column(jnp.asarray(buf), vcol, hcol))
        return Batch(
            names, typelist, cols, jnp.asarray(live),
            {c: t.dicts[c] for c in columns if c in t.dicts},
        )


def _decode_column(col: pa.ChunkedArray, t: Type, d: Optional[Dictionary]):
    """Arrow column → engine-native numpy (codes / unscaled / day ints)."""
    combined = col.combine_chunks() if col.num_chunks > 1 else (
        col.chunk(0) if col.num_chunks == 1 else pa.array([], col.type)
    )
    valid = None
    if combined.null_count:
        valid = np.asarray(combined.is_valid())
    if t.is_string:
        if pa.types.is_dictionary(combined.type):
            # remap this row group's dictionary codes into the table-global
            # dictionary (pure integer gather — no string materialization)
            local_vocab = np.asarray(combined.dictionary.to_pylist(), dtype=object)
            remap = np.searchsorted(d.values, local_vocab.astype(str))
            idx = combined.indices.to_numpy(zero_copy_only=False)
            idx = np.where(idx < 0, 0, idx)
            arr = remap[idx].astype(np.int32)
        else:
            strs = np.asarray(combined.to_pylist(), dtype=object)
            arr = np.array([d.code_of(s) if s is not None else -1 for s in strs], np.int32)
        if valid is not None:
            arr = np.where(valid, arr, -1)
        return arr, valid, None
    if isinstance(t, DecimalType):
        if pa.types.is_decimal(combined.type):
            if t.is_long:
                # int128 unscaled values split into (hi, lo) limbs —
                # host-side python ints, exact (CTAS-of-sums scale data)
                import decimal as _dec

                pyvals = combined.to_pylist()
                lo = np.zeros(len(pyvals), np.int64)
                hi = np.zeros(len(pyvals), np.int64)
                with _dec.localcontext() as _ctx:
                    _ctx.prec = 50
                    for i, v in enumerate(pyvals):
                        if v is None:
                            continue
                        u = int(v.scaleb(t.scale))
                        if not (-(1 << 94) <= u < (1 << 94)):
                            raise ValueError(
                                f"decimal value {v} exceeds the engine's "
                                "two-limb (hi:int64, lo:32-bit) range")
                        lo[i] = u & 0xFFFFFFFF
                        hi[i] = u >> 32
                return (lo, valid, hi)
            arr = combined.cast(pa.decimal128(38, t.scale)).cast(pa.int64(), safe=False)
        else:
            arr = combined  # unscaled int64 storage
        return arr.to_numpy(zero_copy_only=False), valid, None
    if t is DATE:
        return combined.cast(pa.int32()).to_numpy(zero_copy_only=False), valid, None
    return combined.to_numpy(zero_copy_only=False), valid, None


def export_tpch(directory: str, sf: float = 1.0):
    """Materialize the TPC-H dataset to parquet (the dbgen→warehouse path)."""
    from presto_tpu.catalog.tpch import TpchConnector

    os.makedirs(directory, exist_ok=True)
    conn = TpchConnector(sf)
    for tname in conn.table_names():
        conn._ensure(tname)
        mt = conn.tables[tname]
        write_table(
            os.path.join(directory, f"{tname}.parquet"),
            mt.arrays,
            mt.types,
            mt.dicts,
        )


def _to_arrow_columns(data, types, dicts, validity=None, his=None):
    """Engine-native columns → arrow arrays. `validity` maps column name →
    bool mask (False = SQL NULL); `his` maps name → long-decimal hi limbs
    (written as arrow decimal128(38, s) — the only physical type that
    preserves int128 exactness)."""
    arrays, fields = [], []
    for name, arr in data.items():
        t = types[name]
        valid = (validity or {}).get(name)
        mask = None if valid is None else ~np.asarray(valid)
        hi = (his or {}).get(name)
        meta = None
        if isinstance(t, DecimalType) and (hi is not None or t.is_long):
            import decimal as _dec

            lo = np.asarray(arr).astype(object)
            h = (np.zeros(len(lo), np.int64) if hi is None
                 else np.asarray(hi)).astype(object)
            with _dec.localcontext() as _ctx:
                _ctx.prec = 50  # int128 values reach 39 digits; never round
                vals = [
                    None if (mask is not None and mask[i])
                    else _dec.Decimal((int(h[i]) << 32) + int(lo[i])).scaleb(-t.scale)
                    for i in range(len(lo))
                ]
            at = pa.decimal128(38, t.scale)
            a = pa.array(vals, at)
            arrays.append(a)
            fields.append(pa.field(name, at))
            continue
        at = _sql_to_arrow(t)
        if t.is_string:
            d = dicts.get(name)
            if d is None:
                from presto_tpu.dictionary import Dictionary as _Dict

                d = _Dict(np.array([], dtype=object))  # empty/all-NULL column
            codes = np.asarray(arr).astype(np.int32)
            if mask is not None:
                # arrow dictionary arrays null via the index mask
                idx = pa.array(np.where(mask, 0, codes), pa.int32(), mask=mask)
            else:
                idx = pa.array(codes, pa.int32())
            vocab = pa.array([str(v) for v in d.values], pa.string())
            a = pa.DictionaryArray.from_arrays(idx, vocab)
        elif isinstance(t, DecimalType):
            a = pa.array(np.asarray(arr).astype(np.int64), pa.int64(), mask=mask)
            meta = {_DECIMAL_META: f"{t.precision},{t.scale}".encode()}
        elif t is DATE:
            a = pa.array(np.asarray(arr).astype(np.int32), pa.int32(),
                         mask=mask).cast(pa.date32())
        else:
            a = pa.array(np.asarray(arr), at, mask=mask)
        arrays.append(a)
        fields.append(pa.field(name, at, metadata=meta))
    return arrays, pa.schema(fields)


def export_tpcds_chunked(directory: str, sf: float,
                         rows_per_chunk: int = 30_000_000,
                         row_group_rows: int = 1 << 20,
                         log=None):
    """Stream-generate TPC-DS to parquet with bounded memory (dimensions
    whole, store_sales/store_returns chunked — see export_tpch_chunked)."""
    from presto_tpu.catalog.tpcds import TpcdsConnector, TpcdsGenerator, _D72

    os.makedirs(directory, exist_ok=True)
    conn = TpcdsConnector(sf)
    gen = TpcdsGenerator(sf)
    dims = [t for t in conn.table_names()
            if t not in ("store_sales", "store_returns")]
    for tname in dims:
        path = os.path.join(directory, f"{tname}.parquet")
        if os.path.exists(path):
            continue
        conn._ensure(tname)
        mt = conn.tables[tname]
        write_table(path + ".tmp", mt.arrays, mt.types, mt.dicts,
                    row_group_rows=row_group_rows)
        os.replace(path + ".tmp", path)  # atomic: no truncated reuse
        if log:
            log(f"wrote {tname} ({mt.num_rows} rows)")
        del conn.tables[tname]

    s_path = os.path.join(directory, "store_sales.parquet")
    r_path = os.path.join(directory, "store_returns.parquet")
    if os.path.exists(s_path) and os.path.exists(r_path):
        return

    def types_fn(table, data):
        from presto_tpu.types import BIGINT, DATE as _DATE, VARCHAR

        out = {}
        for c, v in data.items():
            if isinstance(v, tuple) and len(v) == 2 and v[0] == "raw72":
                out[c] = _D72
            elif isinstance(v, tuple):
                out[c] = VARCHAR
            elif isinstance(v, np.ndarray) and v.dtype == object:
                out[c] = VARCHAR
            else:
                out[c] = BIGINT
        return out

    def unwrap(data):
        # ("raw72", arr) markers carry plain unscaled arrays for the writer
        return {c: (v[1] if isinstance(v, tuple) and len(v) == 2
                    and v[0] == "raw72" else v)
                for c, v in data.items()}

    n = gen.n_store_sales
    chunk = min(rows_per_chunk, n)
    s_writer = r_writer = None
    done = False
    try:
        for start_row in range(0, n, chunk):
            cnt = min(chunk, n - start_row)
            sales, returns = gen.store_sales_chunk(start_row, cnt)
            for (path, raw, is_sales) in ((s_path, sales, True),
                                          (r_path, returns, False)):
                types = types_fn("x", raw)
                data = unwrap(raw)
                arrays, schema = _to_arrow_columns(data, types, {})
                tbl = pa.Table.from_arrays(arrays, schema=schema)
                if is_sales:
                    if s_writer is None:
                        s_writer = pq.ParquetWriter(path + ".tmp", schema,
                                                    compression="zstd")
                    s_writer.write_table(tbl, row_group_size=row_group_rows)
                else:
                    if r_writer is None:
                        r_writer = pq.ParquetWriter(path + ".tmp", schema,
                                                    compression="zstd")
                    r_writer.write_table(tbl, row_group_size=row_group_rows)
            if log:
                log(f"store_sales chunk {start_row}..{start_row + cnt} of {n}")
        done = True
    finally:
        if s_writer is not None:
            s_writer.close()
        if r_writer is not None:
            r_writer.close()
        if done and s_writer is not None:
            # rename only after BOTH writers closed cleanly — an
            # interrupted export leaves .tmp files, never a silently
            # truncated dataset future rounds would reuse
            os.replace(s_path + ".tmp", s_path)
            os.replace(r_path + ".tmp", r_path)


def export_tpch_chunked(directory: str, sf: float,
                        orders_per_chunk: int = 7_500_000,
                        row_group_rows: int = 1 << 20,
                        log=None):
    """Stream-generate TPC-H to parquet with bounded memory.

    Small tables materialize whole; orders/lineitem generate in
    `orders_per_chunk` chunks appended as row groups (the dbgen -C/-S
    chunking analog), so SF100 (600M lineitems) exports without ever
    holding the table in RAM. Skips tables whose files already exist
    (re-runs are incremental)."""
    from presto_tpu.catalog.tpch import TpchConnector, TpchGenerator

    os.makedirs(directory, exist_ok=True)
    conn = TpchConnector(sf)
    gen = TpchGenerator(sf)
    for tname in ("region", "nation", "supplier", "customer", "part", "partsupp"):
        path = os.path.join(directory, f"{tname}.parquet")
        if os.path.exists(path):
            continue
        conn._ensure(tname)
        mt = conn.tables[tname]
        write_table(path + ".tmp", mt.arrays, mt.types, mt.dicts,
                    row_group_rows=row_group_rows)
        os.replace(path + ".tmp", path)  # atomic: no truncated reuse
        if log:
            log(f"wrote {tname} ({mt.num_rows} rows)")
        del conn.tables[tname]

    o_path = os.path.join(directory, "orders.parquet")
    l_path = os.path.join(directory, "lineitem.parquet")
    if os.path.exists(o_path) and os.path.exists(l_path):
        return
    n_orders = gen.n_orders
    chunk = min(orders_per_chunk, n_orders)
    o_writer = l_writer = None
    done = False
    try:
        for start in range(0, n_orders, chunk):
            cnt = min(chunk, n_orders - start)
            orders, lineitem = gen.orders_lineitem_chunk(start, cnt)
            from presto_tpu.catalog.tpch import _column_types
            for (table, data) in (("orders", orders), ("lineitem", lineitem)):
                plain, dicts = {}, {}
                types = _column_types(table, data)
                for cname, v in data.items():
                    if isinstance(v, tuple):
                        dicts[cname] = v[0]
                        plain[cname] = v[1]
                    else:
                        plain[cname] = v
                arrays, schema = _to_arrow_columns(plain, types, dicts)
                tbl = pa.Table.from_arrays(arrays, schema=schema)
                if table == "orders":
                    if o_writer is None:
                        o_writer = pq.ParquetWriter(o_path + ".tmp", schema,
                                                    compression="zstd")
                    o_writer.write_table(tbl, row_group_size=row_group_rows)
                else:
                    if l_writer is None:
                        l_writer = pq.ParquetWriter(l_path + ".tmp", schema,
                                                    compression="zstd")
                    l_writer.write_table(tbl, row_group_size=row_group_rows)
            if log:
                log(f"orders/lineitem chunk {start}..{start + cnt} of {n_orders}")
        done = True
    finally:
        if o_writer is not None:
            o_writer.close()
        if l_writer is not None:
            l_writer.close()
        if done and o_writer is not None:
            # rename only after BOTH writers closed cleanly (see
            # export_tpcds_chunked — interrupted exports must not be
            # reused as complete datasets)
            os.replace(o_path + ".tmp", o_path)
            os.replace(l_path + ".tmp", l_path)
