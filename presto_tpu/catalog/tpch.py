"""TPC-H data-generator connector.

Analog of presto-tpch (TpchConnectorFactory / TpchMetadata over
io.airlift.tpch): an in-process, deterministic, scale-factor-parameterized
TPC-H dataset served directly as columnar batches.

The generator follows the TPC-H schema, cardinalities and value domains
(dates 1992-01-01..1998-12-31, DECIMAL(15,2) money columns, the standard
enum vocabularies) using seeded numpy, vectorized — it is not bit-compatible
with dbgen (correctness is checked against a pandas oracle over the same
data, the H2QueryRunner pattern, not against published answer sets).

Referential integrity is exact: l_orderkey ⊆ o_orderkey, (l_partkey,
l_suppkey) ⊆ partsupp, o_custkey ⊆ customer, etc., and o_totalprice is
consistent with the order's lineitems, so every TPC-H query shape is
meaningful.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from presto_tpu.catalog.memory import MemoryConnector, MemoryTable
from presto_tpu.types import DATE, DecimalType, INTEGER, BIGINT, VARCHAR

_D = DecimalType(15, 2)

_REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
_NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
_SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
_PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
_SHIP_MODES = ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]
_INSTRUCTIONS = ["COLLECT COD", "DELIVER IN PERSON", "NONE", "TAKE BACK RETURN"]
_TYPE_S1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
_TYPE_S2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
_TYPE_S3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
_CONTAINER_S1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
_CONTAINER_S2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]
_COLORS = [
    "almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
    "blanched", "blue", "blush", "brown", "burlywood", "burnished", "chartreuse",
    "chiffon", "chocolate", "coral", "cornflower", "cornsilk", "cream", "cyan",
    "dark", "deep", "dim", "dodger", "drab", "firebrick", "floral", "forest",
    "frosted", "gainsboro", "ghost", "goldenrod", "green", "grey", "honeydew",
    "hot", "hotpink", "indian", "ivory", "khaki", "lace", "lavender", "lawn",
    "lemon", "light", "lime", "linen", "magenta", "maroon", "medium", "metallic",
    "midnight", "mint", "misty", "moccasin", "navajo", "navy", "olive", "orange",
    "orchid", "pale", "papaya", "peach", "peru", "pink", "plum", "powder",
    "puff", "purple", "red", "rose", "rosy", "royal", "saddle", "salmon",
    "sandy", "seashell", "sienna", "sky", "slate", "smoke", "snow", "spring",
    "steel", "tan", "thistle", "tomato", "turquoise", "violet", "wheat",
    "white", "yellow",
]

_EPOCH_1992 = 8035  # days from 1970-01-01 to 1992-01-01
_EPOCH_1998_END = 10591  # 1998-12-31
_CURRENT_DATE = 9298  # 1995-06-17, the TPC-H "currentdate"


def _money(rng, lo: float, hi: float, n: int) -> np.ndarray:
    """DECIMAL(15,2) unscaled cents."""
    return rng.integers(int(lo * 100), int(hi * 100) + 1, n, dtype=np.int64)


def _keyed_names(prefix: str, keys: np.ndarray) -> np.ndarray:
    """Vectorized f"{prefix}{key:09d}" (np.char, no per-row Python)."""
    return np.char.add(prefix, np.char.zfill(keys.astype("U9"), 9)).astype(object)


def _vocab_codes(prefix: str, rng, n: int, vocab_size: int = 9973):
    """Rotating comment vocabulary as (Dictionary, codes) — the engine's
    dictionary-encoded string form, generated without any per-row Python.
    (Comments are uniform filler in the spec; a bounded sorted vocabulary
    keeps generation and IO linear in vocab size, not row count.)"""
    from presto_tpu.dictionary import Dictionary

    vocab = np.sort(np.array([f"{prefix} {i}" for i in range(vocab_size)]))
    return Dictionary(vocab), rng.integers(0, vocab_size, n).astype(np.int32)


def _phones(keys: np.ndarray, nat: Optional[np.ndarray] = None) -> np.ndarray:
    """Vectorized phone strings "{cc}-{nnn}-{nnnn}" (purely key-derived)."""
    i = keys.astype(np.int64)
    cc = (10 + (nat if nat is not None else i % 25)).astype("U2")
    mid = (i % 900 + 100).astype("U3")
    last = (i % 9000 + 1000).astype("U4")
    return np.char.add(np.char.add(np.char.add(np.char.add(cc, "-"), mid), "-"),
                       last).astype(object)


class TpchGenerator:
    def __init__(self, sf: float = 1.0, seed: int = 19920101):
        self.sf = sf
        self.seed = seed

    def _rng(self, salt: int):
        return np.random.default_rng(self.seed + salt)

    # cardinalities (TPC-H spec §4.2.5)
    @property
    def n_supplier(self):
        return max(1, int(10_000 * self.sf))

    @property
    def n_part(self):
        return max(1, int(200_000 * self.sf))

    @property
    def n_customer(self):
        return max(1, int(150_000 * self.sf))

    @property
    def n_orders(self):
        return max(1, int(1_500_000 * self.sf))

    def region(self) -> Dict[str, np.ndarray]:
        return {
            "r_regionkey": np.arange(5, dtype=np.int64),
            "r_name": np.array(_REGIONS, dtype=object),
            "r_comment": np.array([f"region comment {i}" for i in range(5)], dtype=object),
        }

    def nation(self) -> Dict[str, np.ndarray]:
        return {
            "n_nationkey": np.arange(25, dtype=np.int64),
            "n_name": np.array([n for n, _ in _NATIONS], dtype=object),
            "n_regionkey": np.array([r for _, r in _NATIONS], dtype=np.int64),
            "n_comment": np.array([f"nation comment {i}" for i in range(25)], dtype=object),
        }

    def supplier(self) -> Dict[str, np.ndarray]:
        n = self.n_supplier
        rng = self._rng(1)
        keys = np.arange(1, n + 1, dtype=np.int64)
        # spec: ~5/10000 suppliers carry the "Customer Complaints" marker
        # (Q16's filter); the rest draw from the comment vocabulary
        cd, cc = _vocab_codes("supplier comment", rng, n)
        from presto_tpu.dictionary import Dictionary

        marked = rng.random(n) < 0.0005
        vocab = np.sort(np.append(cd.values, "Customer Complaints"))
        d2 = Dictionary(vocab)
        remap = np.searchsorted(vocab, cd.values)
        codes = np.where(marked, np.searchsorted(vocab, "Customer Complaints"),
                         remap[cc]).astype(np.int32)
        return {
            "s_suppkey": keys,
            "s_name": _keyed_names("Supplier#", keys),
            "s_address": _keyed_names("addrsup#", keys),
            "s_nationkey": rng.integers(0, 25, n, dtype=np.int64),
            "s_phone": _phones(keys),
            "s_acctbal": _money(rng, -999.99, 9999.99, n),
            "s_comment": (d2, codes),
        }

    def customer(self) -> Dict[str, np.ndarray]:
        n = self.n_customer
        rng = self._rng(2)
        nat = rng.integers(0, 25, n, dtype=np.int64)
        keys = np.arange(1, n + 1, dtype=np.int64)
        return {
            "c_custkey": keys,
            "c_name": _keyed_names("Customer#", keys),
            "c_address": _keyed_names("addrcust#", keys),
            "c_nationkey": nat,
            "c_phone": _phones(keys, nat),
            "c_acctbal": _money(rng, -999.99, 9999.99, n),
            "c_mktsegment": np.asarray(rng.choice(_SEGMENTS, n), dtype=object),
            "c_comment": _vocab_codes("customer comment", rng, n),
        }

    def part(self) -> Dict[str, np.ndarray]:
        from presto_tpu.dictionary import Dictionary

        n = self.n_part
        rng = self._rng(3)
        # enum-product columns generate as dictionary codes over the full
        # cross-product vocabulary (150 types, 40 containers, 8464 names) —
        # no per-row Python string construction at any scale factor
        type_vocab = np.sort(np.array(
            [f"{a} {b} {c}" for a in _TYPE_S1 for b in _TYPE_S2 for c in _TYPE_S3]))
        t_d = Dictionary(type_vocab)
        s123 = rng.integers(0, len(type_vocab), n).astype(np.int32)
        cont_vocab = np.sort(np.array(
            [f"{a} {b}" for a in _CONTAINER_S1 for b in _CONTAINER_S2]))
        c_d = Dictionary(cont_vocab)
        c12 = rng.integers(0, len(cont_vocab), n).astype(np.int32)
        name_vocab = np.sort(np.array(
            [f"{a} {b}" for a in _COLORS for b in _COLORS if a != b]))
        n_d = Dictionary(name_vocab)
        nc = rng.integers(0, len(name_vocab), n).astype(np.int32)
        brand_vocab = np.sort(np.array(
            [f"Brand#{m}{x}" for m in range(1, 6) for x in range(1, 6)]))
        b_d = Dictionary(brand_vocab)
        bc = rng.integers(0, len(brand_vocab), n).astype(np.int32)
        mfgr_vocab = np.array([f"Manufacturer#{m}" for m in range(1, 6)])
        m_d = Dictionary(mfgr_vocab)
        mc = rng.integers(0, 5, n).astype(np.int32)
        # retail price formula per spec: 90000+((pk/10)%20001)+100*(pk%1000), in cents
        pk = np.arange(1, n + 1, dtype=np.int64)
        retail = 90000 + (pk // 10) % 20001 + 100 * (pk % 1000)
        return {
            "p_partkey": pk,
            "p_name": (n_d, nc),
            "p_mfgr": (m_d, mc),
            "p_brand": (b_d, bc),
            "p_type": (t_d, s123),
            "p_size": rng.integers(1, 51, n, dtype=np.int64),
            "p_container": (c_d, c12),
            "p_retailprice": retail,
            "p_comment": _vocab_codes("part comment", rng, n),
        }

    def partsupp(self) -> Dict[str, np.ndarray]:
        npart = self.n_part
        nsupp = self.n_supplier
        rng = self._rng(4)
        pk = np.repeat(np.arange(1, npart + 1, dtype=np.int64), 4)
        j = np.tile(np.arange(4, dtype=np.int64), npart)
        # spec §4.2.5.4: supplier = (pk + j*(S/4 + (pk-1)/S)) % S + 1
        S = nsupp
        sk = (pk + j * (S // 4 + (pk - 1) // S)) % S + 1
        n = len(pk)
        return {
            "ps_partkey": pk,
            "ps_suppkey": sk,
            "ps_availqty": rng.integers(1, 10_000, n, dtype=np.int64),
            "ps_supplycost": _money(rng, 1.00, 1000.00, n),
            "ps_comment": _vocab_codes("partsupp comment", rng, n),
        }

    def orders_and_lineitem(self):
        """Full-table generation (single chunk, original RNG streams)."""
        return self.orders_lineitem_chunk(0, self.n_orders, _salt=(5, 6))

    def orders_lineitem_chunk(self, start: int, count: int, _salt=None):
        """Generate orders [start, start+count) plus their lineitems.

        Chunking keeps peak memory proportional to the chunk, letting
        SF100 (150M orders / 600M lineitems) stream to parquet without
        materializing the table (reference: dbgen's -S step/-C chunk
        options). Lines of an order always live in its chunk, so
        o_totalprice/o_orderstatus stay exact. Each chunk draws from its
        own deterministic RNG streams; foreign keys (customer, part,
        supplier) span the full SF domain."""
        n = count
        if _salt is None:
            _salt = (1000 + 2 * (start // max(count, 1)),
                     1001 + 2 * (start // max(count, 1)))
        rng = self._rng(_salt[0])
        # sparse orderkeys like dbgen (every 8-key block uses first 2... we
        # use *4 spacing for simplicity, keys still sparse + sorted)
        okey = np.arange(start + 1, start + n + 1, dtype=np.int64) * 4
        # only 2/3 of customers have orders (spec: custkey % 3 != 0)
        ncust = self.n_customer
        ckey = rng.integers(1, max(ncust // 3, 1) + 1, n, dtype=np.int64) * 3 - 2
        ckey = np.minimum(ckey, ncust)
        odate = rng.integers(_EPOCH_1992, _EPOCH_1998_END - 151, n, dtype=np.int64)

        nline = rng.integers(1, 8, n)  # 1..7 lines per order
        total_lines = int(nline.sum())
        l_order_idx = np.repeat(np.arange(n), nline)  # index into orders
        # linenumber = position within order, vectorized
        starts = np.cumsum(nline) - nline
        lnum_base = np.arange(total_lines) - starts[l_order_idx] + 1

        lrng = self._rng(_salt[1])
        m = total_lines
        lpart = lrng.integers(1, self.n_part + 1, m, dtype=np.int64)
        # one of the 4 partsupp suppliers for that part
        j = lrng.integers(0, 4, m, dtype=np.int64)
        S = self.n_supplier
        lsupp = (lpart + j * (S // 4 + (lpart - 1) // S)) % S + 1
        qty = lrng.integers(1, 51, m, dtype=np.int64)
        # extendedprice = qty * p_retailprice(part)
        retail = 90000 + (lpart // 10) % 20001 + 100 * (lpart % 1000)
        eprice = qty * retail
        disc = lrng.integers(0, 11, m, dtype=np.int64)  # 0.00..0.10 scale-2
        tax = lrng.integers(0, 9, m, dtype=np.int64)  # 0.00..0.08

        l_odate = odate[l_order_idx]
        shipdate = l_odate + lrng.integers(1, 122, m)
        commitdate = l_odate + lrng.integers(30, 91, m)
        receiptdate = shipdate + lrng.integers(1, 31, m)

        # string columns generate as dictionary codes directly (vocabularies
        # are sorted so codes are order-preserving) — no per-row python strs
        from presto_tpu.dictionary import Dictionary

        rf_dict = Dictionary(np.array(["A", "N", "R"]))
        ra = np.where(lrng.integers(0, 2, m) == 0, 0, 2).astype(np.int32)  # A or R
        returnflag = (rf_dict, np.where(receiptdate <= _CURRENT_DATE, ra, 1).astype(np.int32))
        ls_dict = Dictionary(np.array(["F", "O"]))
        ls_codes = (shipdate > _CURRENT_DATE).astype(np.int32)
        linestatus = (ls_dict, ls_codes)

        smode = (Dictionary(np.array(_SHIP_MODES)),
                 lrng.integers(0, len(_SHIP_MODES), m).astype(np.int32))
        sinstr = (Dictionary(np.array(_INSTRUCTIONS)),
                  lrng.integers(0, len(_INSTRUCTIONS), m).astype(np.int32))

        # order totalprice = sum(extendedprice*(1+tax)*(1-disc)) per order —
        # computed exactly in cents with the same rounding as a decimal engine
        line_total = eprice * (100 - disc) * (100 + tax)  # scale 6
        line_total = (line_total + 5000) // 10000 * 1  # round to cents (scale 2)
        ototal = np.zeros(n, dtype=np.int64)
        np.add.at(ototal, l_order_idx, line_total)

        f_mask = ls_codes == 0
        all_f = np.ones(n, bool)
        any_f = np.zeros(n, bool)
        np.logical_and.at(all_f, l_order_idx, f_mask)
        np.logical_or.at(any_f, l_order_idx, f_mask)
        ostatus_codes = np.full(n, 2, dtype=np.int32)  # P
        ostatus_codes[all_f] = 0  # F
        ostatus_codes[~any_f] = 1  # O
        ostatus = (Dictionary(np.array(["F", "O", "P"])), ostatus_codes)

        n_clerk = max(1, int(1000 * self.sf))
        if not hasattr(self, "_clerk_dict"):
            self._clerk_dict = Dictionary(
                _keyed_names("Clerk#", np.arange(1, n_clerk + 1)).astype(str))
            self._ocomment_vocab = np.sort(
                np.array([f"order comment {i}" for i in range(9973)]))
            self._lcomment_dict = Dictionary(
                np.sort(np.array([f"line comment {i}" for i in range(9973)])))
            self._ocomment_dict = Dictionary(self._ocomment_vocab)
        clerk_dict = self._clerk_dict
        orders = {
            "o_orderkey": okey,
            "o_custkey": ckey,
            "o_orderstatus": ostatus,
            "o_totalprice": ototal,
            "o_orderdate": odate,
            "o_orderpriority": (
                Dictionary(np.array(_PRIORITIES)),
                rng.integers(0, len(_PRIORITIES), n).astype(np.int32),
            ),
            "o_clerk": (clerk_dict, rng.integers(0, n_clerk, n).astype(np.int32)),
            "o_shippriority": np.zeros(n, dtype=np.int64),
            "o_comment": (
                self._ocomment_dict,
                rng.integers(0, 9973, n).astype(np.int32),
            ),
        }
        lineitem = {
            "l_orderkey": okey[l_order_idx],
            "l_partkey": lpart,
            "l_suppkey": lsupp,
            "l_linenumber": lnum_base.astype(np.int64),
            "l_quantity": qty,
            "l_extendedprice": eprice,
            "l_discount": disc,
            "l_tax": tax,
            "l_returnflag": returnflag,
            "l_linestatus": linestatus,
            "l_shipdate": shipdate,
            "l_commitdate": commitdate,
            "l_receiptdate": receiptdate,
            "l_shipinstruct": sinstr,
            "l_shipmode": smode,
            "l_comment": (
                self._lcomment_dict,
                lrng.integers(0, 9973, m).astype(np.int32),
            ),
        }
        return orders, lineitem


_TYPES = {
    "region": {"r_regionkey": BIGINT},
    "nation": {"n_nationkey": BIGINT, "n_regionkey": BIGINT},
    "supplier": {"s_suppkey": BIGINT, "s_nationkey": BIGINT, "s_acctbal": _D},
    "customer": {"c_custkey": BIGINT, "c_nationkey": BIGINT, "c_acctbal": _D},
    "part": {"p_partkey": BIGINT, "p_size": BIGINT, "p_retailprice": _D},
    "partsupp": {"ps_partkey": BIGINT, "ps_suppkey": BIGINT, "ps_availqty": BIGINT, "ps_supplycost": _D},
    "orders": {
        "o_orderkey": BIGINT, "o_custkey": BIGINT, "o_totalprice": _D,
        "o_orderdate": DATE, "o_shippriority": BIGINT,
    },
    "lineitem": {
        "l_orderkey": BIGINT, "l_partkey": BIGINT, "l_suppkey": BIGINT,
        "l_linenumber": BIGINT, "l_quantity": BIGINT,
        "l_extendedprice": _D, "l_discount": DecimalType(15, 2), "l_tax": DecimalType(15, 2),
        "l_shipdate": DATE, "l_commitdate": DATE, "l_receiptdate": DATE,
    },
}

# l_discount / l_tax are stored as scale-2 unscaled values already
_PRESCALED = {
    ("supplier", "s_acctbal"), ("customer", "c_acctbal"),
    ("part", "p_retailprice"), ("partsupp", "ps_supplycost"),
    ("orders", "o_totalprice"), ("lineitem", "l_extendedprice"),
    ("lineitem", "l_discount"), ("lineitem", "l_tax"),
}

_PRIMARY_KEYS = {
    "region": ["r_regionkey"],
    "nation": ["n_nationkey"],
    "supplier": ["s_suppkey"],
    "customer": ["c_custkey"],
    "part": ["p_partkey"],
    "orders": ["o_orderkey"],
    "partsupp": ["ps_partkey", "ps_suppkey"],
}


def _column_types(table: str, data: Dict[str, np.ndarray]) -> Dict[str, "Type"]:
    """Full name→Type map for a generated table (export path): explicit
    types from _TYPES, VARCHAR for dictionary/object columns, BIGINT rest."""
    explicit = _TYPES.get(table, {})
    out = {}
    for col, v in data.items():
        if col in explicit:
            out[col] = explicit[col]
        elif isinstance(v, tuple) or (
            isinstance(v, np.ndarray) and v.dtype == object
        ):
            out[col] = VARCHAR
        else:
            out[col] = BIGINT
    return out


class TpchConnector(MemoryConnector):
    """Lazy TPC-H connector: tables generate on first access and are cached.

    Reference: presto-tpch TpchConnectorFactory (data generated in-process,
    deterministically, per scale factor)."""

    def __init__(self, sf: float = 1.0, name: str = "tpch"):
        super().__init__(name)
        self.sf = sf
        self.gen = TpchGenerator(sf)

    def table_names(self) -> List[str]:
        return ["region", "nation", "supplier", "customer", "part",
                "partsupp", "orders", "lineitem"]

    def _ensure(self, name: str):
        if name in self.tables:
            return
        if name in ("orders", "lineitem"):
            orders, lineitem = self.gen.orders_and_lineitem()
            self._add("orders", orders)
            self._add("lineitem", lineitem)
        elif name in ("region", "nation", "supplier", "customer", "part", "partsupp"):
            self._add(name, getattr(self.gen, name)())
        else:
            raise KeyError(f"table not found: {name}")

    def _add(self, name: str, data: Dict[str, np.ndarray]):
        types = dict(_TYPES.get(name, {}))
        converted = {}
        for col, arr in data.items():
            ct = types.get(col)
            # pre-scaled decimal columns must not be rescaled by MemoryTable
            if (ct is not None and isinstance(ct, DecimalType)
                    and (name, col) in _PRESCALED):
                converted[col] = ("raw_decimal", ct, arr)
            else:
                converted[col] = arr
        self.add_generated(
            name, converted,
            types={c: t for c, t in types.items()
                   if (name, c) not in _PRESCALED},
            primary_key=_PRIMARY_KEYS.get(name),
        )

    def get_table(self, name: str):
        self._ensure(name)
        return super().get_table(name)

    def read_split(self, split, columns, capacity=None):
        self._ensure(split.table)
        return super().read_split(split, columns, capacity)


def tpch_catalog(sf: float = 1.0):
    from presto_tpu.connector import Catalog

    cat = Catalog()
    cat.register("tpch", TpchConnector(sf), default=True)
    return cat
