"""TPC-H data-generator connector.

Analog of presto-tpch (TpchConnectorFactory / TpchMetadata over
io.airlift.tpch): an in-process, deterministic, scale-factor-parameterized
TPC-H dataset served directly as columnar batches.

The generator follows the TPC-H schema, cardinalities and value domains
(dates 1992-01-01..1998-12-31, DECIMAL(15,2) money columns, the standard
enum vocabularies) using seeded numpy, vectorized — it is not bit-compatible
with dbgen (correctness is checked against a pandas oracle over the same
data, the H2QueryRunner pattern, not against published answer sets).

Referential integrity is exact: l_orderkey ⊆ o_orderkey, (l_partkey,
l_suppkey) ⊆ partsupp, o_custkey ⊆ customer, etc., and o_totalprice is
consistent with the order's lineitems, so every TPC-H query shape is
meaningful.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from presto_tpu.catalog.memory import MemoryConnector, MemoryTable
from presto_tpu.types import DATE, DecimalType, INTEGER, BIGINT, VARCHAR

_D = DecimalType(15, 2)

_REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
_NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
_SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
_PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
_SHIP_MODES = ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]
_INSTRUCTIONS = ["COLLECT COD", "DELIVER IN PERSON", "NONE", "TAKE BACK RETURN"]
_TYPE_S1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
_TYPE_S2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
_TYPE_S3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
_CONTAINER_S1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
_CONTAINER_S2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]
_COLORS = [
    "almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
    "blanched", "blue", "blush", "brown", "burlywood", "burnished", "chartreuse",
    "chiffon", "chocolate", "coral", "cornflower", "cornsilk", "cream", "cyan",
    "dark", "deep", "dim", "dodger", "drab", "firebrick", "floral", "forest",
    "frosted", "gainsboro", "ghost", "goldenrod", "green", "grey", "honeydew",
    "hot", "hotpink", "indian", "ivory", "khaki", "lace", "lavender", "lawn",
    "lemon", "light", "lime", "linen", "magenta", "maroon", "medium", "metallic",
    "midnight", "mint", "misty", "moccasin", "navajo", "navy", "olive", "orange",
    "orchid", "pale", "papaya", "peach", "peru", "pink", "plum", "powder",
    "puff", "purple", "red", "rose", "rosy", "royal", "saddle", "salmon",
    "sandy", "seashell", "sienna", "sky", "slate", "smoke", "snow", "spring",
    "steel", "tan", "thistle", "tomato", "turquoise", "violet", "wheat",
    "white", "yellow",
]

_EPOCH_1992 = 8035  # days from 1970-01-01 to 1992-01-01
_EPOCH_1998_END = 10591  # 1998-12-31
_CURRENT_DATE = 9298  # 1995-06-17, the TPC-H "currentdate"


def _money(rng, lo: float, hi: float, n: int) -> np.ndarray:
    """DECIMAL(15,2) unscaled cents."""
    return rng.integers(int(lo * 100), int(hi * 100) + 1, n, dtype=np.int64)


class TpchGenerator:
    def __init__(self, sf: float = 1.0, seed: int = 19920101):
        self.sf = sf
        self.seed = seed

    def _rng(self, salt: int):
        return np.random.default_rng(self.seed + salt)

    # cardinalities (TPC-H spec §4.2.5)
    @property
    def n_supplier(self):
        return max(1, int(10_000 * self.sf))

    @property
    def n_part(self):
        return max(1, int(200_000 * self.sf))

    @property
    def n_customer(self):
        return max(1, int(150_000 * self.sf))

    @property
    def n_orders(self):
        return max(1, int(1_500_000 * self.sf))

    def region(self) -> Dict[str, np.ndarray]:
        return {
            "r_regionkey": np.arange(5, dtype=np.int64),
            "r_name": np.array(_REGIONS, dtype=object),
            "r_comment": np.array([f"region comment {i}" for i in range(5)], dtype=object),
        }

    def nation(self) -> Dict[str, np.ndarray]:
        return {
            "n_nationkey": np.arange(25, dtype=np.int64),
            "n_name": np.array([n for n, _ in _NATIONS], dtype=object),
            "n_regionkey": np.array([r for _, r in _NATIONS], dtype=np.int64),
            "n_comment": np.array([f"nation comment {i}" for i in range(25)], dtype=object),
        }

    def supplier(self) -> Dict[str, np.ndarray]:
        n = self.n_supplier
        rng = self._rng(1)
        return {
            "s_suppkey": np.arange(1, n + 1, dtype=np.int64),
            "s_name": np.array([f"Supplier#{i:09d}" for i in range(1, n + 1)], dtype=object),
            "s_address": np.array([f"addr sup {i}" for i in range(1, n + 1)], dtype=object),
            "s_nationkey": rng.integers(0, 25, n, dtype=np.int64),
            "s_phone": np.array([f"{10+i%25}-{i%900+100}-{i%9000+1000}" for i in range(1, n + 1)], dtype=object),
            "s_acctbal": _money(rng, -999.99, 9999.99, n),
            "s_comment": np.array(
                [
                    "Customer Complaints" if x < 0.0005 else f"supplier comment {i}"
                    for i, x in enumerate(rng.random(n))
                ],
                dtype=object,
            ),
        }

    def customer(self) -> Dict[str, np.ndarray]:
        n = self.n_customer
        rng = self._rng(2)
        nat = rng.integers(0, 25, n, dtype=np.int64)
        return {
            "c_custkey": np.arange(1, n + 1, dtype=np.int64),
            "c_name": np.array([f"Customer#{i:09d}" for i in range(1, n + 1)], dtype=object),
            "c_address": np.array([f"addr cust {i}" for i in range(1, n + 1)], dtype=object),
            "c_nationkey": nat,
            "c_phone": np.array(
                [f"{10+int(k)}-{i%900+100}-{i%9000+1000}" for i, k in enumerate(nat)],
                dtype=object,
            ),
            "c_acctbal": _money(rng, -999.99, 9999.99, n),
            "c_mktsegment": np.asarray(rng.choice(_SEGMENTS, n), dtype=object),
            "c_comment": np.array([f"customer comment {i}" for i in range(1, n + 1)], dtype=object),
        }

    def part(self) -> Dict[str, np.ndarray]:
        n = self.n_part
        rng = self._rng(3)
        s1 = rng.integers(0, len(_TYPE_S1), n)
        s2 = rng.integers(0, len(_TYPE_S2), n)
        s3 = rng.integers(0, len(_TYPE_S3), n)
        types = np.array(
            [f"{_TYPE_S1[a]} {_TYPE_S2[b]} {_TYPE_S3[c]}" for a, b, c in zip(s1, s2, s3)],
            dtype=object,
        )
        c1 = rng.integers(0, len(_CONTAINER_S1), n)
        c2 = rng.integers(0, len(_CONTAINER_S2), n)
        containers = np.array(
            [f"{_CONTAINER_S1[a]} {_CONTAINER_S2[b]}" for a, b in zip(c1, c2)],
            dtype=object,
        )
        color_idx = rng.integers(0, len(_COLORS), (n, 2))
        names = np.array(
            [f"{_COLORS[a]} {_COLORS[b]}" for a, b in color_idx],
            dtype=object,
        )
        brands = np.array(
            [f"Brand#{m}{x}" for m, x in zip(rng.integers(1, 6, n), rng.integers(1, 6, n))],
            dtype=object,
        )
        # retail price formula per spec: 90000+((pk/10)%20001)+100*(pk%1000), in cents
        pk = np.arange(1, n + 1, dtype=np.int64)
        retail = 90000 + (pk // 10) % 20001 + 100 * (pk % 1000)
        return {
            "p_partkey": pk,
            "p_name": names,
            "p_mfgr": np.array([f"Manufacturer#{m}" for m in rng.integers(1, 6, n)], dtype=object),
            "p_brand": brands,
            "p_type": types,
            "p_size": rng.integers(1, 51, n, dtype=np.int64),
            "p_container": containers,
            "p_retailprice": retail,
            "p_comment": np.array([f"part comment {i}" for i in range(n)], dtype=object),
        }

    def partsupp(self) -> Dict[str, np.ndarray]:
        npart = self.n_part
        nsupp = self.n_supplier
        rng = self._rng(4)
        pk = np.repeat(np.arange(1, npart + 1, dtype=np.int64), 4)
        j = np.tile(np.arange(4, dtype=np.int64), npart)
        # spec §4.2.5.4: supplier = (pk + j*(S/4 + (pk-1)/S)) % S + 1
        S = nsupp
        sk = (pk + j * (S // 4 + (pk - 1) // S)) % S + 1
        n = len(pk)
        return {
            "ps_partkey": pk,
            "ps_suppkey": sk,
            "ps_availqty": rng.integers(1, 10_000, n, dtype=np.int64),
            "ps_supplycost": _money(rng, 1.00, 1000.00, n),
            "ps_comment": np.array([f"partsupp comment {i}" for i in range(n)], dtype=object),
        }

    def orders_and_lineitem(self):
        n = self.n_orders
        rng = self._rng(5)
        # sparse orderkeys like dbgen (every 8-key block uses first 2... we
        # use *4 spacing for simplicity, keys still sparse + sorted)
        okey = np.arange(1, n + 1, dtype=np.int64) * 4
        # only 2/3 of customers have orders (spec: custkey % 3 != 0)
        ncust = self.n_customer
        ckey = rng.integers(1, max(ncust // 3, 1) + 1, n, dtype=np.int64) * 3 - 2
        ckey = np.minimum(ckey, ncust)
        odate = rng.integers(_EPOCH_1992, _EPOCH_1998_END - 151, n, dtype=np.int64)

        nline = rng.integers(1, 8, n)  # 1..7 lines per order
        total_lines = int(nline.sum())
        l_order_idx = np.repeat(np.arange(n), nline)  # index into orders
        # linenumber = position within order, vectorized
        starts = np.cumsum(nline) - nline
        lnum_base = np.arange(total_lines) - starts[l_order_idx] + 1

        lrng = self._rng(6)
        m = total_lines
        lpart = lrng.integers(1, self.n_part + 1, m, dtype=np.int64)
        # one of the 4 partsupp suppliers for that part
        j = lrng.integers(0, 4, m, dtype=np.int64)
        S = self.n_supplier
        lsupp = (lpart + j * (S // 4 + (lpart - 1) // S)) % S + 1
        qty = lrng.integers(1, 51, m, dtype=np.int64)
        # extendedprice = qty * p_retailprice(part)
        retail = 90000 + (lpart // 10) % 20001 + 100 * (lpart % 1000)
        eprice = qty * retail
        disc = lrng.integers(0, 11, m, dtype=np.int64)  # 0.00..0.10 scale-2
        tax = lrng.integers(0, 9, m, dtype=np.int64)  # 0.00..0.08

        l_odate = odate[l_order_idx]
        shipdate = l_odate + lrng.integers(1, 122, m)
        commitdate = l_odate + lrng.integers(30, 91, m)
        receiptdate = shipdate + lrng.integers(1, 31, m)

        # string columns generate as dictionary codes directly (vocabularies
        # are sorted so codes are order-preserving) — no per-row python strs
        from presto_tpu.dictionary import Dictionary

        rf_dict = Dictionary(np.array(["A", "N", "R"]))
        ra = np.where(lrng.integers(0, 2, m) == 0, 0, 2).astype(np.int32)  # A or R
        returnflag = (rf_dict, np.where(receiptdate <= _CURRENT_DATE, ra, 1).astype(np.int32))
        ls_dict = Dictionary(np.array(["F", "O"]))
        ls_codes = (shipdate > _CURRENT_DATE).astype(np.int32)
        linestatus = (ls_dict, ls_codes)

        smode = (Dictionary(np.array(_SHIP_MODES)),
                 lrng.integers(0, len(_SHIP_MODES), m).astype(np.int32))
        sinstr = (Dictionary(np.array(_INSTRUCTIONS)),
                  lrng.integers(0, len(_INSTRUCTIONS), m).astype(np.int32))

        # order totalprice = sum(extendedprice*(1+tax)*(1-disc)) per order —
        # computed exactly in cents with the same rounding as a decimal engine
        line_total = eprice * (100 - disc) * (100 + tax)  # scale 6
        line_total = (line_total + 5000) // 10000 * 1  # round to cents (scale 2)
        ototal = np.zeros(n, dtype=np.int64)
        np.add.at(ototal, l_order_idx, line_total)

        f_mask = ls_codes == 0
        all_f = np.ones(n, bool)
        any_f = np.zeros(n, bool)
        np.logical_and.at(all_f, l_order_idx, f_mask)
        np.logical_or.at(any_f, l_order_idx, f_mask)
        ostatus_codes = np.full(n, 2, dtype=np.int32)  # P
        ostatus_codes[all_f] = 0  # F
        ostatus_codes[~any_f] = 1  # O
        ostatus = (Dictionary(np.array(["F", "O", "P"])), ostatus_codes)

        n_clerk = max(1, int(1000 * self.sf))
        clerk_dict = Dictionary(np.array([f"Clerk#{i:09d}" for i in range(1, n_clerk + 1)]))
        ocomment_vocab = np.sort(np.array([f"order comment {i}" for i in range(9973)]))
        orders = {
            "o_orderkey": okey,
            "o_custkey": ckey,
            "o_orderstatus": ostatus,
            "o_totalprice": ototal,
            "o_orderdate": odate,
            "o_orderpriority": (
                Dictionary(np.array(_PRIORITIES)),
                rng.integers(0, len(_PRIORITIES), n).astype(np.int32),
            ),
            "o_clerk": (clerk_dict, rng.integers(0, n_clerk, n).astype(np.int32)),
            "o_shippriority": np.zeros(n, dtype=np.int64),
            "o_comment": (
                Dictionary(ocomment_vocab),
                rng.integers(0, 9973, n).astype(np.int32),
            ),
        }
        lineitem = {
            "l_orderkey": okey[l_order_idx],
            "l_partkey": lpart,
            "l_suppkey": lsupp,
            "l_linenumber": lnum_base.astype(np.int64),
            "l_quantity": qty,
            "l_extendedprice": eprice,
            "l_discount": disc,
            "l_tax": tax,
            "l_returnflag": returnflag,
            "l_linestatus": linestatus,
            "l_shipdate": shipdate,
            "l_commitdate": commitdate,
            "l_receiptdate": receiptdate,
            "l_shipinstruct": sinstr,
            "l_shipmode": smode,
            "l_comment": (
                Dictionary(np.sort(np.array([f"line comment {i}" for i in range(9973)]))),
                lrng.integers(0, 9973, m).astype(np.int32),
            ),
        }
        return orders, lineitem


_TYPES = {
    "region": {"r_regionkey": BIGINT},
    "nation": {"n_nationkey": BIGINT, "n_regionkey": BIGINT},
    "supplier": {"s_suppkey": BIGINT, "s_nationkey": BIGINT, "s_acctbal": _D},
    "customer": {"c_custkey": BIGINT, "c_nationkey": BIGINT, "c_acctbal": _D},
    "part": {"p_partkey": BIGINT, "p_size": BIGINT, "p_retailprice": _D},
    "partsupp": {"ps_partkey": BIGINT, "ps_suppkey": BIGINT, "ps_availqty": BIGINT, "ps_supplycost": _D},
    "orders": {
        "o_orderkey": BIGINT, "o_custkey": BIGINT, "o_totalprice": _D,
        "o_orderdate": DATE, "o_shippriority": BIGINT,
    },
    "lineitem": {
        "l_orderkey": BIGINT, "l_partkey": BIGINT, "l_suppkey": BIGINT,
        "l_linenumber": BIGINT, "l_quantity": BIGINT,
        "l_extendedprice": _D, "l_discount": DecimalType(15, 2), "l_tax": DecimalType(15, 2),
        "l_shipdate": DATE, "l_commitdate": DATE, "l_receiptdate": DATE,
    },
}

# l_discount / l_tax are stored as scale-2 unscaled values already
_PRESCALED = {
    ("supplier", "s_acctbal"), ("customer", "c_acctbal"),
    ("part", "p_retailprice"), ("partsupp", "ps_supplycost"),
    ("orders", "o_totalprice"), ("lineitem", "l_extendedprice"),
    ("lineitem", "l_discount"), ("lineitem", "l_tax"),
}

_PRIMARY_KEYS = {
    "region": ["r_regionkey"],
    "nation": ["n_nationkey"],
    "supplier": ["s_suppkey"],
    "customer": ["c_custkey"],
    "part": ["p_partkey"],
    "orders": ["o_orderkey"],
    "partsupp": ["ps_partkey", "ps_suppkey"],
}


class TpchConnector(MemoryConnector):
    """Lazy TPC-H connector: tables generate on first access and are cached.

    Reference: presto-tpch TpchConnectorFactory (data generated in-process,
    deterministically, per scale factor)."""

    def __init__(self, sf: float = 1.0, name: str = "tpch"):
        super().__init__(name)
        self.sf = sf
        self.gen = TpchGenerator(sf)

    def table_names(self) -> List[str]:
        return ["region", "nation", "supplier", "customer", "part",
                "partsupp", "orders", "lineitem"]

    def _ensure(self, name: str):
        if name in self.tables:
            return
        if name in ("orders", "lineitem"):
            orders, lineitem = self.gen.orders_and_lineitem()
            self._add("orders", orders)
            self._add("lineitem", lineitem)
        elif name in ("region", "nation", "supplier", "customer", "part", "partsupp"):
            self._add(name, getattr(self.gen, name)())
        else:
            raise KeyError(f"table not found: {name}")

    def _add(self, name: str, data: Dict[str, np.ndarray]):
        types = dict(_TYPES.get(name, {}))
        converted = {}
        for col, arr in data.items():
            ct = types.get(col)
            # pre-scaled decimal columns must not be rescaled by MemoryTable
            if (ct is not None and isinstance(ct, DecimalType)
                    and (name, col) in _PRESCALED):
                converted[col] = ("raw_decimal", ct, arr)
            else:
                converted[col] = arr
        self.add_generated(
            name, converted,
            types={c: t for c, t in types.items()
                   if (name, c) not in _PRESCALED},
            primary_key=_PRIMARY_KEYS.get(name),
        )

    def get_table(self, name: str):
        self._ensure(name)
        return super().get_table(name)

    def read_split(self, split, columns, capacity=None):
        self._ensure(split.table)
        return super().read_split(split, columns, capacity)


def tpch_catalog(sf: float = 1.0):
    from presto_tpu.connector import Catalog

    cat = Catalog()
    cat.register("tpch", TpchConnector(sf), default=True)
    return cat
