"""Local-file connector — CSV / JSON-lines tables.

Reference: presto-local-file + presto-record-decoder (the csv/json
RowDecoders shared by the kafka/redis connectors). A directory of
<table>.csv / <table>.jsonl / <table>.json files serves as a schema;
decoding happens host-side into engine-native columns (pandas does the
parsing the reference's per-field decoders do), then batches flow
through the device pipeline like any connector's."""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from presto_tpu.batch import Batch, round_up_capacity
from presto_tpu.catalog.memory import DeviceSplitCache, MemoryTable, _infer_type
from presto_tpu.connector import ColumnInfo, Connector, Split, TableHandle

_EXTS = (".csv", ".jsonl", ".json")


class LocalFileConnector(DeviceSplitCache, Connector):
    def __init__(self, directory: str, name: str = "localfile"):
        self.name = name
        self.directory = directory
        self._tables: Dict[str, MemoryTable] = {}
        self._versions: Dict[str, tuple] = {}
        self._lock = threading.Lock()
        self._init_split_cache()

    def _path(self, name: str) -> Optional[str]:
        for ext in _EXTS:
            p = os.path.join(self.directory, name + ext)
            if os.path.exists(p):
                return p
        return None

    def table_names(self) -> List[str]:
        out = []
        for f in sorted(os.listdir(self.directory)):
            base, ext = os.path.splitext(f)
            if ext in _EXTS:
                out.append(base)
        return out

    def _load(self, name: str) -> MemoryTable:
        import pandas as pd

        path = self._path(name)
        if path is None:
            raise KeyError(f"table not found: {name}")
        st = os.stat(path)
        version = (st.st_mtime_ns, st.st_size)
        with self._lock:
            if self._versions.get(name) == version:
                return self._tables[name]
        if path.endswith(".csv"):
            df = pd.read_csv(path)
        else:
            df = pd.read_json(path, lines=path.endswith(".jsonl"))
        data = {c: df[c].to_numpy() for c in df.columns}
        mt = MemoryTable(name, data)
        with self._lock:
            self._tables[name] = mt
            self._versions[name] = version
        self.invalidate_cache(name)
        return mt

    def get_table(self, name: str) -> TableHandle:
        return self._load(name).handle(self.name)

    def splits(self, handle: TableHandle, desired: int = 1) -> List[Split]:
        return [Split(handle.name, i, desired) for i in range(desired)]

    def _read_split_uncached(self, split: Split, columns: Sequence[str],
                             capacity: Optional[int] = None) -> Batch:
        from presto_tpu.catalog.memory import MemoryConnector

        t = self._load(split.table)
        # reuse the memory connector's split reader over the parsed table
        shim = MemoryConnector.__new__(MemoryConnector)
        shim.tables = {split.table: t}
        return MemoryConnector._read_split_uncached(
            shim, split, columns, capacity)
