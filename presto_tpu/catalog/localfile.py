"""Local-file connector — CSV / JSON-lines tables.

Reference: presto-local-file + presto-record-decoder (the csv/json
RowDecoders shared by the kafka/redis connectors). A directory of
<table>.csv / <table>.jsonl / <table>.json files serves as a schema;
decoding happens host-side into engine-native columns (pandas does the
parsing the reference's per-field decoders do), then batches flow
through the device pipeline like any connector's."""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from presto_tpu.batch import Batch, round_up_capacity
from presto_tpu.catalog.memory import DeviceSplitCache, MemoryTable, _infer_type
from presto_tpu.connector import ColumnInfo, Connector, Split, TableHandle

_EXTS = (".csv", ".jsonl", ".json")


class LocalFileConnector(DeviceSplitCache, Connector):
    def __init__(self, directory: str, name: str = "localfile"):
        self.name = name
        self.directory = directory
        self._tables: Dict[str, MemoryTable] = {}
        self._versions: Dict[str, tuple] = {}
        self._lock = threading.Lock()
        self._init_split_cache()

    def _path(self, name: str) -> Optional[str]:
        for ext in _EXTS:
            p = os.path.join(self.directory, name + ext)
            if os.path.exists(p):
                return p
        return None

    def table_names(self) -> List[str]:
        out = []
        for f in sorted(os.listdir(self.directory)):
            base, ext = os.path.splitext(f)
            if ext in _EXTS:
                out.append(base)
        return out

    def _load(self, name: str) -> MemoryTable:
        import pandas as pd

        path = self._path(name)
        if path is None:
            raise KeyError(f"table not found: {name}")
        st = os.stat(path)
        version = (st.st_mtime_ns, st.st_size)
        with self._lock:
            if self._versions.get(name) == version:
                return self._tables[name]
        if path.endswith(".csv"):
            df = pd.read_csv(path)
        else:
            df = pd.read_json(path, lines=path.endswith(".jsonl"))
        data = {c: df[c].to_numpy() for c in df.columns}
        mt = MemoryTable(name, data)
        with self._lock:
            # the pandas read above runs outside the lock by design;
            # racing loaders store (table, version) as an atomic pair, so
            # a stale pair self-heals on the next version probe
            self._tables[name] = mt  # lint: allow(check-then-act)
            self._versions[name] = version  # lint: allow(check-then-act)
        self.invalidate_cache(name)
        return mt

    def get_table(self, name: str) -> TableHandle:
        return self._load(name).handle(self.name)

    def splits(self, handle: TableHandle, desired: int = 1) -> List[Split]:
        return [Split(handle.name, i, desired) for i in range(desired)]

    def split_stats(self, handle: TableHandle, split: Split):
        """Storage-domain min/max over this split's row range (splits are
        contiguous slices of the parsed file) — constrained scans over
        sorted CSV/JSONL data skip whole slices via the generic
        prune_splits, the same elimination the file formats get from
        footer/sidecar stats."""
        import datetime

        from presto_tpu.scan.pruning import SplitStats

        t = self._load(split.table)
        n = next((len(a) for a in t.arrays.values()), 0)
        lo = n * split.part // split.total
        hi = n * (split.part + 1) // split.total
        cols = {}
        for name, arr in t.arrays.items():
            if name in t.struct or t.hi.get(name) is not None:
                continue
            ty = t.types[name]
            sl = arr[lo:hi]
            valid = t.validity.get(name)
            nulls = int((~valid[lo:hi]).sum()) if valid is not None else 0
            if valid is not None:
                sl = sl[valid[lo:hi]]
            if ty.is_string:
                sl = sl[sl >= 0]  # -1 codes are NULLs
            if not len(sl):
                cols[name] = (None, None, nulls)
                continue
            mn, mx = sl.min(), sl.max()
            if ty.is_string:
                d = t.dicts.get(name)
                if d is None:
                    continue
                mn, mx = str(d.values[mn]), str(d.values[mx])
            elif ty.name == "date":
                mn = datetime.date.fromordinal(719163 + int(mn))
                mx = datetime.date.fromordinal(719163 + int(mx))
            else:
                mn, mx = mn.item(), mx.item()
            cols[name] = (mn, mx, nulls)
        return SplitStats(max(hi - lo, 0), cols)

    def _read_split_uncached(self, split: Split, columns: Sequence[str],
                             capacity: Optional[int] = None) -> Batch:
        from presto_tpu.catalog.memory import MemoryConnector

        t = self._load(split.table)
        # reuse the memory connector's split reader over the parsed table
        shim = MemoryConnector.__new__(MemoryConnector)
        shim.tables = {split.table: t}
        return MemoryConnector._read_split_uncached(
            shim, split, columns, capacity)
