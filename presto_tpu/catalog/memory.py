"""In-memory connector — tables registered from host arrays / DataFrames.

Analog of presto-memory (the test/demo connector) and the primary fixture
for the engine's own tests (the role presto-tpch + presto-memory play in
AbstractTestQueries setups).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

try:
    import pandas as pd
except ImportError:  # pandas is effectively always present; stay importable
    pd = None

from presto_tpu.batch import Batch, round_up_capacity
from presto_tpu.connector import (
    ColumnInfo,
    Connector,
    ConnectorIndex,
    Split,
    TableHandle,
)
from presto_tpu.dictionary import Dictionary
from presto_tpu.types import (
    BIGINT,
    BOOLEAN,
    DATE,
    DOUBLE,
    ArrayType,
    DecimalType,
    INTEGER,
    MapType,
    Type,
    VARCHAR,
)


def _is_null(v) -> bool:
    """None, pandas' NA scalar, or the float NaN pandas uses for missing
    object values."""
    if v is None or (isinstance(v, float) and np.isnan(v)):
        return True
    return v is pd.NA if pd is not None else False


def _infer_type(arr: np.ndarray) -> Type:
    if arr.dtype == np.bool_:
        return BOOLEAN
    if np.issubdtype(arr.dtype, np.integer):
        return BIGINT if arr.dtype.itemsize > 4 else INTEGER
    if np.issubdtype(arr.dtype, np.floating):
        return DOUBLE
    if arr.dtype.kind == "O":
        # nullable columns arrive as object arrays; infer from the first
        # non-null value (None-only columns default to varchar). pandas
        # represents missing values in object columns as float NaN, so NaN
        # counts as null here, not as a double.
        first = next((v for v in arr if not _is_null(v)), None)
        if isinstance(first, bool):
            return BOOLEAN
        if isinstance(first, (int, np.integer)):
            return BIGINT
        if isinstance(first, (float, np.floating)):
            return DOUBLE
        if isinstance(first, (bytes, bytearray)):
            from presto_tpu.types import VARBINARY

            return VARBINARY
        if isinstance(first, (list, tuple)):
            elems = [e for v in arr if isinstance(v, (list, tuple))
                     for e in v if e is not None]
            if not elems:
                et = BIGINT
            elif isinstance(elems[0], str):
                et = VARCHAR
            else:
                et = _infer_type(np.asarray(elems))
            return ArrayType(et)
        if isinstance(first, dict):
            ks = [k for v in arr if isinstance(v, dict) for k in v]
            vs = [x for v in arr if isinstance(v, dict)
                  for x in v.values() if x is not None]
            kt = VARCHAR if (ks and isinstance(ks[0], str)) else BIGINT
            vt = _infer_type(np.asarray(vs)) if vs else BIGINT
            return MapType(kt, vt)
        return VARCHAR
    if arr.dtype.kind in ("U", "S"):
        return VARCHAR
    if arr.dtype.kind == "M":  # datetime64
        return DATE
    raise TypeError(f"cannot infer SQL type for {arr.dtype}")


def _batches_to_host(batches):
    """Device result batches → engine-native host columns for the write
    path: {name: (values, validity|None, hi|None, Dictionary|None)}.
    Structural (ARRAY/MAP) columns decode to object arrays of python
    lists/dicts (re-encoded by the target table) — marker tuple
    ("structural", object_array). Live rows compact; padding drops."""
    batches = list(batches)
    if not batches:
        return [], [], {}
    if len(batches) > 1:
        # codes must share one dictionary before concatenation
        from presto_tpu.exec.runtime import _unify_batch_dicts

        batches = _unify_batch_dicts(batches)
    names = list(batches[0].names)
    types = list(batches[0].types)
    out = {}
    for i, name in enumerate(names):
        if isinstance(types[i], (ArrayType, MapType)):
            objs = [
                b._structural_to_py(name, types[i], b.columns[i],
                                    np.asarray(b.live), True)
                for b in batches
            ]
            out[name] = ("structural", np.concatenate(objs))
            continue
        vals, valids, his = [], [], []
        any_valid = any_hi = False
        d = None
        for b in batches:
            live = np.asarray(b.live)
            c = b.columns[i]
            vals.append(np.asarray(c.values)[live])
            if c.validity is not None:
                any_valid = True
                valids.append(np.asarray(c.validity)[live])
            else:
                valids.append(np.ones(int(live.sum()), bool))
            if c.hi is not None:
                any_hi = True
                his.append(np.asarray(c.hi)[live])
            else:
                his.append(np.zeros(int(live.sum()), np.int64))
            if name in b.dicts:
                if d is not None and b.dicts[name] is not d:
                    d = Dictionary.merge(d, b.dicts[name])
                elif d is None:
                    d = b.dicts[name]
        out[name] = (
            np.concatenate(vals) if vals else np.zeros(0, types[i].dtype),
            np.concatenate(valids) if any_valid else None,
            np.concatenate(his) if any_hi else None,
            d,
        )
    return names, types, out


def _encode_structural(col: str, arr: np.ndarray, t: Type, dicts: dict):
    """Object array of python lists/dicts → dense padded planes:
    (values2d, sizes, evalid|None, keys2d|None, row_validity|None).
    String elements dictionary-encode (dicts[col], map keys under
    col+'#keys') — the host-side mirror of the engine's structural
    Column layout."""
    n = len(arr)
    rvalid = np.array([not _is_null(v) for v in arr])
    row_validity = None if rvalid.all() else rvalid

    if isinstance(t, MapType):
        cells = [list(v.items()) if isinstance(v, dict) else [] for v in arr]
    else:
        cells = [list(v) if isinstance(v, (list, tuple)) else [] for v in arr]
    sizes = np.array([len(c) for c in cells], np.int32)
    w = int(sizes.max()) if n else 0

    def encode_plane(get, et, dict_key):
        vals = np.zeros((n, w), dtype=et.dtype)
        evalid = np.ones((n, w), dtype=bool)
        if et.is_string:
            uniq = sorted({get(e) for c in cells for e in c
                           if get(e) is not None})
            d, _ = Dictionary.encode(np.asarray(uniq, dtype=str))
            dicts[dict_key] = d
        for i, c in enumerate(cells):
            for j, e in enumerate(c):
                v = get(e)
                if v is None:
                    evalid[i, j] = False
                    continue
                if et.is_string:
                    vals[i, j] = dicts[dict_key].code_of(str(v))
                elif isinstance(et, DecimalType):
                    vals[i, j] = int(round(float(v) * 10 ** et.scale))
                else:
                    vals[i, j] = v
        return vals, (None if evalid.all() else evalid)

    if isinstance(t, MapType):
        keys2d, _ = encode_plane(lambda kv: kv[0], t.key, col + "#keys")
        vals2d, evalid = encode_plane(lambda kv: kv[1], t.value, col)
        return vals2d, sizes, evalid, keys2d, row_validity
    vals2d, evalid = encode_plane(lambda e: e, t.element, col)
    return vals2d, sizes, evalid, None, row_validity


class MemoryTable:
    def __init__(self, name: str, data: Dict[str, np.ndarray],
                 types: Optional[Dict[str, Type]] = None,
                 primary_key: Optional[List[str]] = None,
                 index_keys: Optional[List[List[str]]] = None):
        self.name = name
        # extra keyed-lookup column sets beyond the primary key
        # (ConnectorIndex SPI; see MemoryConnector.get_index)
        self.index_keys = [list(k) for k in (index_keys or [])]
        self.types: Dict[str, Type] = {}
        self.arrays: Dict[str, np.ndarray] = {}
        self.validity: Dict[str, Optional[np.ndarray]] = {}
        self.dicts: Dict[str, Dictionary] = {}
        # long-decimal high limbs (value = hi·2³² + lo), present only for
        # columns written from precision>18 results (CTAS over sums)
        self.hi: Dict[str, Optional[np.ndarray]] = {}
        # structural planes: col -> (sizes, evalid|None, keys2d|None);
        # the [n, W] value plane lives in self.arrays
        self.struct: Dict[str, tuple] = {}
        self.primary_key = primary_key
        n = None
        for col, raw in data.items():
            # pre-encoded string columns: (Dictionary, codes) — avoids
            # materializing millions of python strings in generators
            if isinstance(raw, tuple) and len(raw) == 2 and isinstance(raw[0], Dictionary):
                d, codes = raw
                n = len(codes) if n is None else n
                self.dicts[col] = d
                self.types[col] = VARCHAR
                self.arrays[col] = np.ascontiguousarray(codes.astype(np.int32))
                self.validity[col] = None
                continue
            arr = np.asarray(raw, dtype=object) if isinstance(raw, list) else np.asarray(raw)
            n = len(arr) if n is None else n
            t = (types or {}).get(col) or _infer_type(arr)
            if isinstance(t, (ArrayType, MapType)):
                vals2d, sizes, evalid, keys2d, rvalid = _encode_structural(
                    col, arr, t, self.dicts)
                self.types[col] = t
                self.arrays[col] = vals2d
                self.validity[col] = rvalid
                self.struct[col] = (sizes, evalid, keys2d)
                continue
            valid = None
            if arr.dtype == object:
                nulls = np.array([_is_null(v) for v in arr])
                if nulls.any():
                    valid = ~nulls
                    arr = np.where(nulls, "" if t.is_string else 0, arr)
            if t.is_string:
                if t.name == "varbinary":
                    # bytes ride the latin-1 bijection into the dictionary
                    arr = np.array(
                        [v.decode("latin-1")
                         if isinstance(v, (bytes, bytearray)) else str(v)
                         for v in arr], dtype=object)
                elif t.name in ("ipaddress", "ipprefix"):
                    # ingest text (or 4/16 raw bytes) as canonical entries;
                    # null slots were masked above and stay ""
                    from presto_tpu.expr import ip as _ip

                    def _canon(v, _pfx=(t.name == "ipprefix")):
                        if v == "":
                            return ""
                        if isinstance(v, (bytes, bytearray)):
                            if _pfx:
                                # 17-byte canonical form only (16-byte
                                # address bytes carry no prefix length)
                                e = v.decode("latin-1")
                                s = e if _ip.format_prefix(e) else None
                            else:
                                s = _ip.address_from_bytes(
                                    v.decode("latin-1"))
                        elif _pfx:
                            s = _ip.parse_prefix(str(v))
                        else:
                            s = _ip.parse_address(str(v))
                        if s is None:
                            raise ValueError(f"invalid {t.name}: {v!r}")
                        return s

                    arr = np.array([_canon(v) for v in arr], dtype=object)
                # canonical-byte types may carry trailing NULs — keep
                # object dtype into encode (dictionary.safe_str_array).
                # Plain varchar keeps the C-level astype(str) fast path:
                # a per-element NUL scan on multi-million-row ingest
                # would be pure overhead there
                nul_risky = t.name in ("varbinary", "ipaddress",
                                       "ipprefix", "tdigest(double)")
                d, codes = Dictionary.encode(
                    arr if arr.dtype == object and nul_risky
                    else arr.astype(str))
                if valid is not None:
                    codes = np.where(valid, codes, -1)
                self.dicts[col] = d
                arr = codes
            elif t is DATE and arr.dtype.kind == "M":
                arr = arr.astype("datetime64[D]").astype(np.int64)
            elif isinstance(t, DecimalType):
                if np.issubdtype(arr.dtype, np.floating):
                    arr = np.round(arr.astype(np.float64) * 10 ** t.scale).astype(np.int64)
                elif arr.dtype == object:
                    # list ingest arrives as object: scale each value
                    # exactly (astype(int64) would TRUNCATE floats first)
                    import decimal as _dec

                    arr = np.array(
                        [int(_dec.Decimal(str(v)).scaleb(t.scale)
                             .to_integral_value(
                                 rounding=_dec.ROUND_HALF_UP))
                         for v in arr], dtype=np.int64)
                else:
                    arr = arr.astype(np.int64) * 10 ** t.scale
            self.types[col] = t
            self.arrays[col] = np.ascontiguousarray(arr.astype(t.dtype))
            self.validity[col] = valid
        self.num_rows = n or 0

    def column_stats(self, col: str) -> "ColumnStats":
        """NDV / null-fraction / min-max for the CBO (computed lazily and
        cached — the analog of ANALYZE writing table statistics; generator
        and user tables are immutable once registered). NDV above the exact
        window is sample-extrapolated (GEE-style: keys saturate to n)."""
        cache = self.__dict__.setdefault("_stats_cache", {})
        if col in cache:
            return cache[col]
        from presto_tpu.connector import ColumnStats

        arr = self.arrays[col]
        valid = self.validity.get(col)
        n = len(arr)
        nf = 0.0 if valid is None else float((~valid).sum()) / max(n, 1)
        if col in self.dicts:
            cs = ColumnStats(ndv=float(len(self.dicts[col])), null_fraction=nf)
        elif n == 0:
            cs = ColumnStats(ndv=0.0, null_fraction=nf)
        else:
            vals = arr if valid is None else arr[valid]
            if len(vals) == 0:
                cs = ColumnStats(ndv=0.0, null_fraction=nf)
            else:
                mn, mx = float(vals.min()), float(vals.max())
                hist = None
                if mx > mn and arr.ndim == 1 and np.issubdtype(
                        arr.dtype, np.number):
                    sample = (vals if len(vals) <= 2_000_000
                              else vals[:: len(vals) // 1_000_000])
                    edges = np.quantile(sample.astype(np.float64),
                                        np.linspace(0.0, 1.0, 33))
                    hist = tuple(float(e) for e in edges)
                if (self.primary_key and self.primary_key == [col]):
                    ndv = float(len(vals))
                elif len(vals) <= 2_000_000:
                    ndv = float(len(np.unique(vals)))
                else:
                    samp = vals[:: max(1, len(vals) // 500_000)]
                    sndv = float(len(np.unique(samp)))
                    if sndv > 0.8 * len(samp):
                        ndv = float(len(vals))  # key-like: saturates
                    else:
                        ndv = sndv  # value-domain-like: sample saw it all
                cs = ColumnStats(ndv=ndv, null_fraction=nf,
                                 min_value=mn, max_value=mx,
                                 histogram=hist)
        cache[col] = cs
        return cs

    def handle(self, catalog: str) -> TableHandle:
        return TableHandle(
            catalog=catalog,
            name=self.name,
            columns=[ColumnInfo(c, t, self.dicts.get(c), self.column_stats(c))
                     for c, t in self.types.items()],
            row_count=float(self.num_rows),
            primary_key=self.primary_key,
        )


class DeviceSplitCache:
    """Device-resident split cache mixin: scans of the same table slice
    re-serve the already-uploaded device arrays instead of re-staging
    host→device per query (the HBM-residency analog of the reference
    keeping hot pages in the buffer/OS cache; host→device PCIe is our
    dominant scan cost). Bounded LRU by device bytes; immutable Batches are
    safe to share. Subclasses implement `_read_split_uncached`."""

    split_cache_bytes: int = 6 << 30

    def _init_split_cache(self):
        import threading
        from collections import OrderedDict

        self._split_cache: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._split_cache_used = 0
        self._cache_epoch = 0
        # worker task threads share the connector; guard the LRU + counter
        self._split_cache_lock = threading.Lock()

    def invalidate_cache(self, table: Optional[str] = None):
        with self._split_cache_lock:
            self._cache_epoch = getattr(self, "_cache_epoch", 0) + 1
            if table is None:
                self._split_cache.clear()
                self._split_cache_used = 0
                return
            for k in [k for k in self._split_cache if k[0] == table]:
                _, nbytes = self._split_cache.pop(k)
                self._split_cache_used -= nbytes

    def read_split(self, split: Split, columns: Sequence[str],
                   capacity: Optional[int] = None) -> Batch:
        key = (split.table, split.part, split.total, tuple(columns), capacity)
        with self._split_cache_lock:
            epoch = getattr(self, "_cache_epoch", 0)
            hit = self._split_cache.get(key)
            if hit is not None:
                self._split_cache.move_to_end(key)
                return hit[0]
        b = self._read_split_uncached(split, columns, capacity)
        from presto_tpu.memory import batch_device_bytes

        nbytes = batch_device_bytes(b)
        if nbytes <= self.split_cache_bytes:
            with self._split_cache_lock:
                # an invalidation while we were reading means `b` may be
                # stale — don't resurrect it into the fresh cache
                if (getattr(self, "_cache_epoch", 0) == epoch
                        and key not in self._split_cache):
                    self._split_cache[key] = (b, nbytes)
                    self._split_cache_used += nbytes
                    while self._split_cache_used > self.split_cache_bytes:
                        _, (_, freed) = self._split_cache.popitem(last=False)
                        self._split_cache_used -= freed
        return b


class MemoryConnector(DeviceSplitCache, Connector):
    def __init__(self, name: str = "memory"):
        self.name = name
        self.tables: Dict[str, MemoryTable] = {}
        self._init_split_cache()

    def add_table(self, name: str, data, types=None, primary_key=None,
                  index_keys=None):
        import pandas as pd

        if isinstance(data, pd.DataFrame):
            cols = {}
            for c in data.columns:
                s = data[c]
                if pd.api.types.is_extension_array_dtype(s.dtype):
                    # nullable extension dtypes (Int64, boolean, …):
                    # to_numpy() would smear NA into float NaN VALUES —
                    # keep them typed and NULL-masked instead
                    cols[c] = s.astype(object).to_numpy()
                else:
                    cols[c] = s.to_numpy()
            data = cols
        self.tables[name] = MemoryTable(name, data, types, primary_key,
                                        index_keys=index_keys)
        self.invalidate_cache(name)

    def add_generated(self, name: str, data: Dict[str, object],
                      types: Optional[Dict[str, Type]] = None,
                      primary_key: Optional[List[str]] = None):
        """Register a generator-produced table. A column value may be a
        plain array or a ("raw_decimal", DecimalType, unscaled_int_array)
        marker for pre-scaled decimal columns that must not be rescaled by
        MemoryTable's float→cents conversion. Column order is preserved."""
        plain, raw = {}, {}
        for col, v in data.items():
            if isinstance(v, tuple) and len(v) == 3 and v[0] == "raw_decimal":
                raw[col] = (v[1], v[2])
            else:
                plain[col] = v
        mt = MemoryTable(name, plain, types, primary_key=primary_key)
        for col, (t, arr) in raw.items():
            mt.types[col] = t
            mt.arrays[col] = arr.astype(np.int64)
            mt.validity[col] = None
            # an all-raw table still has rows (MemoryTable only counted
            # the plain columns)
            mt.num_rows = max(mt.num_rows, len(arr))
        mt.arrays = {c: mt.arrays[c] for c in data.keys()}
        mt.types = {c: mt.types[c] for c in data.keys()}
        self.tables[name] = mt
        self.invalidate_cache(name)

    def table_names(self):
        return list(self.tables)

    def get_table(self, name: str) -> TableHandle:
        if name not in self.tables:
            raise KeyError(f"table not found: {name}")
        return self.tables[name].handle(self.name)

    def get_index(self, handle, key_columns):
        """Keyed lookup over an EXPLICITLY declared index key set
        (reference: presto-tests IndexedTpchPlugin's fake connector
        indexes — here real, backed by a host hash map). Deliberately NOT
        implied by primary_key: exposing an index makes the planner prefer
        per-batch lookups over a hash build, which only pays off on tables
        designed for point access."""
        t = self.tables.get(handle.name)
        if t is None:
            return None
        if any(set(key_columns) == set(k)
               for k in getattr(t, "index_keys", [])):
            return _MemoryIndex(t, list(key_columns))
        return None

    def splits(self, handle: TableHandle, desired: int = 1) -> List[Split]:
        return [Split(handle.name, i, desired) for i in range(desired)]

    # -- write path (reference: MemoryPageSinkProvider — pages append to
    # the in-memory table; TableFinish returns the row count) -------------

    def create_table_from(self, name: str, batches: Sequence[Batch],
                          if_not_exists: bool = False,
                          properties: Optional[dict] = None) -> int:
        if properties:
            raise ValueError(
                "memory connector does not support table properties")
        if name in self.tables:
            if if_not_exists:
                return 0
            raise ValueError(f"table already exists: {name}")
        names, types, data = _batches_to_host(batches)
        mt = MemoryTable(name, {}, {})
        mt.types = dict(zip(names, types))
        rows = 0
        for col, payload in data.items():
            if isinstance(payload[0], str) and payload[0] == "structural":
                obj = payload[1]
                vals2d, sizes, evalid, keys2d, rvalid = _encode_structural(
                    col, obj, mt.types[col], mt.dicts)
                mt.arrays[col] = vals2d
                mt.validity[col] = rvalid
                mt.struct[col] = (sizes, evalid, keys2d)
                rows = len(obj)
                continue
            vals, valid, hi, d = payload
            mt.arrays[col] = vals
            mt.validity[col] = valid
            mt.hi[col] = hi
            if d is not None:
                mt.dicts[col] = d
            rows = len(vals)
        mt.num_rows = rows
        self.tables[name] = mt
        self.invalidate_cache(name)
        return rows

    def insert_into(self, name: str, batches: Sequence[Batch]) -> int:
        if name not in self.tables:
            raise KeyError(f"table not found: {name}")
        mt = self.tables[name]
        names, types, data = _batches_to_host(batches)
        if any(isinstance(t, (ArrayType, MapType)) for t in types) or mt.struct:
            raise NotImplementedError(
                "INSERT INTO with ARRAY/MAP columns is not supported yet "
                "(CTAS is)")
        target_cols = list(mt.arrays.keys())
        if len(names) != len(target_cols):
            raise ValueError(
                f"INSERT arity mismatch: {len(names)} columns vs "
                f"{len(target_cols)} in {name}")
        # positional matching (standard INSERT ... SELECT semantics):
        # the i-th source column feeds the i-th target column
        for src, col, t in zip(names, target_cols, types):
            if t.name != mt.types[col].name:
                raise ValueError(
                    f"INSERT column {col} type mismatch: {t} vs {mt.types[col]}")
        rows = 0
        for src, col in zip(names, target_cols):
            vals, valid, hi, d = data[src]
            old_n = mt.num_rows
            if d is not None and mt.dicts.get(col) is None:
                # string column created without a dictionary (e.g. CTAS of
                # all-NULL varchar): adopt the incoming one so the appended
                # codes stay decodable
                mt.dicts[col] = d
            elif d is not None and d is not mt.dicts[col]:
                # re-encode incoming codes into the table's dictionary space
                m = Dictionary.merge(mt.dicts[col], d)
                if m is not mt.dicts[col]:
                    remap_old = np.concatenate(
                        [[-1], np.searchsorted(m.values, mt.dicts[col].values)]
                    ).astype(np.int32)
                    mt.arrays[col] = remap_old[mt.arrays[col] + 1]
                    mt.dicts[col] = m
                remap_new = np.asarray(d.map_to(m))
                vals = remap_new[vals.astype(np.int32) + 1]
            mt.arrays[col] = np.concatenate([mt.arrays[col], vals])
            if valid is not None or mt.validity.get(col) is not None:
                old_v = (mt.validity.get(col) if mt.validity.get(col) is not None
                         else np.ones(old_n, bool))
                new_v = valid if valid is not None else np.ones(len(vals), bool)
                mt.validity[col] = np.concatenate([old_v, new_v])
            if hi is not None or mt.hi.get(col) is not None:
                old_h = (mt.hi.get(col) if mt.hi.get(col) is not None
                         else np.zeros(old_n, np.int64))
                new_h = hi if hi is not None else np.zeros(len(vals), np.int64)
                mt.hi[col] = np.concatenate([old_h, new_h])
            rows = len(vals)
        mt.num_rows += rows
        mt.__dict__.pop("_stats_cache", None)
        self.invalidate_cache(name)
        return rows

    def drop_table(self, name: str, if_exists: bool = False) -> None:
        if name not in self.tables:
            if if_exists:
                return
            raise KeyError(f"table not found: {name}")
        del self.tables[name]
        self.invalidate_cache(name)

    def create_empty(self, name: str, cols, if_not_exists: bool = False):
        """CREATE TABLE name (schema) — zero rows, explicit types."""
        if name in self.tables:
            if if_not_exists:
                return
            raise ValueError(f"table already exists: {name}")
        data = {
            c: (np.array([], dtype=object) if t.is_string
                else np.zeros(0, dtype=t.dtype))
            for c, t in cols
        }
        self.tables[name] = MemoryTable(name, data, dict(cols))
        self.invalidate_cache(name)

    def truncate_table(self, name: str):
        mt = self.tables.get(name)
        if mt is None:
            raise KeyError(f"table not found: {name}")
        cols = list(mt.types.items())
        del self.tables[name]
        self.create_empty(name, cols)

    def replace_table_from(self, name: str, batches) -> int:
        """DELETE-rewrite target: swap the table for the surviving rows."""
        if name not in self.tables:
            raise KeyError(f"table not found: {name}")
        del self.tables[name]
        return self.create_table_from(name, batches)

    def _read_split_uncached(self, split: Split, columns: Sequence[str],
                             capacity: Optional[int] = None) -> Batch:
        t = self.tables[split.table]
        n = t.num_rows
        lo = n * split.part // split.total
        hi = n * (split.part + 1) // split.total
        scalar_cols = [c for c in columns if c not in t.struct]
        data = {c: t.arrays[c][lo:hi] for c in scalar_cols}
        types = {c: t.types[c] for c in columns}
        b = Batch.from_numpy(data, types,
                             dicts={c: t.dicts[c] for c in scalar_cols
                                    if c in t.dicts},
                             capacity=capacity or round_up_capacity(
                                 max(hi - lo, 1)))
        if len(scalar_cols) < len(columns):
            b = self._attach_structural(b, t, columns, lo, hi)
            b = b.select(list(columns))  # restore requested column order
        # apply column validity / long-decimal high limbs
        import jax.numpy as jnp

        from presto_tpu.batch import Column

        for c in [c for c in columns if c not in t.struct]:
            v = t.validity[c]
            h = t.hi.get(c)
            if v is None and h is None:
                continue
            col = b.column(c)
            vcol = col.validity
            if v is not None:
                pad = np.zeros(b.capacity, dtype=bool)
                pad[: hi - lo] = v[lo:hi]
                vcol = jnp.asarray(pad)
            hcol = None
            if h is not None:
                hpad = np.zeros(b.capacity, dtype=np.int64)
                hpad[: hi - lo] = h[lo:hi]
                hcol = jnp.asarray(hpad)
            idx = b.names.index(c)
            cols = list(b.columns)
            cols[idx] = Column(col.values, vcol, hcol)
            b = Batch(b.names, b.types, cols, b.live, b.dicts)
        return b

    @staticmethod
    def _attach_structural(b: Batch, t: MemoryTable,
                           columns: Sequence[str], lo: int, hi: int) -> Batch:
        """Append the structural (ARRAY/MAP) columns' padded planes to a
        batch built from the scalar columns."""
        import jax.numpy as jnp

        from presto_tpu.batch import Column

        cap = b.capacity
        n = hi - lo

        def pad1(arr, dtype):
            buf = np.zeros(cap, dtype=dtype)
            buf[:n] = arr
            return jnp.asarray(buf)

        def pad2(arr, dtype):
            buf = np.zeros((cap, arr.shape[1]), dtype=dtype)
            buf[:n] = arr
            return jnp.asarray(buf)

        names = list(b.names)
        types = list(b.types)
        cols = list(b.columns)
        dicts = dict(b.dicts)
        live = b.live
        if not any(c not in t.struct for c in columns):
            lv = np.zeros(cap, bool)
            lv[:n] = True
            live = jnp.asarray(lv)
        for c in columns:
            if c not in t.struct:
                continue
            sizes, evalid, keys2d = t.struct[c]
            vals = t.arrays[c][lo:hi]
            rvalid = t.validity.get(c)
            names.append(c)
            types.append(t.types[c])
            cols.append(Column(
                pad2(vals, t.types[c].dtype),
                None if rvalid is None else pad1(rvalid[lo:hi], bool),
                None,
                pad1(sizes[lo:hi], np.int32),
                None if evalid is None else pad2(evalid[lo:hi], bool),
                None if keys2d is None else pad2(
                    keys2d[lo:hi], keys2d.dtype),
            ))
            if c in t.dicts:
                dicts[c] = t.dicts[c]
            if c + "#keys" in t.dicts:
                dicts[c + "#keys"] = t.dicts[c + "#keys"]
        return Batch(names, types, cols, live, dicts)


class _MemoryIndex(ConnectorIndex):
    """Host hash map: decoded key tuple → row positions. The lookup
    materializes only the matching rows as one Batch (reference:
    operator/index/IndexLoader.java — streamed probe keys load an
    index snapshot instead of the whole table)."""

    def __init__(self, table: MemoryTable, key_columns):
        self.t = table
        self.keys = key_columns
        self._map = None

    def _decoded(self, col: str) -> np.ndarray:
        arr = self.t.arrays[col]
        d = self.t.dicts.get(col)
        if d is not None:
            return np.asarray(d.values, dtype=object)[arr]
        return arr

    def _ensure_map(self):
        if self._map is not None:
            return
        cols = [self._decoded(c) for c in self.keys]
        n = len(cols[0])
        valid = np.ones(n, dtype=bool)
        for c in self.keys:
            v = self.t.validity.get(c)
            if v is not None:
                valid &= v
        m: dict = {}
        for i in np.nonzero(valid)[0]:
            k = tuple(col[i] for col in cols)
            m.setdefault(k, []).append(int(i))
        self._map = m

    def lookup(self, keys, columns, capacity=None) -> Batch:
        self._ensure_map()
        t = self.t
        for c in columns:
            if c in t.struct:
                raise NotImplementedError(
                    "index lookup over structural columns")
        probe = [np.asarray(keys[c]) for c in self.keys]
        pos: list = []
        seen = set()
        for row in zip(*probe):
            k = tuple(x.item() if hasattr(x, "item") else x for x in row)
            if k in seen:
                continue
            seen.add(k)
            pos.extend(self._map.get(k, ()))
        pos = np.asarray(sorted(pos), dtype=np.int64)
        data = {c: t.arrays[c][pos] for c in columns}
        cap = capacity or round_up_capacity(max(len(pos), 1))
        b = Batch.from_numpy(
            data, {c: t.types[c] for c in columns},
            dicts={c: t.dicts[c] for c in columns if c in t.dicts},
            capacity=cap)
        if len(pos) == 0:
            b = b.with_live(np.zeros(cap, dtype=bool))
        import jax.numpy as jnp

        from presto_tpu.batch import Column

        for c in columns:
            v = t.validity.get(c)
            h = t.hi.get(c)
            if v is None and h is None:
                continue
            col = b.column(c)
            vcol = col.validity
            if v is not None:
                pad = np.zeros(b.capacity, dtype=bool)
                pad[: len(pos)] = v[pos]
                vcol = jnp.asarray(pad)
            hcol = None
            if h is not None:
                hpad = np.zeros(b.capacity, dtype=np.int64)
                hpad[: len(pos)] = h[pos]
                hcol = jnp.asarray(hpad)
            idx = b.names.index(c)
            cols2 = list(b.columns)
            cols2[idx] = Column(col.values, vcol, hcol)
            b = Batch(b.names, b.types, cols2, b.live, b.dicts)
        return b
