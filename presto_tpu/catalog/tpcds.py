"""TPC-DS data-generator connector.

Analog of presto-tpcds (TpcdsConnectorFactory / TpcdsMetadata over the
teradata tpcds generator): an in-process, deterministic, scale-factor-
parameterized TPC-DS dataset served as columnar batches.

Covers the retail-sales star needed by the benchmark suite's Q64 config and
the common TPC-DS query shapes: store_sales / store_returns fact tables plus
the date_dim, store, item, customer, customer_address,
customer_demographics, household_demographics, income_band and promotion
dimensions. Cardinalities follow the TPC-DS scaling table (store_sales
~2.88M rows/SF; dimension sizes are the spec's discrete per-SF values,
geometrically interpolated between published points). Values are generated
with seeded numpy following the spec's domains — like the TPC-H connector it
is deterministic but not bit-compatible with dsdgen.

Referential integrity is exact: every fact-table surrogate key joins to its
dimension (ss_sold_date_sk ⊆ d_date_sk etc.), and store_returns is a subset
of store_sales items, so star-join plans behave like the real dataset.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from presto_tpu.catalog.memory import MemoryConnector
from presto_tpu.catalog.tpch import _money  # same decimal-cents helper
from presto_tpu.types import DATE, DecimalType

_D72 = DecimalType(7, 2)

# TPC-DS scaling table (spec table 3-2), published points per SF; other SFs
# interpolate geometrically. store_sales scales linearly.
_SCALE_POINTS = {
    # sf: (customer, item, store, promotion)
    1: (100_000, 18_000, 12, 300),
    10: (500_000, 102_000, 42, 500),
    100: (2_000_000, 204_000, 402, 1000),
    1000: (12_000_000, 300_000, 1002, 1500),
}

_DATE_DIM_ROWS = 73_049  # fixed: 1900-01-01 .. 2100-01-01
_D_DATE_SK0 = 2_415_022  # julian day of 1900-01-01 (spec's first d_date_sk)
_EPOCH_1900 = -25_567    # days from 1970-01-01 to 1900-01-01


def _interp(sf: float, idx: int) -> int:
    pts = sorted(_SCALE_POINTS)
    if sf <= pts[0]:
        lo = hi = pts[0]
    elif sf >= pts[-1]:
        lo = hi = pts[-1]
    else:
        lo = max(p for p in pts if p <= sf)
        hi = min(p for p in pts if p >= sf)
    a, b = _SCALE_POINTS[lo][idx], _SCALE_POINTS[hi][idx]
    if lo == hi:
        base = a
    else:
        import math

        t = (math.log(sf) - math.log(lo)) / (math.log(hi) - math.log(lo))
        base = a * (b / a) ** t
    return max(1, int(base))


class TpcdsGenerator:
    def __init__(self, sf: float = 1.0, seed: int = 20030101):
        self.sf = sf
        self.seed = seed
        self.n_customer = _interp(sf, 0)
        self.n_item = _interp(sf, 1)
        self.n_store = _interp(sf, 2)
        self.n_promo = _interp(sf, 3)
        self.n_store_sales = int(2_880_404 * sf)
        self.n_cdemo = 1_920_800  # fixed per spec
        self.n_hdemo = 7_200     # fixed
        self.n_income = 20       # fixed
        self.n_address = max(1, self.n_customer // 2)

    def _rng(self, salt: int) -> np.random.Generator:
        return np.random.default_rng(self.seed + salt)

    def date_dim(self) -> Dict[str, np.ndarray]:
        sk = _D_DATE_SK0 + np.arange(_DATE_DIM_ROWS)
        days = _EPOCH_1900 + np.arange(_DATE_DIM_ROWS)
        dt = days.astype("datetime64[D]")
        years = dt.astype("datetime64[Y]").astype(int) + 1970
        months = dt.astype("datetime64[M]").astype(int) % 12 + 1
        dom = (dt - dt.astype("datetime64[M]")).astype(int) + 1
        dow = (days + 4) % 7  # 1970-01-01 was a Thursday
        return {
            "d_date_sk": sk,
            "d_date": days,
            "d_year": years.astype(np.int64),
            "d_moy": months.astype(np.int64),
            "d_dom": dom.astype(np.int64),
            "d_dow": dow.astype(np.int64),
            "d_qoy": ((months - 1) // 3 + 1).astype(np.int64),
            "d_week_seq": (np.arange(_DATE_DIM_ROWS) // 7 + 1).astype(np.int64),
        }

    def store(self) -> Dict[str, np.ndarray]:
        n = self.n_store
        rng = self._rng(1)
        return {
            "s_store_sk": np.arange(1, n + 1),
            "s_store_id": np.array([f"AAAAAAAA{str(i).zfill(8)}" for i in range(1, n + 1)], object),
            "s_store_name": np.array([f"store#{i % 30}" for i in range(1, n + 1)], object),
            "s_number_employees": rng.integers(200, 301, n),
            "s_floor_space": rng.integers(5_000_000, 10_000_001, n),
            "s_state": np.array([["TN", "CA", "TX", "NY", "OH"][i % 5] for i in range(n)], object),
            "s_market_id": rng.integers(1, 11, n),
            "s_zip": np.array([str(35000 + (i * 97) % 60000)
                               for i in range(n)], object),
        }

    def item(self) -> Dict[str, np.ndarray]:
        n = self.n_item
        rng = self._rng(2)
        cats = ["Books", "Children", "Electronics", "Home", "Jewelry",
                "Men", "Music", "Shoes", "Sports", "Women"]
        return {
            "i_item_sk": np.arange(1, n + 1),
            "i_item_id": np.array([f"AAAAAAAA{str(i).zfill(8)}" for i in range(1, n + 1)], object),
            "i_product_name": np.array([f"product{i % 25_000}" for i in range(1, n + 1)], object),
            "i_current_price": ("raw72", _money(rng, 0.09, 99.99, n)),
            "i_wholesale_cost": ("raw72", _money(rng, 0.02, 88.0, n)),
            "i_brand_id": rng.integers(1, 1001, n) * 10000 + rng.integers(1, 10, n),
            "i_brand": np.array([f"brand#{i % 1000}" for i in range(n)], object),
            "i_category": np.array([cats[i % len(cats)] for i in range(n)], object),
            "i_category_id": (np.arange(n) % len(cats) + 1).astype(np.int64),
            "i_manufact_id": rng.integers(1, 1001, n),
            "i_size": np.array([["small", "medium", "large", "extra large", "economy", "N/A", "petite"][i % 7] for i in range(n)], object),
            "i_color": np.array([["red", "green", "blue", "white", "black", "ivory", "khaki", "salmon"][i % 8] for i in range(n)], object),
        }

    def customer(self) -> Dict[str, np.ndarray]:
        n = self.n_customer
        rng = self._rng(3)
        return {
            "c_customer_sk": np.arange(1, n + 1),
            "c_customer_id": np.array([f"AAAAAAAA{str(i).zfill(8)}" for i in range(1, n + 1)], object),
            "c_current_cdemo_sk": rng.integers(1, self.n_cdemo + 1, n),
            "c_current_hdemo_sk": rng.integers(1, self.n_hdemo + 1, n),
            "c_current_addr_sk": rng.integers(1, self.n_address + 1, n),
            "c_first_shipto_date_sk": _D_DATE_SK0 + rng.integers(36_000, 37_000, n),
            "c_birth_year": rng.integers(1924, 1993, n),
            "c_birth_country": np.array([["UNITED STATES", "CANADA", "MEXICO", "GERMANY", "JAPAN"][i % 5] for i in range(n)], object),
        }

    def customer_address(self) -> Dict[str, np.ndarray]:
        n = self.n_address
        rng = self._rng(4)
        return {
            "ca_address_sk": np.arange(1, n + 1),
            "ca_city": np.array([f"city{i % 700}" for i in range(n)], object),
            "ca_state": np.array([["TN", "CA", "TX", "NY", "OH", "GA", "IL", "WA"][i % 8] for i in range(n)], object),
            "ca_zip": np.array([str(10000 + (i * 7) % 89999) for i in range(n)], object),
            "ca_country": np.array(["United States"] * n, object),
            "ca_gmt_offset": rng.choice([-8, -7, -6, -5], n).astype(np.int64),
        }

    def customer_demographics(self) -> Dict[str, np.ndarray]:
        n = self.n_cdemo
        return {
            "cd_demo_sk": np.arange(1, n + 1),
            "cd_gender": np.array([["M", "F"][i % 2] for i in range(n)], object),
            "cd_marital_status": np.array([["M", "S", "D", "W", "U"][(i // 2) % 5] for i in range(n)], object),
            "cd_education_status": np.array([["Primary", "Secondary", "College", "2 yr Degree", "4 yr Degree", "Advanced Degree", "Unknown"][(i // 10) % 7] for i in range(n)], object),
            "cd_purchase_estimate": ((i0 := np.arange(n)) // 70 % 20 * 500 + 500).astype(np.int64),
            "cd_dep_count": (i0 // 1400 % 7).astype(np.int64),
        }

    def household_demographics(self) -> Dict[str, np.ndarray]:
        n = self.n_hdemo
        return {
            "hd_demo_sk": np.arange(1, n + 1),
            "hd_income_band_sk": (np.arange(n) % self.n_income + 1).astype(np.int64),
            "hd_buy_potential": np.array([[">10000", "5001-10000", "1001-5000", "501-1000", "0-500", "Unknown"][i % 6] for i in range(n)], object),
            "hd_dep_count": (np.arange(n) // 6 % 10).astype(np.int64),
            "hd_vehicle_count": (np.arange(n) // 60 % 5).astype(np.int64),
        }

    def income_band(self) -> Dict[str, np.ndarray]:
        n = self.n_income
        lb = np.arange(n, dtype=np.int64) * 10_000
        return {
            "ib_income_band_sk": np.arange(1, n + 1),
            "ib_lower_bound": lb,
            "ib_upper_bound": lb + 10_000,
        }

    def promotion(self) -> Dict[str, np.ndarray]:
        n = self.n_promo
        return {
            "p_promo_sk": np.arange(1, n + 1),
            "p_promo_id": np.array([f"AAAAAAAA{str(i).zfill(8)}" for i in range(1, n + 1)], object),
            "p_channel_email": np.array([["N", "Y"][i % 10 == 0] for i in range(n)], object),
            "p_channel_tv": np.array([["N", "Y"][i % 7 == 0] for i in range(n)], object),
        }

    # -- remaining dimensions (spec table 3-2 fixed/scaled sizes) ---------

    def time_dim(self) -> Dict[str, np.ndarray]:
        n = 86_400  # fixed: one row per second of day
        sec = np.arange(n, dtype=np.int64)
        return {
            "t_time_sk": sec,
            "t_time": sec,
            "t_hour": sec // 3600,
            "t_minute": sec % 3600 // 60,
            "t_second": sec % 60,
            "t_am_pm": np.array([["AM", "PM"][s >= 43200] for s in
                                 range(0, n, 1)], object),
            "t_shift": np.array(
                [["third", "first", "second"][min(s // 28800, 2)]
                 for s in range(0, n, 1)], object),
        }

    @property
    def n_warehouse(self) -> int:
        return max(1, int(round(5 * max(self.sf, 1) ** 0.5)))

    def warehouse(self) -> Dict[str, np.ndarray]:
        n = self.n_warehouse
        rng = self._rng(10)
        return {
            "w_warehouse_sk": np.arange(1, n + 1),
            "w_warehouse_id": np.array(
                [f"AAAAAAAA{str(i).zfill(8)}" for i in range(1, n + 1)], object),
            "w_warehouse_name": np.array(
                [f"warehouse#{i}" for i in range(n)], object),
            "w_warehouse_sq_ft": rng.integers(50_000, 1_000_001, n),
            "w_state": np.array([["TN", "CA", "TX", "NY", "OH"][i % 5]
                                 for i in range(n)], object),
        }

    def ship_mode(self) -> Dict[str, np.ndarray]:
        n = 20  # fixed
        types = ["EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR", "LIBRARY"]
        carriers = ["UPS", "FEDEX", "AIRBORNE", "USPS", "DHL",
                    "TBS", "ZHOU", "LATVIAN", "MSC", "ALLIANCE"]
        return {
            "sm_ship_mode_sk": np.arange(1, n + 1),
            "sm_ship_mode_id": np.array(
                [f"AAAAAAAA{str(i).zfill(8)}" for i in range(1, n + 1)], object),
            "sm_type": np.array([types[i % 5] for i in range(n)], object),
            "sm_carrier": np.array([carriers[i % 10] for i in range(n)],
                                   object),
        }

    def reason(self) -> Dict[str, np.ndarray]:
        n = max(1, int(round(35 * max(self.sf, 1) ** 0.2)))
        descs = ["Package was damaged", "Stopped working",
                 "Did not get it on time", "Not the product that was ordered",
                 "Parts missing", "Does not work with a product that I have",
                 "Gift exchange", "Did not like the color",
                 "Did not like the model", "Did not like the make",
                 "Did not fit", "Wrong size", "Lost my job",
                 "Found a better price in a store", "Not working any more",
                 "unknown"]
        return {
            "r_reason_sk": np.arange(1, n + 1),
            "r_reason_id": np.array(
                [f"AAAAAAAA{str(i).zfill(8)}" for i in range(1, n + 1)], object),
            "r_reason_desc": np.array([descs[i % len(descs)]
                                       for i in range(n)], object),
        }

    def call_center(self) -> Dict[str, np.ndarray]:
        n = max(1, int(round(6 * max(self.sf, 1) ** 0.3)))
        rng = self._rng(11)
        return {
            "cc_call_center_sk": np.arange(1, n + 1),
            "cc_call_center_id": np.array(
                [f"AAAAAAAA{str(i).zfill(8)}" for i in range(1, n + 1)], object),
            "cc_name": np.array([f"call center {i}" for i in range(n)], object),
            "cc_class": np.array([["small", "medium", "large"][i % 3]
                                  for i in range(n)], object),
            "cc_employees": rng.integers(1, 7_000_000, n),
            "cc_manager": np.array([f"manager{i % 40}" for i in range(n)],
                                   object),
        }

    def catalog_page(self) -> Dict[str, np.ndarray]:
        n = max(1, int(round(11_718 * max(self.sf, 1) ** 0.3)))
        rng = self._rng(12)
        return {
            "cp_catalog_page_sk": np.arange(1, n + 1),
            "cp_catalog_page_id": np.array(
                [f"AAAAAAAA{str(i).zfill(8)}" for i in range(1, n + 1)], object),
            "cp_catalog_number": (np.arange(n) // 108 + 1).astype(np.int64),
            "cp_catalog_page_number": (np.arange(n) % 108 + 1).astype(np.int64),
            "cp_start_date_sk": _D_DATE_SK0 + rng.integers(35_000, 36_000, n),
            "cp_type": np.array([["bi-annual", "quarterly", "monthly"][i % 3]
                                 for i in range(n)], object),
        }

    def web_site(self) -> Dict[str, np.ndarray]:
        n = max(1, int(round(30 * max(self.sf, 1) ** 0.25)))
        return {
            "web_site_sk": np.arange(1, n + 1),
            "web_site_id": np.array(
                [f"AAAAAAAA{str(i).zfill(8)}" for i in range(1, n + 1)], object),
            "web_name": np.array([f"site_{i % 15}" for i in range(n)], object),
            "web_class": np.array(["Unknown"] * n, object),
            "web_manager": np.array([f"manager{i % 20}" for i in range(n)],
                                    object),
        }

    def web_page(self) -> Dict[str, np.ndarray]:
        n = max(1, int(round(60 * max(self.sf, 1) ** 0.5)))
        rng = self._rng(13)
        return {
            "wp_web_page_sk": np.arange(1, n + 1),
            "wp_web_page_id": np.array(
                [f"AAAAAAAA{str(i).zfill(8)}" for i in range(1, n + 1)], object),
            "wp_creation_date_sk": _D_DATE_SK0 + rng.integers(35_000, 36_500, n),
            "wp_url": np.array(["http://www.foo.com"] * n, object),
            "wp_type": np.array(
                [["ad", "dynamic", "feedback", "general", "order",
                  "protected", "welcome"][i % 7] for i in range(n)], object),
            "wp_char_count": rng.integers(100, 8_000, n),
        }

    def inventory(self) -> Dict[str, np.ndarray]:
        """Weekly stock per (warehouse, item). Below SF1 items are sampled
        (deviation from the spec's full cross product — keeps small test
        scale factors tractable; at SF>=1 every item is covered)."""
        n_item = self.n_item if self.sf >= 1 else max(
            1, int(self.n_item * self.sf))
        weeks = 261  # spec: weekly snapshots over the 5-year window
        nw = self.n_warehouse
        rng = self._rng(14)
        item = np.tile(np.repeat(np.arange(1, n_item + 1), nw), weeks)
        wh = np.tile(np.arange(1, nw + 1), n_item * weeks)
        date = np.repeat(
            _D_DATE_SK0 + 35_795 + np.arange(weeks, dtype=np.int64) * 7,
            n_item * nw)
        n = item.shape[0]
        return {
            "inv_date_sk": date,
            "inv_item_sk": item.astype(np.int64),
            "inv_warehouse_sk": wh.astype(np.int64),
            "inv_quantity_on_hand": rng.integers(0, 1_000, n),
        }

    # -- catalog / web sales channels -------------------------------------

    def _channel_sales(self, prefix: str, n: int, salt: int,
                       extra_fk: Dict[str, int]):
        """Shared generator for catalog_sales / web_sales (the channels
        differ in prefix and channel-specific FK columns)."""
        rng = self._rng(salt)
        d_lo = _D_DATE_SK0 + 35_795
        d_hi = _D_DATE_SK0 + 37_621
        qty = rng.integers(1, 101, n, dtype=np.int64)
        wholesale = _money(rng, 1.0, 100.0, n)
        list_price = wholesale + _money(rng, 0.0, 100.0, n)
        discount = rng.integers(0, 100, n, dtype=np.int64)
        sales_price = list_price * (100 - discount) // 100
        ext_sales = sales_price * qty
        ship_cost = _money(rng, 0.0, 10.0, n) * qty
        sold_date = rng.integers(d_lo, d_hi + 1, n)
        out = {
            f"{prefix}_sold_date_sk": sold_date,
            f"{prefix}_sold_time_sk": rng.integers(0, 86_400, n),
            f"{prefix}_ship_date_sk": np.minimum(
                sold_date + rng.integers(2, 121, n), d_hi),
            f"{prefix}_item_sk": rng.integers(1, self.n_item + 1, n),
            f"{prefix}_order_number": np.arange(1, n + 1),
            f"{prefix}_quantity": qty,
            f"{prefix}_wholesale_cost": ("raw72", wholesale),
            f"{prefix}_list_price": ("raw72", list_price),
            f"{prefix}_sales_price": ("raw72", sales_price),
            f"{prefix}_ext_sales_price": ("raw72", ext_sales),
            f"{prefix}_ext_ship_cost": ("raw72", ship_cost),
            f"{prefix}_net_paid": ("raw72", ext_sales),
            f"{prefix}_net_profit": ("raw72",
                                     ext_sales - wholesale * qty),
        }
        for col, domain in extra_fk.items():
            out[col] = rng.integers(1, domain + 1, n)
        return out

    def catalog_sales(self) -> Dict[str, np.ndarray]:
        n = int(1_441_548 * self.sf)
        return self._channel_sales("cs", max(n, 1), 15, {
            "cs_bill_customer_sk": self.n_customer,
            "cs_ship_customer_sk": self.n_customer,
            "cs_call_center_sk": max(1, int(round(6 * max(self.sf, 1) ** 0.3))),
            "cs_catalog_page_sk": max(1, int(round(11_718 * max(self.sf, 1) ** 0.3))),
            "cs_ship_mode_sk": 20,
            "cs_warehouse_sk": self.n_warehouse,
            "cs_promo_sk": self.n_promo,
        })

    def catalog_returns(self) -> Dict[str, np.ndarray]:
        sales = self._ensure_channel("cs")
        return self._channel_returns("cs", "cr", sales, 16, {
            "cr_reason_sk": max(1, int(round(35 * max(self.sf, 1) ** 0.2))),
        })

    def web_sales(self) -> Dict[str, np.ndarray]:
        n = int(719_384 * self.sf)
        return self._channel_sales("ws", max(n, 1), 17, {
            "ws_bill_customer_sk": self.n_customer,
            "ws_ship_customer_sk": self.n_customer,
            "ws_web_site_sk": max(1, int(round(30 * max(self.sf, 1) ** 0.25))),
            "ws_web_page_sk": max(1, int(round(60 * max(self.sf, 1) ** 0.5))),
            "ws_ship_mode_sk": 20,
            "ws_warehouse_sk": self.n_warehouse,
            "ws_promo_sk": self.n_promo,
        })

    def web_returns(self) -> Dict[str, np.ndarray]:
        sales = self._ensure_channel("ws")
        return self._channel_returns("ws", "wr", sales, 18, {
            "wr_reason_sk": max(1, int(round(35 * max(self.sf, 1) ** 0.2))),
        })

    _channel_cache: Dict[str, Dict[str, np.ndarray]] = None  # type: ignore

    def _ensure_channel(self, prefix: str) -> Dict[str, np.ndarray]:
        if self._channel_cache is None:
            self._channel_cache = {}
        if prefix not in self._channel_cache:
            self._channel_cache[prefix] = (
                self.catalog_sales() if prefix == "cs" else self.web_sales())
        return self._channel_cache[prefix]

    def _channel_returns(self, sp: str, rp: str, sales, salt: int,
                         extra_fk: Dict[str, int]):
        """~10% of channel sales return; item/order join keys are subsets
        of the sales table (exact referential integrity)."""
        rng = self._rng(salt)
        n = sales[f"{sp}_order_number"].shape[0]
        n_ret = max(n // 10, 1)
        ridx = rng.choice(n, n_ret, replace=False)
        qty = sales[f"{sp}_quantity"][ridx]
        ret_qty = np.minimum(rng.integers(1, 101, n_ret, dtype=np.int64), qty)
        price = sales[f"{sp}_sales_price"][1][ridx]
        out = {
            f"{rp}_returned_date_sk": np.minimum(
                sales[f"{sp}_sold_date_sk"][ridx]
                + rng.integers(1, 91, n_ret),
                _D_DATE_SK0 + 37_621),
            f"{rp}_item_sk": sales[f"{sp}_item_sk"][ridx],
            f"{rp}_order_number": sales[f"{sp}_order_number"][ridx],
            f"{rp}_return_quantity": ret_qty,
            f"{rp}_return_amount": ("raw72", price * ret_qty),
            f"{rp}_net_loss": ("raw72", price * ret_qty // 2),
        }
        out[f"{rp}_refunded_customer_sk"] = (
            sales[f"{sp}_bill_customer_sk"][ridx])
        for col, domain in extra_fk.items():
            out[col] = rng.integers(1, domain + 1, n_ret)
        return out

    def store_sales_and_returns(self):
        """Full-table generation (single chunk, original RNG stream)."""
        return self.store_sales_chunk(0, self.n_store_sales, _salt=7)

    def store_sales_chunk(self, start: int, count: int, _salt=None):
        """Generate store_sales rows [start, start+count) plus their
        returns. Chunking bounds peak memory so SF100 (288M rows) streams
        to parquet (see tpch.orders_lineitem_chunk — same pattern; returns
        reference only sales inside the chunk, preserving the ticket-number
        join)."""
        n = count
        if _salt is None:
            _salt = 2000 + start // max(count, 1)
        rng = self._rng(_salt)
        # sales dates cluster in 1998-2002 (spec's active range)
        d_lo = _D_DATE_SK0 + 35_795  # ~1998-01-01
        d_hi = _D_DATE_SK0 + 37_621  # ~2002-12-31
        qty = rng.integers(1, 101, n, dtype=np.int64)
        # per-unit amounts (spec domains); ss_ext_* carry unit × quantity
        wholesale = _money(rng, 1.0, 100.0, n)
        list_price = wholesale + _money(rng, 0.0, 100.0, n)
        discount = rng.integers(0, 100, n, dtype=np.int64)  # percent
        sales_price = list_price * (100 - discount) // 100
        ext_sales = sales_price * qty
        ext_wholesale = wholesale * qty
        ext_list = list_price * qty
        coupon = np.where(rng.random(n) < 0.1,
                          ext_sales // 10, np.int64(0))
        sales = {
            "ss_sold_date_sk": rng.integers(d_lo, d_hi + 1, n),
            "ss_item_sk": rng.integers(1, self.n_item + 1, n),
            "ss_customer_sk": rng.integers(1, self.n_customer + 1, n),
            "ss_cdemo_sk": rng.integers(1, self.n_cdemo + 1, n),
            "ss_hdemo_sk": rng.integers(1, self.n_hdemo + 1, n),
            "ss_addr_sk": rng.integers(1, self.n_address + 1, n),
            "ss_store_sk": rng.integers(1, self.n_store + 1, n),
            "ss_promo_sk": rng.integers(1, self.n_promo + 1, n),
            "ss_ticket_number": np.arange(start + 1, start + n + 1),
            "ss_quantity": qty,
            "ss_wholesale_cost": ("raw72", wholesale),
            "ss_list_price": ("raw72", list_price),
            "ss_sales_price": ("raw72", sales_price),
            "ss_ext_wholesale_cost": ("raw72", ext_wholesale),
            "ss_ext_list_price": ("raw72", ext_list),
            "ss_ext_sales_price": ("raw72", ext_sales),
            "ss_coupon_amt": ("raw72", coupon),
            "ss_net_paid": ("raw72", ext_sales - coupon),
            "ss_net_profit": ("raw72", ext_sales - coupon - ext_wholesale),
        }
        # ~10% of sales are returned (spec return ratio)
        n_ret = n // 10
        ridx = rng.choice(n, n_ret, replace=False)
        ret_qty = np.minimum(rng.integers(1, 101, n_ret, dtype=np.int64), qty[ridx])
        returns = {
            "sr_returned_date_sk": np.minimum(
                sales["ss_sold_date_sk"][ridx] + rng.integers(1, 91, n_ret), d_hi
            ),
            "sr_item_sk": sales["ss_item_sk"][ridx],
            "sr_customer_sk": sales["ss_customer_sk"][ridx],
            "sr_ticket_number": sales["ss_ticket_number"][ridx],
            "sr_return_quantity": ret_qty,
            "sr_return_amt": ("raw72", sales_price[ridx] * ret_qty),
            "sr_store_sk": sales["ss_store_sk"][ridx],
        }
        # drawn LAST so the pre-existing columns' RNG stream is unchanged
        # (deterministic data must stay stable across additions)
        sales["ss_sold_time_sk"] = rng.integers(0, 86_400, n)
        return sales, returns


_DS_TYPES: Dict[str, Dict[str, object]] = {
    "date_dim": {"d_date": DATE},
}


class TpcdsConnector(MemoryConnector):
    """Lazy TPC-DS connector: tables generate on first access and are cached
    (presto-tpcds TpcdsConnectorFactory analog)."""

    def __init__(self, sf: float = 1.0, name: str = "tpcds"):
        super().__init__(name)
        self.sf = sf
        self.gen = TpcdsGenerator(sf)

    def table_names(self) -> List[str]:
        # all 24 spec tables (3 sales channels + inventory + dimensions)
        return ["date_dim", "time_dim", "store", "item", "customer",
                "customer_address", "customer_demographics",
                "household_demographics", "income_band", "promotion",
                "warehouse", "ship_mode", "reason", "call_center",
                "catalog_page", "web_site", "web_page",
                "store_sales", "store_returns",
                "catalog_sales", "catalog_returns",
                "web_sales", "web_returns", "inventory"]

    def _ensure(self, name: str):
        if name in self.tables:
            return
        if name in ("store_sales", "store_returns"):
            sales, returns = self.gen.store_sales_and_returns()
            self._add("store_sales", sales)
            self._add("store_returns", returns)
        elif name in ("catalog_sales", "catalog_returns"):
            self._add("catalog_sales", self.gen._ensure_channel("cs"))
            self._add("catalog_returns", self.gen.catalog_returns())
            self.gen._channel_cache.pop("cs", None)  # release generator copy
        elif name in ("web_sales", "web_returns"):
            self._add("web_sales", self.gen._ensure_channel("ws"))
            self._add("web_returns", self.gen.web_returns())
            self.gen._channel_cache.pop("ws", None)
        elif name in self.table_names():
            self._add(name, getattr(self.gen, name)())
        else:
            raise KeyError(f"table not found: {name}")

    def _add(self, name: str, data: Dict[str, np.ndarray]):
        converted = {
            c: (("raw_decimal", _D72, v[1])
                if isinstance(v, tuple) and len(v) == 2 and v[0] == "raw72"
                else v)
            for c, v in data.items()
        }
        self.add_generated(name, converted, types=_DS_TYPES.get(name))

    def get_table(self, name: str):
        self._ensure(name)
        return super().get_table(name)

    def read_split(self, split, columns, capacity=None):
        self._ensure(split.table)
        return super().read_split(split, columns, capacity)


def tpcds_catalog(sf: float = 1.0):
    from presto_tpu.connector import Catalog

    cat = Catalog()
    cat.register("tpcds", TpcdsConnector(sf), default=True)
    return cat
