"""ORC connector — stripe-parallel reads + CTAS writes via pyarrow.orc.

Reference: presto-orc (the fork's flagship module — OrcReader,
OrcSelectiveRecordReader.java:54, StripeReader) and presto-hive's ORC page
sources. The reference hand-decodes ORC streams with predicate-during-
decode (Aria); here arrow does the decode and the engine's selective
machinery operates on the decoded batch (filter = live-mask &=, fused into
the scan program at trace time — see exec/runtime.collapse_chain). Stripes
map to splits exactly as row groups do for parquet; string columns decode
straight into the table-global dictionary (codes only on device).

pyarrow exposes no per-stripe column statistics, so the writer persists a
sidecar stats file next to each table at CTAS/export time:
`<table>.orc.stats.json` = {"version", "file_size", "num_rows",
"stripes": [{"num_rows", "columns": {col: {"min", "max", "null_count",
"kind"?}}}]} (dates ride ISO strings with a "kind": "date" tag; see
scan/pruning.py). `split_stats` serves those per-stripe bounds to the
generic `prune_splits`, so constrained scans eliminate stripes without
opening them — the stripe-skipping half of the Aria selective reader —
and `read_split_selective` runs the value-filter cascade during decode.
A stale or missing sidecar (file_size mismatch after an out-of-band
rewrite) degrades to unpruned scans, never to wrong results.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

import numpy as np
import pyarrow as pa
import pyarrow.orc as po

from presto_tpu.batch import Batch, round_up_capacity
from presto_tpu.catalog.memory import DeviceSplitCache, _batches_to_host
from presto_tpu.catalog.parquet import (
    _arrow_to_sql,
    _decode_column,
    _sql_to_arrow,
    _to_arrow_columns,
)
from presto_tpu.connector import ColumnInfo, Connector, Split, TableHandle
from presto_tpu.dictionary import Dictionary
from presto_tpu.types import ArrayType, MapType


def _undictionarize(tbl: pa.Table) -> pa.Table:
    """ORC has no dictionary physical type in arrow's writer: cast
    dictionary columns to their value type (ORC files still dictionary-
    encode internally; the engine rebuilds the table-global dictionary at
    open)."""
    cols, fields = [], []
    for i, field in enumerate(tbl.schema):
        col = tbl.column(i)
        if pa.types.is_dictionary(field.type):
            col = col.cast(field.type.value_type)
            field = pa.field(field.name, field.type.value_type)
        cols.append(col)
        fields.append(field)
    return pa.Table.from_arrays(cols, schema=pa.schema(fields))


class _OrcTable:
    __slots__ = ("path", "handle", "dicts", "num_rows", "n_stripes",
                 "version")

    def __init__(self, path, handle, dicts, num_rows, n_stripes, version):
        self.path = path
        self.handle = handle
        self.dicts = dicts
        self.num_rows = num_rows
        self.n_stripes = n_stripes
        self.version = version


class OrcConnector(DeviceSplitCache, Connector):
    """Directory of <table>.orc files."""

    host_cache_bytes: int = 2 << 30

    def __init__(self, directory: str, name: str = "orc"):
        self.name = name
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._tables: Dict[str, _OrcTable] = {}
        self._init_split_cache()
        self._host_cache: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._host_cache_used = 0
        self._host_cache_lock = threading.Lock()
        # (path, version) -> per-stripe SplitStats list | None
        self._sidecar_cache: Dict[tuple, object] = {}

    def table_names(self) -> List[str]:
        return sorted(
            f[:-4] for f in os.listdir(self.directory) if f.endswith(".orc")
        )

    @staticmethod
    def _file_version(path: str) -> tuple:
        st = os.stat(path)
        return (st.st_mtime_ns, st.st_size)

    def _check_fresh(self, name: str):
        t = self._tables.get(name)
        if t is None:
            return
        path = os.path.join(self.directory, f"{name}.orc")
        if not os.path.exists(path) or self._file_version(path) != t.version:
            self._invalidate_table(name)

    def _invalidate_table(self, name: str):
        with self._host_cache_lock:
            self._tables.pop(name, None)
        self.invalidate_cache(name)
        with self._host_cache_lock:
            for k in [k for k in self._host_cache if k[0].endswith(
                    os.sep + f"{name}.orc")]:
                _, nbytes = self._host_cache.pop(k)
                self._host_cache_used -= nbytes

    def _load(self, name: str) -> _OrcTable:
        self._check_fresh(name)
        if name in self._tables:
            return self._tables[name]
        path = os.path.join(self.directory, f"{name}.orc")
        if not os.path.exists(path):
            raise KeyError(f"table not found: {name}")
        f = po.ORCFile(path)
        schema = f.schema
        cols = []
        dicts: Dict[str, Dictionary] = {}
        for field in schema:
            t = _arrow_to_sql(field)
            if t.is_string:
                # table-global dictionary: one pass over the column at open
                vocab = set()
                for s in range(f.nstripes):
                    col = f.read_stripe(s, columns=[field.name]).column(
                        field.name)
                    arr = col.combine_chunks() if isinstance(
                        col, pa.ChunkedArray) else col
                    if pa.types.is_dictionary(arr.type):
                        vocab.update(arr.dictionary.to_pylist())
                    else:
                        vocab.update(arr.to_pylist())
                d = Dictionary(
                    np.array(sorted(v for v in vocab if v is not None)))
                dicts[field.name] = d
                cols.append(ColumnInfo(field.name, t, d))
            else:
                cols.append(ColumnInfo(field.name, t, None))
        handle = TableHandle(self.name, name, cols,
                             row_count=float(f.nrows))
        t = _OrcTable(path, handle, dicts, f.nrows, f.nstripes,
                      self._file_version(path))
        # concurrent loaders both build the table (the open is outside
        # any lock by design); the insert is idempotent, the lock keeps
        # the dict consistent
        with self._host_cache_lock:
            self._tables[name] = t
        return t

    def get_table(self, name: str) -> TableHandle:
        return self._load(name).handle

    def splits(self, handle: TableHandle, desired: int = 1) -> List[Split]:
        """One split per stripe, sub-split when fewer stripes than desired
        (mirrors the parquet connector's row-group sub-splitting)."""
        t = self._load(handle.name)
        n = max(t.n_stripes, 1)
        if n >= desired or t.num_rows == 0:
            return [Split(handle.name, (s, 0, 1), n)
                    for s in range(t.n_stripes)] or [
                        Split(handle.name, (0, 0, 1), 1)]
        sub = -(-desired // n)
        out = []
        for s in range(n):
            for i in range(sub):
                out.append(Split(handle.name, (s, i, sub), n * sub))
        return out

    # -- write path (CTAS/DROP; reference: HiveWriterFactory ORC path) ----

    def create_table_from(self, name: str, batches,
                          if_not_exists: bool = False,
                          properties: Optional[dict] = None) -> int:
        if properties:
            raise ValueError(
                "orc connector does not support table properties")
        path = os.path.join(self.directory, f"{name}.orc")
        if os.path.exists(path):
            if if_not_exists:
                return 0
            raise ValueError(f"table already exists: {name}")
        names, types, data = _batches_to_host(batches)
        if any(isinstance(t, (ArrayType, MapType)) for t in types):
            raise NotImplementedError(
                "ORC writer does not support ARRAY/MAP columns yet")
        plain = {c: v[0] for c, v in data.items()}
        validity = {c: v[1] for c, v in data.items() if v[1] is not None}
        his = {c: v[2] for c, v in data.items() if v[2] is not None}
        dicts = {c: v[3] for c, v in data.items() if v[3] is not None}
        arrays, schema = _to_arrow_columns(plain, dict(zip(names, types)),
                                           dicts, validity, his)
        tbl = _undictionarize(pa.Table.from_arrays(arrays, schema=schema))
        po.write_table(tbl, path + ".tmp")
        os.replace(path + ".tmp", path)
        _write_sidecar(path)
        self._invalidate_table(name)
        return int(tbl.num_rows)

    def drop_table(self, name: str, if_exists: bool = False) -> None:
        path = os.path.join(self.directory, f"{name}.orc")
        if not os.path.exists(path):
            if if_exists:
                return
            raise KeyError(f"table not found: {name}")
        os.remove(path)
        from presto_tpu.scan.pruning import sidecar_path

        if os.path.exists(sidecar_path(path)):
            os.remove(sidecar_path(path))
        self._invalidate_table(name)

    # -- read path --------------------------------------------------------

    def read_split(self, split: Split, columns: Sequence[str],
                   capacity: Optional[int] = None) -> Batch:
        self._check_fresh(split.table)
        return super().read_split(split, columns, capacity)

    def _stripe_stats(self, t: _OrcTable):
        """Sidecar-backed per-stripe SplitStats list (None = no usable
        sidecar), cached per (path, file version)."""
        from presto_tpu.scan.pruning import load_orc_sidecar

        key = (t.path, t.version)
        with self._host_cache_lock:
            if key in self._sidecar_cache:
                return self._sidecar_cache[key]
        stats = load_orc_sidecar(t.path)  # file I/O stays outside the lock
        with self._host_cache_lock:
            while len(self._sidecar_cache) > 64:
                # eviction is sized-check and pop in this one section;
                # the earlier membership probe plays no part in it
                self._sidecar_cache.pop(next(iter(self._sidecar_cache)))  # lint: allow(check-then-act)
            # racing loaders read the same sidecar file; the insert is
            # idempotent, so re-checking membership buys nothing
            self._sidecar_cache[key] = stats  # lint: allow(check-then-act)
        return stats

    def split_stats(self, handle: TableHandle, split: Split):
        t = self._load(handle.name)
        stats = self._stripe_stats(t)
        if not stats:
            return None
        stripe = split.part[0] if isinstance(split.part, tuple) else split.part
        if stripe >= len(stats):
            return None
        # sub-splits of one stripe share its bounds (a superset — still a
        # correct pruning witness)
        return stats[stripe]

    def read_split_selective(self, split: Split, columns: Sequence[str],
                             filters, capacity: Optional[int] = None,
                             adaptive=None, counters=None) -> Batch:
        """Predicate-during-decode over one stripe (see
        scan/selective.py); bypasses the device split cache like the
        parquet selective path."""
        from presto_tpu.scan.selective import selective_read

        self._check_fresh(split.table)
        t = self._load(split.table)
        stripe, sub, sub_count = split.part

        def _decode(cols):
            return self._decoded_columns(t, stripe, sub, sub_count, cols)

        return selective_read(_decode, t.handle, columns, filters,
                              capacity=capacity, dicts=t.dicts,
                              adaptive=adaptive, counters=counters)

    def _decoded_columns(self, t: _OrcTable, stripe: int, sub: int,
                         sub_count: int, columns: Sequence[str]):
        key = (t.path, stripe, sub, sub_count, tuple(columns))
        with self._host_cache_lock:
            hit = self._host_cache.get(key)
            if hit is not None:
                self._host_cache.move_to_end(key)
                return hit[0]
        f = po.ORCFile(t.path)
        if t.n_stripes == 0:
            tbl = f.read(columns=list(columns))
        else:
            tbl = f.read_stripe(stripe, columns=list(columns))
            if not isinstance(tbl, pa.Table):
                tbl = pa.Table.from_batches([tbl])
        if sub_count > 1:
            per = -(-tbl.num_rows // sub_count)
            tbl = tbl.slice(sub * per, per)
        n = tbl.num_rows
        out = {}
        nbytes = 0
        for name in columns:
            st = t.handle.column(name).type
            arr, valid, hi = _decode_column(tbl.column(name), st,
                                            t.dicts.get(name))
            arr = np.ascontiguousarray(np.asarray(arr))
            out[name] = (arr, valid, hi)
            nbytes += arr.nbytes + (valid.nbytes if valid is not None else 0)
            nbytes += hi.nbytes if hi is not None else 0
        result = (out, n)
        if nbytes <= self.host_cache_bytes:
            with self._host_cache_lock:
                # the decode above ran outside the lock on purpose (it is
                # the expensive step); membership is RE-VALIDATED here
                # before the insert, so the stale first read cannot
                # double-account
                if key not in self._host_cache:
                    self._host_cache[key] = (result, nbytes)  # lint: allow(check-then-act)
                    self._host_cache_used += nbytes
                    while self._host_cache_used > self.host_cache_bytes:
                        _, (_, freed) = self._host_cache.popitem(last=False)  # lint: allow(check-then-act)
                        self._host_cache_used -= freed
        return result

    def _read_split_uncached(self, split: Split, columns: Sequence[str],
                             capacity: Optional[int] = None) -> Batch:
        import jax.numpy as jnp

        from presto_tpu.batch import Column

        t = self._load(split.table)
        stripe, sub, sub_count = split.part
        decoded, n = self._decoded_columns(t, stripe, sub, sub_count,
                                           columns)
        cap = capacity or round_up_capacity(max(n, 1))
        names, typelist, cols = [], [], []
        live = np.zeros(cap, bool)
        live[:n] = True
        for name in columns:
            st = t.handle.column(name).type
            arr, valid, hi = decoded[name]
            buf = np.zeros(cap, dtype=st.dtype)
            buf[:n] = arr
            vcol = None
            if valid is not None:
                vb = np.zeros(cap, bool)
                vb[:n] = valid
                vcol = jnp.asarray(vb)
            hcol = None
            if hi is not None:
                hb = np.zeros(cap, np.int64)
                hb[:n] = hi
                hcol = jnp.asarray(hb)
            names.append(name)
            typelist.append(st)
            cols.append(Column(jnp.asarray(buf), vcol, hcol))
        return Batch(
            names, typelist, cols, jnp.asarray(live),
            {c: t.dicts[c] for c in columns if c in t.dicts},
        )


def _write_sidecar(path: str) -> None:
    """Best-effort stripe-stats sidecar: a stats failure must never fail
    the write itself (the scan degrades to unpruned, not to an error)."""
    from presto_tpu.scan.pruning import write_orc_sidecar

    try:
        write_orc_sidecar(path)
    except Exception:
        pass


def export_table_to_orc(directory: str, name: str, data, types,
                        dicts=None, stripe_size: Optional[int] = None,
                        validity=None) -> str:
    """Materialize host columns as <directory>/<name>.orc (test fixture
    helper, the dbgen→ORC-warehouse path). `stripe_size` (bytes) forces
    small multi-stripe files so split-elimination paths are testable at
    fixture scale; `validity` maps column → bool mask (False = NULL)."""
    os.makedirs(directory, exist_ok=True)
    arrays, schema = _to_arrow_columns(data, types, dicts or {}, validity)
    path = os.path.join(directory, f"{name}.orc")
    tbl = _undictionarize(pa.Table.from_arrays(arrays, schema=schema))
    if stripe_size:
        po.write_table(tbl, path, stripe_size=stripe_size)
    else:
        po.write_table(tbl, path)
    _write_sidecar(path)
    return path
