"""Remote-service connector — federate an external data service over RPC.

Reference: presto-thrift-connector(-api): an external service implements a
small RPC surface (prestoListTables / prestoGetTableMetadata /
prestoGetSplits / prestoGetRows with continuation tokens and
`desiredColumns` + TupleDomain pushdown) and any number of Presto
clusters query it. Here the same four-call shape runs as JSON over HTTP
(the engine's control-plane idiom; drift/thrift adds codegen without
adding capability):

    GET  {base}/v1/tables                      → {"tables": [name, …]}
    GET  {base}/v1/tables/{t}/schema           → {"columns": [{name, type}],
                                                  "rowCount": n}
    GET  {base}/v1/tables/{t}/splits?desired=N → {"splits": [id, …]}
    POST {base}/v1/tables/{t}/rows             → {"columns": {name: [v,…]},
         {"split": id, "columns": [...],          "nextToken": tok|null}
          "constraints": {col: [lo, hi]},
          "token": tok|null, "maxRows": n}

Projection pushdown = the `columns` list; predicate pushdown = the
`constraints` ranges (TupleDomain analog); paging = `token` continuation
exactly like the thrift `nextToken`. A reference in-process service
(`RemoteTableService`) doubles as the test fixture — the analog of the
thrift connector's TestingThriftService.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
import urllib.request
from typing import Dict, List, Optional, Sequence

import numpy as np

from presto_tpu.batch import Batch, round_up_capacity
from presto_tpu.catalog.memory import DeviceSplitCache
from presto_tpu.connector import ColumnInfo, Connector, Split, TableHandle
from presto_tpu.dictionary import Dictionary
from presto_tpu.types import BIGINT, BOOLEAN, DOUBLE, Type, VARCHAR

_TYPES = {"bigint": BIGINT, "double": DOUBLE, "varchar": VARCHAR,
          "boolean": BOOLEAN}


def _type_name(t: Type) -> str:
    for k, v in _TYPES.items():
        if v is t:
            return k
    return "varchar"


class RemoteServiceConnector(DeviceSplitCache, Connector):
    """Engine-side client of the remote table service."""

    def __init__(self, base_url: str, name: str = "remote",
                 page_rows: int = 1 << 16):
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.page_rows = page_rows
        self._handles: Dict[str, TableHandle] = {}
        self._dicts: Dict[str, Dict[str, Dictionary]] = {}
        self._lock = threading.Lock()
        self._init_split_cache()

    def _get(self, path: str) -> dict:
        with urllib.request.urlopen(self.base_url + path, timeout=30) as r:
            return json.loads(r.read())

    def _post(self, path: str, body: dict) -> dict:
        req = urllib.request.Request(
            self.base_url + path, data=json.dumps(body).encode(),
            method="POST", headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            return json.loads(r.read())

    def table_names(self) -> List[str]:
        return list(self._get("/v1/tables")["tables"])

    def get_table(self, name: str) -> TableHandle:
        with self._lock:
            h = self._handles.get(name)
            if h is not None:
                return h
        meta = self._get(f"/v1/tables/{urllib.parse.quote(name)}/schema")
        cols = [ColumnInfo(c["name"], _TYPES.get(c["type"], VARCHAR), None)
                for c in meta["columns"]]
        h = TableHandle(self.name, name, cols,
                        row_count=float(meta.get("rowCount") or 0))
        with self._lock:
            # the schema fetch above runs outside the lock by design;
            # racing fetches produce equivalent handles and the insert is
            # idempotent (last writer wins)
            self._handles[name] = h  # lint: allow(check-then-act)
        return h

    def splits(self, handle: TableHandle, desired: int = 1) -> List[Split]:
        got = self._get(
            f"/v1/tables/{urllib.parse.quote(handle.name)}/splits"
            f"?desired={desired}")["splits"]
        return [Split(handle.name, i, len(got)) for i in range(len(got))]

    def read_split_constrained(self, split: Split, columns: Sequence[str],
                               capacity: Optional[int] = None,
                               constraints=None) -> Batch:
        """Predicate-pushdown read: bypasses the split cache (cache keys
        don't carry constraints) and ships the ranges to the service.
        Only JSON-native numeric bounds travel; anything else (dates as
        datetime objects) stays engine-side — the filter above the scan
        re-applies every predicate regardless."""
        num = {c: (lo, hi) for c, (lo, hi) in (constraints or {}).items()
               if all(v is None or isinstance(v, (int, float))
                      for v in (lo, hi))}
        return self._read_split_uncached(split, columns, capacity,
                                         constraints=num)

    def _read_split_uncached(self, split: Split, columns: Sequence[str],
                             capacity: Optional[int] = None,
                             constraints=None) -> Batch:
        h = self.get_table(split.table)
        col_types = {c.name: c.type for c in h.columns}
        data: Dict[str, list] = {c: [] for c in columns}
        token = None
        while True:
            out = self._post(
                f"/v1/tables/{urllib.parse.quote(split.table)}/rows",
                {"split": split.part, "nSplits": split.total,
                 "columns": list(columns),
                 "constraints": {c: [lo, hi] for c, (lo, hi)
                                 in (constraints or {}).items()},
                 "token": token, "maxRows": self.page_rows})
            for c in columns:
                data[c].extend(out["columns"][c])
            token = out.get("nextToken")
            if token is None:
                break
        return self._to_batch(split.table, columns, col_types, data, capacity)

    def _to_batch(self, table, columns, col_types, data, capacity):
        import jax.numpy as jnp

        from presto_tpu.batch import Column

        n = len(data[columns[0]]) if columns else 0
        cap = max(capacity or 0, round_up_capacity(max(n, 1)))
        live = np.zeros(cap, bool)
        live[:n] = True
        names, types, cols, dicts = [], [], [], {}
        for cname in columns:
            t = col_types[cname]
            raw = data[cname]
            valid = np.array([v is not None for v in raw])
            vcol = None
            if t.is_string:
                with self._lock:
                    d = self._dicts.setdefault(table, {}).get(cname)
                    vocab = sorted({str(v) for v in raw if v is not None})
                    nd = Dictionary(np.asarray(vocab, dtype=str))
                    if d is not None:
                        nd = Dictionary.merge(d, nd)
                    self._dicts[table][cname] = nd
                buf = np.full(cap, -1, np.int32)
                buf[:n] = [nd.code_of(str(v)) if v is not None else -1
                           for v in raw]
                dicts[cname] = nd
            else:
                buf = np.zeros(cap, dtype=t.dtype)
                buf[:n] = [v if v is not None else 0 for v in raw]
            if not valid.all():
                vb = np.zeros(cap, bool)
                vb[:n] = valid
                vcol = jnp.asarray(vb)
            names.append(cname)
            types.append(t)
            cols.append(Column(jnp.asarray(buf), vcol))
        return Batch(names, types, cols, jnp.asarray(live), dicts)


class RemoteTableService:
    """Reference implementation of the service side, backed by pandas
    DataFrames — in-process HTTP server used by tests/examples (the
    TestingThriftService analog). Records every /rows request so tests
    can assert projection/predicate pushdown reached the service."""

    def __init__(self, tables, port: int = 0, n_splits: int = 2):
        import pandas as pd  # noqa: F401 — service side is host-only

        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.tables = tables
        self.n_splits = n_splits
        self.requests: List[dict] = []  # /rows bodies, for pushdown asserts
        svc = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _json(self, obj, code=200):
                data = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                path = urllib.parse.urlparse(self.path)
                parts = [p for p in path.path.split("/") if p]
                if parts == ["v1", "tables"]:
                    return self._json({"tables": sorted(svc.tables)})
                if (len(parts) == 4 and parts[:2] == ["v1", "tables"]
                        and parts[3] == "schema"):
                    df = svc.tables.get(urllib.parse.unquote(parts[2]))
                    if df is None:
                        return self._json({"error": "no such table"}, 404)
                    cols = []
                    for c in df.columns:
                        k = df[c].dtype.kind
                        cols.append({"name": c, "type":
                                     "bigint" if k in "iu" else
                                     "double" if k == "f" else
                                     "boolean" if k == "b" else "varchar"})
                    return self._json({"columns": cols,
                                       "rowCount": int(len(df))})
                if (len(parts) == 4 and parts[:2] == ["v1", "tables"]
                        and parts[3] == "splits"):
                    q = urllib.parse.parse_qs(path.query)
                    desired = int(q.get("desired", ["1"])[0])
                    n = min(max(desired, 1), svc.n_splits)
                    return self._json({"splits": list(range(n))})
                self._json({"error": "not found"}, 404)

            def do_POST(self):
                path = urllib.parse.urlparse(self.path)
                parts = [p for p in path.path.split("/") if p]
                if not (len(parts) == 4 and parts[:2] == ["v1", "tables"]
                        and parts[3] == "rows"):
                    return self._json({"error": "not found"}, 404)
                body = json.loads(self.rfile.read(
                    int(self.headers.get("Content-Length", "0"))))
                svc.requests.append(body)
                df = svc.tables[urllib.parse.unquote(parts[2])]
                # split slicing (row ranges — the service owns its split
                # semantics, like thrift splits carry opaque payloads)
                i, total = int(body["split"]), int(body.get("nSplits", 1))
                lo = len(df) * i // total
                hi = len(df) * (i + 1) // total
                part = df.iloc[lo:hi]
                # predicate pushdown: range constraints filter server-side
                for c, (clo, chi) in (body.get("constraints") or {}).items():
                    if clo is not None:
                        part = part[part[c] >= clo]
                    if chi is not None:
                        part = part[part[c] <= chi]
                # continuation token = row offset into the filtered part
                tok = int(body.get("token") or 0)
                page = part.iloc[tok:tok + int(body.get("maxRows", 65536))]
                nxt = tok + len(page)
                cols = {c: [None if v != v else
                            (v.item() if hasattr(v, "item") else v)
                            for v in page[c]]
                        for c in body["columns"]}
                return self._json({
                    "columns": cols,
                    "nextToken": nxt if nxt < len(part) else None})

        self._http = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._http.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self._thread = threading.Thread(target=self._http.serve_forever,
                                        daemon=True, name="remote-table-svc")
        self._thread.start()

    def close(self):
        self._http.shutdown()
        self._http.server_close()
