"""SQL type system mapped onto TPU-friendly device representations.

Reference surface: presto-spi/src/main/java/com/facebook/presto/spi/type/
(Type.java, BigintType, DoubleType, DecimalType, VarcharType, DateType, ...).

Design (TPU-first, not a port):

- Every type has exactly one flat device representation (a jnp dtype); there
  are no variable-width device values. VARCHAR is dictionary-encoded: the
  device sees order-preserving int32 codes, the host keeps the dictionary
  (see presto_tpu.dictionary). This generalizes Presto's DictionaryBlock
  (spi/block/DictionaryBlock.java) from an optimization into the only string
  representation the device ever touches.
- DECIMAL(p, s) with p <= 18 is a scaled int64 ("unscaled value", like
  Presto's short decimal, spi/type/DecimalType.java); arithmetic is exact
  int64 math with explicit rescales. p > 18 ("long decimal") carries a
  second int64 limb on the Column (`Column.hi`: value = hi·2³² + lo, lo
  canonical in [0, 2³²)) — produced by sum(decimal) aggregation states
  and carried exactly through joins, sorts, exchanges and spill
  (reference: UnscaledDecimal128Arithmetic.java two-long layout). General
  long-decimal multiplication/division is not implemented; comparisons
  and min/max fall back to combined float64.
- DATE is int32 days since 1970-01-01 (same as Presto, spi/type/DateType).
- TIMESTAMP is int64 microseconds since epoch.
- ARRAY(T) / MAP(K, V) (spi/type/ArrayType.java, MapType.java) use a dense
  padded layout instead of the reference's offsets-into-flat-block
  (spi/block/ColumnarArray.java): an array column's device value is a
  [capacity, W] plane of element values (W = static per-batch max
  cardinality, padded to keep shapes compile-cache friendly) plus an int32
  `sizes` vector and an element-validity plane. Rows gather through joins
  and sorts as plain 2D row gathers, elementwise array functions vectorize
  over the whole plane, and UNNEST is a static reshape — no ragged offsets
  ever reach the device.
- ROW(fields) is a planning-time type: analysis flattens row construction
  and field access into the underlying scalar columns (spi/type/RowType
  without a device representation of its own).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class Type:
    """Base class for SQL types. Frozen/hashable: types are plan-time values."""

    name: str

    @property
    def dtype(self):
        raise NotImplementedError

    @property
    def is_string(self) -> bool:
        return False

    @property
    def null_value(self):
        """Placeholder stored in value slots whose validity bit is 0."""
        return np.zeros((), dtype=self.dtype).item()

    def __str__(self) -> str:
        return self.name


@dataclasses.dataclass(frozen=True)
class _FixedType(Type):
    _dtype: str

    @property
    def dtype(self):
        return jnp.dtype(self._dtype)


@dataclasses.dataclass(frozen=True)
class DecimalType(Type):
    """DECIMAL(p, s). p <= 18: scaled int64. p > 18 ("long decimal"):
    two-limb representation — Column.values holds the low 32 bits
    (nonnegative int64) and Column.hi the arithmetic high limb, so
    value = hi * 2^32 + lo exactly (the reference's
    UnscaledDecimal128Arithmetic int128 on two int64 limbs)."""

    precision: int = 18
    scale: int = 0

    def __init__(self, precision: int = 18, scale: int = 0):
        if precision > 38:
            raise ValueError("DECIMAL precision > 38 unsupported")
        object.__setattr__(self, "name", f"decimal({precision},{scale})")
        object.__setattr__(self, "precision", precision)
        object.__setattr__(self, "scale", scale)

    @property
    def is_long(self) -> bool:
        return self.precision > 18

    @property
    def dtype(self):
        return jnp.dtype("int64")


@dataclasses.dataclass(frozen=True)
class VarcharType(Type):
    """Dictionary-encoded string. Device value: int32 code, order-preserving."""

    def __init__(self):
        object.__setattr__(self, "name", "varchar")

    @property
    def dtype(self):
        return jnp.dtype("int32")

    @property
    def is_string(self) -> bool:
        return True

    @property
    def null_value(self):
        return -1  # codes are >= 0; -1 marks null even without a validity mask


class VarbinaryType(VarcharType):
    """Byte strings, stored through the SAME dictionary machinery as
    VARCHAR via the latin-1 bijection (bytes 0x00-0xFF ↔ U+0000-U+00FF):
    lexicographic order on the mapped text IS byte order, equality is
    byte equality, and `length` is the byte count. Reference:
    spi/type/VarbinaryType + operator/scalar/VarbinaryFunctions."""

    def __init__(self):
        object.__setattr__(self, "name", "varbinary")


class IpAddressType(VarcharType):
    """IPADDRESS: dictionary-encoded like VARCHAR, but the dictionary
    entry is the canonical 16-byte IPv6 form (IPv4 → v4-mapped ::ffff:…)
    through the latin-1 bijection. Byte order on the canonical form IS
    address order, so comparisons / grouping / joins / sorts ride the
    order-preserving code machinery unchanged. Reference:
    presto-main/.../type/IpAddressType.java (16-byte Slice value)."""

    def __init__(self):
        object.__setattr__(self, "name", "ipaddress")


class IpPrefixType(VarcharType):
    """IPPREFIX: canonical 16-byte network address + one prefix-length
    byte; byte order gives the reference's (address, length) ordering.
    Reference: presto-main/.../type/IpPrefixType.java."""

    def __init__(self):
        object.__setattr__(self, "name", "ipprefix")


class HyperLogLogType(VarcharType):
    """HYPERLOGLOG: a serialized sparse-register sketch stored as a
    dictionary entry (expr/hll.py); approx_set/merge/cardinality share
    the approx_distinct lowering's hash + estimator exactly. Reference:
    presto-main/.../type/HyperLogLogType.java."""

    def __init__(self):
        object.__setattr__(self, "name", "hyperloglog")


class TDigestType(VarcharType):
    """TDIGEST(DOUBLE): a serialized centroid-list sketch stored as a
    dictionary entry (expr/tdigest.py) — digests travel as int32 codes
    and scalar functions over them evaluate once per distinct digest.
    Reference: presto-main/.../type/TDigestType.java (Slice-backed)."""

    def __init__(self):
        object.__setattr__(self, "name", "tdigest(double)")


@dataclasses.dataclass(frozen=True)
class ArrayType(Type):
    """ARRAY(element). Device value: [capacity, W] plane of element values
    (element dtype), with per-row `sizes` and an element-validity plane on
    the Column. W is static per batch."""

    element: Type = None  # type: ignore[assignment]

    def __init__(self, element: Type):
        object.__setattr__(self, "name", f"array({element.name})")
        object.__setattr__(self, "element", element)

    @property
    def dtype(self):
        return self.element.dtype


@dataclasses.dataclass(frozen=True)
class MapType(Type):
    """MAP(key, value). Device value: two aligned [capacity, W] planes
    (keys on Column.keys, values on Column.values) sharing `sizes`.
    Map keys are non-null (Presto semantics); map values may be null via
    the element-validity plane."""

    key: Type = None  # type: ignore[assignment]
    value: Type = None  # type: ignore[assignment]

    def __init__(self, key: Type, value: Type):
        object.__setattr__(self, "name", f"map({key.name},{value.name})")
        object.__setattr__(self, "key", key)
        object.__setattr__(self, "value", value)

    @property
    def dtype(self):
        return self.value.dtype


@dataclasses.dataclass(frozen=True)
class RowType(Type):
    """ROW(name type, ...). Planning-time only: analysis flattens field
    access / row construction to the underlying columns."""

    fields: tuple = ()  # tuple[(name, Type), ...]

    def __init__(self, fields):
        fields = tuple((str(n), t) for n, t in fields)
        object.__setattr__(
            self, "name",
            "row(" + ", ".join(f"{n} {t.name}" for n, t in fields) + ")")
        object.__setattr__(self, "fields", fields)

    def field_type(self, name: str) -> "Type":
        for n, t in self.fields:
            if n == name:
                return t
        raise KeyError(f"row type has no field {name}")

    @property
    def dtype(self):
        raise TypeError("ROW has no single device representation")


def is_structural(t: Type) -> bool:
    return isinstance(t, (ArrayType, MapType, RowType))


BOOLEAN = _FixedType("boolean", "bool")
TINYINT = _FixedType("tinyint", "int8")
SMALLINT = _FixedType("smallint", "int16")
INTEGER = _FixedType("integer", "int32")
BIGINT = _FixedType("bigint", "int64")
REAL = _FixedType("real", "float32")
DOUBLE = _FixedType("double", "float64")
DATE = _FixedType("date", "int32")
TIMESTAMP = _FixedType("timestamp", "int64")
# TIME: microseconds since midnight (the reference's TIME w/o time zone;
# spi/type/TimeType — millis there, micros here matching TIMESTAMP)
TIME = _FixedType("time", "int64")
# geometries live as int32 codes into per-expression parsed-WKT tables
# (expr/geo.py); never stored in tables — ST_AsText round-trips to varchar
GEOMETRY = _FixedType("geometry", "int32")
VARCHAR = VarcharType()
VARBINARY = VarbinaryType()
IPADDRESS = IpAddressType()
IPPREFIX = IpPrefixType()
TDIGEST = TDigestType()
HYPERLOGLOG = HyperLogLogType()


_NUMERIC_RANK = {
    "tinyint": 1,
    "smallint": 2,
    "integer": 3,
    "bigint": 4,
    "real": 6,
    "double": 7,
}


def is_numeric(t: Type) -> bool:
    return t.name in _NUMERIC_RANK or isinstance(t, DecimalType)


def is_integral(t: Type) -> bool:
    return t.name in ("tinyint", "smallint", "integer", "bigint")


def is_floating(t: Type) -> bool:
    return t.name in ("real", "double")


def common_super_type(a: Type, b: Type) -> Type:
    """Implicit coercion for binary ops (analog of TypeCoercion in
    sql/analyzer — simplified to the numeric tower + identical types)."""
    if a == b:
        return a
    if isinstance(a, DecimalType) and isinstance(b, DecimalType):
        scale = max(a.scale, b.scale)
        intd = max(a.precision - a.scale, b.precision - b.scale)
        return DecimalType(min(18, intd + scale), scale)
    if isinstance(a, DecimalType) and is_integral(b):
        return a
    if isinstance(b, DecimalType) and is_integral(a):
        return b
    if isinstance(a, DecimalType) and is_floating(b):
        return DOUBLE
    if isinstance(b, DecimalType) and is_floating(a):
        return DOUBLE
    if a.name in _NUMERIC_RANK and b.name in _NUMERIC_RANK:
        r = max(_NUMERIC_RANK[a.name], _NUMERIC_RANK[b.name])
        for name, rank in _NUMERIC_RANK.items():
            if rank == r:
                return {"tinyint": TINYINT, "smallint": SMALLINT,
                        "integer": INTEGER, "bigint": BIGINT,
                        "real": REAL, "double": DOUBLE}[name]
    if a.name == "date" and b.name == "date":
        return DATE
    raise TypeError(f"no common type for {a} and {b}")


def _split_top(s: str) -> list:
    """Split on commas at paren depth 0 ("row(a bigint, b double)" safe)."""
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        parts.append("".join(cur))
    return parts


def parse_type(s: str) -> Type:
    """Parse a SQL type name (for CAST and DDL)."""
    s = s.strip().lower()
    if s.startswith("array(") and s.endswith(")"):
        return ArrayType(parse_type(s[6:-1]))
    if s.startswith("map(") and s.endswith(")"):
        k, v = _split_top(s[4:-1])
        return MapType(parse_type(k), parse_type(v))
    if s.startswith("row(") and s.endswith(")"):
        fields = []
        for part in _split_top(s[4:-1]):
            name, _, ft = part.strip().partition(" ")
            fields.append((name, parse_type(ft)))
        return RowType(fields)
    simple = {
        "boolean": BOOLEAN,
        "tinyint": TINYINT,
        "smallint": SMALLINT,
        "int": INTEGER,
        "integer": INTEGER,
        "bigint": BIGINT,
        "real": REAL,
        "float": REAL,
        "double": DOUBLE,
        "date": DATE,
        "time": TIME,
        "timestamp": TIMESTAMP,
        "geometry": GEOMETRY,
        "varchar": VARCHAR,
        "string": VARCHAR,
        "varbinary": VARBINARY,
        "ipaddress": IPADDRESS,
        "ipprefix": IPPREFIX,
        "tdigest": TDIGEST,
        "tdigest(double)": TDIGEST,
        "hyperloglog": HYPERLOGLOG,
        "p4hyperloglog": HYPERLOGLOG,
    }
    if s in simple:
        return simple[s]
    if s.startswith("varchar(") and s.endswith(")"):
        return VARCHAR
    if s.startswith("decimal"):
        if "(" in s:
            args = s[s.index("(") + 1 : s.rindex(")")].split(",")
            p = int(args[0])
            sc = int(args[1]) if len(args) > 1 else 0
            return DecimalType(p, sc)
        return DecimalType(18, 0)
    raise ValueError(f"unknown type: {s}")
