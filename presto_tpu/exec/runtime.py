"""Batch-streaming execution of logical plans.

The analog of the reference's worker data plane — LocalExecutionPlanner
(operator factory construction), Driver.processInternal:347 (the page loop)
and the operator implementations (HashAggregationOperator,
HashBuilderOperator/LookupJoinOperator, OrderByOperator, ...) — re-shaped
for XLA:

- every *stateless* chain (Filter/Project) between pipeline breakers is
  collapsed into one traced function, so scan→filter→project→partial-agg is
  ONE XLA program per batch (the fusion Presto gets from
  ScanFilterAndProjectOperator + generated PageProcessors, here done by the
  compiler);
- pipeline breakers (Aggregate, Join build, Sort) accumulate fixed-capacity
  device state and grow it geometrically on overflow (detected via returned
  group counts — the recompile-on-growth discipline replaces rehashing);
- streams are python generators of Batches — the Driver loop, at batch not
  page granularity.
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from types import SimpleNamespace
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu.batch import (
    Batch,
    Column,
    concat_columns,
    round_up_capacity,
    slice_column,
)
from presto_tpu.connector import Catalog
from presto_tpu.exec import farm as _farm
from presto_tpu.exec import fragment_jit as _fragment_jit
from presto_tpu.exec import programs as _programs
from presto_tpu.expr.compile import compile_expr, compile_predicate
from presto_tpu.obs import trace as _obs_trace
from presto_tpu.expr.ir import Constant, InputRef, substitute_params
from presto_tpu.expr.structural import StructVal
from presto_tpu.ops.grouping import (KeyCol, StateCol, grouped_merge,
                                     partition_skew)
from presto_tpu.ops.join import (
    BuildTable,
    MwSpec,
    align_probe_strings,
    build_side,
    gather_join_output,
    hash_build_side,
    hash_probe_counts,
    hash_probe_expand,
    hash_probe_unique,
    join_compare_dtypes,
    multiway_counts,
    multiway_expand,
    multiway_probe_unique,
    probe_counts,
    probe_expand,
    probe_unique,
    table_rows,
)
from presto_tpu.ops.sort import (
    SortKey,
    compact,
    limit_batch,
    permute_batch,
    sort_batch,
    sort_permutation,
)
from presto_tpu.plan.agg_states import (
    agg_state_layout,
    limb_pairs,
    sum_state_type,
)
from presto_tpu.plan.nodes import (
    Aggregate,
    AggSpec,
    Filter,
    HashJoin,
    IndexJoin,
    Limit,
    MultiwayJoin,
    NestedLoopJoin,
    OneRow,
    Output,
    PlanNode,
    Project,
    QueryPlan,
    RemoteSource,
    SemiJoin,
    SetOp,
    Sort,
    TableScan,
    Unnest,
    Window,
)
from presto_tpu.types import BIGINT, DOUBLE, DecimalType, Type


@dataclasses.dataclass
class ExecConfig:
    """Session knobs (reference: SystemSessionProperties / TaskManagerConfig)."""

    batch_rows: int = 1 << 17  # rows per scan batch
    agg_capacity: int = 1 << 12  # initial group-table capacity
    # High-NDV group tables are the wrong tool on XLA: every merge step
    # sorts (capacity + batch) rows, so a CBO-pre-sized multi-million-slot
    # table makes each batch pay a mostly-dead mega-sort (measured: q3 SF1
    # RUN went 68.7s -> small-cap partitioned in seconds on CPU). Above
    # this ceiling the aggregation goes GRACE: raw input hash-partitions to
    # spill (host-side, dynamic shapes are free there) and each partition
    # merges independently at small capacity — the reference's
    # SpillableHashAggregationBuilder / grouped-execution shape.
    agg_cap_ceiling: int = 1 << 17
    # how many aggregate merge steps may be in flight before their group
    # counts are confirmed on the host. Device→host syncs on a tunneled TPU
    # cost a full round trip (~70-90 ms measured), so the driver dispatches
    # optimistically and replays from a held checkpoint on the rare
    # capacity overflow (reference analog: none — the JVM has no dispatch
    # latency; this is TPU-native pipelining)
    agg_pipeline_depth: int = 3
    topn_slack: int = 4
    join_out_capacity: Optional[int] = None  # default: probe batch capacity
    # coalesce sparse join output batches before downstream operators
    # (MergingPageOutput analog; see _merging_output)
    merge_sparse_output: bool = True
    max_growth_retries: int = 24
    # EXPLAIN ANALYZE: per-operator wall/rows/batches accounting (forces a
    # device sync per batch — off in production, like Presto's verbose stats)
    collect_stats: bool = False
    # query-lifecycle span tracing (obs/trace.py): operator, compile,
    # host_decode, device_transfer, exchange_wait spans. Cheap enough to
    # stay on (no per-batch device sync); False makes every span site a
    # single attribute check on the NOOP tracer
    tracing: bool = True
    # memory + spill (reference: MemoryPool / spiller; None = unlimited)
    memory_pool_bytes: Optional[int] = None
    spill_enabled: bool = True
    spill_dir: Optional[str] = None
    spill_partitions: int = 8
    # dynamic hybrid hash spill (spiller.py): how many times a spill
    # partition may split by the next hash bits — mid-build when it blows
    # past its byte budget, or at replay when the partition still doesn't
    # fit the memory budget (recursive repartitioning). A partition that
    # exceeds the budget at max depth fails with SPILL_LIMIT_EXCEEDED
    # (identical keys share every hash bit and can never split).
    spill_max_depth: int = 4
    # spill directory byte budget: a spill write that would push the
    # directory's live footprint past this fails the spilling query with
    # SPILL_LIMIT_EXCEEDED instead of filling the disk. None = unlimited.
    spill_dir_budget_bytes: Optional[int] = None
    memory_revoking_threshold: float = 0.9
    memory_revoking_target: float = 0.5
    # Aria selective scan (scan/ package): constrained scans on connectors
    # with a read_split_selective path filter rows DURING host decode and
    # upload only survivors. Off → decode-everything + device-side filter
    # (the pre-Aria shape; also the oracle for bit-identical-result tests)
    selective_scan: bool = True
    # background split prefetch depth: decode/stage split i+1..i+depth on a
    # host thread while the device computes split i (the IO/compute overlap
    # of the reference's async split loading — PageSourceProvider readers
    # run ahead of the driver). 0 disables.
    scan_prefetch: int = 2
    # query-level elastic retry (the reference's RetryPolicy.QUERY): on a
    # failed/unreachable worker the coordinator re-probes the cluster,
    # drops dead nodes, and re-executes the whole query this many times
    query_retry_count: int = 1
    # stage scheduling policy (reference: execution/scheduler/
    # AllAtOnceExecutionPolicy vs PhasedExecutionSchedule): "phased" defers
    # probe-side stages until their join build stages finish, cutting peak
    # cluster memory on multi-join plans
    execution_policy: str = "all-at-once"
    # recoverable grouped execution (SystemSessionProperties.java:69): a
    # colocated-join fragment schedules one task per lifespan (bucket) in
    # a gated phase; a worker lost mid-phase re-runs only its unfinished
    # buckets on survivors instead of retrying the whole query
    recoverable_grouped_execution: bool = False
    # phased mode: how long one build phase may run before the query fails
    phase_wait_timeout_s: float = 600.0
    # coordinator-side split placement with rendezvous-hash soft affinity
    # (reference: scheduler/NodeScheduler + SimpleNodeSelector and the
    # SOFT_AFFINITY NodeSelectionStrategy): a split lands on the same
    # worker across queries, so the worker's device split cache turns
    # placement stability into real scan locality. Off → static
    # task_index::n_tasks striding.
    split_affinity: bool = True
    # within-worker radix partitioning for pipeline breakers (ops/radix.py):
    # joins and keyed aggregations split both sides by the top bits of the
    # content hash and run each partition's build/probe (or group merge) at
    # a small bounded capacity — the same handful of compiled shapes
    # regardless of input size. Must be a power of two; 0/1 = off (the
    # classic single-table path).
    radix_partitions: int = 0
    # hybrid spill: a radix partition whose build side exceeds this byte
    # budget serializes its batches to host spill (serde page format) and is
    # processed after the in-memory partitions. None = never (partitions
    # stay resident); the reference analog is the dynamic hybrid hash
    # join's per-partition memory budget.
    join_spill_budget_bytes: Optional[int] = None
    # bounded-recompile guard (analysis/recompile.py): fail the query when
    # any single node program compiled more than this many distinct shapes
    # — the "bounded compiled shapes" promise of the radix/bucketing work
    # enforced, not just rendered by EXPLAIN ANALYZE. None = off.
    max_compiled_shapes: Optional[int] = None
    # per-operator-CLASS overrides of the guard: streaming scan-chain
    # nodes emit one padded capacity and should stay near 1-2 shapes,
    # while pipeline breakers legitimately see pow2 growth ladders. None
    # = fall back to max_compiled_shapes.
    max_compiled_shapes_scan: Optional[int] = None
    max_compiled_shapes_breaker: Optional[int] = None
    # donate accumulator buffers on linearly-threaded stepping programs
    # (TopN step, global-aggregate step): the caller never reuses the
    # input accumulator, so XLA may update it in place instead of
    # double-buffering accumulator HBM. Keyed-agg steps are NOT donated —
    # the optimistic dispatch window holds acc_before for overflow replay.
    donate_stepping: bool = True
    # ahead-of-stream precompilation: trace+compile scan-side fused chain
    # programs on this many background threads at plan install, so
    # compilation overlaps host scan decode instead of serializing in
    # front of batch 0. 0 disables.
    precompile_workers: int = 0
    # whole-fragment device residency (exec/fragment_jit.py): stack up to
    # fragment_window consecutive same-structure scan batches and fold the
    # breaker step over the window inside ONE compiled program (lax.scan),
    # collapsing O(batches) per-batch dispatches to O(batches / window).
    # Applies to scan-rooted leaf fragments feeding a decomposable
    # aggregate or a TopN sort; everything else (unnest, host projections,
    # spill replay, grouped execution, radix) keeps the per-batch path.
    # fragment_fusion=False preserves the per-batch path everywhere.
    fragment_fusion: bool = True
    fragment_window: int = 8
    # breaker engine selection (ops/pallas_hash vs the sorted-primitive
    # engine): "auto" lets the CBO (plan/stats.choose_breaker_engine) pick
    # per breaker from derived NDV/row-count/payload-width stats; "sort" /
    # "hash" force one engine everywhere (the hash side of the forcing is
    # what the engine-equivalence verifier sweeps run)
    breaker_engine: str = "auto"
    # multiway join collapse (plan/multiway.py): "auto" lets the CBO
    # (plan/stats.choose_join_mode) fold eligible star-schema join chains
    # into one MultiwayJoin probe program per HBO-corrected build sizes
    # and selectivities; "multiway" forces every eligible chain;
    # "binary" runs the pass but always declines (stamping the verdict
    # in EXPLAIN); "off" skips the pass — the pre-collapse plan
    # bit-for-bit.
    join_mode: str = "auto"
    # history-based optimization (obs/runstats.py): "observe" (default)
    # records estimate-vs-actual drift at every stats-driven decision site
    # keyed on structural fingerprints; "correct" additionally feeds
    # observed values back into engine choice / presize / lane sizing on a
    # repeat of the same structure; "off" is a strict no-op — the pre-HBO
    # engine bit-for-bit (no observation syncs, no history writes).
    hbo: str = "observe"
    # device cost & HBM accounting plane (obs/devprof.py): "on" records
    # XLA cost_analysis/memory_analysis per compiled program, samples
    # device.memory_stats() watermarks, and reconciles them against the
    # MemoryPool ledger; "off" (default) is a strict no-op — no extra
    # lowering, no sampler thread, today's engine bit-for-bit.
    devprof: str = "off"
    # on-demand jax.profiler capture for this query's execution, dumped
    # under PRESTO_TPU_CACHE_DIR (no-op with a warning when the profiler
    # or the cache dir is unavailable)
    profile: bool = False
    # serving-plane SLO telemetry (obs/lifecycle.py): "on" makes worker
    # task sinks count emitted rows/batches so heartbeats carry live
    # query progress; "off" is a strict no-op — pre-lifecycle task path
    # and heartbeat doc bit-for-bit.
    lifecycle: str = "on"
    # semantic result cache (server/result_cache.py): "query" memoizes
    # final results keyed on (structural plan sha, catalog snapshot token,
    # session catalog.schema); "subplan" additionally materializes and
    # reuses breaker-subplan results; "off" (default) is a strict no-op —
    # no cache consult, no metric families, no events, today's engine
    # bit-for-bit.
    result_cache: str = "off"
    # pow2 shape bucketing (exec/farm.py subsystem): "pow2" pads
    # merging-output flushes and partial jit windows up to their
    # power-of-two bucket (capped at the stream's target capacity), so the
    # distinct-aval set reaching _node_jit collapses to one shape per
    # stream instead of a per-flush ladder — fewer avals, fewer compiles,
    # charged once per bucket against the recompile budgets. "off"
    # (default) is a strict no-op — today's flush/window shapes
    # bit-for-bit. Padding only adds dead lanes (live=False), which every
    # kernel already masks, so results are identical either way.
    shape_bucketing: str = "off"
    # ahead-of-traffic compile farm (exec/farm.py): "on" records every
    # installed plan into the persistent farm corpus under
    # PRESTO_TPU_CACHE_DIR and lets server planes boot-arm the program
    # cache / speculatively precompile during queue wait; "off" (default)
    # is a strict no-op — no corpus writes, no claims, no metric families.
    compile_farm: str = "off"
    # mid-flight telemetry plane (obs/inflight.py): "on" makes drivers
    # publish operator watermarks (windows dispatched, rows in/out, spill
    # depth/repartitions, replay caps, lane util) into the per-query
    # inflight store at wave/window boundaries — host-held counts only,
    # never a fresh device sync; "off" (default) is a strict no-op — no
    # publishes, no watcher, no metric families, today's engine
    # bit-for-bit.
    inflight: str = "off"
    # stall detector bound: row watermarks frozen for this many seconds
    # while the query executes → stall_detected event + forensics dump
    stall_threshold_s: float = 2.0
    # straggler detector bound: a fragment site > factor x behind its
    # siblings' window watermark → straggler_detected event + slow-log doc
    straggler_factor: float = 4.0
    # in-run adaptation (exec/adaptive.py): "off" (default) is a strict
    # no-op — pre-adaptive engine bit-for-bit; "observe" evaluates every
    # decision point and logs what it WOULD do (events, EXPLAIN, doctor)
    # without acting; "on" acts — engine flips between replay waves,
    # forward-propagating presize/lane sizing, device-radix partition
    # growth, largest-partition-first partial revocation. Cache-volatile:
    # a flipped engine forks program keys via the existing @h suffix, so
    # the knob itself never changes what any one program computes.
    adaptive: str = "off"


def _node_jit(node: PlanNode, key: str, builder, _shared=True, **jit_kwargs):
    """Node-facing jit memoization, delegating to the process-wide
    structural program cache (exec/programs.py — the analog of Presto's
    codegen class cache: ExpressionCompiler's generated classes are keyed
    by expression structure and reused across every execution of the same
    plan shape). Nodes stamped by ``programs.install_plan`` share one
    compiled program per (structural namespace, key, jit kwargs) across
    plans, fragments, concurrent tasks and queries; unstamped nodes (and
    ``_shared=False`` call sites, whose builders close over runtime data
    such as a materialized build table) keep a private entry.

    Compile events (count + wall, detected via jit cache-size growth) are
    claimed under the entry's lock — exact under concurrency — and mirrored
    into node._jit_stats[key] for EXPLAIN ANALYZE and the recompile guard."""
    cache = node.__dict__.setdefault("_jit_cache", {})
    fn = cache.get(key)
    if fn is None:
        stats = node.__dict__.setdefault("_jit_stats", {}).setdefault(
            key, {"compiles": 0, "compile_wall_s": 0.0})
        ns = node.__dict__.get("_program_ns") if _shared else None
        entry = _programs.entry_for(
            ns, type(node).__name__, key, jit_kwargs,
            lambda: jax.jit(builder(), **jit_kwargs))
        fn = cache[key] = _programs.wrap(entry, stats,
                                         type(node).__name__, key)
    return fn


class ExecContext:
    def __init__(self, catalog: Catalog, config: ExecConfig,
                 memory_pool=None, spill_manager=None):
        from presto_tpu.memory import MemoryPool
        from presto_tpu.spiller import SpillManager

        self.catalog = catalog
        self.config = config
        self.stats: Dict[str, float] = {}
        # per-plan-node OperatorStats analog (keyed by id(node)):
        # {"rows": ..., "batches": ..., "wall_s": ..., "bytes": ...}
        self.node_stats: Dict[int, Dict[str, float]] = {}
        # span tracer (obs/trace.py). NOOP unless a server plane (worker
        # task / coordinator run) or the LocalRunner installs a real one —
        # config.tracing only matters where a tracer gets installed
        self.tracer = _obs_trace.NOOP
        # distributed task context (set by the worker; None for LocalRunner):
        # this task reads splits[task_index::n_tasks] of every scanned table
        # (SOURCE_DISTRIBUTION split placement, statically assigned)
        self.task_index: int = 0
        self.n_tasks: int = 1
        # coordinator-assigned split ordinals per table (soft-affinity
        # placement — scheduler/NodeScheduler analog). None → static
        # task_index::n_tasks striding; ordinals index the connector's
        # deterministic unpruned split enumeration; split_counts carries
        # the coordinator's enumeration size per table (mismatch at scan
        # time = the table changed underneath the plan → loud failure)
        self.split_assignment: Optional[Dict[str, List[int]]] = None
        self.split_counts: Optional[Dict[str, int]] = None
        # grouped (lifespan) execution: when set, scans of bucketed tables
        # read ONLY this bucket's splits (Lifespan.java:26-38 — the driver
        # group id); the colocated-join executor sweeps it over the task's
        # assigned buckets
        self.lifespan: Optional[int] = None
        # total lifespans of the active grouped-execution sweep (None when
        # not sweeping): lets operators size per-bucket state (a bucket
        # holds ~1/lifespans of the groups) and run memory-tight
        self.lifespans: Optional[int] = None
        # fragment_id -> callable returning an iterator of Batches pulled
        # from the exchange (the ExchangeOperator's client)
        self.remote_sources = None
        # memory + spill: worker-shared when provided, else per-context
        # (QueryContext → MemoryPool; SpillSpaceTracker)
        self.memory_pool = memory_pool or MemoryPool(
            config.memory_pool_bytes,
            revoke_threshold=config.memory_revoking_threshold,
            revoke_target=config.memory_revoking_target,
        )
        self.spill_manager = spill_manager or SpillManager(config.spill_dir)
        if (config.spill_dir_budget_bytes is not None
                and self.spill_manager.budget_bytes is None):
            self.spill_manager.budget_bytes = config.spill_dir_budget_bytes
        # every spiller/spill-file an operator opens registers here so task
        # teardown can close+unlink them even when the operator generator
        # died mid-spill (failed or canceled query) — close() is idempotent
        self.spill_resources: List = []
        # mid-flight telemetry publisher (obs/inflight.TaskInflight) —
        # installed by the worker task when the `inflight` session
        # property is on; None = every publish hook is a no-op
        self.inflight = None
        # in-run adaptation controller (exec/adaptive.AdaptiveState) —
        # None when the `adaptive` session property is off, which keeps
        # every decision site a single attribute check (strict no-op)
        self.adaptive = None
        if getattr(config, "adaptive", "off") != "off":
            from presto_tpu.exec.adaptive import AdaptiveState

            self.adaptive = AdaptiveState(config.adaptive)

    def track_spill(self, resource) -> None:
        self.spill_resources.append(resource)

    def cleanup_spill(self) -> None:
        """Leak guard: close (and unlink) every spill resource this context
        ever opened. Safe to call repeatedly and after normal closes."""
        for r in self.spill_resources:
            try:
                r.close()
            except Exception:
                pass
        self.spill_resources = []

    def should_spill(self, projected_delta_bytes: int) -> bool:
        """Would adding this reservation cross the revoke threshold?"""
        pool = self.memory_pool
        if pool.limit is None or not self.config.spill_enabled:
            return False
        return (pool.reserved + projected_delta_bytes
                > pool.limit * pool.revoke_threshold)

    def record(self, node, rows: int, wall_s: float, bytes_: int = 0):
        s = self.node_stats.setdefault(
            id(node), {"rows": 0, "batches": 0, "wall_s": 0.0, "bytes": 0}
        )
        s["rows"] += rows
        s["batches"] += 1
        s["wall_s"] += wall_s
        s["bytes"] += bytes_


# ---------------------------------------------------------------------------
# stateless chain fusion


def collapse_chain(node: PlanNode) -> Tuple[PlanNode, Callable[[Batch], Batch]]:
    """Peel Filter/Project off `node` until a breaker; return (base, fn)
    where fn applies the whole chain at trace time (so it fuses into
    whatever jit program calls it). Memoized per node so repeated
    executions of a cached plan reuse the same function objects (and hence
    every jit trace)."""
    memo = node.__dict__.get("_collapsed")
    if memo is not None:
        return memo
    steps: List[Callable[[Batch], Batch]] = []
    cur = node
    while True:
        if isinstance(cur, Filter):
            pred = compile_predicate(cur.predicate)

            def step(b: Batch, pred=pred) -> Batch:
                return b.with_live(b.live & pred(b))

            steps.append(step)
            cur = cur.child
        elif isinstance(cur, Project):
            compiled = [(s, e.type, compile_expr(e), e) for s, e in cur.exprs]

            def step(b: Batch, compiled=compiled) -> Batch:
                names, types, cols = [], [], []
                dicts = {}
                for s, t, fn, e in compiled:
                    if isinstance(e, InputRef):
                        # identity projection: reuse the column object —
                        # cheaper, and preserves long-decimal limbs that a
                        # re-evaluation through the expression compiler
                        # would truncate to int64
                        names.append(s)
                        types.append(t)
                        cols.append(b.column(e.name))
                        if e.name in b.dicts:
                            dicts[s] = b.dicts[e.name]
                        if e.name + "#keys" in b.dicts:
                            dicts[s + "#keys"] = b.dicts[e.name + "#keys"]
                        continue
                    v, valid = fn(b)
                    if isinstance(v, StructVal):
                        # structural (ARRAY/MAP) expression result
                        names.append(s)
                        types.append(t)
                        cols.append(Column(v.values, valid, sizes=v.sizes,
                                           evalid=v.evalid, keys=v.keys))
                        ed, kd = fn.sdicts(b)
                        if ed is not None:
                            dicts[s] = ed
                        if kd is not None:
                            dicts[s + "#keys"] = kd
                        continue
                    v = jnp.broadcast_to(v, (b.capacity,)).astype(t.dtype)
                    if valid is not None and getattr(valid, "ndim", 1) == 0:
                        # scalar validity (e.g. divide-by-constant guard)
                        # must widen with the values: downstream gathers
                        # index it per row
                        valid = jnp.broadcast_to(valid, (b.capacity,))
                    names.append(s)
                    types.append(t)
                    cols.append(Column(v, valid))
                    # identity projections keep their dictionary; computed
                    # string expressions carry their synthesized one
                    if isinstance(e, InputRef) and e.name in b.dicts:
                        dicts[s] = b.dicts[e.name]
                    elif getattr(fn, "out_dict", None) is not None:
                        dicts[s] = fn.out_dict
                    elif getattr(fn, "dyn_dict", None) is not None:
                        d = fn.dyn_dict(b)
                        if d is not None:
                            dicts[s] = d
                return Batch(names, types, cols, b.live, dicts)

            steps.append(step)
            cur = cur.child
        else:
            break

    if not steps:
        result = (cur, None)
    else:
        steps.reverse()

        def chain(b: Batch) -> Batch:
            for s in steps:
                b = s(b)
            return b

        result = (cur, chain)
    node.__dict__["_collapsed"] = result
    return result


# ---------------------------------------------------------------------------
# node executors


def execute_node(node: PlanNode, ctx: ExecContext) -> Iterator[Batch]:
    """Execute a plan node to a stream of batches. Any Filter/Project chain
    sitting on top of a breaker is applied per output batch (jitted once);
    breakers fuse the chain *below* them into their own stepping programs
    via _fused_child."""
    base, down = collapse_chain(node)
    stream = _execute_base(base, ctx)
    if ctx.config.collect_stats:
        stream = _instrumented(stream, base, ctx)
    if ctx.tracer.enabled:
        stream = _traced(stream, base, ctx)
    if down is not None:
        jfn = _node_jit(node, "down", lambda: down)
        stream = (jfn(b) for b in stream)
    if ctx.config.merge_sparse_output and isinstance(
            base, (HashJoin, MultiwayJoin, SemiJoin, NestedLoopJoin,
                   IndexJoin)):
        # selective operators emit batches at probe CAPACITY whose live
        # occupancy can be ~1%; every downstream per-batch cost (sorts,
        # merges, probes) is capacity-shaped, so coalesce before fanning
        # out (reference: operator/project/MergingPageOutput.java)
        stream = _merging_output(stream, ctx.config.batch_rows,
                                 bucket=ctx.config.shape_bucketing != "off")
    yield from stream


def _pad_batch(b: Batch, cap: int) -> Batch:
    """Pad rows with dead lanes up to cap (keeps capacities power-of-two
    so downstream per-shape jit caches stay bounded)."""
    extra = cap - b.capacity
    if extra <= 0:
        return b

    def padp(p, fill=0):
        if p is None:
            return None
        widths = [(0, extra)] + [(0, 0)] * (p.ndim - 1)
        return jnp.pad(p, widths, constant_values=fill)

    cols = [
        Column(padp(c.values),
               padp(c.validity, False),
               padp(c.hi), padp(c.sizes), padp(c.evalid, False),
               padp(c.keys))
        for c in b.columns
    ]
    return Batch(b.names, b.types, cols, padp(b.live, False), b.dicts)


def _merging_output(stream: Iterator[Batch], target_cap: int,
                    bucket: bool = False) -> Iterator[Batch]:
    """MergingPageOutput analog: compact sparse batches (live rows to the
    front), slice them to their power-of-two bucket, and concatenate until
    a full batch accumulates. Dense batches pass through untouched; empty
    batches are dropped. Costs one host sync per input batch (num_live) —
    repaid many times over by the capacity-shaped work it removes
    downstream on selective multi-join plans.

    ``bucket`` (shape_bucketing=pow2) additionally pads every flush —
    including the single-batch passthrough — up to the stream's pow2
    target capacity, so downstream programs see ONE flush shape instead
    of a per-flush pow2 ladder; padding adds only dead lanes (live=False),
    which every kernel masks, so results are unchanged."""
    pending: List[Batch] = []
    pending_live = 0
    bucket_cap = round_up_capacity(max(int(target_cap), 1)) if bucket else 0

    def flush():
        nonlocal pending, pending_live
        if len(pending) == 1:
            out = pending[0]
        else:
            out = _collect_concat(iter(pending))
            # concat of mixed pow2 slices is no longer pow2 itself —
            # re-bucket so downstream programs see a bounded shape set
            out = _pad_batch(out, round_up_capacity(out.capacity))
        if bucket:
            out = _pad_batch(
                out, max(round_up_capacity(out.capacity), bucket_cap))
        pending, pending_live = [], 0
        return out

    def consume(b, n):
        nonlocal pending_live
        if n == 0:
            return None
        if 2 * n >= b.capacity:
            return b  # dense: pass through (flushing pending first)
        pending.append(_truncate(_JIT_COMPACT(b), round_up_capacity(n)))
        pending_live += n
        return None

    # one-batch lookahead: the live count is dispatched and fetched
    # asynchronously while the NEXT batch computes, so dense streams don't
    # pay a blocking device→host sync per batch (same optimistic pattern
    # as the aggregate's dispatch window)
    window: List[Tuple[Batch, "jnp.ndarray"]] = []

    def drain(block_all: bool):
        while window and (block_all or len(window) > 1):
            b, cnt = window.pop(0)
            dense = consume(b, int(cnt))
            if dense is not None:
                if pending:
                    yield flush()
                yield dense
            elif pending_live >= target_cap:
                yield flush()

    for b in stream:
        cnt = jnp.sum(b.live)
        try:
            cnt.copy_to_host_async()
        except Exception:
            pass
        window.append((b, cnt))
        yield from drain(block_all=False)
    yield from drain(block_all=True)
    if pending:
        yield flush()


def _instrumented(stream: Iterator[Batch], node: PlanNode, ctx: ExecContext):
    """OperatorStats collection (reference: OperationTimer stamping every
    addInput/getOutput into OperatorStats, Driver.java:277)."""
    import time as _time

    from presto_tpu.memory import batch_device_bytes

    while True:
        t0 = _time.perf_counter()
        try:
            b = next(stream)
        except StopIteration:
            return
        rows = int(jnp.sum(b.live))  # forces device sync
        ctx.record(node, rows, _time.perf_counter() - t0,
                   bytes_=batch_device_bytes(b))
        yield b


def _traced(stream: Iterator[Batch], node: PlanNode, ctx: ExecContext):
    """Span wrapper: one aggregate `operator` span per plan node (total
    span = first pull to exhaustion; busy_s = time actually spent inside
    next()), plus a kernel-wall histogram observation per batch. No device
    syncs — this stays on in production, unlike _instrumented."""
    import time as _time

    from presto_tpu.obs import metrics as _obs_metrics

    tracer = ctx.tracer
    parent = tracer.current_parent()
    start = _time.time()
    busy = 0.0
    batches = 0
    try:
        while True:
            t0 = _time.perf_counter()
            try:
                b = next(stream)
            except StopIteration:
                return
            dt = _time.perf_counter() - t0
            busy += dt
            batches += 1
            _obs_metrics.BATCH_KERNEL_WALL.observe(dt, plane="worker")
            yield b
    finally:
        tracer.record(type(node).__name__, "operator", start, _time.time(),
                      parent_id=parent, busy_s=round(busy, 6),
                      batches=batches)


def _fused_child(node: PlanNode, ctx: ExecContext):
    """(raw input stream, chain-to-apply-inside-your-jit) for a breaker's
    child — the ScanFilterAndProject fusion point."""
    base, up = collapse_chain(node)
    stream = _execute_base(base, ctx)
    if ctx.config.collect_stats:
        stream = _instrumented(stream, base, ctx)
    if ctx.tracer.enabled:
        stream = _traced(stream, base, ctx)
    if ctx.config.merge_sparse_output and isinstance(
            base, (HashJoin, MultiwayJoin, SemiJoin, NestedLoopJoin,
                   IndexJoin)):
        # breakers pull children through here, not execute_node — apply
        # the same sparse-output coalescing before the consumer's chain
        stream = _merging_output(stream, ctx.config.batch_rows,
                                 bucket=ctx.config.shape_bucketing != "off")
    return stream, (up or (lambda b: b))


def _execute_base(base: PlanNode, ctx: ExecContext) -> Iterator[Batch]:
    if isinstance(base, TableScan):
        yield from _scan_batches(base, ctx)
        return
    if isinstance(base, Aggregate):
        yield from _execute_aggregate(base, ctx)
        return
    if isinstance(base, HashJoin):
        yield from _execute_join(base, ctx)
        return
    if isinstance(base, MultiwayJoin):
        yield from _execute_multiway_join(base, ctx)
        return
    if isinstance(base, IndexJoin):
        yield from _execute_index_join(base, ctx)
        return
    if isinstance(base, NestedLoopJoin):
        yield from _execute_nljoin(base, ctx)
        return
    if isinstance(base, SemiJoin):
        yield from _execute_semijoin(base, ctx)
        return
    if isinstance(base, SetOp):
        yield from _execute_setop(base, ctx)
        return
    if isinstance(base, Unnest):
        yield from _execute_unnest(base, ctx)
        return
    if isinstance(base, OneRow):
        cap = 128
        live = np.zeros(cap, bool)
        live[0] = True
        yield Batch([], [], [], jnp.asarray(live), {})
        return
    from presto_tpu.plan.nodes import HostProject as _HP

    if isinstance(base, _HP):
        yield from _execute_host_project(base, ctx)
        return
    from presto_tpu.plan.nodes import TableWriter as _TW

    if isinstance(base, _TW):
        # scaled writer: this task writes its stream as one part and
        # emits its row count (TableWriterOperator analog)
        conn = ctx.catalog.connectors[base.catalog]
        batches = list(execute_node(base.child, ctx))
        n = conn.write_part(base.table,
                            f"{base.write_id}-{ctx.task_index:04d}",
                            batches) if batches else 0
        vals = np.zeros(128, np.int64)
        vals[0] = n
        live = np.zeros(128, bool)
        live[0] = True
        yield Batch(["rows"], [BIGINT],
                    [Column(jnp.asarray(vals), None)],
                    jnp.asarray(live), {})
        return
    if isinstance(base, Sort):
        yield from _execute_sort(base, ctx)
        return
    if isinstance(base, Window):
        yield from _execute_window(base, ctx)
        return
    if isinstance(base, Limit):
        remaining = base.count
        jlimit = _JIT_LIMIT  # `n` traced: one compile per shape
        for b in execute_node(base.child, ctx):
            out = jlimit(b, remaining)
            n = out.num_live()
            remaining -= n
            yield out
            if remaining <= 0:
                return
        return
    if isinstance(base, Output):
        # project to the user-facing schema (worker-side in distributed
        # plans; run_plan applies the same projection for local plans)
        for b in execute_node(base.child, ctx):
            yield b.select(base.symbols).rename(base.names)
        return
    if isinstance(base, RemoteSource):
        if ctx.remote_sources is None:
            raise RuntimeError("RemoteSource outside a distributed task")
        yield from ctx.remote_sources(base.fragment_id)
        return
    raise NotImplementedError(f"no executor for {type(base).__name__}")


# -- scan -------------------------------------------------------------------


def _scan_batches(scan: TableScan, ctx: ExecContext) -> Iterator[Batch]:
    conn = ctx.catalog.connectors[scan.catalog]
    handle = conn.get_table(scan.table)
    nrows = int(handle.row_count or 0)
    nsplits = max(1, -(-nrows // ctx.config.batch_rows))
    columns = list(scan.assignments.values())
    symbols = list(scan.assignments.keys())
    if not columns:
        # COUNT(*)-style scan with no referenced columns: fabricate liveness.
        # In a distributed task each task accounts its slice of the rows.
        per = nrows // ctx.n_tasks + (1 if ctx.task_index < nrows % ctx.n_tasks else 0)
        cap = round_up_capacity(min(per, ctx.config.batch_rows) or 1)
        done = 0
        while done < per or (done == 0 and ctx.task_index == 0):
            take = min(cap, per - done)
            live = np.zeros(cap, bool)
            live[:take] = True
            yield Batch([], [], [], jnp.asarray(live), {})
            done += take
            if done >= per:
                return
        return
    cap = round_up_capacity(min(nrows, ctx.config.batch_rows) or 1)
    splits = conn.splits(handle, nsplits)
    read_split = conn.read_split
    assigned = (ctx.split_assignment or {}).get(scan.table)
    if ctx.lifespan is not None and any(
            s.bucket is not None for s in splits):
        # grouped execution: this pass reads one bucket only; bucket→task
        # assignment already happened in the lifespan sweep
        splits = [s for s in splits if s.bucket == ctx.lifespan]
    elif assigned is not None:
        # coordinator soft-affinity placement: ordinals index the
        # UNPRUNED enumeration (both sides enumerate deterministically).
        # A count mismatch means the table changed between planning and
        # scan — silently proceeding would drop (or double-read) splits
        expected = (ctx.split_counts or {}).get(scan.table)
        if expected is not None and expected != len(splits):
            raise RuntimeError(
                f"split enumeration for {scan.table} changed underneath "
                f"the plan (coordinator saw {expected}, scan sees "
                f"{len(splits)}) — retry the query")
        splits = [splits[i] for i in assigned if i < len(splits)]
    elif ctx.n_tasks > 1:
        splits = splits[ctx.task_index::ctx.n_tasks]
    if scan.constraints and hasattr(conn, "prune_splits"):
        storage_bounds = _constraints_to_storage(scan, handle)
        if storage_bounds:
            from presto_tpu.scan import metrics as _scan_metrics

            before = len(splits)
            splits = conn.prune_splits(handle, splits, storage_bounds)
            ctx.stats[f"scan.{scan.table}.splits_pruned"] = before - len(splits)
            _scan_metrics.record("splits_pruned", before - len(splits))
    if scan.constraints and hasattr(conn, "read_split_constrained"):
        # full predicate pushdown: the connector evaluates the range
        # constraints at the source (remote service / SQL WHERE) instead
        # of just pruning splits (TupleDomain → getRows semantics)
        bounds = _constraints_to_storage(scan, handle)
        if bounds:
            def read_split(split, columns, capacity=None,
                           _b=bounds):  # noqa: E306
                return conn.read_split_constrained(
                    split, columns, capacity=capacity, constraints=_b)
    if (scan.constraints and ctx.config.selective_scan
            and hasattr(conn, "read_split_selective")):
        # Aria selective scan: compile the constraints into host value
        # filters (scan/filters.py) and read each split through the
        # predicate-during-decode path — filter columns decode first, the
        # cascade shrinks a selection vector in adaptive order, payload
        # columns decode/upload only for survivors. The exact device
        # filter above the scan still runs (host filters are conservative
        # supersets), so results never depend on this layer.
        from presto_tpu.scan import metrics as _scan_metrics
        from presto_tpu.scan.adaptive import AdaptiveFilterOrder
        from presto_tpu.scan.filters import filters_from_constraints

        filters = filters_from_constraints(scan.constraints, handle)
        if filters:
            adaptive = AdaptiveFilterOrder()
            _prefix = f"scan.{scan.table}"

            def _count(name, delta, _p=_prefix):
                key = f"{_p}.{name}"
                ctx.stats[key] = ctx.stats.get(key, 0) + delta
                _scan_metrics.record(name, delta)

            def read_split(split, columns, capacity=None,  # noqa: E306
                           _f=filters, _a=adaptive):
                return conn.read_split_selective(
                    split, columns, _f, capacity=capacity, adaptive=_a,
                    counters=_count)
    if ctx.tracer.enabled:
        # host_decode / device_transfer sub-spans per split. The parent is
        # captured HERE (the consumer thread, under the task/query span) —
        # the prefetch producer thread has no span stack of its own.
        _tracer = ctx.tracer
        _scan_parent = _tracer.current_parent()
        _inner_read = read_split

        def read_split(split, columns, capacity=None,  # noqa: E306
                       _rs=_inner_read):
            w0 = time.time()
            b = _rs(split, columns, capacity=capacity)
            w1 = time.time()
            _tracer.record("host_decode", "host_decode", w0, w1,
                           parent_id=_scan_parent, table=scan.table)
            # upload dispatch only — never block on device readiness here:
            # a sync per split would serialize the prefetch pipeline the
            # engine is built around (collect_stats is the opt-in sync path)
            _tracer.record("device_transfer", "device_transfer", w1,
                           time.time(), parent_id=_scan_parent,
                           table=scan.table)
            return b
    depth = ctx.config.scan_prefetch
    if depth <= 0 or len(splits) <= 1:
        for split in splits:
            b = read_split(split, columns, capacity=cap)
            yield b.rename(symbols)
        return
    # pipelined scan: a host thread decodes/stages splits ahead of the
    # device (bounded queue so memory stays O(depth) batches)
    import queue as _queue
    import threading as _threading

    q: "_queue.Queue" = _queue.Queue(maxsize=depth)
    _SENTINEL = object()
    stop = _threading.Event()

    def producer():
        try:
            # the producer thread carries the query's tracer so span sites
            # below the connector (selective cascade) keep recording
            with _obs_trace.use(ctx.tracer):
                for split in splits:
                    if stop.is_set():
                        break
                    q.put(read_split(split, columns, capacity=cap))
            q.put(_SENTINEL)
        except BaseException as e:  # surface read errors on the consumer
            q.put(e)

    t = _threading.Thread(target=producer, daemon=True,
                          name="scan-prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            if item is _SENTINEL:
                return
            if isinstance(item, BaseException):
                raise item
            yield item.rename(symbols)
    finally:
        # early termination (LIMIT / error): stop the producer after its
        # current read and unblock any pending put
        stop.set()
        while t.is_alive():
            try:
                item = q.get(timeout=0.1)
                if item is _SENTINEL or isinstance(item, BaseException):
                    break
            except _queue.Empty:
                continue


def _constraints_to_storage(scan: TableScan, handle):
    """Engine-level (lo, hi) bounds → the connector's storage value domain
    (dates become datetime.date for parquet date32 statistics)."""
    import datetime

    col_types = {c.name: c.type for c in handle.columns}
    out = {}
    for col, (lo, hi) in scan.constraints.items():
        t = col_types.get(col)
        if t is None:
            continue
        if t.name == "date":
            conv = lambda d: None if d is None else datetime.date.fromordinal(719163 + int(d))
            out[col] = (conv(lo), conv(hi))
        else:
            out[col] = (lo, hi)
    return out


# -- unnest -----------------------------------------------------------------


def unnest_expand(node: Unnest, b: Batch) -> Batch:
    """Traceable core of UNNEST (shared by the streaming executor and the
    mesh executor). TPU-native redesign of operator/unnest/
    UnnestOperator.java: instead of walking per-position offsets, output
    row (i, j) of the static [cap, W] element plane is live iff
    j < max(sizes_src[i]); everything is broadcast + reshape, no dynamic
    shapes (output capacity = cap * W, W = widest source plane)."""
    cap = b.capacity
    srcs = [b.column(s) for s in node.sources]
    w = max([c.values.shape[1] for c in srcs] + [1])

    counts = None
    for c in srcs:
        sz = c.sizes
        if c.validity is not None:
            sz = jnp.where(c.validity, sz, 0)
        counts = sz if counts is None else jnp.maximum(counts, sz)
    counts = jnp.where(b.live, counts, 0)
    j = jnp.arange(w, dtype=jnp.int32)[None, :]
    out_live = (j < counts[:, None]).reshape(-1)

    def flat_plane(plane, width, fill):
        """[cap, width] → [cap*w] padding columns beyond width."""
        if width == w:
            return plane.reshape(-1)
        if width == 0:
            return jnp.full(cap * w, fill, plane.dtype)
        pad = jnp.full((cap, w - width), fill, plane.dtype)
        return jnp.concatenate([plane, pad], axis=1).reshape(-1)

    names, types, cols = [], [], []
    dicts = {}
    child_types = dict(node.child.output)
    for s in node.replicate:
        c = b.column(s)
        cols.append(Column(
            jnp.repeat(c.values, w, axis=0),
            None if c.validity is None else jnp.repeat(c.validity, w),
            None if c.hi is None else jnp.repeat(c.hi, w),
            None if c.sizes is None else jnp.repeat(c.sizes, w),
            None if c.evalid is None else jnp.repeat(c.evalid, w, axis=0),
            None if c.keys is None else jnp.repeat(c.keys, w, axis=0),
        ))
        names.append(s)
        types.append(child_types[s])
        if s in b.dicts:
            dicts[s] = b.dicts[s]
        if s + "#keys" in b.dicts:
            dicts[s + "#keys"] = b.dicts[s + "#keys"]
    for src, c, syms, etypes in zip(node.sources, srcs, node.out_syms,
                                    node.out_types):
        cw = c.values.shape[1]
        present = (jnp.arange(cw, dtype=jnp.int32)[None, :]
                   < c.sizes[:, None]) if cw else jnp.zeros((cap, 0), bool)
        evalid = present if c.evalid is None else (present & c.evalid)
        ev_flat = flat_plane(evalid, cw, False)
        if len(syms) == 2:  # map → (key, value)
            cols.append(Column(flat_plane(c.keys, cw, 0),
                               flat_plane(present, cw, False)))
            names.append(syms[0])
            types.append(etypes[0])
            if src + "#keys" in b.dicts:
                dicts[syms[0]] = b.dicts[src + "#keys"]
            cols.append(Column(flat_plane(c.values, cw, 0), ev_flat))
            names.append(syms[1])
            types.append(etypes[1])
            if src in b.dicts:
                dicts[syms[1]] = b.dicts[src]
        else:
            cols.append(Column(flat_plane(c.values, cw, 0), ev_flat))
            names.append(syms[0])
            types.append(etypes[0])
            if src in b.dicts:
                dicts[syms[0]] = b.dicts[src]
    if node.ordinality_sym:
        ordv = jnp.broadcast_to(
            (j + 1).astype(jnp.int64), (cap, w)).reshape(-1)
        cols.append(Column(ordv, None))
        names.append(node.ordinality_sym)
        types.append(BIGINT)
    return Batch(names, types, cols, out_live, dicts)


def _execute_unnest(node: Unnest, ctx: ExecContext) -> Iterator[Batch]:
    in_stream, chain = _fused_child(node.child, ctx)

    def expand(b: Batch) -> Batch:
        return unnest_expand(node, chain(b))

    jfn = _node_jit(node, "expand", lambda: expand)
    for b in in_stream:
        yield jfn(b)


# -- aggregation ------------------------------------------------------------

_VARIANCE_FNS = {"var_samp", "var_pop", "stddev_samp", "stddev_pop"}
_COVAR_FNS = {"covar_pop", "covar_samp", "corr"}
_NON_DECOMPOSABLE_FNS = {"approx_percentile", "__approx_percentile_w",
                         "max_by", "min_by", "array_agg", "map_agg",
                         "numeric_histogram", "tdigest_agg", "merge",
                         "approx_set",
                         "count_distinct", "sum_distinct", "avg_distinct"}

_CHECKSUM_NULL = jnp.int64(-7046029254386353131)  # fixed NULL contribution


def _as_double(c: Column, t: Type):
    """Column values as float64, unscaling decimals (limb-combined for
    long decimals)."""
    v = c.combined_f64() if c.hi is not None else c.values.astype(jnp.float64)
    if isinstance(t, DecimalType):
        v = v / (10.0 ** t.scale)
    return v


def _content_hash(c: Column, t: Type, dictionary) -> jnp.ndarray:
    """Order-independent per-row content hash for checksum()
    (reference: ChecksumAggregationFunction — XXHash64 of the block value).
    Strings hash by dictionary VALUE (content), not code."""
    if dictionary is not None:
        from presto_tpu.spiller import _strhash_lut

        v = jnp.asarray(_strhash_lut(dictionary))[c.values.astype(jnp.int32) + 1]
    elif jnp.issubdtype(c.values.dtype, jnp.floating):
        v = jax.lax.bitcast_convert_type(
            c.values.astype(jnp.float64), jnp.int64
        )
    else:
        v = c.values.astype(jnp.int64)
    h = v * jnp.int64(-7070675565921424023)  # golden-ratio mix
    h = h ^ (h >> 31)
    if c.validity is not None:
        h = jnp.where(c.validity, h, _CHECKSUM_NULL)
    return h


def _input_state(b: Batch, name: str, op: str, a: AggSpec, st: Type,
                 in_types: Dict[str, Type]) -> StateCol:
    """Raw input column(s) → one state column for grouped_merge
    (the accumulator `addInput` step of the reference's per-fn states:
    VarianceState tracks count/mean/m2; we track count/sum/sumsq etc.)."""
    suffix = name[len(a.symbol):] if name.startswith(a.symbol) else ""
    if op == "count_add":
        if a.fn == "count_if":
            c = b.column(a.arg)
            vals = c.values.astype(jnp.int64)
            if c.validity is not None:
                vals = jnp.where(c.validity, vals, 0)
            return StateCol(vals, None, "count_add")
        if a.fn in _COVAR_FNS:
            both = b.column(a.arg).valid_mask() & b.column(a.arg2).valid_mask()
            return StateCol(both.astype(jnp.int64), None, "count_add")
        if a.fn == "count_star" or a.arg is None:
            return StateCol(b.live.astype(jnp.int64), None, "count_add")
        c = b.column(a.arg)
        vals = (c.validity.astype(jnp.int64) if c.validity is not None
                else jnp.ones(b.capacity, jnp.int64))
        return StateCol(vals, None, "count_add")
    if suffix in ("$hi", "$sum_hi", "$lo", "$sum_lo"):
        # int128 decimal sum limbs (UnscaledDecimal128Arithmetic analog):
        # value = hi * 2^32 + lo, lo canonical in [0, 2^32). Short-decimal
        # input splits arithmetically; long-decimal input is already limbed.
        c = b.column(a.arg)
        if suffix.endswith("hi"):
            vals = c.hi if c.hi is not None else (c.values >> 32)
        else:
            vals = c.values if c.hi is not None else (c.values & 0xFFFFFFFF)
        return StateCol(vals.astype(jnp.int64), c.validity, "sum")
    if a.fn == "checksum":
        c = b.column(a.arg)
        return StateCol(_content_hash(c, in_types[a.arg], b.dicts.get(a.arg)),
                        None, "sum")
    if a.fn in ("bool_and", "bool_or"):
        c = b.column(a.arg)
        return StateCol(c.values.astype(jnp.int8), c.validity, op)
    if a.fn in _VARIANCE_FNS:
        c = b.column(a.arg)
        x = _as_double(c, in_types[a.arg])
        return StateCol(x * x if suffix == "$sumsq" else x, c.validity, "sum")
    if a.fn in _COVAR_FNS:
        cx, cy = b.column(a.arg), b.column(a.arg2)
        x = _as_double(cx, in_types[a.arg])
        y = _as_double(cy, in_types[a.arg2])
        both = cx.valid_mask() & cy.valid_mask()
        val = {"$sx": x, "$sy": y, "$sxy": x * y,
               "$sxx": x * x, "$syy": y * y}[suffix]
        return StateCol(val, both, "sum")
    if a.fn == "geometric_mean":
        c = b.column(a.arg)
        x = _as_double(c, in_types[a.arg])
        return StateCol(jnp.log(x), c.validity, "sum")
    from presto_tpu.functions import registry as _freg

    udf = _freg().aggregate(a.fn)
    if udf is not None:
        # registered UDAF: per-state elementwise input transform over the
        # float64 argument (the addInput step of its accumulator);
        # count_add states took the generic branch at the top
        c = b.column(a.arg)
        x = _as_double(c, in_types[a.arg])
        transform = next(t for s, o, t in udf.states
                         if a.symbol + s == name)
        return StateCol(transform(x) if transform is not None else x,
                        c.validity, op)
    c = b.column(a.arg)
    if c.hi is not None:
        # long-decimal input to min/max/arbitrary: combined float64 value,
        # scaled to the SQL value (matches the DOUBLE output type and the
        # implicit decimal→double casts in comparisons)
        return StateCol(_as_double(c, in_types[a.arg]), c.validity, op)
    return StateCol(c.values.astype(st.dtype), c.validity, op)


def _renorm_limbs(sout: list, pairs) -> list:
    """Carry-propagate int128 limb states after a merge: keep lo canonical
    in [0, 2^32) so limb sums never overflow int64 regardless of row count."""
    for ih, il in pairs:
        hi_s, lo_s = sout[ih], sout[il]
        carry = lo_s.values >> 32
        sout[il] = StateCol(lo_s.values - (carry << 32), lo_s.validity, lo_s.op)
        sout[ih] = StateCol(hi_s.values + carry, hi_s.validity, hi_s.op)
    return sout


def _minmax_ident(dtype, want_min: bool):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf if want_min else -jnp.inf, dtype)
    info = jnp.iinfo(dtype)
    return jnp.array(info.max if want_min else info.min, dtype)


def _sorted_group_agg(b: Batch, key_syms, a: AggSpec, cap: int):
    """Per-group order-dependent aggregate over materialized input:
    approx_percentile (exact per-group quantile), max_by / min_by.
    Sorts by (deadness, group keys, order value) — the group enumeration
    (stable sort on the same key operands) matches grouped_merge's, so the
    returned arrays align with its group table rows."""
    n = b.capacity
    dead = (~b.live).astype(jnp.int32)
    operands = [dead]
    for k in key_syms:
        c = b.column(k)
        if c.validity is not None:
            operands.append((~c.validity).astype(jnp.int32))
            operands.append(jnp.where(c.validity, c.values, jnp.zeros_like(c.values)))
        else:
            operands.append(c.values)
    num_key_ops = len(operands)

    cx = b.column(a.arg)
    if a.fn in ("approx_percentile", "__approx_percentile_w",
                "count_distinct", "sum_distinct", "avg_distinct"):
        ov = cx.valid_mask()
        sortval = jnp.where(ov, cx.values, _minmax_ident(cx.values.dtype, True))
    elif a.fn == "max_by":
        cy = b.column(a.arg2)
        ov = cy.valid_mask()
        # NULL-ordering rows first so the LAST row is the max valid
        sortval = jnp.where(ov, cy.values, _minmax_ident(cy.values.dtype, True))
    else:  # min_by
        cy = b.column(a.arg2)
        ov = cy.valid_mask()
        # NULLs last so the FIRST row is the min valid
        sortval = jnp.where(ov, cy.values, _minmax_ident(cy.values.dtype, False))
    operands.append(sortval)

    perm = jnp.arange(n, dtype=jnp.int32)
    sorted_ops = jax.lax.sort(operands + [perm], num_keys=len(operands))
    sperm = sorted_ops[-1]
    sdead = sorted_ops[0]
    change = jnp.zeros(n, dtype=bool).at[0].set(True)
    for sk in sorted_ops[:num_key_ops]:
        change = change.at[1:].set(change[1:] | (sk[1:] != sk[:-1]))
    seg = jnp.cumsum(change.astype(jnp.int32)) - 1
    seg = jnp.where(sdead == 1, cap, seg)
    idx = jnp.arange(n, dtype=jnp.int32)
    start = jnp.full(cap, n, jnp.int32).at[seg].min(idx, mode="drop")
    cnt = jax.ops.segment_sum(jnp.ones(n, jnp.int32), seg, num_segments=cap + 1)[:cap]
    ov_sorted = ov[sperm]
    cntv = jax.ops.segment_sum(ov_sorted.astype(jnp.int32), seg,
                               num_segments=cap + 1)[:cap]
    valid = cntv > 0

    if a.fn in ("count_distinct", "sum_distinct", "avg_distinct"):
        # DISTINCT accumulators (MarkDistinct analog): after the
        # (keys, value) sort, the first row of each equal-value run inside
        # a segment carries the value; everything else contributes zero
        sv = cx.values[sperm]
        ov_sorted2 = ov[sperm] & (sdead == 0)
        prev_same = jnp.zeros(n, bool).at[1:].set(
            (sv[1:] == sv[:-1]) & ~change[1:])
        first_distinct = ov_sorted2 & ~prev_same
        dcount = jax.ops.segment_sum(
            first_distinct.astype(jnp.int64), seg,
            num_segments=cap + 1)[:cap]
        if a.fn == "count_distinct":
            return dcount, None
        acc_dtype = (sv.dtype if jnp.issubdtype(sv.dtype, jnp.floating)
                     else jnp.int64)
        contrib = jnp.where(first_distinct, sv.astype(acc_dtype),
                            jnp.zeros((), acc_dtype))
        dsum = jax.ops.segment_sum(contrib, seg, num_segments=cap + 1)[:cap]
        if a.fn == "sum_distinct":
            return dsum, dcount > 0
        scale = (b.type_of(a.arg).scale
                 if isinstance(b.type_of(a.arg), DecimalType) else 0)
        avg = (dsum.astype(jnp.float64) / (10.0 ** scale)
               / jnp.maximum(dcount, 1).astype(jnp.float64))
        return avg, dcount > 0
    if a.fn == "__approx_percentile_w":
        # weighted-rank selection over sketch bucket rows: the value is the
        # bucket minimum whose cumulative count first reaches ceil(p·total)
        # (the final qdigest.valueAt step of the approx_percentile
        # lowering — inputs here are ≤ occupied-bucket rows, not raw data)
        from presto_tpu.ops.grouping import _segmented_scan

        p = float(a.param)  # lint: allow(host-sync)
        wcol = b.column(a.arg2)
        wsorted = wcol.values.astype(jnp.int64)[sperm]
        wsorted = jnp.where(ov_sorted & (sdead == 0), wsorted, 0)
        cum = _segmented_scan(wsorted, change, "sum")
        totals = jax.ops.segment_sum(wsorted, seg, num_segments=cap + 1)[:cap]
        thresh = jnp.clip(jnp.ceil(p * totals).astype(jnp.int64), 1, None)
        row_thresh = jnp.concatenate([thresh, jnp.zeros(1, jnp.int64)])[
            jnp.clip(seg, 0, cap)]
        candidate = (cum >= row_thresh) & (wsorted > 0)
        idxs = jnp.arange(n, dtype=jnp.int32)
        pick = jnp.full(cap, n, jnp.int32).at[seg].min(
            jnp.where(candidate, idxs, n), mode="drop")
        rows = sperm[jnp.clip(pick, 0, n - 1)]
        vals = cx.values[rows]
        return vals, totals > 0
    if a.fn == "approx_percentile":
        # exact quantile: index ceil(p*n_valid)-1 of the sorted valid values
        # (NULLs sort first, valid range is [start+cnt-cntv, start+cnt))
        p = float(a.param)  # lint: allow(host-sync)
        k = jnp.clip(jnp.ceil(p * cntv).astype(jnp.int32) - 1, 0, jnp.maximum(cntv - 1, 0))
        pos = start + (cnt - cntv) + k
        pos = jnp.clip(pos, 0, n - 1)
        rows = sperm[pos]
        vals = cx.values[rows]
        if cx.validity is not None:
            valid = valid & cx.validity[rows]
        return vals, valid
    if a.fn == "max_by":
        pos = jnp.clip(start + cnt - 1, 0, n - 1)
    else:
        pos = jnp.clip(start, 0, n - 1)
    rows = sperm[pos]
    vals = cx.values[rows]
    if cx.validity is not None:
        valid = valid & cx.validity[rows]
    return vals, valid


def _execute_materialized_aggregate(node: Aggregate, ctx: ExecContext) -> Iterator[Batch]:
    """Aggregates with order-dependent, non-mergeable state
    (approx_percentile / max_by / min_by): materialize the input and compute
    per-group over one global sort. The fragmenter gathers such aggregations
    to a single task (reference computes these via mergeable digest states;
    exact computation satisfies the same contract)."""
    from presto_tpu.plan.agg_states import (
        agg_state_layout as _asl,
        state_types as _sts,
    )

    in_stream, chain = _fused_child(node.child, ctx)
    in_types = dict(node.child.output)
    key_syms = node.group_keys
    key_types = [in_types[k] for k in key_syms]
    decomp = [a for a in node.aggs if a.fn not in _NON_DECOMPOSABLE_FNS]
    _HOST_AGGS = ("array_agg", "map_agg", "numeric_histogram",
                  "tdigest_agg", "merge", "approx_set")
    ndec = [a for a in node.aggs
            if a.fn in _NON_DECOMPOSABLE_FNS and a.fn not in _HOST_AGGS]
    arr_aggs = [a for a in node.aggs if a.fn in _HOST_AGGS]
    layout = _asl(decomp, in_types)
    state_types = _sts(layout, in_types)
    jchain = _node_jit(node, "mat_chain", lambda: chain)
    full = _collect_concat(jchain(b) for b in in_stream)
    if full is None:
        yield _finalize_aggregate(node, None, layout, key_syms, key_types,
                                  state_types, in_types)
        return

    def compute(full: Batch) -> Batch:
        cap = full.capacity  # groups ≤ live rows; trace-time constant
        keys = [KeyCol(full.column(k).values, full.column(k).validity)
                for k in key_syms]
        states = [
            _input_state(full, name, op, a, st, in_types)
            for (name, op, a), st in zip(layout, state_types)
        ]
        kout, sout, out_live, _ = grouped_merge(keys, states, full.live, cap)
        sout = _renorm_limbs(list(sout), limb_pairs(layout))
        cols = [Column(k.values, k.validity) for k in kout] + [
            Column(s.values, s.validity if s.op != "count_add" else None)
            for s in sout
        ]
        names = list(key_syms) + [nm for nm, _, _ in layout]
        types = key_types + state_types
        dicts = {k: full.dicts[k] for k in key_syms if k in full.dicts}
        for nm, op, a in layout:
            if op in ("min", "max") and a.arg in full.dicts:
                dicts[nm] = full.dicts[a.arg]
        acc = Batch(names, types, cols, out_live, dicts)
        for a in ndec:
            vals, valid = _sorted_group_agg(full, key_syms, a, cap)
            acc = acc.with_column(
                a.symbol, a.type, Column(vals.astype(a.type.dtype), valid),
                dictionary=full.dicts.get(a.arg),
            )
        return acc

    acc = _node_jit(node, "mat_compute", lambda: compute)(full)
    if arr_aggs:
        acc = _attach_array_aggs(acc, full, arr_aggs, key_syms)
    yield _finalize_aggregate(node, acc, layout, key_syms, key_types,
                              state_types, in_types)


def _attach_numeric_histogram(acc: Batch, full: Batch, a, row_gi,
                              live) -> Batch:
    """numeric_histogram(buckets, x) → map<double,double> per group
    (reference: NumericHistogramAggregation over aggregation/NumericHistogram
    — streaming nearest-centroid merging). Materialized form: per group,
    start from the distinct (value, count) pairs and merge the CLOSEST
    adjacent pair (weighted mean, summed count) until ≤ buckets remain —
    the same fixed-size centroid invariant, computed over the gathered
    input."""
    b = int(a.param)
    c = full.column(a.arg)
    vals = np.asarray(c.values)[live].astype(np.float64)
    valid = np.asarray(c.valid_mask())[live]
    cap = acc.capacity
    per_group: Dict[int, list] = {}
    for r in np.nonzero(valid)[0]:
        per_group.setdefault(int(row_gi[r]), []).append(vals[r])

    hists = {}
    w = 1
    for gi, xs in per_group.items():
        u, cnt = np.unique(np.asarray(xs), return_counts=True)
        u = u.astype(np.float64)
        cnt = cnt.astype(np.float64)
        while len(u) > b:
            gaps = np.diff(u)
            i = int(np.argmin(gaps))
            tot = cnt[i] + cnt[i + 1]
            merged = (u[i] * cnt[i] + u[i + 1] * cnt[i + 1]) / tot
            u = np.concatenate([u[:i], [merged], u[i + 2:]])
            cnt = np.concatenate([cnt[:i], [tot], cnt[i + 2:]])
        hists[gi] = (u, cnt)
        w = max(w, len(u))

    keys2d = np.zeros((cap, w), np.float64)
    plane = np.zeros((cap, w), np.float64)
    sizes = np.zeros(cap, np.int32)
    # a group whose inputs were all NULL yields SQL NULL, not an empty
    # map (NumericHistogramAggregation's no-input-accumulated contract)
    validity = np.zeros(cap, bool)
    for gi, (u, cnt) in hists.items():
        keys2d[gi, :len(u)] = u
        plane[gi, :len(u)] = cnt
        sizes[gi] = len(u)
        validity[gi] = True
    return acc.with_column(
        a.symbol, a.type,
        Column(jnp.asarray(plane), jnp.asarray(validity),
               sizes=jnp.asarray(sizes),
               evalid=None,
               keys=jnp.asarray(keys2d)))


def _host_format_value(kind: str, param, t, v) -> str:
    """One distinct value → its text (HostProject formatting kernels).
    varchar_cast mirrors the reference's cast-to-varchar renderings;
    date_format uses the MySQL format vocabulary."""
    import datetime as _d

    if kind == "date_format":
        from presto_tpu.expr.compile import mysql_format_to_strptime

        fmt = mysql_format_to_strptime(str(param))
        if t.name == "date":
            dt = _d.datetime(1970, 1, 1) + _d.timedelta(days=int(v))
        else:
            dt = _d.datetime(1970, 1, 1) + _d.timedelta(microseconds=int(v))
        return dt.strftime(fmt)
    # varchar_cast
    if t.name == "boolean":
        return "true" if v else "false"
    if t.name == "date":
        return str(_d.date(1970, 1, 1) + _d.timedelta(days=int(v)))
    if t.name in ("timestamp", "time"):
        if t.name == "time":
            dt = _d.datetime(1970, 1, 1) + _d.timedelta(microseconds=int(v))
            out = dt.strftime("%H:%M:%S.%f")[:-3]
        else:
            dt = _d.datetime(1970, 1, 1) + _d.timedelta(microseconds=int(v))
            out = dt.strftime("%Y-%m-%d %H:%M:%S.%f")[:-3]
        return out
    if isinstance(t, DecimalType):
        import decimal as _dec

        return str(_dec.Decimal(int(v)).scaleb(-t.scale))
    if t.name == "real":
        # numpy's shortest float32 repr — float(v) would widen to float64
        # and print garbage mantissa digits ('1.100000023841858')
        return str(np.float32(v))
    if t.name == "double":
        return str(float(v))
    return str(int(v))


def _execute_host_project(node, ctx: ExecContext) -> Iterator[Batch]:
    """HostProject: string-producing scalars (cast-to-varchar,
    date_format) evaluated on the host at the root, once per DISTINCT
    input value per batch, re-encoded as a fresh dictionary column
    (plan/nodes.HostProject)."""
    from presto_tpu.dictionary import Dictionary
    from presto_tpu.types import VARCHAR as _VC

    in_types = dict(node.child.output)
    for b in execute_node(node.child, ctx):
        for sym, kind, in_sym, param in node.items:
            t = in_types[in_sym]
            c = b.column(in_sym)
            vals = np.asarray(c.values)
            if c.hi is not None:
                # long decimal: exact int128 from the two limbs
                his = np.asarray(c.hi)
                vals = np.array(
                    [(int(h) << 32) + int(lo) for h, lo in zip(his, vals)],
                    dtype=object)
            live = np.asarray(b.live)
            valid = np.asarray(c.valid_mask()) & live
            # format once per distinct value; dead/null lanes format a 0
            # placeholder that the validity mask hides
            safe = np.where(valid, vals, np.zeros((), dtype=vals.dtype)
                            if vals.dtype != object else 0)
            uniq, inv = np.unique(safe, return_inverse=True)
            strs = np.asarray(
                [_host_format_value(kind, param, t, u) for u in uniq],
                dtype=object)
            d, ucodes = Dictionary.encode(strs)
            row_codes = ucodes[inv].astype(np.int32)
            row_codes = np.where(valid, row_codes, -1)
            b = b.with_column(
                sym, _VC,
                Column(jnp.asarray(row_codes), jnp.asarray(valid)),
                dictionary=d)
        yield b


def _attach_sketch(acc: Batch, full: Batch, a, row_gi, live, valid,
                   group_fn) -> Batch:
    """Shared scaffolding for sketch-valued host aggregates (tdigest,
    HyperLogLog): gather valid row indices per group, compute ONE
    serialized entry per group (`group_fn(rows) -> entry | None`; None =
    SQL NULL), and attach the result as a fresh dictionary column."""
    from presto_tpu.dictionary import Dictionary

    cap = acc.capacity
    per_group: Dict[int, list] = {}
    for r in np.nonzero(valid)[0]:
        per_group.setdefault(int(row_gi[r]), []).append(int(r))
    out_entries = np.full(cap, "", dtype=object)
    validity = np.zeros(cap, bool)
    for gi, rows in per_group.items():
        e = group_fn(rows)
        if e is not None:
            out_entries[gi] = e
            validity[gi] = True
    d, codes = Dictionary.encode(out_entries)
    return acc.with_column(
        a.symbol, a.type,
        Column(jnp.asarray(codes.astype(np.int32)), jnp.asarray(validity)),
        dictionary=d)


def _attach_tdigest(acc: Batch, full: Batch, a, row_gi, live) -> Batch:
    """tdigest_agg(x[, w][, compression]) / merge(tdigest) → one digest
    entry per group (expr/tdigest.py). Runs at the gathered single task
    like the other host aggregates (reference:
    TDigestAggregationFunction / MergeTDigestAggregation)."""
    from presto_tpu.expr import tdigest as _td

    c = full.column(a.arg)
    valid = np.asarray(c.valid_mask())[live]
    if a.fn == "merge":
        entries = full.dicts[a.arg].decode(np.asarray(c.values)[live])

        def group_fn(rows):
            return _td.merge([entries[r] for r in rows
                              if entries[r] is not None])
    else:
        vals = np.asarray(c.values)[live].astype(np.float64)
        if a.arg2 is not None:
            wc = full.column(a.arg2)
            wvals = np.asarray(wc.values)[live].astype(np.float64)
            valid = valid & np.asarray(wc.valid_mask())[live]
        else:
            wvals = None
        compression = float(a.param) if a.param else _td.DEFAULT_COMPRESSION

        def group_fn(rows):
            return _td.build(vals[rows],
                             None if wvals is None else wvals[rows],
                             compression)
    return _attach_sketch(acc, full, a, row_gi, live, valid, group_fn)


def _attach_hll(acc: Batch, full: Batch, a, row_gi, live) -> Batch:
    """approx_set(x) / merge(hyperloglog) → one sketch entry per group
    (expr/hll.py). The hash pipeline matches the approx_distinct device
    lowering exactly (content hash for strings, canonical bit pattern
    for doubles), so cardinality(approx_set(x)) == approx_distinct(x).
    Reference: ApproximateSetAggregation / MergeHyperLogLogAggregation."""
    from presto_tpu.expr import hll as _hll

    c = full.column(a.arg)
    valid = np.asarray(c.valid_mask())[live]
    if a.fn == "merge":
        entries = full.dicts[a.arg].decode(np.asarray(c.values)[live])

        def group_fn(rows):
            return _hll.merge([entries[r] for r in rows
                               if entries[r] is not None])
    else:
        vals = np.asarray(c.values)[live]
        hashes = None
        if a.arg in full.dicts:
            lut = np.asarray(full.dicts[a.arg].content_hash_lut())
            hashes = lut[vals.astype(np.int64) + 1]
        reg, rank = _hll.regs_and_ranks(vals, hashes)

        def group_fn(rows):
            return _hll.build(reg[rows], rank[rows])
    return _attach_sketch(acc, full, a, row_gi, live, valid, group_fn)


def _attach_array_aggs(acc: Batch, full: Batch, aggs, key_syms) -> Batch:
    """array_agg: per-group element lists built host-side over the
    materialized input (reference: ArrayAggregationFunction's grouped
    block builders — inherently variable-width output, so it runs at the
    single gathered task and materializes padded [groups, W] planes).
    Element order is input order; NULL elements are kept."""
    live = np.asarray(full.live)
    kvals = [np.asarray(full.column(k).values)[live] for k in key_syms]
    kvalid = [np.asarray(full.column(k).valid_mask())[live] for k in key_syms]
    acc_live = np.asarray(acc.live)
    gkeys = [np.asarray(acc.column(k).values) for k in key_syms]
    gvalid = [np.asarray(acc.column(k).valid_mask()) for k in key_syms]
    _NAN = object()  # canonical NaN key: NaN != NaN would miss the dict,
    # but grouped_merge puts all NaNs in one group — match that here

    def _ckey(v, ok):
        if not ok:
            return None
        x = v.item()
        return _NAN if isinstance(x, float) and x != x else x

    gmap = {}
    for gi in np.nonzero(acc_live)[0]:
        key = tuple(
            _ckey(gv[gi], gva[gi]) for gv, gva in zip(gkeys, gvalid)
        )
        gmap[key] = int(gi)
    cap = acc.capacity
    nrows = int(live.sum())
    row_gi = np.empty(nrows, np.int64)
    for r in range(nrows):
        key = tuple(
            _ckey(kv[r], kva[r]) for kv, kva in zip(kvals, kvalid)
        )
        row_gi[r] = gmap[key]
    for a in aggs:
        if a.fn == "numeric_histogram":
            acc = _attach_numeric_histogram(acc, full, a, row_gi, live)
            continue
        if a.fn == "approx_set" or (
                a.fn == "merge"
                and full.type_of(a.arg).name == "hyperloglog"):
            acc = _attach_hll(acc, full, a, row_gi, live)
            continue
        if a.fn in ("tdigest_agg", "merge"):
            acc = _attach_tdigest(acc, full, a, row_gi, live)
            continue
        is_map = a.fn == "map_agg"
        c = full.column(a.arg)
        vals = np.asarray(c.values)[live]
        valid = np.asarray(c.valid_mask())[live]
        if is_map:
            # map_agg(k, v): k drives placement (first occurrence of each
            # key per group wins, like MapAggregation's first-write), v is
            # the stored element
            vc = full.column(a.arg2)
            mvals = np.asarray(vc.values)[live]
            mvalid = np.asarray(vc.valid_mask())[live]
        sizes = np.zeros(cap, np.int32)
        np.add.at(sizes, row_gi, 1)
        w = max(int(sizes.max()) if cap else 0, 1)
        plane = np.zeros(
            (cap, w), dtype=(mvals.dtype if is_map else vals.dtype))
        kplane = np.zeros((cap, w), dtype=vals.dtype) if is_map else None
        evalid = np.zeros((cap, w), bool)
        slot = np.zeros(cap, np.int32)
        seen: dict = {}
        for r in range(nrows):
            gi = row_gi[r]
            if is_map:
                if not valid[r]:
                    continue  # NULL keys are dropped
                kk = (gi, vals[r].item())
                if kk in seen:
                    continue
                seen[kk] = True
                j = slot[gi]
                kplane[gi, j] = vals[r]
                plane[gi, j] = mvals[r]
                evalid[gi, j] = mvalid[r]
            else:
                j = slot[gi]
                plane[gi, j] = vals[r]
                evalid[gi, j] = valid[r]
            slot[gi] = j + 1
        if is_map:
            sizes = slot  # deduped per-group entry counts
        acc = acc.with_column(
            a.symbol, a.type,
            Column(jnp.asarray(plane), None,
                   sizes=jnp.asarray(sizes),
                   evalid=jnp.asarray(evalid),
                   keys=None if kplane is None else jnp.asarray(kplane)),
            dictionary=(full.dicts.get(a.arg2) if is_map
                        else full.dicts.get(a.arg)),
        )
        if is_map and a.arg in full.dicts:
            acc.dicts[a.symbol + "#keys"] = full.dicts[a.arg]
    return acc


def _registered_aggregate_fn(fn: str):
    from presto_tpu.functions import registry

    return registry().aggregate(fn)


class _GraceOverflow(Exception):
    """Raised when group-table growth crosses the grace ceiling: the
    aggregation switches to hash-partitioned (grace) mode. Carries the
    optimistic window's unmerged raw input batches."""

    def __init__(self, entries):
        super().__init__("aggregate group table crossed the grace ceiling")
        self.entries = entries


class _EngineFlip(Exception):
    """Raised from an overflow replay when the adaptive layer flips the
    breaker engine instead of replaying the loser wider. Only raised when
    the replay checkpoint is EMPTY (the whole aggregation restarts from
    batch 0), so no accumulator state needs converting between engine
    layouts. Carries the unmerged raw input batches, the wave's observed
    group count, and the engine to restart under."""

    def __init__(self, batches, groups, engine):
        super().__init__("adaptive breaker engine flip")
        self.batches = batches
        self.groups = groups
        self.engine = engine


# Fan-out of one adaptive device-side radix partition growth step: the
# budget-blowing partition re-splits by the next two hash bits
# (ops/radix.radix_child_perm), mirroring the host spiller's
# grow_partition recursion — one level deep, then hybrid spill.
_RADIX_GROW_FANOUT = 4


def _adaptive_site(node: PlanNode, ctx: "ExecContext") -> str:
    """Site fingerprint for adaptive_action events: the HBO structural
    fingerprint when derivable (so in-run actions and cross-run history
    key the same way), else a node-typed fallback."""
    try:
        from presto_tpu.obs import runstats as _runstats

        fp = _runstats.node_fingerprint(node, ctx.catalog)
        if fp:
            return fp
    except Exception:
        pass
    return f"{type(node).__name__}:{id(node)}"


def _adaptive_flip_verdict(node: PlanNode, ctx: "ExecContext", engine: str,
                           ngi: int, rows_seen: int) -> Optional[str]:
    """Between replay waves: re-choose the breaker engine from the wave's
    OBSERVED group count / duplication instead of the estimates the first
    choice trusted. Returns the engine to restart under when the adaptive
    layer should act, else None. Flip-at-most-once-per-site: the first
    overflow wave's verdict pins the site for the rest of the query — no
    oscillation, and the pin also covers observe-mode so one run logs one
    would-flip decision per site."""
    if ctx.adaptive is None or node.__dict__.get("_adaptive_engine_pinned"):
        return None
    node.__dict__["_adaptive_engine_pinned"] = True
    if getattr(ctx.config, "breaker_engine", "auto") != "auto":
        return None  # session override forced the engine — nothing to flip
    from presto_tpu.plan.stats import choose_breaker_engine_observed

    try:
        want, why = choose_breaker_engine_observed(
            node, float(ngi), float(rows_seen) if rows_seen else None)
    except Exception:
        return None
    if want == engine:
        return None
    acted = ctx.adaptive.decide(
        "engine_flip", node=node, site=_adaptive_site(node, ctx),
        before=engine, after=want, detail=f"flip {engine}->{want}",
        groups=int(ngi), rows=int(rows_seen or 0), why=why)
    if not acted:
        return None
    # the CONVERGED verdict is what EXPLAIN shows and HBO records — the
    # initial guess lives on only inside the why-string provenance
    node.__dict__["_breaker_engine"] = want
    node.__dict__["_breaker_engine_why"] = f"{why} (adaptive: flipped)"
    node.__dict__["_adaptive_engine_flipped"] = True
    ctx.stats["breaker.engine_flips"] = (
        ctx.stats.get("breaker.engine_flips", 0) + 1)
    return want


def _adaptive_presize_grow(node: PlanNode, ctx: "ExecContext", ngi: int,
                           cap: int, limit: Optional[int]) -> Optional[int]:
    """Forward-propagating presize: a completed window CONFIRMED ``ngi``
    groups within 1/8 of the table capacity, so the next window is odds-on
    to overflow and replay. Grow the table now — the next merge step
    migrates the accumulator to the bigger capacity with zero replay (the
    pow2 ladder step is the same compile the overflow would have paid,
    minus the re-merged batches). ``limit`` bounds growth at the grace
    ceiling when spill is live; per-capacity damping keeps observe mode
    at one logged decision per proposed size."""
    if ctx.adaptive is None or ngi * 8 < cap * 7:
        return None
    want = cap * 2
    if limit is not None and want > limit:
        return None
    if node.__dict__.get("_adaptive_presize_seen", 0) >= cap:
        return None
    node.__dict__["_adaptive_presize_seen"] = cap
    acted = ctx.adaptive.decide(
        "presize_grow", node=node, site=_adaptive_site(node, ctx),
        before=int(cap), after=int(want), detail=f"presize {cap}->{want}",
        groups=int(ngi))
    return want if acted else None


def _grouped_execution_lifespans(node: Aggregate) -> int:
    """GroupedExecutionTagger (reference PlanFragmenter.java:914): when every
    group key traces — through streaming Filter/Project identity refs — down
    to a colocated bucketed join whose preserved-side join keys the group
    keys cover, every group's rows live inside ONE bucket (bucket =
    content-hash of those keys), so the WHOLE agg-over-join pipeline can run
    lifespan-by-lifespan: build one bucket, probe it, aggregate it, finalize
    and RELEASE it. Returns the bucket count, or 0 when not applicable."""
    from presto_tpu.expr.ir import InputRef

    keys = set(node.group_keys)
    if not keys:
        return 0
    n = node.child
    while True:
        if isinstance(n, Filter):
            n = n.child
        elif isinstance(n, Project):
            m = dict(n.exprs)
            mapped = set()
            for k in keys:
                e = m.get(k)
                if not isinstance(e, InputRef):
                    return 0  # computed key — can't trace to a bucket column
                mapped.add(e.name)
            keys = mapped
            n = n.child
        elif isinstance(n, HashJoin) and n.colocated:
            # NULL-extended rows of an outer join carry NULL keys on the
            # non-preserved side and would scatter one NULL group across
            # buckets — only the preserved side's keys qualify (RIGHT is
            # canonicalized to left-with-swapped-sides at plan time, so
            # kind here is only ever inner/left/full)
            if set(n.left_keys) <= keys and n.kind in ("inner", "left"):
                return n.colocated
            if set(n.right_keys) <= keys and n.kind == "inner":
                return n.colocated
            return 0
        else:
            return 0


def _breaker_engine_choice(node: PlanNode, ctx: "ExecContext",
                           record: bool = True) -> str:
    """Resolve the breaker engine ("sort" | "hash") for a pipeline
    breaker: session override (ExecConfig.breaker_engine) first, else the
    CBO's NDV/row-count/payload-width thresholds
    (plan/stats.choose_breaker_engine). Stamps the decision + rationale
    on the node for EXPLAIN and, when ``record``, bumps the
    engine-labeled dispatch counters (ctx.stats + /v1/metrics)."""
    from presto_tpu.plan.stats import choose_breaker_engine
    from presto_tpu.scan import metrics as _scan_metrics

    override = getattr(ctx.config, "breaker_engine", "auto")
    hbo = getattr(ctx.config, "hbo", "observe")
    try:
        engine, why = choose_breaker_engine(node, ctx.catalog, override,
                                            hbo=hbo)
    except Exception:
        engine, why = "sort", "stats derivation failed"
    node.__dict__["_breaker_engine"] = engine
    node.__dict__["_breaker_engine_why"] = why
    if record:
        key = f"breaker.engine_{engine}"
        ctx.stats[key] = ctx.stats.get(key, 0) + 1
        _scan_metrics.record(f"breaker_dispatches_{engine}", 1)
        if "(hbo: observed)" in why:
            try:
                from presto_tpu.obs import runstats as _runstats
                _runstats.record_correction("breaker_engine")
            except Exception:
                pass
        if ctx.tracer.enabled:
            t = time.time()
            ctx.tracer.record("breaker_engine", "breaker_engine", t, t,
                              node=type(node).__name__, engine=engine,
                              why=why)
    return engine


def _engine_key(key: str, engine: str) -> str:
    """Jit-cache key for an engine-dependent program: the hash engine's
    traces differ structurally from the sort engine's, so they must not
    share a structural program-cache entry."""
    return key if engine == "sort" else f"{key}@h"


def _agg_steps(node: Aggregate, engine: str = "sort") -> SimpleNamespace:
    """Structural merge-step closures for one Aggregate node, memoized on
    the node (per breaker engine) so the executor and the install-time
    breaker warmers hand _node_jit the SAME function objects (one trace,
    one shared program). Everything here derives from the node, its
    collapsed child chain and the engine — no runtime data is captured,
    which is what makes the steps warmable ahead of the stream."""
    memos = node.__dict__.setdefault("_agg_steps", {})
    memo = memos.get(engine)
    if memo is not None:
        return memo
    from presto_tpu.plan.agg_states import state_types as _layout_state_types

    _, chain0 = collapse_chain(node.child)
    chain = chain0 or (lambda b: b)
    in_types = dict(node.child.output)
    layout = agg_state_layout(node.aggs, in_types)
    lpairs = limb_pairs(layout)
    key_syms = node.group_keys
    key_types = [in_types[k] for k in key_syms]
    final_mode = node.step == "final"
    if final_mode:
        # input columns ARE the partial state columns (post-exchange)
        state_types = [in_types[name] for name, _, _ in layout]
    else:
        state_types = _layout_state_types(layout, in_types)

    def _key_domain(b: Batch, k: str, t: Type):
        """Static value-domain bound for the direct (sort-free) group path:
        dictionary codes ∈ [0, |dict|), booleans ∈ {0, 1}."""
        d = b.dicts.get(k)
        if d is not None:
            return len(d)
        if t.name == "boolean":
            return 2
        return None

    def in_to_states(b: Batch):
        keys = [KeyCol(b.column(k).values, b.column(k).validity,
                       _key_domain(b, k, t))
                for k, t in zip(key_syms, key_types)]
        states = []
        for (name, op, a), st in zip(layout, state_types):
            if final_mode:
                c = b.column(name)
                # count_add over count values degenerates to summing them
                states.append(StateCol(c.values.astype(st.dtype), c.validity, op))
            else:
                states.append(_input_state(b, name, op, a, st, in_types))
        return keys, states

    def acc_to_states(acc: Batch):
        keys = [KeyCol(acc.column(k).values, acc.column(k).validity,
                       _key_domain(acc, k, t))
                for k, t in zip(key_syms, key_types)]
        states = []
        for name, op, a in layout:
            c = acc.column(name)
            states.append(StateCol(c.values, c.validity, op))
        return keys, states

    def merge_step(acc: Optional[Batch], b: Batch, cap: int,
                   prechained: bool = False):
        if not prechained:
            b = chain(b)
        if acc is not None:
            # group keys from different sources (UNION ALL branches,
            # exchange pages) may be coded against different dictionaries;
            # group equality is string equality, so re-encode first
            acc, b = _unify_batch_dicts([acc, b])
        kin, sin = in_to_states(b)
        live = b.live
        if acc is not None:
            ka, sa = acc_to_states(acc)
            kin = [
                KeyCol(
                    jnp.concatenate([a.values, i.values]),
                    _concat_validity(a.validity, i.validity, acc.capacity, b.capacity),
                    a.domain if a.domain == i.domain else None,
                )
                for a, i in zip(ka, kin)
            ]
            sin = [
                StateCol(
                    jnp.concatenate([a.values, i.values]),
                    _concat_validity(a.validity, i.validity, acc.capacity, b.capacity),
                    a.op,
                )
                for a, i in zip(sa, sin)
            ]
            live = jnp.concatenate([acc.live, live])
        kout, sout, out_live, n_groups = grouped_merge(kin, sin, live, cap,
                                                       engine=engine)
        sout = _renorm_limbs(list(sout), lpairs)
        cols = [Column(k.values, k.validity) for k in kout] + [
            Column(s.values, s.validity if s.op != "count_add" else None) for s in sout
        ]
        names = list(key_syms) + [name for name, _, _ in layout]
        types = key_types + state_types
        dicts = {k: b.dicts[k] for k in key_syms if k in b.dicts}
        # string-valued states (min/max/arbitrary) keep the arg's dictionary
        # (final mode: the state column itself carries it post-exchange)
        for name, op, a in layout:
            if op in ("min", "max"):
                if a.arg in b.dicts:
                    dicts[name] = b.dicts[a.arg]
                elif name in b.dicts:
                    dicts[name] = b.dicts[name]
        out = Batch(names, types, cols, out_live, dicts)
        return out, n_groups

    def acc_merge_step(acc: Optional[Batch], b: Batch, cap: int):
        """Merge a previously-spilled accumulator batch (state columns, not
        raw input) into acc — both sides use accumulator semantics."""
        if acc is not None:
            acc, b = _unify_batch_dicts([acc, b])
        kin, sin = acc_to_states(b)
        live = b.live
        if acc is not None:
            ka, sa = acc_to_states(acc)
            kin = [
                KeyCol(
                    jnp.concatenate([a.values, i.values]),
                    _concat_validity(a.validity, i.validity, acc.capacity, b.capacity),
                    a.domain if a.domain == i.domain else None,
                )
                for a, i in zip(ka, kin)
            ]
            sin = [
                StateCol(
                    jnp.concatenate([a.values, i.values]),
                    _concat_validity(a.validity, i.validity, acc.capacity, b.capacity),
                    a.op,
                )
                for a, i in zip(sa, sin)
            ]
            live = jnp.concatenate([acc.live, live])
        kout, sout, out_live, n_groups = grouped_merge(kin, sin, live, cap,
                                                       engine=engine)
        sout = _renorm_limbs(list(sout), lpairs)
        cols = [Column(k.values, k.validity) for k in kout] + [
            Column(s.values, s.validity if s.op != "count_add" else None) for s in sout
        ]
        names = list(key_syms) + [name for name, _, _ in layout]
        types = key_types + state_types
        dicts = {k: v for k, v in b.dicts.items() if k in names}
        return Batch(names, types, cols, out_live, dicts), n_groups

    memo = SimpleNamespace(
        chain=chain, in_types=in_types, layout=layout, lpairs=lpairs,
        key_syms=key_syms, key_types=key_types, state_types=state_types,
        in_to_states=in_to_states, acc_to_states=acc_to_states,
        merge_step=merge_step, acc_merge_step=acc_merge_step)
    memos[engine] = memo
    return memo


def _agg_presize(node: Aggregate, ctx: "ExecContext"):
    """CBO group-table pre-sizing + grace decision for an Aggregate,
    shared by the executor and the install-time breaker warmers (the
    warmers need the same capacity fingerprint the run will use or the
    warm compiles the wrong shape). Returns (cap, ceiling, can_spill,
    grace_from_start)."""
    key_syms = node.group_keys
    cap = ctx.config.agg_capacity
    can_spill = bool(key_syms) and ctx.config.spill_enabled
    ceiling = max(ctx.config.agg_cap_ceiling, ctx.config.agg_capacity)
    if key_syms:
        # CBO capacity pre-sizing: a group table sized from derived NDV
        # stats skips the overflow→replay growth ladder entirely
        # (DetermineJoinDistributionType's cousin for aggregation; the
        # reference sizes hash tables from expectedGroups hints)
        try:
            from presto_tpu.plan.stats import derive as _derive_stats

            _st = _derive_stats(node, ctx.catalog)
        except Exception:
            _st = None
        rows = _st.rows if (_st is not None and _st.rows) else None
        if getattr(ctx.config, "hbo", "observe") == "correct":
            # HBO: a previous run of this structure measured the real
            # group count — presize from the high-water mark instead of
            # the NDV estimate (replaces it: shrinking a bloated estimate
            # is as valid as growing a blind one)
            try:
                from presto_tpu.obs import runstats as _runstats

                h = _runstats.lookup_node(node, ctx.catalog, "agg_groups")
            except Exception:
                h = None
            if h and h.get("actual"):
                rows = float(h["actual"])
                try:
                    _runstats.record_correction("agg_presize")
                except Exception:
                    pass
        if rows:
            if ctx.lifespans:
                # grouped execution: one bucket holds ~1/lifespans of the
                # groups — size the table for a bucket, not the table
                rows = rows / ctx.lifespans
            want = round_up_capacity(int(min(rows * 1.25, float(1 << 23))))
            cap = max(cap, want)
    # Past the ceiling a fixed-capacity table stops being the right tool
    # (every merge sorts `capacity + batch` rows, nearly all of them dead):
    # go grace from the start — raw input hash-partitions to spill and each
    # partition merges at small capacity (SpillableHashAggregationBuilder /
    # grouped execution; see ExecConfig.agg_cap_ceiling).
    grace_from_start = can_spill and cap > ceiling
    if can_spill:
        cap = min(cap, ceiling)
    return cap, ceiling, can_spill, grace_from_start


def _fragment_eligibility(node: PlanNode, config: ExecConfig) -> Optional[str]:
    """Why a breaker's ingest loop can NOT run as a fused fragment
    (None = eligible). Static structure only — the executors add the
    per-query gates (grouped-execution sweeps, radix engagement,
    grace-from-start). Conservative by design: anything the fuser can't
    prove inert under lax.scan (unnest, host projections, non-scan bases)
    keeps the per-batch path."""
    if not config.fragment_fusion:
        return "off"
    if config.fragment_window < 2:
        return "window < 2"
    if isinstance(node, Aggregate):
        if any(a.fn in _NON_DECOMPOSABLE_FNS for a in node.aggs):
            return "non-decomposable aggregate"
    elif isinstance(node, Sort):
        if node.limit is None:
            return "full sort materializes"
    else:
        return "not a fused breaker"
    try:
        base, _ = collapse_chain(node.child)
    except Exception:
        return "chain does not collapse"
    if not isinstance(base, TableScan):
        return "chain base is not a table scan"
    return None


def _record_fragment_dispatch(node: PlanNode, ctx: "ExecContext",
                              fused: bool, k: int = 1) -> None:
    """Dispatch accounting for breaker ingest loops: one fused fragment
    dispatch covers k batches; a per-batch step covers one. Feeds the
    per-node EXPLAIN ANALYZE rendering, ctx.stats, and the process-wide
    presto_tpu_{fragment,batch}_dispatches_total counters."""
    from presto_tpu.scan import metrics as _scan_metrics

    fs = node.__dict__.setdefault(
        "_fragment_stats",
        {"fragment_dispatches": 0, "batch_dispatches": 0, "fused_batches": 0})
    if fused:
        fs["fragment_dispatches"] += 1
        fs["fused_batches"] += k
        ctx.stats["fragment.dispatches"] = (
            ctx.stats.get("fragment.dispatches", 0) + 1)
        ctx.stats["fragment.fused_batches"] = (
            ctx.stats.get("fragment.fused_batches", 0) + k)
        _scan_metrics.record("fragment_dispatches", 1)
    else:
        fs["batch_dispatches"] += 1
        ctx.stats["fragment.batch_dispatches"] = (
            ctx.stats.get("fragment.batch_dispatches", 0) + 1)
        _scan_metrics.record("batch_dispatches", 1)
    if ctx.inflight is not None:
        # window-boundary heartbeat: counts the driver already holds —
        # never a device sync (obs/inflight.py off-discipline)
        ctx.inflight.publish(type(node).__name__,
                             windows=1 if fused else 0, batches=k)


def _inflight_window_hook(node: PlanNode, ctx: "ExecContext"):
    """WindowSource on_window callback publishing the staging watermark
    (windows stacked ahead of the consumer) into the inflight plane.
    None when the plane is off, so the producer thread pays nothing."""
    inf = ctx.inflight
    if inf is None:
        return None
    op = type(node).__name__
    staged = {"n": 0}

    def hook(k: int, width: int) -> None:
        staged["n"] += 1
        inf.publish(op, stagedWindows=staged["n"])

    return hook


def _inflight_spill_hook(node: PlanNode, ctx: "ExecContext"):
    """PartitioningSpiller on_spill callback publishing the spill
    watermark (cumulative bytes + partition-tree depth) per routed
    batch. None when the plane is off."""
    inf = ctx.inflight
    if inf is None:
        return None
    op = type(node).__name__

    def hook(nbytes: int, depth: int) -> None:
        inf.publish(op, spilledBytes=int(nbytes), spillDepth=int(depth))

    return hook


def _bump_replay_wave(node: PlanNode, ctx: "ExecContext",
                      hbo_obs: Optional[dict] = None,
                      cap_to: Optional[int] = None) -> None:
    """Account one overflow-replay wave: a stats-sized capacity proved too
    small and a breaker re-merged from a checkpoint at a bigger size.
    Plain telemetry (ctx.stats + process counter + zero-width span), not
    gated on hbo — the wave happened regardless of who is watching."""
    from presto_tpu.scan import metrics as _scan_metrics

    ctx.stats["breaker.replay_waves"] = (
        ctx.stats.get("breaker.replay_waves", 0) + 1)
    _scan_metrics.record("breaker_replay_waves", 1)
    if hbo_obs is not None:
        hbo_obs["replays"] += 1
    if ctx.tracer.enabled:
        t = time.time()
        attrs = {"node": type(node).__name__}
        if cap_to is not None:
            attrs["cap_to"] = cap_to
        ctx.tracer.record("overflow_replay", "overflow_replay", t, t,
                          **attrs)
    if ctx.inflight is not None:
        ctx.inflight.publish(type(node).__name__,
                             wave=ctx.stats["breaker.replay_waves"],
                             cap=cap_to)


def _spill_stats_for(node: PlanNode, ctx: "ExecContext") -> dict:
    """Per-node spill accounting stamped for EXPLAIN ANALYZE's
    [spill: P=… depth=… reversed=…] rendering and the HBO spill sites."""
    return node.__dict__.setdefault(
        "_spill_stats",
        {"partitions": 0, "repartitions": 0, "reversed": 0, "depth": 0,
         "revocations": 0, "bytes": 0})


def _note_spill_repartition(node: PlanNode, ctx: "ExecContext",
                            child, parent_p: int) -> None:
    """One next-hash-bits split happened (mid-build growth or replay-time
    recursive repartitioning): counters + span + EXPLAIN stats."""
    from presto_tpu.scan import metrics as _scan_metrics

    st = _spill_stats_for(node, ctx)
    st["repartitions"] += 1
    st["depth"] = max(st["depth"], child.depth)
    ctx.stats["spill.repartitions"] = ctx.stats.get("spill.repartitions", 0) + 1
    _scan_metrics.record("spill_repartitions", 1)
    if ctx.tracer.enabled:
        t = time.time()
        ctx.tracer.record("spill_repartition", "spill_repartition", t, t,
                          node=type(node).__name__, partition=int(parent_p),
                          depth=int(child.depth),
                          fanout=int(child.n_partitions))
    if ctx.inflight is not None:
        ctx.inflight.publish(type(node).__name__,
                             repartitions=st["repartitions"],
                             spillDepth=st["depth"])


def _note_spill_revoke(node: PlanNode, ctx: "ExecContext",
                       freed: int) -> None:
    """A pool-pressure revoke request was honored: spillable operator
    state left the device at a batch boundary."""
    from presto_tpu.scan import metrics as _scan_metrics

    st = _spill_stats_for(node, ctx)
    st["revocations"] += 1
    ctx.stats["spill.revocations"] = ctx.stats.get("spill.revocations", 0) + 1
    _scan_metrics.record("spill_revocations", 1)
    if ctx.tracer.enabled:
        t = time.time()
        ctx.tracer.record("spill_revoke", "spill_revoke", t, t,
                          node=type(node).__name__, freed=int(freed))
    if ctx.inflight is not None:
        ctx.inflight.publish(type(node).__name__,
                             spilledBytes=int(freed))


def _spill_replay_budget(ctx: "ExecContext") -> Optional[int]:
    """Byte budget one replayed spill partition's build side must fit in:
    the explicit per-partition budget when set, else the memory pool's
    revoke target (the replay concat has to fit back under the pool limit
    with headroom). None = unbudgeted (replay whole partitions)."""
    if ctx.config.join_spill_budget_bytes is not None:
        return ctx.config.join_spill_budget_bytes
    pool = ctx.memory_pool
    if pool.limit is not None:
        return max(1, int(pool.limit * pool.revoke_target))
    return None


def _hbo_spill_partitions(node: PlanNode, ctx: "ExecContext", site: str,
                          default_p: int) -> int:
    """hbo=correct: seed the initial spill partition count from the leaf
    count a previous run of this structure converged to, so the repeat run
    skips the repartition waves entirely."""
    if getattr(ctx.config, "hbo", "observe") != "correct":
        return default_p
    try:
        from presto_tpu.obs import runstats as _runstats

        h = _runstats.lookup_node(node, ctx.catalog, site)
    except Exception:
        h = None
    if h and h.get("actual"):
        want = int(h["actual"])
        if want > default_p:
            try:
                _runstats.record_correction("spill_partitions")
            except Exception:
                pass
            return min(want, 1024)
    return default_p


def _hbo_radix_partitions(node: PlanNode, ctx: "ExecContext", site: str,
                          default_p: int) -> int:
    """hbo=correct: seed the device-side radix partition count from the
    row count a previous run of this structure observed (join_build /
    agg_groups), targeting ~HASH_MAX_BUILD_ROWS rows per partition — the
    ROADMAP item-3 residual: the radix plane no longer runs a fixed
    per-plan-node count when history knows the state is bigger (same
    discipline as _hbo_spill_partitions for the spiller). Pow2, bounded;
    a changed count is correctness-safe because _radix_tag verifies the
    producer's partition count and falls back to the splitter on
    mismatch — only exchange alignment is lost, never rows."""
    if getattr(ctx.config, "hbo", "observe") != "correct":
        return default_p
    try:
        from presto_tpu.obs import runstats as _runstats

        h = _runstats.lookup_node(node, ctx.catalog, site)
    except Exception:
        h = None
    if h and h.get("actual"):
        from presto_tpu.plan.stats import HASH_MAX_BUILD_ROWS

        want = round_up_capacity(
            max(1, int(float(h["actual"])) // HASH_MAX_BUILD_ROWS))
        if want > default_p:
            try:
                from presto_tpu.obs import runstats as _runstats

                _runstats.record_correction("radix_partitions")
            except Exception:
                pass
            return min(want, 256)
    return default_p


def _record_spill_done(node: PlanNode, ctx: "ExecContext", site: str,
                       est_p: int, spilled_bytes: int, side: str) -> None:
    """Close out one spilling operator: final leaf count to the counter
    plane, spilled bytes to the histogram plane, and the whole shape
    (partitions / repartitions / reversals / depth / skew-visible bytes)
    into HBO history keyed on the node's structural fingerprint."""
    from presto_tpu.obs import metrics as _obs_metrics
    from presto_tpu.scan import metrics as _scan_metrics

    st = _spill_stats_for(node, ctx)
    st["bytes"] += int(spilled_bytes)
    if st["partitions"]:
        _scan_metrics.record("spill_partitions", st["partitions"])
        ctx.stats["spill.partitions"] = (
            ctx.stats.get("spill.partitions", 0) + st["partitions"])
    if spilled_bytes:
        _obs_metrics.SPILLED_BYTES.observe(
            float(spilled_bytes), plane="worker", side=side)
    if getattr(ctx.config, "hbo", "observe") == "off":
        return
    try:
        from presto_tpu.obs import runstats as _runstats

        fp = _runstats.node_fingerprint(node, ctx.catalog)
        if fp is None:
            return
        _runstats.observe(
            fp, site, type(node).__name__.lower(), float(est_p),
            float(max(st["partitions"], 1)),
            extra={"repartitions": int(st["repartitions"]),
                   "reversals": int(st["reversed"]),
                   "depth": int(st["depth"]),
                   "spilled_bytes": int(spilled_bytes)})
    except Exception:
        pass


def _hbo_record_agg(node: Aggregate, ctx: "ExecContext", obs: dict,
                    skew: Optional[float] = None) -> None:
    """Record the aggregate's observed group count into the runstats
    history (the exact confirmed `ng` the overflow protocol already
    fetched — no extra device sync), stamp the node for EXPLAIN ANALYZE
    drift rendering, and count whether the engine choice would flip on
    the observed value."""
    if getattr(ctx.config, "hbo", "observe") == "off":
        return
    if not node.group_keys or not obs.get("groups"):
        return
    try:
        from presto_tpu.obs import runstats as _runstats
        from presto_tpu.plan.stats import choose_breaker_engine
        from presto_tpu.plan.stats import derive as _derive_stats

        fp = _runstats.node_fingerprint(node, ctx.catalog)
        if fp is None:
            return
        try:
            st = _derive_stats(node, ctx.catalog)
        except Exception:
            st = None
        est = float(st.rows) if (st is not None and st.rows) else None
        actual = float(obs["groups"])
        extra = {"replays": int(obs.get("replays", 0))}
        if skew is not None:
            extra["skew"] = float(skew)
        if obs.get("final_cap"):
            # the CONVERGED capacity, not the initial presize — a
            # hbo=correct structure repeat starts where this run ended
            extra["final_cap"] = int(obs["final_cap"])
        made0 = node.__dict__.get("_breaker_engine")
        if made0:
            # the CONVERGED engine: after an adaptive flip this is the
            # winner, with `(adaptive: flipped)` provenance — history
            # records what the run ended on, not what it guessed
            extra["engine"] = made0
            if node.__dict__.get("_adaptive_engine_flipped"):
                extra["adaptive"] = "flipped"
        if getattr(ctx.config, "devprof", "off") == "on" \
                and ctx.memory_pool is not None \
                and getattr(ctx.memory_pool, "peak", 0):
            # devprof plane: the ledger's high-water so far rides the
            # fingerprint into history — ROADMAP item-3 spill sizing
            # reads it back as peak_bytes on a structure repeat
            extra["peak_bytes"] = float(ctx.memory_pool.peak)
        _runstats.observe(fp, "agg_groups", "aggregate", est, actual,
                          extra=extra)
        node.__dict__["_runstats"] = {
            "site": "agg_groups", "est": est, "actual": actual}
        made = node.__dict__.get("_breaker_engine")
        if made:
            would, _ = choose_breaker_engine(
                node, ctx.catalog,
                getattr(ctx.config, "breaker_engine", "auto"),
                hbo="correct")
            if would != made:
                _runstats.record_flip("breaker_engine")
    except Exception:
        pass


def _hbo_fragment_window(node: PlanNode, ctx: "ExecContext") -> int:
    """Fused-fragment window width: the configured value, shrunk to the
    observed batch count of the fragment's base scan (hbo=correct, warm
    history) — stacking an 8-batch window over a source that emits 2
    batches pushes 6 batches of dead padding through every fused step."""
    win = max(1, ctx.config.fragment_window)
    if getattr(ctx.config, "hbo", "observe") != "correct":
        return win
    try:
        from presto_tpu.obs import runstats as _runstats

        base, _ = collapse_chain(node.child)
        if not isinstance(base, TableScan):
            return win
        fp = _runstats.node_fingerprint(base, ctx.catalog)
        h = _runstats.lookup(fp, "scan_rows") if fp else None
        if not h or not h.get("actual"):
            return win
        batches = -(-int(h["actual"]) // max(1, ctx.config.batch_rows))
        if 0 < batches < win:
            _runstats.record_correction("fragment_window")
            return batches
    except Exception:
        pass
    return win


def _hbo_record_scans(root: PlanNode, ctx: "ExecContext") -> None:
    """Observe per-scan actual rows against the derived estimates
    (collect_stats runs only — the row counts ride the instrumented
    stream's existing host sync; an uninstrumented run records nothing
    rather than adding a sync of its own)."""
    if getattr(ctx.config, "hbo", "observe") == "off":
        return
    if not ctx.config.collect_stats or not ctx.node_stats:
        return
    try:
        from presto_tpu.obs import runstats as _runstats
        from presto_tpu.plan.stats import derive as _derive_stats

        def walk(n):
            if isinstance(n, TableScan):
                rec = ctx.node_stats.get(id(n))
                if rec and rec.get("rows"):
                    fp = _runstats.node_fingerprint(n, ctx.catalog)
                    try:
                        st = _derive_stats(n, ctx.catalog)
                    except Exception:
                        st = None
                    est = (float(st.rows)
                           if (st is not None and st.rows) else None)
                    _runstats.observe(fp, "scan_rows", "tablescan", est,
                                      float(rec["rows"]))
                    n.__dict__["_runstats"] = {
                        "site": "scan_rows", "est": est,
                        "actual": float(rec["rows"])}
            for c in n.children():
                walk(c)

        walk(root)
    except Exception:
        pass


def _execute_aggregate(node: Aggregate, ctx: ExecContext) -> Iterator[Batch]:
    if ctx.lifespan is None:
        ls = _grouped_execution_lifespans(node)
        if ls:
            # grouped execution covers the aggregation too: sweep the
            # task's buckets with the sweep rooted HERE so each bucket's
            # accumulator is finalized and freed before the next builds
            try:
                ctx.lifespans = ls
                for b in range(ctx.task_index, ls, ctx.n_tasks):
                    ctx.lifespan = b
                    yield from _execute_aggregate(node, ctx)
            finally:
                ctx.lifespan = None
                ctx.lifespans = None
            return

    if any(a.fn in _NON_DECOMPOSABLE_FNS for a in node.aggs):
        if node.step != "single":
            raise RuntimeError(
                "non-decomposable aggregates must run single-step "
                "(fragmenter gathers them)"
            )
        yield from _execute_materialized_aggregate(node, ctx)
        return

    in_stream, _ = _fused_child(node.child, ctx)
    engine = _breaker_engine_choice(node, ctx)
    steps = _agg_steps(node, engine)
    chain = steps.chain
    in_types = steps.in_types
    layout = steps.layout
    key_syms = steps.key_syms
    key_types = steps.key_types
    state_types = steps.state_types
    in_to_states = steps.in_to_states
    merge_step = steps.merge_step
    acc_merge_step = steps.acc_merge_step

    # global (ungrouped) aggregation threads the accumulator linearly and
    # never replays (no_overflow below): the input acc is dead the moment
    # the step returns, so its device buffers can be donated and updated
    # in place. Keyed aggregation CANNOT donate — the optimistic dispatch
    # window keeps acc_before alive as the overflow-replay checkpoint.
    _step_jit_kw = {}
    if ctx.config.donate_stepping and not key_syms:
        _step_jit_kw["donate_argnums"] = (0,)
    jit_chain = _node_jit(node, "chain_only", lambda: chain)

    from presto_tpu.memory import LocalMemoryContext, batch_device_bytes

    import threading as _threading

    cap, ceiling, can_spill, grace_from_start = _agg_presize(node, ctx)
    # HBO observation scratchpad: confirmed group-count high-water mark +
    # overflow-replay waves, recorded once the stream is fully absorbed
    hbo_obs = {"groups": 0, "replays": 0}
    # whole-fragment fusion gate: static eligibility plus the per-query
    # modes whose ingest must stay per-batch (memory-tight lifespan
    # sweeps pin ~window× the state the mode exists to avoid)
    frag_why = _fragment_eligibility(node, ctx.config)
    if frag_why is None and ctx.lifespans is not None:
        frag_why = "grouped-execution sweep"
    if frag_why is None and grace_from_start:
        frag_why = "grace-from-start spill"
    node.__dict__["_fragment_fusion"] = (
        "fused" if frag_why is None else frag_why)

    # Every engine-keyed closure lives behind one binder so an adaptive
    # mid-query flip (_EngineFlip) can re-point all of them at the other
    # engine's steps under fresh @h-forked program-cache keys. Each call
    # captures that engine's merge closures by VALUE (`ms`/`ams` are
    # locals of the call, one cell per invocation): a later rebind must
    # never leak the new engine's function into a not-yet-traced builder
    # registered under the old engine's cache key.
    _ek = None
    jit_step = jit_step0 = jit_accstep = None
    jit_step_raw = jit_step0_raw = None
    jit_frag_step = jit_frag_step0 = None

    def _bind_engine(new_engine):
        nonlocal engine, steps, merge_step, acc_merge_step, _ek
        nonlocal jit_step, jit_step0, jit_accstep
        nonlocal jit_step_raw, jit_step0_raw
        nonlocal jit_frag_step, jit_frag_step0
        engine = new_engine
        steps = _agg_steps(node, engine)
        ms = merge_step = steps.merge_step
        ams = acc_merge_step = steps.acc_merge_step
        _ek = lambda k: _engine_key(k, new_engine)  # noqa: E731
        jit_step = _node_jit(
            node, _ek("step"),
            lambda: (lambda acc, b, cap: ms(acc, b, cap)),
            static_argnums=(2,), **_step_jit_kw)
        jit_step0 = _node_jit(
            node, _ek("step0"), lambda: (lambda b, cap: ms(None, b, cap)),
            static_argnums=(1,))
        jit_accstep = _node_jit(node, _ek("accstep"), lambda: ams,
                                static_argnums=(2,))
        # grace (hash-partitioned) aggregation: partition replay feeds
        # batches that went through `chain` before spilling — merge must
        # not re-chain
        jit_step_raw = _node_jit(
            node, _ek("step_raw"),
            lambda: (lambda acc, b, cap: ms(acc, b, cap, prechained=True)),
            static_argnums=(2,))
        jit_step0_raw = _node_jit(
            node, _ek("step0_raw"),
            lambda: (lambda b, cap: ms(None, b, cap, prechained=True)),
            static_argnums=(1,))
        if frag_why is None:
            jit_frag_step = _node_jit(
                node, _ek("fragment_step"),
                lambda: _fragment_jit.scan_stepper(ms, False),
                static_argnums=(2,), **_step_jit_kw)
            jit_frag_step0 = _node_jit(
                node, _ek("fragment_step0"),
                lambda: _fragment_jit.scan_stepper(ms, True),
                static_argnums=(1,))

    _bind_engine(engine)

    if node.step == "partial" and grace_from_start:
        node.__dict__["_fragment_fusion"] = "partial passthrough"
        # Adaptive partial-aggregation bypass (reference: partial agg
        # adaptivity — when NDV ≈ row count the partial merge does no
        # reduction): emit per-row state contributions unmerged; the final
        # step after the exchange does the one real merge, partitioned.
        def row_states(b: Batch):
            b = chain(b)
            kin, sin = in_to_states(b)
            cols = [Column(k.values, k.validity) for k in kin] + [
                Column(s.values, s.validity if s.op != "count_add" else None)
                for s in sin]
            names = list(key_syms) + [name for name, _, _ in layout]
            types = key_types + state_types
            dicts = {k: b.dicts[k] for k in key_syms if k in b.dicts}
            for name, op, a in layout:
                if op in ("min", "max") and a.arg in b.dicts:
                    dicts[name] = b.dicts[a.arg]
            return Batch(names, types, cols, b.live, dicts)

        jit_rows = _node_jit(node, "partial_passthrough", lambda: row_states)
        for b in in_stream:
            yield jit_rows(b)
        return

    # Radix only pays when the group table is genuinely large: when the
    # CBO presize fits the base capacity the accumulator already has one
    # small bounded shape, and splitting every input batch by group key
    # would be pure overhead. A spill budget engages it regardless —
    # bounding device residency is the point then, not shapes.
    if (key_syms and ctx.config.radix_partitions > 1
            and (ctx.config.join_spill_budget_bytes is not None
                 or cap > ctx.config.agg_capacity)):
        # Radix-partitioned group-by (ops/radix.py): chained input splits
        # by the top hash bits, each partition merges into its OWN small
        # accumulator with the prechained step closures — P bounded group
        # tables instead of one query-size-dependent one. Per input batch,
        # every partition's merge dispatches before any confirms, so the
        # growth-check sync overlaps the other partitions' device work
        # (the full optimistic window would pin P×depth checkpoints of
        # device state for little extra gain). Partitions whose accumulator
        # exceeds join_spill_budget_bytes hybrid-spill: the confirmed
        # state pages plus all later raw sub-batches go to host files and
        # replay one-at-a-time at the end.
        from presto_tpu.memory import batch_device_bytes as _bdb
        from presto_tpu.obs import metrics as _obs_metrics
        from presto_tpu.scan import metrics as _scan_metrics
        from presto_tpu.spiller import SpillFile

        node.__dict__["_fragment_fusion"] = "radix-partitioned"
        P = _hbo_radix_partitions(node, ctx, "agg_groups",
                                  ctx.config.radix_partitions)
        budget = ctx.config.join_spill_budget_bytes
        split = _radix_splitter(node, ctx, key_syms, P, "agg_")
        jit_accstep0 = _node_jit(
            node, _ek("accstep0"),
            lambda: (lambda b, c: acc_merge_step(None, b, c)),
            static_argnums=(1,))
        # CBO pre-sizing applies per partition: each holds ~1/P of the
        # estimated groups, and the pow2 ladder steps are shared across
        # partitions so one compile serves all P
        start_cap = max(ctx.config.agg_capacity,
                        round_up_capacity(max(cap // P, 1)))
        caps = [start_cap] * P
        accs: List[Optional[Batch]] = [None] * P
        rrows = [0] * P
        part_ng = [0] * P  # confirmed per-partition group counts (host ints)
        afiles: Dict[int, SpillFile] = {}  # spilled accumulator state pages
        rfiles: Dict[int, SpillFile] = {}  # spilled raw (chained) input

        def _stat(key, delta):
            ctx.stats[key] = ctx.stats.get(key, 0) + delta

        _stat("radix.agg_engaged", 1)

        def merge_into(p, sub, step_fn, step0_fn, first=None):
            for attempt in range(ctx.config.max_growth_retries):
                if first is not None and attempt == 0:
                    out, ng = first
                elif accs[p] is None:
                    out, ng = step0_fn(sub, caps[p])
                else:
                    out, ng = step_fn(accs[p], sub, caps[p])
                n2 = int(ng)
                if n2 <= caps[p]:
                    accs[p] = out
                    part_ng[p] = max(part_ng[p], n2)
                    return
                # acc unchanged on overflow: retry same inputs bigger
                caps[p] = round_up_capacity(n2)
                _bump_replay_wave(node, ctx, hbo_obs, cap_to=caps[p])
            raise RuntimeError("aggregate capacity growth exceeded retries")

        def _emit(acc):
            if node.step == "partial":
                return acc
            return _finalize_aggregate(node, acc, layout, key_syms,
                                       key_types, state_types, in_types)

        def spill_partition(p):
            """Hybrid-spill partition p: the confirmed state pages plus all
            later raw sub-batches go to host files and replay at the end."""
            af = ctx.spill_manager.spill_file(f"radix-agg-acc-p{p}")
            ctx.track_spill(af)
            if accs[p] is not None:
                af.append(accs[p])
            afiles[p] = af
            rfiles[p] = ctx.spill_manager.spill_file(f"radix-agg-raw-p{p}")
            ctx.track_spill(rfiles[p])
            accs[p] = None
            caps[p] = start_cap
            _stat("radix.partitions_spilled", 1)
            _scan_metrics.record("radix_partitions_spilled", 1)

        rev = {"flag": False, "targets": []}

        def _revoke(_need):
            # pool-pressure REQUEST honored at the next batch boundary
            # (spilling synchronously inside reserve() would re-enter the
            # accounting — same protocol as the non-radix agg revoker)
            rev["flag"] = True
            return 0

        # adaptive device-side radix growth (ops/radix.radix_child_perm):
        # parent partition id -> {"caps","accs","ng"} child state. A
        # grown partition re-splits its input by the NEXT hash bits down,
        # so a budget-blowing partition stays on device as F small
        # children instead of round-tripping through host spill files.
        grown: Dict[int, dict] = {}
        _child = {"perm": None, "win": None}

        def _child_split(sub):
            if _child["perm"] is None:
                from presto_tpu.ops import radix as _radix

                keys = tuple(key_syms)
                _child["perm"] = _node_jit(
                    node, "agg_child_perm",
                    lambda: (lambda b: _radix.radix_child_perm(
                        b, keys, P, _RADIX_GROW_FANOUT)))
                # same gather program the parent splitter compiles — the
                # shared cache key reuses it instead of re-tracing
                _child["win"] = _node_jit(
                    node, "agg_radix_window",
                    lambda: _radix.radix_window_perm,
                    static_argnames=("bucket",))
            sperm, counts = _child["perm"](sub)
            cnts = np.asarray(counts)
            starts = np.concatenate([[0], np.cumsum(cnts)])
            for c in range(_RADIX_GROW_FANOUT):
                n = int(cnts[c])
                if n:
                    yield c, _child["win"](
                        sub, sperm, np.int32(starts[c]), np.int32(n),
                        bucket=round_up_capacity(n)), n

        def child_merge(p, c, sub, step_fn, step0_fn):
            ch = grown[p]
            for _ in range(ctx.config.max_growth_retries):
                if ch["accs"][c] is None:
                    out, ng = step0_fn(sub, ch["caps"][c])
                else:
                    out, ng = step_fn(ch["accs"][c], sub, ch["caps"][c])
                n2 = int(ng)
                if n2 <= ch["caps"][c]:
                    ch["accs"][c] = out
                    ch["ng"][c] = max(ch["ng"][c], n2)
                    return
                ch["caps"][c] = round_up_capacity(n2)
                _bump_replay_wave(node, ctx, hbo_obs, cap_to=ch["caps"][c])
            raise RuntimeError("aggregate capacity growth exceeded retries")

        def grow_partition_device(p):
            """Adaptive device-side grow_partition: split resident
            partition p by the next hash bits. The confirmed accumulator
            is itself a valid state-page batch, so each child slice
            re-merges through the acc-merge step at a small capacity —
            hot-but-distinct keys separate under fresh entropy while the
            parent decomposition (and any partition-aligned exchange
            tags at the parent P) stays valid."""
            acc0 = accs[p]
            grown[p] = {"caps": [start_cap] * _RADIX_GROW_FANOUT,
                        "accs": [None] * _RADIX_GROW_FANOUT,
                        "ng": [0] * _RADIX_GROW_FANOUT}
            accs[p] = None
            caps[p] = start_cap
            _stat("radix.partitions_grown", 1)
            _scan_metrics.record("radix_partitions_grown", 1)
            if acc0 is not None:
                for c, ss, _n in _child_split(acc0):
                    child_merge(p, c, ss, jit_accstep, jit_accstep0)

        def spill_grown(p):
            """A grown partition's child blew the budget too: fall back
            to hybrid spill for the WHOLE parent partition (children
            rejoin as state pages — child ids refine parent ids, so the
            end-of-stream replay is untouched by the growth detour)."""
            ch = grown.pop(p)
            af = ctx.spill_manager.spill_file(f"radix-agg-acc-p{p}")
            ctx.track_spill(af)
            for a in ch["accs"]:
                if a is not None:
                    af.append(a)
            afiles[p] = af
            rfiles[p] = ctx.spill_manager.spill_file(f"radix-agg-raw-p{p}")
            ctx.track_spill(rfiles[p])
            caps[p] = start_cap
            _stat("radix.partitions_spilled", 1)
            _scan_metrics.record("radix_partitions_spilled", 1)

        def over_budget(p):
            """Budget enforcement with the adaptive rung in front: the
            first breach grows the partition on device (radix_grow); a
            child breach — or adaptive off/observe — hybrid-spills."""
            if p in grown:
                if any(a is not None and _bdb(a) > budget
                       for a in grown[p]["accs"]):
                    spill_grown(p)
                return
            nbytes = _bdb(accs[p])
            if nbytes <= budget:
                return
            if ctx.adaptive is not None:
                acted = ctx.adaptive.decide(
                    "radix_grow", node=node,
                    site=_adaptive_site(node, ctx),
                    before=f"p{p}", after=f"p{p}/{_RADIX_GROW_FANOUT}",
                    detail=(f"grow p{p} into {_RADIX_GROW_FANOUT} "
                            "device children"),
                    bytes=int(nbytes), budget=int(budget))
                if acted:
                    grow_partition_device(p)
                    return
            spill_partition(p)

        # resident-state accounting (LocalMemoryContext protocol, same as
        # the grace path's mctx): without it the pool never sees radix
        # residency and partition-granular revocation has no pressure
        # source to react to. Gated to adaptive=on — off/observe must
        # keep the seed's exact reserve/replay sequence, and only the
        # partial-revocation protocol consumes this pressure anyway.
        from presto_tpu.memory import LocalMemoryContext as _LMC
        _account_on = ctx.adaptive is not None and ctx.adaptive.mode == "on"
        mctx_r = _LMC(ctx.memory_pool, "radix-aggregate")

        def _account_resident():
            if not _account_on:
                return
            total = sum(_bdb(a) for a in accs if a is not None)
            for ch in grown.values():
                total += sum(_bdb(a) for a in ch["accs"] if a is not None)
            mctx_r.set_bytes(int(total))

        _partial_fn = None
        if ctx.config.spill_enabled:
            if ctx.adaptive is not None and ctx.adaptive.mode == "on":
                # partition-granular revocation: pool pressure marks the
                # LARGEST partitions (cross-owner largest-first ranking
                # lives in MemoryPool.request_partial_revoke) instead of
                # flag-spilling blind — cold partitions leave, hot ones
                # stay resident
                def _psizes():
                    return [(pp, int(_bdb(accs[pp]))) for pp in range(P)
                            if accs[pp] is not None and pp not in rfiles
                            and pp not in grown]

                def _prevoke(pp):
                    est = int(_bdb(accs[pp])) if accs[pp] is not None else 0
                    rev["targets"].append(pp)
                    return est

                _partial_fn = ctx.memory_pool.add_partial_revoker(
                    SimpleNamespace(partition_sizes=_psizes,
                                    revoke_partition=_prevoke))
            else:
                ctx.memory_pool.add_revoker(_revoke)
        try:
            for raw_b in in_stream:
                rid = _radix_tag(raw_b, P, key_syms)
                if rid is not None:
                    ub = jit_chain(_untag_batch(raw_b))
                    # num_live stays a device scalar — summed lazily so the
                    # aligned fast path adds no sync of its own
                    subs = [(rid, ub, ub.num_live())]
                    _stat("radix.aligned_batches", 1)
                    _scan_metrics.record("radix_aligned_batches", 1)
                else:
                    subs = split(jit_chain(_untag_batch(raw_b)))
                pend = []
                for p, sub, n in subs:
                    rrows[p] = rrows[p] + n
                    if p in rfiles:
                        rfiles[p].append(sub)
                        continue
                    if p in grown:
                        # grown partitions merge synchronously per child
                        # (the sub re-splits by the next hash bits first)
                        for c, ss, _cn in _child_split(sub):
                            child_merge(p, c, ss, jit_step_raw,
                                        jit_step0_raw)
                        if budget is not None:
                            over_budget(p)
                        continue
                    # dispatch wave: split() yields each partition at most
                    # once per batch, so all merges are independent
                    if accs[p] is None:
                        first = jit_step0_raw(sub, caps[p])
                    else:
                        first = jit_step_raw(accs[p], sub, caps[p])
                    pend.append((p, sub, first))
                for p, sub, first in pend:
                    merge_into(p, sub, jit_step_raw, jit_step0_raw, first)
                    if budget is not None:
                        over_budget(p)
                if rev["flag"] or rev["targets"]:
                    # partition-granular marks first (adaptive partial
                    # revocation, honored here at the batch boundary)
                    targets = []
                    while rev["targets"]:
                        pp = rev["targets"].pop(0)
                        if (accs[pp] is not None and pp not in rfiles
                                and pp not in grown and pp not in targets):
                            targets.append(pp)
                    for pp in targets:
                        nbytes = _bdb(accs[pp])
                        ctx.adaptive.decide(
                            "partial_revoke", node=node,
                            site=_adaptive_site(node, ctx),
                            before=f"p{pp}", after="host",
                            detail=f"revoke p{pp} to host",
                            bytes=int(nbytes))
                        spill_partition(pp)
                        _note_spill_revoke(node, ctx, nbytes)
                    if rev["flag"]:
                        # whole-operator rung (adaptive off/observe):
                        # spill the LARGEST resident partition to host
                        rev["flag"] = False
                        resident = [(pp, _bdb(accs[pp])) for pp in range(P)
                                    if accs[pp] is not None
                                    and pp not in rfiles
                                    and pp not in grown]
                        if resident:
                            pp, nbytes = max(resident, key=lambda t: t[1])
                            if (ctx.adaptive is not None
                                    and ctx.adaptive.mode == "observe"):
                                ctx.adaptive.decide(
                                    "partial_revoke", node=node,
                                    site=_adaptive_site(node, ctx),
                                    before=f"p{pp}", after="host",
                                    detail=f"revoke p{pp} to host",
                                    bytes=int(nbytes))
                            spill_partition(pp)
                            _note_spill_revoke(node, ctx, nbytes)
                # post-boundary accounting: a reserve() here that crosses
                # the pool threshold marks partitions (or sets the flag)
                # for the NEXT boundary — never frees inline
                _account_resident()
            rrows = [int(r) for r in rrows]
            for p in range(P):
                if rrows[p]:
                    _obs_metrics.RADIX_PARTITION_ROWS.observe(
                        rrows[p], plane="worker", side="group")
                if p in grown:
                    ch = grown[p]
                    part_ng[p] = sum(ch["ng"])
                    for c in range(_RADIX_GROW_FANOUT):
                        if ch["accs"][c] is not None:
                            yield _emit(ch["accs"][c])
                            ch["accs"][c] = None
                    continue
                if p in rfiles or accs[p] is None:
                    continue
                yield _emit(accs[p])
                accs[p] = None
            # hybrid-spilled partitions, one resident at a time
            for p in sorted(rfiles):
                t0 = time.time()
                accs[p] = None
                caps[p] = start_cap
                for sub in rfiles[p].read():
                    merge_into(p, sub, jit_step_raw, jit_step0_raw)
                for sub in afiles[p].read():
                    merge_into(p, sub, jit_accstep, jit_accstep0)
                if ctx.tracer.enabled:
                    ctx.tracer.record("radix_spill_replay",
                                      "radix_spill_replay", t0, time.time(),
                                      partition=p, rows=rrows[p])
                if accs[p] is not None:
                    yield _emit(accs[p])
                    accs[p] = None
            if ctx.lifespans is None:
                hbo_obs["groups"] = sum(part_ng)
                _hbo_record_agg(node, ctx, hbo_obs,
                                skew=partition_skew(rrows))
        finally:
            mctx_r.close()
            if ctx.config.spill_enabled:
                ctx.memory_pool.remove_revoker(
                    _partial_fn if _partial_fn is not None else _revoke)
            spilled = (sum(f.bytes for f in afiles.values())
                       + sum(f.bytes for f in rfiles.values()))
            if spilled:
                _stat("radix.spill_bytes", spilled)
                _scan_metrics.record("radix_spill_bytes", spilled)
                ctx.spill_manager.record(spilled)
                _obs_metrics.SPILLED_BYTES.observe(
                    float(spilled), plane="worker", side="group")
            for f in afiles.values():
                f.close()
            for f in rfiles.values():
                f.close()
        return

    # An aligned exchange may still stamp pages with radix tags (the sink
    # can't see the CBO gate above) — strip them before anything jits.
    if ctx.config.radix_partitions > 1:
        in_stream = (_untag_batch(b) for b in in_stream)

    # rows_seen: host-known input watermark (batch capacities — no device
    # sync) feeding the adaptive flip's observed-duplication estimate
    state = {"acc": None, "spiller": None, "raw_spiller": None,
             "revoke_requested": False, "rows_seen": 0}
    mctx = LocalMemoryContext(ctx.memory_pool, "aggregate")
    owner_thread = _threading.get_ident()
    # dynamic hybrid hash: the initial partition count is an ESTIMATE —
    # hbo=correct seeds it from the leaf count a previous run of this
    # structure converged to, so the repeat skips the repartition waves
    grace_P = (_hbo_spill_partitions(node, ctx, "spill_agg",
                                     ctx.config.spill_partitions)
               if can_spill else ctx.config.spill_partitions)

    def mk_raw_spiller():
        if state["raw_spiller"] is None:
            state["raw_spiller"] = ctx.spill_manager.partitioning_spiller(
                key_syms, grace_P, "agg-raw",
                on_grow=lambda child, pp: _note_spill_repartition(
                    node, ctx, child, pp),
                on_spill=_inflight_spill_hook(node, ctx))
            ctx.track_spill(state["raw_spiller"])
        return state["raw_spiller"]

    def do_spill() -> int:
        """Partition-spill the accumulator (SpillableHashAggregationBuilder:
        state pages leave memory partitioned by hash(keys) so each partition
        finalizes independently later)."""
        acc0 = state["acc"]
        if acc0 is None:
            return 0
        if state["spiller"] is None:
            state["spiller"] = ctx.spill_manager.partitioning_spiller(
                key_syms, grace_P, "agg",
                on_grow=lambda child, pp: _note_spill_repartition(
                    node, ctx, child, pp),
                on_spill=_inflight_spill_hook(node, ctx))
            ctx.track_spill(state["spiller"])
        state["spiller"].spill(acc0)
        freed = mctx.bytes
        state["acc"] = None
        mctx.set_bytes(0)
        ctx.spill_manager.record(freed)
        return freed

    def revoke(_need: int) -> int:
        """Pool-pressure callback. Like the reference's revocable-memory
        protocol this is always a REQUEST honored at the next batch
        boundary: spilling synchronously here would re-enter set_bytes
        (a reserve() mid-flight can trigger our own revoker) and corrupt
        the accounting on a worker-shared pool."""
        state["revoke_requested"] = True
        return 0

    def _ceiling_overflow(mode, entries):
        if mode == "fail":
            from presto_tpu.spiller import SpillLimitExceeded

            raise SpillLimitExceeded(
                "aggregate spill partition exceeds the grace ceiling at "
                f"max recursion depth {max(0, ctx.config.spill_max_depth)} "
                "(group keys share too many hash bits to split further)")
        raise _GraceOverflow(entries)

    if can_spill:
        ctx.memory_pool.add_revoker(revoke)
    try:
        def absorb(stream, step_fn, step0_fn, allow_spill=True,
                   on_ceiling=None):
            """Merge the stream into the accumulator with OPTIMISTIC
            dispatch: the per-step group count `ng` (the only data-dependent
            control input) is fetched asynchronously and confirmed up to
            `agg_pipeline_depth` steps later, so the device pipeline never
            stalls on a host round trip (70-90 ms each through the TPU
            tunnel — the dominant cost of the old sync-per-batch loop).
            A window of (checkpoint-acc, input-batch) pairs is held; on the
            rare capacity overflow the window replays synchronously from
            the last confirmed checkpoint at a bigger capacity.

            `on_ceiling` names what growth past the grace ceiling does:
            "grace" raises _GraceOverflow (hand the input to the
            hash-partitioned spill path — the mid-stream default and the
            replay-time recursive-repartition trigger), "grow" keeps
            growing the table (spill unavailable), "fail" raises
            SpillLimitExceeded (recursive repartitioning hit its depth
            bound without converging)."""
            nonlocal cap
            mode = on_ceiling or ("grace" if allow_spill else "grow")
            if not can_spill:
                mode = "grow"
            depth = max(1, ctx.config.agg_pipeline_depth)
            no_overflow = not key_syms  # global agg: ng ≤ 1, never grows
            # (acc_before, batch, ng_device_scalar, dispatch_cap): the
            # capacity each entry was MERGED at rides the window — after
            # an adaptive presize the overflow check must compare against
            # the entry's own capacity, not the grown one (an acc built
            # at the small cap truncated its overflow groups)
            window = []

            def dispatch(b):
                acc_before = state["acc"]
                if acc_before is None:
                    out, ng = step0_fn(b, cap)
                else:
                    out, ng = step_fn(acc_before, b, cap)
                state["acc"] = out
                state["rows_seen"] += b.capacity
                _record_fragment_dispatch(node, ctx, fused=False)
                if no_overflow:
                    return
                try:
                    ng.copy_to_host_async()
                except Exception:
                    pass
                window.append((acc_before, b, ng, cap))

            def replay(entries, ngi):
                """Re-merge `entries` from the first entry's checkpoint at a
                capacity that fits `ngi` groups (synchronous — rare path).
                Growth past the grace ceiling instead hands the unmerged
                batches to the hash-partitioned path (_GraceOverflow) —
                an ever-bigger table would make every later merge sort
                millions of dead slots."""
                nonlocal cap
                state["acc"] = entries[0][0]
                if entries[0][0] is None and allow_spill:
                    # adaptive flip window: the checkpoint is EMPTY, so
                    # the whole aggregation can restart under the engine
                    # the OBSERVED group count picks — instead of
                    # replaying the loser wider
                    flipped = _adaptive_flip_verdict(
                        node, ctx, engine, ngi, state["rows_seen"])
                    if flipped is not None:
                        raise _EngineFlip([e[1] for e in entries],
                                          ngi, flipped)
                want2 = round_up_capacity(ngi)
                if mode != "grow" and want2 > ceiling:
                    _ceiling_overflow(mode, entries)
                cap = want2
                _bump_replay_wave(node, ctx, hbo_obs, cap_to=cap)
                for i, e in enumerate(entries):
                    b = e[1]
                    for _ in range(ctx.config.max_growth_retries):
                        acc_before = state["acc"]
                        if acc_before is None:
                            out, ng2 = step0_fn(b, cap)
                        else:
                            out, ng2 = step_fn(acc_before, b, cap)
                        n2 = int(ng2)
                        if n2 <= cap:
                            state["acc"] = out
                            hbo_obs["groups"] = max(hbo_obs["groups"], n2)
                            break
                        # power-of-two bucketing already gives ≤2× headroom;
                        # doubling on top would 4× the memory footprint
                        want2 = round_up_capacity(n2)
                        if mode != "grow" and want2 > ceiling:
                            # acc still holds the pre-entry checkpoint:
                            # entries[i:] have not been merged into it
                            _ceiling_overflow(mode, entries[i:])
                        cap = want2
                    else:
                        raise RuntimeError(
                            "aggregate capacity growth exceeded retries")

            def confirm(block):
                nonlocal cap
                while window and (block or len(window) > depth):
                    ngi = int(window[0][2])  # usually already on host
                    dcap = window[0][3]  # capacity the entry merged at
                    if ngi <= dcap:
                        hbo_obs["groups"] = max(hbo_obs["groups"], ngi)
                        window.pop(0)
                        if ctx.adaptive is not None and allow_spill:
                            # forward presize: grow BEFORE the overflow
                            # the near-full table is about to pay (the
                            # next merge migrates the acc, zero replay)
                            want = _adaptive_presize_grow(
                                node, ctx, ngi, cap,
                                ceiling if mode != "grow" else None)
                            if want is not None:
                                cap = want
                        continue
                    entries = list(window)
                    window.clear()
                    replay(entries, ngi)

            for b in stream:
                dispatch(b)
                # while replaying spilled partitions (allow_spill=False) or
                # sweeping lifespans run synchronously: the optimistic
                # window pins ~3× the accumulator footprint, which is
                # exactly what the memory-bounded modes cannot afford
                confirm(block=not allow_spill or ctx.lifespans is not None)
                # account EVERYTHING the optimistic window pins on device:
                # the live accumulator plus each unconfirmed checkpoint and
                # its input batch — otherwise spill/revoke fires ~depth×
                # too late
                out_bytes = batch_device_bytes(state["acc"])
                for acc_before, wb, _, _dc in window:
                    out_bytes += batch_device_bytes(wb)
                    if acc_before is not None:
                        out_bytes += batch_device_bytes(acc_before)
                if allow_spill and can_spill and (
                    state["revoke_requested"]
                    or ctx.should_spill(out_bytes - mctx.bytes)
                ):
                    confirm(block=True)  # spill only a confirmed accumulator
                    was_revoke = state["revoke_requested"]
                    state["revoke_requested"] = False
                    freed = do_spill()
                    if was_revoke:
                        _note_spill_revoke(node, ctx, freed)
                else:
                    mctx.set_bytes(out_bytes)
            confirm(block=True)

        def grace_ingest(stream):
            """Hash-partition chained input batches straight to spill (the
            grace-hash build phase; host-side, so dynamic row counts are
            free). No device merge happens until the per-partition phase."""
            raw = mk_raw_spiller()
            for b in stream:
                raw.spill(jit_chain(b))
            ctx.spill_manager.record(raw.spilled_bytes)

        def absorb_fused(stream):
            """Whole-fragment ingest: consecutive same-structure batches
            arrive STACKED (WindowSource double-buffers them), and one
            fused program folds chain+merge over the whole window on-device
            via lax.scan — O(batches / window) dispatches instead of
            O(batches). The overflow protocol matches absorb(): an
            optimistic window of (checkpoint, item, max-ng) confirms up to
            `depth` items late and replays from the checkpoint on the rare
            capacity overflow, with whole windows as the replay unit.
            Growth past the grace ceiling unstacks the unmerged windows
            back to raw batches for the hash-partitioned spill path."""
            nonlocal cap
            depth = max(1, ctx.config.agg_pipeline_depth)
            no_overflow = not key_syms
            # (acc_before, WindowItem, ng, dispatch_cap) — see absorb():
            # each entry confirms against the capacity it merged at
            window = []

            def apply(acc_before, item, c):
                if isinstance(item, _fragment_jit.Window):
                    if acc_before is None:
                        return jit_frag_step0(item.stacked, c)
                    return jit_frag_step(acc_before, item.stacked, c)
                if acc_before is None:
                    return jit_step0(item, c)
                return jit_step(acc_before, item, c)

            def expand(entries):
                """Unmerged optimistic-window entries → raw-batch triples
                the _GraceOverflow handler understands."""
                out = []
                for e in entries:
                    item = e[1]
                    if isinstance(item, _fragment_jit.Window):
                        out.extend(
                            (None, rb, None) for rb in
                            _fragment_jit.unstack_batch(item.stacked, item.k))
                    else:
                        out.append((None, item, None))
                return out

            def dispatch(item):
                acc_before = state["acc"]
                t0 = time.time()
                out, ng = apply(acc_before, item, cap)
                state["acc"] = out
                fused = isinstance(item, _fragment_jit.Window)
                state["rows_seen"] += (item.k * item.width if fused
                                       else item.capacity)
                _record_fragment_dispatch(node, ctx, fused,
                                          item.k if fused else 1)
                if fused and ctx.tracer.enabled:
                    ctx.tracer.record("fragment_step", "fragment_step", t0,
                                      time.time(), batches=item.k,
                                      width=item.width)
                if no_overflow:
                    return
                try:
                    ng.copy_to_host_async()
                except Exception:
                    pass
                window.append((acc_before, item, ng, cap))

            def replay(entries, ngi):
                nonlocal cap
                state["acc"] = entries[0][0]
                if entries[0][0] is None:
                    # adaptive flip window — see absorb().replay
                    flipped = _adaptive_flip_verdict(
                        node, ctx, engine, ngi, state["rows_seen"])
                    if flipped is not None:
                        raise _EngineFlip(
                            [rb for _, rb, _ in expand(entries)],
                            ngi, flipped)
                want2 = round_up_capacity(ngi)
                if can_spill and want2 > ceiling:
                    raise _GraceOverflow(expand(entries))
                cap = want2
                _bump_replay_wave(node, ctx, hbo_obs, cap_to=cap)
                for i, e in enumerate(entries):
                    item = e[1]
                    for _ in range(ctx.config.max_growth_retries):
                        acc_before = state["acc"]
                        out, ng2 = apply(acc_before, item, cap)
                        n2 = int(ng2)
                        if n2 <= cap:
                            state["acc"] = out
                            hbo_obs["groups"] = max(hbo_obs["groups"], n2)
                            break
                        want2 = round_up_capacity(n2)
                        if can_spill and want2 > ceiling:
                            # acc holds the pre-entry checkpoint:
                            # entries[i:] have not been merged into it
                            raise _GraceOverflow(expand(entries[i:]))
                        cap = want2
                    else:
                        raise RuntimeError(
                            "aggregate capacity growth exceeded retries")

            def confirm(block):
                nonlocal cap
                while window and (block or len(window) > depth):
                    ngi = int(window[0][2])
                    dcap = window[0][3]
                    if ngi <= dcap:
                        hbo_obs["groups"] = max(hbo_obs["groups"], ngi)
                        window.pop(0)
                        if ctx.adaptive is not None:
                            want = _adaptive_presize_grow(
                                node, ctx, ngi, cap,
                                ceiling if can_spill else None)
                            if want is not None:
                                cap = want
                        continue
                    entries = list(window)
                    window.clear()
                    replay(entries, ngi)

            def pinned_bytes(item):
                if isinstance(item, _fragment_jit.Window):
                    return _fragment_jit.window_device_bytes(item)
                return batch_device_bytes(item)

            src = _fragment_jit.WindowSource(
                stream, _hbo_fragment_window(node, ctx),
                bucket=ctx.config.shape_bucketing != "off",
                on_window=_inflight_window_hook(node, ctx))
            try:
                for item in src:
                    dispatch(item)
                    confirm(block=False)
                    out_bytes = batch_device_bytes(state["acc"])
                    for acc_before, wi, _, _dc in window:
                        out_bytes += pinned_bytes(wi)
                        if acc_before is not None:
                            out_bytes += batch_device_bytes(acc_before)
                    if can_spill and (
                        state["revoke_requested"]
                        or ctx.should_spill(out_bytes - mctx.bytes)
                    ):
                        confirm(block=True)
                        was_revoke = state["revoke_requested"]
                        state["revoke_requested"] = False
                        freed = do_spill()
                        if was_revoke:
                            _note_spill_revoke(node, ctx, freed)
                    else:
                        mctx.set_bytes(out_bytes)
                confirm(block=True)
            except _GraceOverflow as ov:
                # recover everything the producer pulled but never delivered
                # so the grace handler spills the COMPLETE remaining input
                rest = src.drain()
                raise _GraceOverflow(list(ov.entries)
                                     + [(None, rb, None) for rb in rest])
            except _EngineFlip as fl:
                # same recovery for a flip: the restart must re-absorb the
                # COMPLETE remaining input under the new engine
                rest = src.drain()
                raise _EngineFlip(fl.batches + list(rest), fl.groups,
                                  fl.engine)
            finally:
                src.close()

        if grace_from_start:
            grace_ingest(in_stream)
        else:
            try:
                try:
                    if frag_why is None:
                        absorb_fused(in_stream)
                    else:
                        absorb(in_stream, jit_step, jit_step0)
                except _EngineFlip as fl:
                    # the wave's OBSERVED group count re-ran the engine
                    # choice and the other engine won: re-absorb the
                    # unmerged input through the flipped engine's programs
                    # (fresh @h-forked cache keys) at a capacity sized to
                    # the observed count — instead of replaying the loser
                    # wider and paying the same overflow again next wave
                    import itertools as _it

                    _bind_engine(fl.engine)
                    want = round_up_capacity(int(fl.groups))
                    cap = min(want, ceiling) if can_spill else want
                    # rebind in_stream so a later _GraceOverflow's
                    # grace_ingest still sees the un-pulled remainder
                    in_stream = _it.chain(iter(fl.batches), in_stream)
                    if frag_why is None:
                        absorb_fused(in_stream)
                    else:
                        absorb(in_stream, jit_step, jit_step0)
            except _GraceOverflow as ov:
                # the table outgrew the ceiling mid-stream: spill the
                # confirmed accumulator as state pages, the unmerged window
                # + the rest of the input as raw partitions
                do_spill()
                raw = mk_raw_spiller()
                # entries are raw-batch triples from expand() or 4-tuple
                # window entries (batch at [1] either way)
                for e in ov.entries:
                    raw.spill(jit_chain(e[1]))
                grace_ingest(in_stream)

        if state["spiller"] is None and state["raw_spiller"] is None:
            if ctx.lifespans is None:
                # spilled/sweeping runs hold only per-bucket group counts,
                # which would poison the history as a whole-table total
                hbo_obs["final_cap"] = cap
                _hbo_record_agg(node, ctx, hbo_obs)
            acc = state["acc"]
            if node.step == "partial":
                # emit raw state columns for the exchange; no finalization
                if acc is not None:
                    yield acc
                return
            yield _finalize_aggregate(node, acc, layout, key_syms, key_types,
                                      state_types, in_types)
            return

        # spilled: finalize bucket-by-bucket (grouped-execution style).
        # Spilling to NEW files stays off during the per-partition merge,
        # but a partition whose replay outgrows the grace ceiling no longer
        # fails the query: it re-partitions by the NEXT hash bits
        # ((hash // divisor) % fanout — fresh entropy, so skewed-but-
        # distinct keys do split) and recurses, bounded by spill_max_depth.
        # Only keys that share every hash bit (one-hot identical groups
        # never overflow a 1-group table, so in practice adversarial
        # collisions) reach the bound and fail with SPILL_LIMIT_EXCEEDED.
        do_spill()
        ctx.memory_pool.remove_revoker(revoke)
        spiller = state["spiller"]
        raw_spiller = state["raw_spiller"]
        jit_accstep0 = _node_jit(
            node, "accstep0", lambda: (lambda b, cap: acc_merge_step(None, b, cap)),
            static_argnums=(1,),
        )
        max_sdepth = max(0, ctx.config.spill_max_depth)

        def finalize_leaf(rsp, asp, p, sdepth):
            nonlocal cap
            state["acc"] = None
            # each bucket holds ~1/P of the groups — shrink the table back
            # (it regrows geometrically if a bucket is skewed)
            cap = ctx.config.agg_capacity
            try:
                mode = "grace" if sdepth < max_sdepth else "fail"
                if rsp is not None:
                    absorb(rsp.read_partition(p), jit_step_raw,
                           jit_step0_raw, allow_spill=False, on_ceiling=mode)
                if asp is not None:
                    absorb(asp.read_partition(p), jit_accstep, jit_accstep0,
                           allow_spill=False, on_ceiling=mode)
            except _GraceOverflow:
                # replay outgrew the ceiling: the partition's files are
                # still intact on disk, so drop the partial merge, split
                # by the next hash bits, and finalize the children (raw
                # and state-page trees split in lockstep → co-partitioned)
                state["acc"] = None
                mctx.set_bytes(0)
                sub_r = rsp.grow_partition(p) if rsp is not None else None
                sub_a = (asp.grow_partition(
                    p, fanout=(sub_r.n_partitions if sub_r is not None
                               else None))
                    if asp is not None else None)
                fanout = (sub_r or sub_a).n_partitions
                for q in range(fanout):
                    yield from finalize_leaf(sub_r, sub_a, q, sdepth + 1)
                return
            acc = state["acc"]
            if acc is None:
                return
            _spill_stats_for(node, ctx)["partitions"] += 1
            if node.step == "partial":
                yield acc
            else:
                yield _finalize_aggregate(node, acc, layout, key_syms,
                                          key_types, state_types, in_types)
            mctx.set_bytes(0)

        for p in range((raw_spiller or spiller).n_partitions):
            yield from finalize_leaf(raw_spiller, spiller, p, 0)
        spilled_total = ((raw_spiller.spilled_bytes if raw_spiller else 0)
                         + (spiller.spilled_bytes if spiller else 0))
        _record_spill_done(node, ctx, "spill_agg", grace_P, spilled_total,
                           side="group")
        if spiller is not None:
            spiller.close()
        if raw_spiller is not None:
            raw_spiller.close()
    finally:
        if can_spill:
            ctx.memory_pool.remove_revoker(revoke)
        mctx.set_bytes(0)
        if state["spiller"] is not None:
            state["spiller"].close()
        if state["raw_spiller"] is not None:
            state["raw_spiller"].close()


def _concat_validity(a, b, cap_a, cap_b):
    if a is None and b is None:
        return None
    av = a if a is not None else jnp.ones(cap_a, dtype=bool)
    bv = b if b is not None else jnp.ones(cap_b, dtype=bool)
    return jnp.concatenate([av, bv])


def _finalize_aggregate(node, acc, layout, key_syms, key_types, state_types, in_types):
    out_syms = [s for s, _ in node.output]
    out_types = [t for _, t in node.output]
    if acc is None:
        # empty input: global aggregation still yields one row
        if not key_syms:
            data = {}
            cols = []
            live = np.zeros(128, bool)
            live[0] = True
            for a in node.aggs:
                from presto_tpu.types import ArrayType as _AT, MapType as _MT

                if isinstance(a.type, (_AT, _MT)):
                    cols.append(Column(
                        jnp.zeros((128, 1), a.type.dtype),
                        jnp.zeros(128, bool),
                        sizes=jnp.zeros(128, jnp.int32),
                    ))
                    continue
                vals = np.zeros(128, dtype=a.type.dtype)
                if a.fn in ("count", "count_star", "count_if"):
                    cols.append(Column(jnp.asarray(vals), None))
                else:
                    cols.append(Column(jnp.asarray(vals), jnp.zeros(128, bool)))
            return Batch(
                [a.symbol for a in node.aggs],
                [a.type for a in node.aggs],
                cols,
                jnp.asarray(live),
                {},
            )
        return Batch(
            out_syms,
            out_types,
            [Column(jnp.zeros(128, t.dtype), None) for t in out_types],
            jnp.zeros(128, dtype=bool),
            {},
        )

    return _node_jit(
        node, "finalize",
        lambda: build_agg_finalizer(node, key_syms, key_types, in_types),
    )(acc)


def build_agg_finalizer(node, key_syms, key_types, in_types):
    """Traceable accumulator→final-values function (avg division, variance
    assembly, int128 limb recombination). Shared by the streaming executor
    and the mesh executor (parallel/mesh_exec.py), which traces it inside
    one shard_map program."""

    def finalize(acc: Batch):
        names, types, cols = [], [], []
        for k, t in zip(key_syms, key_types):
            c = acc.column(k)
            names.append(k)
            types.append(t)
            cols.append(c)
        for a in node.aggs:
            if a.fn == "avg":
                c = acc.column(a.symbol + "$cnt")
                cnt = c.values
                ok = cnt > 0
                denom = jnp.where(ok, cnt, 1).astype(jnp.float64)
                if (a.symbol + "$sum_hi") in acc.names:
                    # int128 decimal sum limbs; scale rides the lo state type
                    hi = acc.column(a.symbol + "$sum_hi").values
                    lo = acc.column(a.symbol + "$sum_lo").values
                    lo_t = acc.type_of(a.symbol + "$sum_lo")
                    num = (hi.astype(jnp.float64) * float(1 << 32)
                           + lo.astype(jnp.float64)) / (10.0 ** lo_t.scale)
                else:
                    s = acc.column(a.symbol + "$sum")
                    if node.step == "final":
                        src_t = in_types[a.symbol + "$sum"]
                    else:
                        src_t = sum_state_type(a, in_types)
                    if isinstance(src_t, DecimalType):
                        num = s.values.astype(jnp.float64) / (10.0 ** src_t.scale)
                    else:
                        num = s.values.astype(jnp.float64)
                vals = num / denom
                cols.append(Column(vals, ok))
            elif a.fn == "sum" and (a.symbol + "$hi") in acc.names:
                # exact int128 decimal total as a two-limb long-decimal column
                hi = acc.column(a.symbol + "$hi")
                lo = acc.column(a.symbol + "$lo")
                cols.append(Column(lo.values, lo.validity, hi.values))
            elif a.fn in _VARIANCE_FNS:
                n = acc.column(a.symbol + "$cnt").values.astype(jnp.float64)
                s = acc.column(a.symbol + "$sum").values
                ss = acc.column(a.symbol + "$sumsq").values
                pop = a.fn.endswith("_pop")
                ok = n > (0 if pop else 1)
                nn = jnp.where(n > 0, n, 1.0)
                denom = jnp.where(ok, n if pop else n - 1, 1.0)
                var = jnp.maximum((ss - s * s / nn) / denom, 0.0)
                vals = jnp.sqrt(var) if a.fn.startswith("stddev") else var
                cols.append(Column(vals, ok))
            elif a.fn in ("covar_pop", "covar_samp"):
                n = acc.column(a.symbol + "$cnt").values.astype(jnp.float64)
                sx = acc.column(a.symbol + "$sx").values
                sy = acc.column(a.symbol + "$sy").values
                sxy = acc.column(a.symbol + "$sxy").values
                pop = a.fn.endswith("_pop")
                ok = n > (0 if pop else 1)
                nn = jnp.where(n > 0, n, 1.0)
                denom = jnp.where(ok, n if pop else n - 1, 1.0)
                cols.append(Column((sxy - sx * sy / nn) / denom, ok))
            elif a.fn == "corr":
                n = acc.column(a.symbol + "$cnt").values.astype(jnp.float64)
                sx = acc.column(a.symbol + "$sx").values
                sy = acc.column(a.symbol + "$sy").values
                sxy = acc.column(a.symbol + "$sxy").values
                sxx = acc.column(a.symbol + "$sxx").values
                syy = acc.column(a.symbol + "$syy").values
                vx = n * sxx - sx * sx
                vy = n * syy - sy * sy
                ok = (n > 1) & (vx > 0) & (vy > 0)
                denom = jnp.sqrt(jnp.where(ok, vx * vy, 1.0))
                cols.append(Column((n * sxy - sx * sy) / denom, ok))
            elif a.fn == "geometric_mean":
                n = acc.column(a.symbol + "$cnt").values.astype(jnp.float64)
                ls = acc.column(a.symbol + "$lsum").values
                ok = n > 0
                cols.append(Column(jnp.exp(ls / jnp.where(ok, n, 1.0)), ok))
            elif a.fn in ("bool_and", "bool_or"):
                c = acc.column(a.symbol)
                cols.append(Column(c.values.astype(bool), c.validity))
            elif a.fn == "checksum":
                c = acc.column(a.symbol)
                cols.append(Column(c.values, None))
            elif _registered_aggregate_fn(a.fn) is not None:
                udf = _registered_aggregate_fn(a.fn)
                states = {s: acc.column(a.symbol + s).values
                          for s, _, _ in udf.states}
                vals = udf.finalize(states)
                cnt = next((s for s, op, _ in udf.states
                            if op == "count_add"), None)
                if cnt is not None:
                    ok = acc.column(a.symbol + cnt).values > 0
                else:
                    first = udf.states[0][0]
                    ok = acc.column(a.symbol + first).validity
                cols.append(Column(vals.astype(a.type.dtype), ok))
            else:
                # count/sum/min/max/arbitrary/count_if + materialized
                # (approx_percentile/max_by/min_by) pass through
                c = acc.column(a.symbol)
                cols.append(c)
            names.append(a.symbol)
            types.append(a.type)
        live = acc.live
        if not key_syms:
            # SQL: global aggregation yields exactly one row even when every
            # input row was filtered out (count=0, sums NULL)
            live = live.at[0].set(True)
        return Batch(names, types, cols, live, acc.dicts)

    return finalize


# -- joins ------------------------------------------------------------------


def _cat_batches(bs: List[Batch]) -> Batch:
    names = bs[0].names
    types = bs[0].types
    caps = [b.capacity for b in bs]
    cols = [
        concat_columns([b.columns[i] for b in bs], caps)
        for i in range(len(names))
    ]
    live = jnp.concatenate([b.live for b in bs])
    dicts = {}
    for b in bs:
        dicts.update(b.dicts)
    return Batch(names, types, cols, live, dicts)


# module-level jit wrappers: trace caches persist across queries
_JIT_CAT = jax.jit(_cat_batches)
_JIT_COMPACT = jax.jit(compact)
_JIT_LIMIT = jax.jit(limit_batch)


def _unify_batch_dicts(batches: List[Batch]) -> List[Batch]:
    """Before concatenating, re-encode any string column whose batches
    carry DIFFERENT Dictionary objects against their merged dictionary
    (code equality must mean string equality across the result — the
    DictionaryBlock id-canonicalization of the reference). Batches from
    one table share dictionary objects, so this is a no-op on hot paths."""
    from presto_tpu.dictionary import Dictionary

    todo = {}
    for name in batches[0].names:
        ds = [b.dicts.get(name) for b in batches]
        present = [d for d in ds if d is not None]
        if not present or all(d is present[0] for d in present):
            continue
        m = present[0]
        for d in present[1:]:
            if d is not m:
                m = Dictionary.merge(m, d)
        todo[name] = m
    if not todo:
        return batches
    out = []
    for b in batches:
        cols = list(b.columns)
        dicts = dict(b.dicts)
        for name, m in todo.items():
            d = b.dicts.get(name)
            dicts[name] = m
            if d is None or d is m:
                continue
            i = b.names.index(name)
            remap = jnp.asarray(d.map_to(m))
            c = cols[i]
            cols[i] = Column(remap[c.values.astype(jnp.int32) + 1], c.validity)
        out.append(Batch(b.names, b.types, cols, b.live, dicts))
    return out


def _collect_concat(stream: Iterator[Batch]) -> Optional[Batch]:
    batches = list(stream)
    if not batches:
        return None
    if len(batches) == 1:
        return batches[0]
    return _JIT_CAT(_unify_batch_dicts(batches))


# ---------------------------------------------------------------------------
# radix-partitioned breakers (ops/radix.py drivers)


def _radix_tag(b: Batch, num_partitions: int, key_names) -> Optional[int]:
    """Radix id if `b` arrived partition-aligned from an OUT_HASH sink with
    a compatible decomposition (same partition count, same key symbols),
    else None — the consumer then re-partitions on device as usual."""
    tag = getattr(b, "radix", None)
    if tag is None:
        return None
    r, total, keys = tag
    if int(total) == num_partitions and tuple(keys) == tuple(key_names):
        return int(r)
    return None


def _untag_batch(b: Batch) -> Batch:
    """Plain Batch from a (possibly) tagged one. Tagged batches are a
    serde-level subclass that is NOT pytree-registered — they must never
    reach a jitted function."""
    if type(b) is Batch:
        return b
    return Batch(b.names, b.types, b.columns, b.live, b.dicts)


def _radix_splitter(node: PlanNode, ctx: ExecContext, key_names, P: int,
                    jkey: str):
    """Per-node split driver: batch → iterator of (partition, sub-batch,
    live rows). One jitted stable sort by radix id per input capacity, a
    P-element count transfer to the host, then one jitted window gather
    per occupied partition — shapes keyed only by (capacity, pow2 bucket).
    """
    from presto_tpu.ops.radix import radix_perm, radix_window_perm

    keys = tuple(key_names)
    jsort = _node_jit(node, jkey + "radix_perm",
                      lambda: (lambda b: radix_perm(b, keys, P)))
    jwin = _node_jit(node, jkey + "radix_window", lambda: radix_window_perm,
                     static_argnames=("bucket",))
    tr = ctx.tracer

    def split(b: Batch):
        t0 = time.time()
        sperm, counts = jsort(b)
        cnts = np.asarray(counts)  # the host-side slicing boundary
        starts = np.concatenate([[0], np.cumsum(cnts)])
        if tr.enabled:
            tr.record("radix_split", "radix_split", t0, time.time(),
                      partitions=int((cnts > 0).sum()), rows=int(cnts.sum()))
        for p in range(P):
            n = int(cnts[p])
            if n == 0:
                continue
            bucket = round_up_capacity(n)
            yield p, jwin(b, sperm, np.int32(starts[p]), np.int32(n),
                          bucket=bucket), n

    return split


def _host_concat(batches: List[Batch]) -> Optional[Batch]:
    """Live rows of many fixed-capacity batches packed into ONE batch of
    pow2 capacity, assembled on the host. The radix join uses this to turn
    a partition's sub-batch list into its build input: a device-side
    concat would compile one program per (cap_1..cap_k) combination —
    exactly the shape storm radix exists to avoid — while the host pays
    one round trip on the (smaller) build side."""
    batches = [b for b in batches if b is not None]
    if not batches:
        return None
    batches = _unify_batch_dicts(batches)
    first = batches[0]
    sel = [np.flatnonzero(np.asarray(b.live)) for b in batches]
    total = int(sum(len(s) for s in sel))
    cap = round_up_capacity(total)

    def stack(planes, fill, width=None):
        """Concatenate the live rows of one plane across batches; `fill`
        synthesizes it for batches where it is None (same defaults as
        concat_columns); 2D planes align on `width`."""
        if all(p is None for p in planes):
            return None
        parts = []
        for p, s in zip(planes, sel):
            a = fill(len(s)) if p is None else np.asarray(p)[s]
            if width is not None and a.ndim == 2 and a.shape[1] < width:
                a = np.concatenate(
                    [a, np.zeros((a.shape[0], width - a.shape[1]), a.dtype)],
                    axis=1)
            parts.append(a)
        out = np.concatenate(parts, axis=0)
        pad = np.zeros((cap - total,) + out.shape[1:], out.dtype)
        return jnp.asarray(np.concatenate([out, pad], axis=0))

    cols = []
    for i in range(len(first.names)):
        cs = [b.columns[i] for b in batches]
        twod = any(c.values.ndim == 2 for c in cs)
        w = max(c.values.shape[1] for c in cs) if twod else None
        vals = stack([c.values for c in cs], None, w)
        valid = stack([c.validity for c in cs],
                      lambda n: np.ones(n, bool))
        hi = stack([c.hi for c in cs], lambda n: np.zeros(n, np.int64))
        sizes = stack([c.sizes for c in cs], lambda n: np.zeros(n, np.int32))
        evalid = stack([c.evalid for c in cs],
                       lambda n: np.ones((n, w), bool), w)
        kd = next((np.asarray(c.keys).dtype for c in cs
                   if c.keys is not None), None)
        keys = stack([c.keys for c in cs],
                     lambda n: np.zeros((n, w), kd), w)
        cols.append(Column(vals, valid, hi, sizes, evalid, keys))
    live = np.zeros(cap, bool)
    live[:total] = True
    dicts = {}
    for b in batches:
        dicts.update(b.dicts)
    return Batch(first.names, first.types, cols, jnp.asarray(live), dicts)


def _radix_join(node: HashJoin, ctx: ExecContext,
                probe_stream: Iterator[Batch],
                build_stream: Iterator[Batch], chain) -> Iterator[Batch]:
    """Radix-partitioned hash join: both sides split by the top bits of
    the content hash (ops/radix.py), each partition built + probed at a
    small bounded capacity by its own _JoinProber. Partitions whose build
    side exceeds `join_spill_budget_bytes` hybrid-spill: their batches go
    to host spill files (serde page format) and are joined one-at-a-time
    after the in-memory partitions, so an oversized build degrades to disk
    instead of recompiling at ever-larger capacities."""
    from presto_tpu.memory import batch_device_bytes
    from presto_tpu.obs import metrics as _obs_metrics
    from presto_tpu.scan import metrics as _scan_metrics
    from presto_tpu.spiller import SpillFile

    cfg = ctx.config
    P = _hbo_radix_partitions(node, ctx, "join_build",
                              cfg.radix_partitions)
    budget = cfg.join_spill_budget_bytes
    tr = ctx.tracer
    split_b = _radix_splitter(node, ctx, node.right_keys, P, "radixb_")
    split_p = _radix_splitter(node, ctx, node.left_keys, P, "radixp_")

    def _stat(key, delta):
        ctx.stats[key] = ctx.stats.get(key, 0) + delta

    parts: List[List[Batch]] = [[] for _ in range(P)]
    pbytes = [0] * P
    prows = [0] * P
    bfiles: Dict[int, "SpillFile"] = {}
    pfiles: Dict[int, "SpillFile"] = {}

    def spill_build_partition(p):
        """Move partition p's resident build batches to a host spill file;
        later build rows for p append straight to it."""
        f = ctx.spill_manager.spill_file(f"radix-join-build-p{p}")
        ctx.track_spill(f)
        for bb in parts[p]:
            f.append(bb)
        parts[p] = []
        pbytes[p] = 0
        bfiles[p] = f
        _stat("radix.partitions_spilled", 1)
        _scan_metrics.record("radix_partitions_spilled", 1)

    rev = {"flag": False}

    def _revoke(_need):
        # pool-pressure REQUEST honored at the next batch boundary
        rev["flag"] = True
        return 0

    if ctx.config.spill_enabled:
        ctx.memory_pool.add_revoker(_revoke)
    try:
        for b in build_stream:
            rid = _radix_tag(b, P, node.right_keys)
            if rid is not None:
                ub = _untag_batch(b)
                # num_live stays a device scalar — summed lazily so the
                # aligned fast path adds no per-page sync
                subs = [(rid, ub, ub.num_live())]
                _stat("radix.aligned_batches", 1)
                _scan_metrics.record("radix_aligned_batches", 1)
            else:
                subs = split_b(_untag_batch(b))
            for p, sub, n in subs:
                prows[p] = prows[p] + n
                if p in bfiles:
                    bfiles[p].append(sub)
                    continue
                parts[p].append(sub)
                pbytes[p] += batch_device_bytes(sub)
                if budget is not None and pbytes[p] > budget:
                    spill_build_partition(p)
            if rev["flag"]:
                # revoke ladder asked for memory back: spill the LARGEST
                # resident build partition down to host
                rev["flag"] = False
                resident = [(pp, pbytes[pp]) for pp in range(P)
                            if parts[pp] and pp not in bfiles]
                if resident:
                    pp, nbytes = max(resident, key=lambda t: t[1])
                    spill_build_partition(pp)
                    _note_spill_revoke(node, ctx, nbytes)
        prows = [int(r) for r in prows]
        for p in range(P):
            if prows[p]:
                _obs_metrics.RADIX_PARTITION_ROWS.observe(
                    prows[p], plane="worker", side="build")

        ident = lambda bb: bb  # noqa: E731 — chain applied before the split
        probers: Dict[int, _JoinProber] = {}
        for p in range(P):
            if p in bfiles:
                continue
            build_in = _host_concat(parts[p])
            parts[p] = []
            probers[p] = _JoinProber(node, ctx, build_in, ident,
                                     jkey="radix_", fanout_scan=16)

        jchain = _node_jit(node, "radix_pchain", lambda: chain)
        for raw in probe_stream:
            rid = _radix_tag(raw, P, node.left_keys)
            if rid is not None:
                _stat("radix.aligned_batches", 1)
                _scan_metrics.record("radix_aligned_batches", 1)
                subs = [(rid, jchain(_untag_batch(raw)), 0)]
            else:
                subs = split_p(jchain(_untag_batch(raw)))
            # dispatch wave: start every partition of this batch before
            # syncing any, so the P per-partition count round trips to the
            # host overlap instead of serializing
            pend = []
            for p, sub, _n in subs:
                if p in bfiles:
                    f = pfiles.get(p)
                    if f is None:
                        f = pfiles[p] = ctx.spill_manager.spill_file(
                            f"radix-join-probe-p{p}")
                        ctx.track_spill(f)
                    f.append(sub)
                else:
                    pend.append((p, probers[p].probe_start(sub)))
            for p, st in pend:
                yield from probers[p].probe_finish(st)
        for p in sorted(probers):
            yield from probers[p].tail()

        # hybrid-spilled partitions, one resident at a time
        for p in sorted(bfiles):
            t0 = time.time()
            build_in = _host_concat(list(bfiles[p].read()))
            prober = _JoinProber(node, ctx, build_in, ident,
                                 jkey="radix_", fanout_scan=16)
            pf = pfiles.get(p)
            if pf is not None:
                for sub in pf.read():
                    yield from prober.probe_batch(sub)
            yield from prober.tail()
            if tr.enabled:
                tr.record("radix_spill_replay", "radix_spill_replay", t0,
                          time.time(), partition=p, rows=prows[p])
    finally:
        if ctx.config.spill_enabled:
            ctx.memory_pool.remove_revoker(_revoke)
        spilled = (sum(f.bytes for f in bfiles.values())
                   + sum(f.bytes for f in pfiles.values()))
        if spilled:
            _stat("radix.spill_bytes", spilled)
            _scan_metrics.record("radix_spill_bytes", spilled)
            ctx.spill_manager.record(spilled)
            _obs_metrics.SPILLED_BYTES.observe(
                float(spilled), plane="worker", side="build")
        for f in bfiles.values():
            f.close()
        for f in pfiles.values():
            f.close()


def _execute_join(node: HashJoin, ctx: ExecContext) -> Iterator[Batch]:
    from presto_tpu.memory import LocalMemoryContext, batch_device_bytes

    if node.colocated and ctx.lifespan is None:
        # grouped (lifespan) execution over a colocated bucketed join
        # (FixedSourcePartitionedScheduler driving lifespans): this task
        # sweeps its buckets sequentially — each pass builds from ONE
        # bucket of the build table and probes the SAME bucket of the
        # probe table, so peak memory is one bucket's build side, and no
        # exchange ever moves a row. Nested colocated joins execute
        # within the sweep (ctx.lifespan already set).
        try:
            for b in range(ctx.task_index, node.colocated, ctx.n_tasks):
                ctx.lifespan = b
                yield from _execute_join(node, ctx)
        finally:
            ctx.lifespan = None
        return

    probe_stream, chain = _fused_child(node.left, ctx)
    build_stream = execute_node(node.right, ctx)

    if ctx.config.radix_partitions > 1:
        yield from _radix_join(node, ctx, probe_stream, build_stream, chain)
        return

    yield from _join_with_spill(node, ctx, probe_stream, build_stream, chain)


def _join_with_spill(node: HashJoin, ctx: ExecContext,
                     probe_stream: Iterator[Batch],
                     build_stream: Iterator[Batch], chain,
                     jkey: str = "") -> Iterator[Batch]:
    """One binary hash join over already-opened child streams. Collect the
    build side with memory accounting; crossing the revoke threshold (or a
    pool-pressure revoke request) switches to the partitioned-spill path
    (HashBuilderOperator's SPILLING_INPUT state +
    GenericPartitioningSpiller): both sides are hash-partitioned to disk
    on the join keys and each bucket is joined independently — with the
    dynamic hybrid-hash escape hatches (mid-build growth, recursive
    repartitioning, per-partition role reversal) when the partition-count
    estimate proves wrong. Also the per-leg engine of the multiway
    executor's binary-cascade fallback (jkey='mwb{i}_'), where the child
    streams are cascade intermediates rather than plan children."""
    from presto_tpu.memory import LocalMemoryContext, batch_device_bytes

    mctx = LocalMemoryContext(ctx.memory_pool, "join-build")
    build_batches: List[Batch] = []
    bspiller = None
    pspiller = None
    est_p = ctx.config.spill_partitions
    can_spill = ctx.config.spill_enabled
    rev = {"flag": False}

    def _revoke(_need: int) -> int:
        # flag only — the spill happens at the next build-batch boundary
        # (spilling synchronously inside pool.reserve would re-enter the
        # ledger mid-update)
        rev["flag"] = True
        return 0

    if can_spill:
        ctx.memory_pool.add_revoker(_revoke)
    try:
        for b in build_stream:
            nb = batch_device_bytes(b)
            if can_spill and (rev["flag"] or ctx.should_spill(nb)):
                est_p = _hbo_spill_partitions(node, ctx, "spill_join",
                                              ctx.config.spill_partitions)
                bspiller = ctx.spill_manager.partitioning_spiller(
                    node.right_keys, est_p, "join-build",
                    partition_budget_bytes=_spill_replay_budget(ctx),
                    max_depth=max(0, ctx.config.spill_max_depth),
                    on_grow=lambda child, pp: _note_spill_repartition(
                        node, ctx, child, pp),
                    on_spill=_inflight_spill_hook(node, ctx))
                ctx.track_spill(bspiller)
                for bb in build_batches:
                    bspiller.spill(bb)
                if rev["flag"]:
                    _note_spill_revoke(node, ctx, mctx.bytes)
                    rev["flag"] = False
                build_batches = []
                mctx.set_bytes(0)
                bspiller.spill(b)
                for bb in build_stream:
                    bspiller.spill(bb)
                break
            build_batches.append(b)
            mctx.set_bytes(mctx.bytes + nb)

        if bspiller is None:
            build_in = _collect_concat(iter(build_batches))
            yield from _join_probe(node, ctx, build_in, probe_stream, chain,
                                   jkey=jkey)
            return

        # spill the (chained) probe side partitioned by the probe keys —
        # co-partitioned with the build because both sides hash the key
        # CONTENT (string keys by dictionary-independent value hash) with
        # the same divisor/fanout schedule
        pspiller = ctx.spill_manager.partitioning_spiller(
            node.left_keys, bspiller.n_partitions, "join-probe")
        ctx.track_spill(pspiller)
        jchain = _node_jit(node, jkey + "spill_chain", lambda: chain)
        for pb in probe_stream:
            pspiller.spill(jchain(pb))
        # mid-build growth may have split build partitions: mirror the
        # split tree onto the probe side so replay pairs leaf-for-leaf
        pspiller.align_to(bspiller)
        yield from _replay_spilled_join(node, ctx, bspiller, pspiller, mctx)
    finally:
        if can_spill:
            ctx.memory_pool.remove_revoker(_revoke)
        if bspiller is not None:
            spilled = bspiller.spilled_bytes + (
                pspiller.spilled_bytes if pspiller is not None else 0)
            ctx.spill_manager.record(spilled)
            _record_spill_done(node, ctx, "spill_join", est_p, spilled,
                               side="build")
            bspiller.close()
        if pspiller is not None:
            pspiller.close()
        mctx.set_bytes(0)


def _reversed_join_shim(node: HashJoin) -> HashJoin:
    """The same inner join with build/probe roles swapped. Sound only for
    kind == 'inner' with no residual (match semantics are symmetric there;
    outer joins and residual filters are side-dependent). Cached on the
    node so _node_jit reuses one shim's program entries across partitions;
    build_unique is dropped — uniqueness of the original build side says
    nothing about the reversed one."""
    shim = node.__dict__.get("_reversed_shim")
    if shim is None:
        shim = HashJoin(kind="inner", left=node.right, right=node.left,
                        left_keys=list(node.right_keys),
                        right_keys=list(node.left_keys),
                        residual=None, build_unique=False)
        node.__dict__["_reversed_shim"] = shim
    return shim


def _reorder_output(b: Batch, names: List[str]) -> Batch:
    """Columns of b in `names` order — a reversed-role join emits
    right-then-left columns while the consumer contracted for the node's
    left-then-right."""
    return Batch(list(names), [b.type_of(n) for n in names],
                 [b.column(n) for n in names], b.live, b.dicts)


def _replay_spilled_join(node: HashJoin, ctx: ExecContext,
                         bspiller, pspiller, mctx) -> Iterator[Batch]:
    """Replay a co-partitioned spilled join leaf-by-leaf with the dynamic
    hybrid-hash degradation ladder: a leaf whose nominal build side misses
    the replay budget first tries ROLE REVERSAL (build from the smaller
    probe side — inner joins without residuals only), then RECURSIVE
    REPARTITIONING by the next hash bits (both sides split in lockstep so
    leaves stay co-partitioned), and only at the depth bound fails with a
    structured SPILL_LIMIT_EXCEEDED."""
    from presto_tpu.memory import batch_device_bytes
    from presto_tpu.scan import metrics as _scan_metrics
    from presto_tpu.spiller import SpillLimitExceeded

    budget = _spill_replay_budget(ctx)
    max_depth = max(0, ctx.config.spill_max_depth)
    st = _spill_stats_for(node, ctx)
    out_names = [s for s, _ in node.output]
    ident = lambda b: b  # noqa: E731 — chain already applied pre-spill

    def replay_leaf(bsp, psp, p: int) -> Iterator[Batch]:
        bc, pc = bsp.children.get(p), psp.children.get(p)
        if bc is not None or pc is not None:
            # one side split here (mid-build growth or an earlier replay
            # pass): mirror so both sides expose the identical leaf set
            if bc is None:
                bc = bsp.grow_partition(p, fanout=pc.n_partitions)
            if pc is None:
                pc = psp.grow_partition(p, fanout=bc.n_partitions)
            bc.align_to(pc)
            pc.align_to(bc)
            for q in range(bc.n_partitions):
                yield from replay_leaf(bc, pc, q)
            return

        bb = bsp.partition_est_bytes(p)
        pb = psp.partition_est_bytes(p)
        reversed_ = (budget is not None and bb > budget and pb < bb
                     and node.kind == "inner" and node.residual is None)
        build_bytes = pb if reversed_ else bb
        if budget is not None and build_bytes > budget:
            # even the smaller side misses the budget: split this leaf by
            # the NEXT hash bits and recurse — bounded by the depth cap
            if bsp.depth >= max_depth:
                raise SpillLimitExceeded(
                    f"join spill partition is {build_bytes} bytes against a "
                    f"{budget}-byte replay budget at max recursion depth "
                    f"{max_depth} (join keys too skewed to split further)")
            sub_b = bsp.grow_partition(p)
            sub_p = psp.grow_partition(p, fanout=sub_b.n_partitions)
            for q in range(sub_b.n_partitions):
                yield from replay_leaf(sub_b, sub_p, q)
            return

        if reversed_:
            st["reversed"] += 1
            ctx.stats["spill.role_reversals"] = (
                ctx.stats.get("spill.role_reversals", 0) + 1)
            _scan_metrics.record("spill_role_reversals", 1)
            if ctx.tracer.enabled:
                t = time.time()
                ctx.tracer.record(
                    "spill_role_reversal", "spill_role_reversal", t, t,
                    node=type(node).__name__, partition=int(p),
                    build_bytes=int(pb), probe_bytes=int(bb))
            build_sp, probe_sp = psp, bsp
            jnode, jkey = _reversed_join_shim(node), "spill_rev_"
        else:
            build_sp, probe_sp = bsp, psp
            jnode, jkey = node, "spill_"

        st["partitions"] += 1
        st["depth"] = max(st["depth"], bsp.depth)
        build_in = _collect_concat(build_sp.read_partition(p))
        if build_in is None and node.kind == "inner":
            return
        # account the materialized bucket — a skewed partition that
        # exceeds the pool limit must fail cleanly, not OOM silently
        if build_in is not None:
            mctx.set_bytes(batch_device_bytes(build_in))
        out = _join_probe(jnode, ctx, build_in,
                          probe_sp.read_partition(p), ident, jkey=jkey)
        if reversed_:
            for ob in out:
                yield _reorder_output(ob, out_names)
        else:
            yield from out
        mctx.set_bytes(0)

    for p in range(bspiller.n_partitions):
        yield from replay_leaf(bspiller, pspiller, p)


def _execute_index_join(node, ctx: ExecContext) -> Iterator[Batch]:
    """Index join (reference: operator/index/IndexLoader.java driving a
    connector ConnectorIndex): each probe batch's live key values are fed
    to the connector's keyed lookup; only the matching build rows come
    back, and the regular sorted-hash probe joins them batch-wise. No
    full-table scan, no full build — the host sync to extract keys is the
    price (the reference pays the same in IndexLoader's key snapshots)."""
    conn = ctx.catalog.connectors[node.catalog]
    handle = conn.get_table(node.table)
    idx = conn.get_index(handle, node.index_key_cols)
    if idx is None:
        raise RuntimeError(
            f"connector {node.catalog!r} no longer provides an index over "
            f"{node.index_key_cols} on {node.table!r}")

    # shim HashJoin so _join_probe's machinery (and its per-node jit
    # caches) applies unchanged: the 'right' child is a never-executed
    # scan carrying the index-side symbols
    shim = node.__dict__.get("_probe_shim")
    if shim is None:
        inv = {c: s for s, c in node.assignments.items()}
        shim = HashJoin(
            kind=node.kind, left=node.left,
            right=TableScan(catalog=node.catalog, table=node.table,
                            assignments=dict(node.assignments),
                            output=list(node.index_output)),
            left_keys=list(node.left_keys),
            right_keys=[inv[c] for c in node.index_key_cols],
            build_unique=node.build_unique,
        )
        node.__dict__["_probe_shim"] = shim

    probe_stream, chain = _fused_child(node.left, ctx)
    jit_chain = _node_jit(node, "index_chain", lambda: chain)
    ident = lambda b: b  # noqa: E731 — chain applied before key extraction
    src_cols = [node.assignments[s] for s, _ in node.index_output]
    syms = [s for s, _ in node.index_output]

    for b in probe_stream:
        b = jit_chain(b)
        live = np.asarray(b.live)
        valid = live.copy()
        key_vals = {}
        for sym, col_name in zip(node.left_keys, node.index_key_cols):
            c = b.column(sym)
            if c.validity is not None:
                valid &= np.asarray(c.validity)
            vals = np.asarray(c.values)
            d = b.dicts.get(sym)
            if d is not None:
                codes = vals.astype(np.int64)
                safe = np.clip(codes, 0, max(len(d) - 1, 0))
                vals = np.asarray(d.values, dtype=object)[safe]
            key_vals[col_name] = vals
        key_vals = {c: v[valid] for c, v in key_vals.items()}
        looked = idx.lookup(key_vals, src_cols)
        build = Batch(syms, [t for _, t in node.index_output],
                      [looked.column(c) for c in src_cols], looked.live,
                      {s: looked.dicts[c] for s, c in zip(syms, src_cols)
                       if c in looked.dicts})
        yield from _join_probe(shim, ctx, build, iter([b]), ident,
                               jkey="index_")


def _join_plan_cdt(node) -> tuple:
    """Per-key-position pairwise-promoted compare dtypes of an equi-join,
    derived from PLAN output types alone (ops/join.join_compare_dtypes is
    the batch-side twin). Purely structural, so probe closures computing
    it stay shareable across the structural program cache."""
    ltypes = dict(node.left.output)
    rtypes = dict(node.right.output)
    return tuple(
        jnp.result_type(jnp.dtype(rtypes[rk].dtype),
                        jnp.dtype(ltypes[lk].dtype))
        for lk, rk in zip(node.left_keys, node.right_keys))


class _JoinProber:
    """One build table, probed incrementally.

    The body of the classic `_join_probe` split into (construct,
    probe_batch, tail) so the radix driver can hold P probers at once and
    feed each its per-partition probe sub-batches as they arrive — a
    probe stream can only be consumed once, so probing cannot restart per
    partition. `probe_batch` yields the matches for one probe batch
    (LEFT/FULL null-extension included); `tail` yields the FULL OUTER
    build remainder.
    """

    def __init__(self, node: HashJoin, ctx: ExecContext,
                 build_in: Optional[Batch], chain, jkey: str = "",
                 fanout_scan: int = 8):
        # jkey prefixes the per-node jit-cache keys: the spilled/radix paths
        # probe with an identity chain and must not reuse closures compiled
        # with the real one
        self.node, self.ctx = node, ctx
        lsyms = self.lsyms = [n for n, _ in node.left.output]
        rsyms = self.rsyms = [n for n, _ in node.right.output]
        self.overflow_rows = 0
        # probe-selectivity accumulators (device scalars, summed lazily;
        # one host sync at tail): output rows / probe rows feeds the
        # join_probe_sel HBO site for choose_join_mode
        self._n_probe = jnp.zeros((), jnp.int64)
        self._n_out = jnp.zeros((), jnp.int64)
        self.empty = build_in is None and node.kind == "inner"
        if self.empty:
            return  # empty build side: no output
        if build_in is None:
            build_in = Batch(
                rsyms,
                [t for _, t in node.right.output],
                [Column(jnp.zeros(128, t.dtype), None) for _, t in node.right.output],
                jnp.zeros(128, bool),
                {},
            )

        engine = _breaker_engine_choice(node, ctx)
        # pairwise-promoted compare dtypes come from the PLAN's output
        # types on both sides, so the probe closures (shared across the
        # radix path's P probers, never seeing a build batch) agree with
        # hash_build_side's encode. An executed batch that deviates from
        # its plan-declared dtype would silently mis-encode — fall back.
        ltypes = dict(node.left.output)
        probe_dtypes = tuple(
            jnp.dtype(ltypes[lk].dtype) for lk in node.left_keys)
        if engine == "hash" and join_compare_dtypes(
                build_in, tuple(node.right_keys),
                probe_dtypes) != _join_plan_cdt(node):
            engine = "sort"
            node.__dict__["_breaker_engine"] = "sort"
            node.__dict__["_breaker_engine_why"] = (
                "build batch dtypes deviate from plan types")
        self.engine = engine
        self.fanout_scan = fanout_scan
        _ek = lambda k: _engine_key(k, engine)  # noqa: E731
        self._ek, self._jkey, self._chain = _ek, jkey, chain

        if engine == "hash":
            table = _node_jit(
                node, _ek("build"), lambda: hash_build_side,
                static_argnames=("key_names", "probe_dtypes"))(
                build_in, tuple(node.right_keys), probe_dtypes)
        else:
            table = _node_jit(node, "build", lambda: build_side, static_argnames=("key_names",))(
                build_in, tuple(node.right_keys)
            )
        self.table = table
        self._hbo_observe_build()

        self.want_full = node.kind == "full"
        build_cap = int(table.hashes.shape[0])
        self.bm = jnp.zeros(build_cap, bool) if self.want_full else None

        def build_remainder_fn(t: BuildTable, bm):
            """FULL OUTER tail: build rows no probe row matched, with NULL
            probe columns (reference: LookupJoinOperators.fullOuterJoin's
            lookup-outer positions pass)."""
            ltypes = dict(node.left.output)
            names, types, cols = [], [], []
            cap = t.hashes.shape[0]
            for c in lsyms:
                names.append(c)
                types.append(ltypes[c])
                cols.append(Column(jnp.zeros(cap, ltypes[c].dtype),
                                   jnp.zeros(cap, bool)))
            for c in rsyms:
                names.append(c)
                types.append(t.batch.type_of(c))
                cols.append(t.batch.column(c))
            # orig_live, not batch.live: NULL-key build rows were live-killed
            # for matching but a FULL JOIN must still emit them unmatched
            live = t.orig_live & ~bm
            return Batch(names, types, cols, live,
                         {c: t.batch.dicts[c] for c in rsyms if c in t.batch.dicts})

        self.jremainder = _node_jit(node, jkey + "full_tail",
                                    lambda: build_remainder_fn)

        if node.build_unique:

            def probe_fn(table, pb: Batch, bm):
                pb = chain(pb)
                pba = align_probe_strings(pb, tuple(node.left_keys), table, tuple(node.right_keys))
                if engine == "hash":
                    idx, matched = hash_probe_unique(
                        table, pba, tuple(node.left_keys),
                        _join_plan_cdt(node))
                else:
                    idx, matched = probe_unique(table, pba, tuple(node.left_keys), tuple(node.right_keys))
                out = gather_join_output(
                    pb, table, jnp.arange(pb.capacity, dtype=jnp.int32), idx,
                    pb.live, lsyms, rsyms,
                )
                if bm is not None:
                    bm = bm.at[idx].max(matched & pb.live, mode="drop")
                n_probe = jnp.sum(pb.live).astype(jnp.int64)
                if node.kind == "inner":
                    return out.with_live(out.live & matched), bm, n_probe
                # left/full outer: keep probe rows; null out build columns
                # where unmatched
                cols = list(out.columns)
                for i, nme in enumerate(out.names):
                    if nme in rsyms:
                        c = cols[i]
                        valid = c.validity if c.validity is not None else jnp.ones(out.capacity, bool)
                        cols[i] = Column(c.values, valid & matched, c.hi)
                return (Batch(out.names, out.types, cols, out.live,
                              out.dicts), bm, n_probe)

            self.jfn = _node_jit(node, _ek(jkey + "probe"), lambda: probe_fn)
            return

        # general fanout join (inner / left): counts pass + chunked
        # expansion. LEFT semantics: track verified per-probe existence
        # across chunks and emit the NULL-extended non-matching probe rows
        # at the end (the role of LookupJoinOperators.probeOuterJoin in the
        # reference).
        # `t` is an argument, not a closure capture: the jit cache entry is
        # shared across probers with the same jkey (the radix path keeps P
        # of them), so a captured table would bake the first prober's build
        # side into the compiled program as a constant
        def chain_align(t, pb):
            pb = chain(pb)
            pba = align_probe_strings(pb, tuple(node.left_keys), t, tuple(node.right_keys))
            return pb, pba

        self.chain_j = _node_jit(node, jkey + "chain_align", lambda: chain_align)
        # the fanout window is part of the compiled closure: a non-default
        # scan width (the radix path probes with a wider one, the hash
        # engine's overflow ladder doubles it) keys its own cache entry
        self.counts_fn = self._counts_program(fanout_scan)

        def expand_fn(t, pb, pba, lo, counts, offsets, base, out_cap, bm):
            # hash engine: `lo` is the match matrix mm[n, F] (exact build
            # row indices); sort engine: the range starts, re-verified
            if engine == "hash":
                pr, bi, ol = hash_probe_expand(
                    t, lo, counts, offsets, base, out_cap)
            else:
                pr, bi, ol = probe_expand(
                    t, pba, tuple(node.left_keys), tuple(node.right_keys),
                    lo, counts, offsets, base, out_cap,
                )
            out = gather_join_output(pb, t, pr, bi, ol, lsyms, rsyms)
            exists = (
                jnp.zeros(pb.capacity, dtype=jnp.int32)
                .at[pr]
                .max(ol.astype(jnp.int32), mode="drop")
                .astype(bool)
            )
            if bm is not None:
                bm = bm.at[bi].max(ol, mode="drop")
            return out, exists, bm

        def null_extend_fn(t, pb, exists):
            # unmatched probe rows with NULL build columns
            zero_idx = jnp.zeros(pb.capacity, dtype=jnp.int32)
            out = gather_join_output(
                pb, t, jnp.arange(pb.capacity, dtype=jnp.int32), zero_idx,
                pb.live & ~exists, lsyms, rsyms,
            )
            cols = list(out.columns)
            for i, nme in enumerate(out.names):
                if nme in rsyms:
                    cols[i] = Column(cols[i].values, jnp.zeros(out.capacity, bool),
                                     cols[i].hi)
            return Batch(out.names, out.types, cols, out.live, out.dicts)

        self.jexpand = _node_jit(node, _ek("expand"), lambda: expand_fn,
                                 static_argnames=("out_cap",))
        self.jnull = _node_jit(node, "null_extend", lambda: null_extend_fn)

    def _hbo_observe_build(self) -> None:
        """Observe the build side's actual live row count (one host sync of
        an already-materialized device scalar) against the CBO's estimate.
        Whole-build probers only — the radix/spilled drivers hold P probers
        over per-partition sub-builds whose counts are not table totals."""
        ctx = self.ctx
        if getattr(ctx.config, "hbo", "observe") == "off" or self._jkey:
            return
        try:
            from presto_tpu.obs import runstats as _runstats
            from presto_tpu.plan.stats import choose_breaker_engine
            from presto_tpu.plan.stats import derive as _derive_stats

            node = self.node
            fp = _runstats.node_fingerprint(node, ctx.catalog)
            if fp is None:
                return
            actual = float(table_rows(self.table))
            if actual <= 0:
                return
            try:
                bst = _derive_stats(node.right, ctx.catalog)
            except Exception:
                bst = None
            est = float(bst.rows) if (bst is not None and bst.rows) else None
            _runstats.observe(fp, "join_build", type(node).__name__.lower(),
                              est, actual)
            node.__dict__["_runstats"] = {
                "site": "join_build", "est": est, "actual": actual}
            made = node.__dict__.get("_breaker_engine")
            if made:
                would, _ = choose_breaker_engine(
                    node, ctx.catalog,
                    getattr(ctx.config, "breaker_engine", "auto"),
                    hbo="correct")
                if would != made:
                    _runstats.record_flip("breaker_engine")
        except Exception:
            pass

    def _counts_program(self, fanout: int):
        """Counting-pass program for one fanout width (jit-cached per
        width: the hash engine's overflow ladder re-probes at doubled
        widths, each its own compiled shape)."""
        node = self.node
        if self.engine == "hash":
            return _node_jit(
                self.node, f"counts@h{fanout}",
                lambda: lambda t, pba: hash_probe_counts(
                    t, pba, tuple(node.left_keys), _join_plan_cdt(node),
                    max_fanout_scan=fanout,
                ),
            )
        ckey = "counts" if fanout == 8 else f"counts{fanout}"
        return _node_jit(
            self.node, ckey,
            lambda: lambda t, pba: probe_counts(
                t, pba, tuple(node.left_keys), tuple(node.right_keys),
                max_fanout_scan=fanout,
            ),
        )

    def probe_start(self, pb_raw: Batch):
        """Dispatch phase of one probe batch: everything up to (not
        including) the host sync on `total`. Chunk 0 is dispatched
        unconditionally while `total` travels to the host (it is usually
        the only chunk). The radix driver starts ALL partitions of a batch
        before finishing any, so the P count round trips overlap instead
        of serializing."""
        if self.empty:
            return None
        node, table = self.node, self.table
        if node.build_unique:
            out, self.bm, n_probe = self.jfn(table, pb_raw, self.bm)
            self._n_probe = self._n_probe + n_probe
            return ("u", out)
        pb, pba = self.chain_j(table, pb_raw)
        self._n_probe = self._n_probe + jnp.sum(pb.live)
        lo, counts, offsets, total, _, ovf = self.counts_fn(table, pba)
        try:
            total.copy_to_host_async()
            ovf.copy_to_host_async()
        except Exception:
            pass
        out_cap = self.ctx.config.join_out_capacity or pb.capacity
        out, exists_acc, self.bm = self.jexpand(
            table, pb, pba, lo, counts, offsets, 0, out_cap, self.bm)
        return ("g", pb, pba, lo, counts, offsets, total, ovf, out_cap,
                out, exists_acc)

    def probe_finish(self, st) -> Iterator[Batch]:
        if st is None:
            return
        node, table = self.node, self.table
        if st[0] == "u":
            self._n_out = self._n_out + jnp.sum(st[1].live)
            yield st[1]
            return
        (_, pb, pba, lo, counts, offsets, total, ovf, out_cap, out,
         exists_acc) = st
        # the sort engine's overflow is informational (counts already
        # widened) and syncs after the chunk loop; the hash engine's must
        # be confirmed BEFORE chunk 0 is yielded
        ovn = int(ovf) if self.engine == "hash" else 0
        if ovn:
            # hash-engine fanout overflow: counts/total are EXACT but the
            # match matrix truncated past its width — the optimistically
            # dispatched chunk 0 would duplicate the last held match, so
            # discard it, re-probe at doubled widths until every row fits,
            # and redo chunk 0 from the full matrix. (The discarded
            # chunk's bm/exists updates only marked GENUINE matches, so
            # they stand.) Counts don't change, so no re-cumsum drift.
            ov_rows = ovn
            fanout = self.fanout_scan
            while ovn:
                fanout *= 2
                if fanout > int(self.table.slot_row.shape[0]):
                    raise RuntimeError(
                        "join fanout exceeded build table capacity")
                _bump_replay_wave(node, self.ctx, cap_to=fanout)
                lo, counts, offsets, total, _, ovf = self._counts_program(
                    fanout)(table, pba)
                ovn = int(ovf)
            out, exists, self.bm = self.jexpand(
                table, pb, pba, lo, counts, offsets, 0, out_cap, self.bm)
            exists_acc = exists_acc | exists
            ovn = ov_rows  # recorded after the chunk loop
        self._n_out = self._n_out + jnp.sum(out.live)
        yield out
        tot = int(total)
        base = out_cap
        while base < tot:
            out, exists, self.bm = self.jexpand(
                table, pb, pba, lo, counts, offsets, base, out_cap, self.bm)
            exists_acc = exists_acc | exists
            self._n_out = self._n_out + jnp.sum(out.live)
            yield out
            base += out_cap
        if self.engine != "hash":
            ovn = int(ovf)
        if ovn:
            from presto_tpu.scan import metrics as _scan_metrics

            self.overflow_rows += ovn
            key = "join.fanout_overflow_rows"
            self.ctx.stats[key] = self.ctx.stats.get(key, 0) + ovn
            _scan_metrics.record("join_fanout_overflow_rows", ovn)
            if getattr(self.ctx.config, "hbo", "observe") != "off":
                try:
                    from presto_tpu.obs import runstats as _runstats

                    _runstats.note(
                        _runstats.node_fingerprint(node, self.ctx.catalog),
                        "join_build", fanout_overflow_rows=ovn)
                except Exception:
                    pass
        if node.kind in ("left", "full"):
            nb = self.jnull(table, pb, exists_acc)
            self._n_out = self._n_out + jnp.sum(nb.live)
            yield nb

    def probe_batch(self, pb_raw: Batch) -> Iterator[Batch]:
        yield from self.probe_finish(self.probe_start(pb_raw))

    def tail(self) -> Iterator[Batch]:
        if not self.empty and self.want_full:
            b = self.jremainder(self.table, self.bm)
            self._n_out = self._n_out + jnp.sum(b.live)
            yield b
        self._observe_selectivity()

    def _observe_selectivity(self) -> None:
        """Record the join's observed probe selectivity (output rows /
        probe rows) under its structural fingerprint — the site
        choose_join_mode consults, so the multiway-vs-binary verdict is
        history-corrected on fingerprint repeat. Whole-build probers only
        (the radix/spilled drivers see partition slices); one host sync
        of two already-materialized device scalars."""
        ctx = self.ctx
        if (self.empty or self._jkey
                or getattr(ctx.config, "hbo", "observe") == "off"):
            return
        try:
            from presto_tpu.obs import runstats as _runstats
            from presto_tpu.plan.stats import derive as _derive

            n_probe = float(self._n_probe)
            if n_probe <= 0:
                return
            fp = _runstats.node_fingerprint(self.node, ctx.catalog)
            if fp is None:
                return
            est = None
            try:
                pst = _derive(self.node.left, ctx.catalog)
                ost = _derive(self.node, ctx.catalog)
                if pst is not None and ost is not None and pst.rows:
                    est = ost.rows / pst.rows
            except Exception:
                pass
            _runstats.observe(fp, "join_probe_sel",
                              type(self.node).__name__.lower(), est,
                              float(self._n_out) / n_probe,
                              extra={"probe_rows": n_probe})
        except Exception:
            pass


def _join_probe(node: HashJoin, ctx: ExecContext, build_in: Optional[Batch],
                probe_stream: Iterator[Batch], chain,
                jkey: str = "") -> Iterator[Batch]:
    prober = _JoinProber(node, ctx, build_in, chain, jkey=jkey)
    for pb in probe_stream:
        yield from prober.probe_batch(pb)
    yield from prober.tail()


# ---------------------------------------------------------------------------
# multiway (N-ary) join executor — plan/multiway.py's MultiwayJoin node:
# N resident build tables, one probe pass through all N probes per batch
# inside one fragment (ops/join.multiway_*). Budget-exceeded builds fall
# back to the binary cascade so each leg keeps the partitioned spiller.


def _mw_stub_build(node: MultiwayJoin, i: int) -> Batch:
    """Zero-row stand-in for an empty LEFT-leg build stream (inner legs
    with an empty build short-circuit the whole node instead)."""
    schema = node.builds[i].output
    return Batch([s for s, _ in schema], [t for _, t in schema],
                 [Column(jnp.zeros(128, t.dtype), None) for _, t in schema],
                 jnp.zeros(128, bool), {})


def _mw_cascade_shims(node: MultiwayJoin) -> List[HashJoin]:
    """Per-leg binary HashJoin shims: leg i's join with a never-executed
    scan stub standing in for the cascade intermediate (probe output +
    payloads of legs < i) on the left. They carry the leg's key/kind/
    uniqueness contract for _JoinProber / choose_breaker_engine and give
    _node_jit a stable per-leg home for the fallback path's programs
    (same trick as _execute_index_join's _probe_shim)."""
    shims = node.__dict__.get("_mw_shims")
    if shims is None:
        shims = []
        schema = list(node.probe.output)
        for i in range(len(node.builds)):
            stub = TableScan(catalog="", table=f"__mw_cascade_{i}__",
                             assignments={}, output=list(schema))
            shims.append(HashJoin(
                kind=node.kinds[i], left=stub, right=node.builds[i],
                left_keys=list(node.probe_keys[i]),
                right_keys=list(node.build_keys[i]),
                build_unique=bool(node.build_unique[i])))
            schema = schema + list(node.builds[i].output)
        node.__dict__["_mw_shims"] = shims
    return shims


def _mw_plan_specs(node: MultiwayJoin):
    """Plan-only per-leg key plumbing, memoized on the node: key sources
    (-1 = probe batch, j >= 0 = unique build j's payload), the planned
    probe-side encode dtypes, and the pairwise-promoted compare dtypes
    (the multiway twin of _join_plan_cdt)."""
    memo = node.__dict__.get("_mw_plan")
    if memo is not None:
        return memo
    pout = dict(node.probe.output)
    bouts = [dict(b.output) for b in node.builds]
    legs = []
    for i in range(len(node.builds)):
        sources, pdts = [], []
        for sym in node.probe_keys[i]:
            if sym in pout:
                sources.append(-1)
                pdts.append(jnp.dtype(pout[sym].dtype))
            else:
                for j in range(i):
                    if node.build_unique[j] and sym in bouts[j]:
                        sources.append(j)
                        pdts.append(jnp.dtype(bouts[j][sym].dtype))
                        break
                else:
                    raise KeyError(
                        f"multiway probe key {sym!r} resolves against no "
                        f"probe column or earlier unique build payload")
        cdts = tuple(
            jnp.result_type(jnp.dtype(bouts[i][bk].dtype), pd)
            for bk, pd in zip(node.build_keys[i], pdts))
        legs.append((tuple(sources), tuple(pdts), cdts))
    node.__dict__["_mw_plan"] = legs
    return legs


def _mw_stat(ctx: ExecContext, key: str, delta: int = 1) -> None:
    ctx.stats[key] = ctx.stats.get(key, 0) + delta


class _MultiwayProber:
    """N resident build tables, probed in one pass per batch.

    Per leg: unique builds probe through the sorted engine's single-match
    kernel; fanout builds through the Pallas hash kernel (exact counts —
    required for LEFT null-extension) or, for inner kinds, the sorted
    range engine (expand re-verifies keys). All-unique chains — the
    dominant star shape — run ONE compiled program per probe batch with
    the fused child chain inlined; general chains run a counts pass (per-
    leg fanout ladder on hash overflow) plus chunked mixed-radix
    expansion. ``cascade`` set at construction means a leg cannot run
    fused (left fanout leg without exact counts) and the caller must fall
    back to the binary cascade."""

    def __init__(self, node: MultiwayJoin, ctx: ExecContext,
                 builds_in: List[Optional[Batch]], chain):
        self.node, self.ctx = node, ctx
        self.cascade = None  # reason string when fused execution is off
        self.empty = any(
            b is None and k == "inner"
            for b, k in zip(builds_in, node.kinds))
        if self.empty:
            return
        N = len(node.builds)
        self.psyms = [s for s, _ in node.probe.output]
        self.bsyms = tuple(
            tuple(s for s, _ in b.output) for b in node.builds)
        legs = _mw_plan_specs(node)
        shims = _mw_cascade_shims(node)
        override = getattr(ctx.config, "breaker_engine", "auto")
        hbo = getattr(ctx.config, "hbo", "observe")

        specs, tables = [], []
        for i in range(N):
            build_in = builds_in[i]
            if build_in is None:
                build_in = _mw_stub_build(node, i)
            sources, pdts, cdts = legs[i]
            unique = bool(node.build_unique[i])
            hash_engine = False
            if not unique:
                from presto_tpu.plan.stats import choose_breaker_engine
                try:
                    eng, _ = choose_breaker_engine(
                        shims[i], ctx.catalog, override, hbo=hbo)
                except Exception:
                    eng = "sort"
                hash_engine = eng == "hash"
                if hash_engine and join_compare_dtypes(
                        build_in, tuple(node.build_keys[i]), pdts) != cdts:
                    # executed batch deviates from plan dtypes: the hash
                    # encode would be wrong — same gate as _JoinProber
                    hash_engine = False
                if not hash_engine and node.kinds[i] == "left":
                    # sorted fanout counts can widen, which breaks the
                    # left leg's digit-0 null-extension — whole-node
                    # binary decomposition instead of a wrong answer
                    self.cascade = (
                        f"left fanout leg {i} lacks exact counts")
                    return
            specs.append(MwSpec(
                probe_keys=tuple(node.probe_keys[i]),
                build_keys=tuple(node.build_keys[i]),
                sources=sources, kind=node.kinds[i], unique=unique,
                hash_engine=hash_engine,
                compare_dtypes=cdts if hash_engine else ()))
            if hash_engine:
                table = _node_jit(
                    node, f"mw_build{i}@h", lambda: hash_build_side,
                    static_argnames=("key_names", "probe_dtypes"))(
                    build_in, tuple(node.build_keys[i]), pdts)
            else:
                table = _node_jit(
                    node, f"mw_build{i}", lambda: build_side,
                    static_argnames=("key_names",))(
                    build_in, tuple(node.build_keys[i]))
            tables.append(table)
        self.specs = tuple(specs)
        self.tables = tuple(tables)
        # per-leg engine vector: hbo/override-chosen engines are volatile
        # config, so the shared probe-program keys must fork on them the
        # same way _JoinProber's `@h` suffix forks the binary path
        self._evec = "".join(
            "h" if s.hash_engine else "u" if s.unique else "s"
            for s in self.specs)
        self.fanouts = tuple(
            0 if s.unique else 16 for s in self.specs)
        self.all_unique = all(s.unique for s in self.specs)
        self._hbo_observe_builds()

        # selectivity accumulators (device scalars; one host sync in
        # tail): probe rows in, leg-0 binary-equivalent rows, final rows
        self._n_probe = jnp.zeros((), jnp.int64)
        self._n_leg0 = jnp.zeros((), jnp.int64)
        self._n_out = jnp.zeros((), jnp.int64)

        psyms, bsyms = self.psyms, self.bsyms
        specs_t = self.specs

        if self.all_unique:
            def unique_fn(ts, pb_raw):
                pb = chain(pb_raw)
                out, n_probe, n_leg0 = multiway_probe_unique(
                    ts, pb, specs_t, psyms, bsyms)
                return out, n_probe, n_leg0
            self.junique = _node_jit(
                node, f"mw_unique@e{self._evec}", lambda: unique_fn)
            return

        def expand_fn(ts, pb, state, chats, offsets, T, base, out_cap):
            return multiway_expand(ts, pb, specs_t, state, chats, offsets,
                                   T, base, out_cap, psyms, bsyms)
        self.jexpand = _node_jit(
            node, f"mw_expand@e{self._evec}", lambda: expand_fn,
            static_argnames=("out_cap",))
        self._chain = chain
        self._counts_cache = {}

    def _counts_program(self, fanouts):
        """Counting-pass program for one per-leg fanout vector (jit-cached
        per vector: a hash leg's overflow ladder doubles only that leg's
        width, each combination its own compiled shape). The fused child
        chain is inlined, so the chained probe batch comes back as an
        output alongside the per-leg state."""
        fn = self._counts_cache.get(fanouts)
        if fn is None:
            chain, specs = self._chain, self.specs

            def counts_fn(ts, pb_raw):
                pb = chain(pb_raw)
                return (pb,) + multiway_counts(ts, pb, specs, fanouts)
            fn = self._counts_cache[fanouts] = _node_jit(
                self.node,
                f"mw_counts@f{','.join(map(str, fanouts))}"
                f"@e{self._evec}",
                lambda: counts_fn)
        return fn

    def _hbo_observe_builds(self) -> None:
        """Per-leg build row counts into HBO under the ORIGINAL binary
        joins' fingerprints (stashed by the collapse pass), so
        choose_join_mode's per-join build sizing is history-corrected on
        fingerprint repeat even when the chain ran multiway."""
        ctx = self.ctx
        if getattr(ctx.config, "hbo", "observe") == "off":
            return
        leg_fps = self.node.__dict__.get("_leg_fps") or []
        if not leg_fps:
            return
        try:
            from presto_tpu.obs import runstats as _runstats

            for i, fp in enumerate(leg_fps):
                if fp is None or i >= len(self.tables):
                    continue
                actual = float(table_rows(self.tables[i]))
                if actual <= 0:
                    continue
                try:
                    from presto_tpu.plan.stats import derive as _derive
                    bst = _derive(self.node.builds[i], ctx.catalog)
                except Exception:
                    bst = None
                est = float(bst.rows) if (bst is not None
                                          and bst.rows) else None
                _runstats.observe(fp, "join_build", "multiwayjoin",
                                  est, actual)
        except Exception:
            pass

    def probe_batch(self, pb_raw: Batch) -> Iterator[Batch]:
        if self.empty:
            return
        node, ctx, tables = self.node, self.ctx, self.tables
        if self.all_unique:
            out, n_probe, n_leg0 = self.junique(tables, pb_raw)
            self._n_probe = self._n_probe + n_probe
            self._n_leg0 = self._n_leg0 + n_leg0
            self._n_out = self._n_out + jnp.sum(out.live)
            yield out
            return
        fanouts = self.fanouts
        (pb, state, chats, offsets, T, total,
         ovfs) = self._counts_program(fanouts)(tables, pb_raw)
        try:
            total.copy_to_host_async()
            ovfs.copy_to_host_async()
        except Exception:
            pass
        out_cap = ctx.config.join_out_capacity or pb.capacity
        # optimistic chunk-0 dispatch while total/ovfs travel to the host
        out = self.jexpand(tables, pb, state, chats, offsets, T, 0, out_cap)
        ovn = np.asarray(ovfs)
        if int(ovn.sum()):
            # hash-leg fanout overflow: counts are EXACT but that leg's
            # match matrix truncated — the dispatched chunk 0 would
            # duplicate its last held match, so discard it, double the
            # overflowing legs' widths until every row fits, and redo
            # chunk 0 (the widening-replay ladder, per table)
            ov_rows = int(ovn.sum())
            while int(ovn.sum()):
                fanouts = tuple(
                    f * 2 if int(ovn[i]) else f
                    for i, f in enumerate(fanouts))
                for i, f in enumerate(fanouts):
                    if (self.specs[i].hash_engine
                            and f > int(tables[i].slot_row.shape[0])):
                        raise RuntimeError(
                            "multiway join fanout exceeded build table "
                            f"capacity on leg {i}")
                _bump_replay_wave(node, ctx, cap_to=max(fanouts))
                (pb, state, chats, offsets, T, total,
                 ovfs) = self._counts_program(fanouts)(tables, pb_raw)
                ovn = np.asarray(ovfs)
            out = self.jexpand(tables, pb, state, chats, offsets, T, 0,
                               out_cap)
            self._note_overflow(ov_rows, ovn)
        self._n_probe = self._n_probe + jnp.sum(pb.live)
        self._n_leg0 = self._n_leg0 + jnp.sum(
            jnp.where(pb.live, chats[0], 0))
        self._n_out = self._n_out + jnp.sum(out.live)
        yield out
        tot = int(total)
        base = out_cap
        while base < tot:
            out = self.jexpand(tables, pb, state, chats, offsets, T, base,
                               out_cap)
            self._n_out = self._n_out + jnp.sum(out.live)
            yield out
            base += out_cap

    def _note_overflow(self, ov_rows: int, _ovn) -> None:
        """Per-table overflow accounting into the same counters the binary
        widening-replay ladder feeds."""
        from presto_tpu.scan import metrics as _scan_metrics

        _mw_stat(self.ctx, "join.fanout_overflow_rows", ov_rows)
        _mw_stat(self.ctx, "multiway.fanout_overflow_rows", ov_rows)
        _scan_metrics.record("join_fanout_overflow_rows", ov_rows)
        if getattr(self.ctx.config, "hbo", "observe") != "off":
            try:
                from presto_tpu.obs import runstats as _runstats

                fp = _runstats.node_fingerprint(self.node,
                                                self.ctx.catalog)
                if fp is not None:
                    _runstats.note(fp, "join_build",
                                   fanout_overflow_rows=ov_rows)
            except Exception:
                pass

    def tail(self) -> None:
        """Stream end: one host sync of the selectivity accumulators, then
        the HBO probe-selectivity observations (satellite: history-
        corrected multiway-vs-binary verdicts). Leg-0's binary-equivalent
        selectivity lands on the ORIGINAL bottom join's fingerprint (the
        one choose_join_mode consults); the overall chain selectivity on
        the node's own fingerprint and the collapsed top join's."""
        ctx = self.ctx
        if self.empty or getattr(ctx.config, "hbo", "observe") == "off":
            return
        try:
            from presto_tpu.obs import runstats as _runstats

            n_probe = float(self._n_probe)
            if n_probe <= 0:
                return
            leg0_sel = float(self._n_leg0) / n_probe
            out_sel = float(self._n_out) / n_probe
            leg_fps = self.node.__dict__.get("_leg_fps") or []
            if leg_fps and leg_fps[0] is not None:
                _runstats.observe(leg_fps[0], "join_probe_sel",
                                  "multiwayjoin", None, leg0_sel,
                                  extra={"probe_rows": n_probe})
            for fp in (
                    _runstats.node_fingerprint(self.node, ctx.catalog),
                    self.node.__dict__.get("_origin_fp")):
                if fp is not None:
                    _runstats.observe(fp, "join_probe_sel", "multiwayjoin",
                                      None, out_sel,
                                      extra={"probe_rows": n_probe})
        except Exception:
            pass


def _mw_binary_cascade(node: MultiwayJoin, ctx: ExecContext,
                       probe_stream: Iterator[Batch], chain,
                       collected: List[List[Batch]],
                       pressure_at: Optional[int],
                       partial: List[Batch], bstream,
                       reason: str) -> Iterator[Batch]:
    """Binary decomposition of the chain over the already-opened streams:
    leg i joins the cascade intermediate against build i through the
    regular binary machinery, so a budget-exceeded build degrades through
    the PR 15 partitioned spiller (per leaf) instead of failing. Builds
    collected before the pressure point replay from memory; the
    pressure-point build resumes its partially-consumed stream; later
    builds execute normally."""
    import itertools

    from presto_tpu.scan import metrics as _scan_metrics

    _mw_stat(ctx, "multiway.cascade_fallbacks")
    _scan_metrics.record("multiway_cascade_fallbacks", 1)
    if ctx.tracer.enabled:
        t = time.time()
        ctx.tracer.record("multiway_cascade", "multiway_cascade", t, t,
                          node=type(node).__name__, reason=reason)
    shims = _mw_cascade_shims(node)
    ident = lambda b: b  # noqa: E731 — chain applied by leg 0 only
    stream = probe_stream
    for i, shim in enumerate(shims):
        leg_chain = chain if i == 0 else ident
        jkey = f"mwb{i}_"
        if pressure_at is None or i < pressure_at:
            build_in = (_collect_concat(iter(collected[i]))
                        if i < len(collected) else
                        _collect_concat(execute_node(node.builds[i], ctx)))
            stream = _join_probe(shim, ctx, build_in, stream, leg_chain,
                                 jkey=jkey)
        else:
            if i == pressure_at:
                bs = itertools.chain(
                    iter(partial),
                    bstream if bstream is not None else iter(()))
            else:
                bs = execute_node(node.builds[i], ctx)
            stream = _join_with_spill(shim, ctx, stream, bs, leg_chain,
                                      jkey=jkey)
    yield from stream


def _execute_multiway_join(node: MultiwayJoin,
                           ctx: ExecContext) -> Iterator[Batch]:
    """MultiwayJoin executor: collect all N build sides (memory-accounted),
    then run the fused N-ary probe — ONE probe pass per batch, no
    intermediate materialization between legs. Pool pressure during build
    collection, or a leg the fused path cannot run exactly, degrades to
    the binary cascade (each leg keeping the partitioned spiller)."""
    from presto_tpu.memory import LocalMemoryContext, batch_device_bytes

    probe_stream, chain = _fused_child(node.probe, ctx)
    N = len(node.builds)
    _mw_stat(ctx, "multiway.joins", 1)
    _mw_stat(ctx, "multiway.legs", N)

    mctx = LocalMemoryContext(ctx.memory_pool, "mw-join-build")
    rev = {"flag": False}

    def _revoke(_need: int) -> int:
        rev["flag"] = True
        return 0

    can_spill = ctx.config.spill_enabled
    if can_spill:
        ctx.memory_pool.add_revoker(_revoke)
    try:
        collected: List[List[Batch]] = []
        total_bytes = 0
        pressure_at = None
        partial: List[Batch] = []
        bstream = None
        for i in range(N):
            bstream = execute_node(node.builds[i], ctx)
            partial = []
            for b in bstream:
                nb = batch_device_bytes(b)
                if can_spill and (rev["flag"] or ctx.should_spill(nb)):
                    rev["flag"] = False
                    pressure_at = i
                    partial.append(b)
                    break
                partial.append(b)
                total_bytes += nb
                mctx.set_bytes(total_bytes)
            if pressure_at is not None:
                break
            collected.append(partial)
            partial, bstream = [], None

        if pressure_at is not None:
            yield from _mw_binary_cascade(
                node, ctx, probe_stream, chain, collected, pressure_at,
                partial, bstream, "build memory pressure")
            return

        builds_in = [_collect_concat(iter(bb)) for bb in collected]
        prober = _MultiwayProber(node, ctx, builds_in, chain)
        if prober.cascade is not None:
            yield from _mw_binary_cascade(
                node, ctx, probe_stream, chain, collected, None, [], None,
                prober.cascade)
            return
        _mw_stat(ctx, "multiway.fused_dispatches")
        for pb in probe_stream:
            yield from prober.probe_batch(pb)
        prober.tail()
    finally:
        if can_spill:
            ctx.memory_pool.remove_revoker(_revoke)
        mctx.set_bytes(0)


def _column_chunk(c: Column, off, size: int) -> Column:
    """Rows [off, off+size) of every plane (traced offset, static size)."""
    def dsl(a):
        return jax.lax.dynamic_slice_in_dim(a, off, size, axis=0)

    return Column(
        dsl(c.values),
        None if c.validity is None else dsl(c.validity),
        None if c.hi is None else dsl(c.hi),
        None if c.sizes is None else dsl(c.sizes),
        None if c.evalid is None else dsl(c.evalid),
        None if c.keys is None else dsl(c.keys),
    )


def _column_repeat(c: Column, k: int) -> Column:
    """Each row k times (out row i*k+j = in row i)."""
    def rep(a):
        return jnp.repeat(a, k, axis=0)

    return Column(
        rep(c.values),
        None if c.validity is None else rep(c.validity),
        None if c.hi is None else rep(c.hi),
        None if c.sizes is None else rep(c.sizes),
        None if c.evalid is None else rep(c.evalid),
        None if c.keys is None else rep(c.keys),
    )


def _column_tile(c: Column, k: int) -> Column:
    """The whole column k times (out row i*n+j = in row j)."""
    def tile(a):
        reps = (k,) + (1,) * (a.ndim - 1)
        return jnp.tile(a, reps)

    return Column(
        tile(c.values),
        None if c.validity is None else tile(c.validity),
        None if c.hi is None else tile(c.hi),
        None if c.sizes is None else tile(c.sizes),
        None if c.evalid is None else tile(c.evalid),
        None if c.keys is None else tile(c.keys),
    )


def _execute_nljoin(node: NestedLoopJoin, ctx: ExecContext) -> Iterator[Batch]:
    """Nested-loop inner join (cross product / non-equi ON). Reference:
    NestedLoopJoinOperator.java — there per-position page crossing; here
    each output batch is one probe batch × one fixed-size build chunk,
    expanded by repeat/tile with the residual predicate fused into the
    same program (static shapes: chunk size is a trace-time constant)."""
    from presto_tpu.expr.compile import compile_predicate

    probe_stream, chain = _fused_child(node.left, ctx)
    build = _collect_concat(execute_node(node.right, ctx))
    if build is None:
        return
    build = _JIT_COMPACT(build)  # live rows to the front
    nb = build.num_live()
    if nb == 0:
        return
    pred = (compile_predicate(node.residual)
            if node.residual is not None else None)
    lnames = [s for s, _ in node.left.output]
    rnames = [s for s, _ in node.right.output]
    out_names = lnames + rnames
    out_types = [t for _, t in node.left.output] + [
        t for _, t in node.right.output]

    def chunk_size(np_cap: int) -> int:
        # ≤512 build rows per output batch, bounded to ~2^21 output rows;
        # powers of two dividing the (pow2) build capacity, so fixed-size
        # dynamic slices never clamp (a clamped tail slice would re-read
        # earlier rows and duplicate join output)
        return min(512, max(1, (1 << 21) // max(np_cap, 1)), build.capacity)

    def expand(pb: Batch, bb: Batch, off):
        pb = chain(pb)
        np_cap = pb.capacity
        c = chunk_size(np_cap)
        chunk_cols = [_column_chunk(col, off, c) for col in bb.columns]
        chunk_live = jax.lax.dynamic_slice_in_dim(bb.live, off, c)
        cols = [_column_repeat(col, c) for col in pb.columns] + [
            _column_tile(col, np_cap) for col in chunk_cols
        ]
        live = (jnp.repeat(pb.live, c) & jnp.tile(chunk_live, np_cap))
        dicts = dict(bb.dicts)
        dicts.update(pb.dicts)
        out = Batch(out_names, out_types, cols, live, dicts)
        if pred is not None:
            out = out.with_live(out.live & pred(out))
        return out

    # chunk size must match expand()'s: recompute identically per capacity.
    # _shared=False: chunk_size bakes THIS build table's capacity into the
    # trace, so a structurally-identical node with a different build side
    # must not reuse the program.
    jexpand = _node_jit(node, "expand", lambda: expand, _shared=False)
    for raw in probe_stream:
        c = chunk_size(raw.capacity)
        for off in range(0, nb, c):
            # traced offset: one compiled program per (capacity) shape,
            # not per chunk position
            yield jexpand(raw, build, jnp.int32(off))


def _execute_semijoin(node: SemiJoin, ctx: ExecContext) -> Iterator[Batch]:
    right_in = _collect_concat(execute_node(node.right, ctx))
    probe_stream, chain = _fused_child(node.left, ctx)
    lkeys, rkeys = tuple(node.left_keys), tuple(node.right_keys)
    if right_in is None:
        jfn = _node_jit(node, "chain", lambda: chain)
        for pb in probe_stream:
            b = jfn(pb)
            if node.negated:
                yield b
            else:
                yield b.with_live(jnp.zeros(b.capacity, bool))
        return

    if node.residual is None:
        engine = _breaker_engine_choice(node, ctx)
        ltypes = dict(node.left.output)
        probe_dtypes = tuple(jnp.dtype(ltypes[lk].dtype) for lk in lkeys)
        if engine == "hash" and join_compare_dtypes(
                right_in, rkeys, probe_dtypes) != _join_plan_cdt(node):
            engine = "sort"
            node.__dict__["_breaker_engine"] = "sort"
            node.__dict__["_breaker_engine_why"] = (
                "build batch dtypes deviate from plan types")
        _ek = lambda k: _engine_key(k, engine)  # noqa: E731

        if engine == "hash":
            # the linear-probing table tolerates duplicate build keys (the
            # probe walks the whole chain; EXISTS only needs count > 0),
            # so the sort engine's dedup pass has no hash twin
            def dedup_build(b: Batch):
                return hash_build_side(b, rkeys, probe_dtypes)
        else:
            def dedup_build(b: Batch):
                cols = [b.column(r) for r in rkeys]
                keys, _, out_live, _ = grouped_merge(
                    [KeyCol(c.values, c.validity) for c in cols], [], b.live, b.capacity
                )
                db = Batch(
                    list(rkeys), [b.type_of(r) for r in rkeys],
                    [Column(k.values, k.validity) for k in keys], out_live, b.dicts,
                )
                return build_side(db, rkeys)

        table = _node_jit(node, _ek("dedup_build"), lambda: dedup_build)(right_in)

        def probe_fn(t, pb: Batch):
            b = chain(pb)
            ba = align_probe_strings(b, lkeys, t, rkeys)
            if engine == "hash":
                _, matched = hash_probe_unique(
                    t, ba, lkeys, _join_plan_cdt(node))
            else:
                _, matched = probe_unique(t, ba, lkeys, rkeys)
            if node.negated:
                if node.null_aware:
                    # SQL: NULL NOT IN (non-empty set) is NULL → row filtered.
                    # (Deviation: NULLs *inside* the subquery should poison
                    # every row; that case is documented as unsupported.)
                    key_valid = jnp.ones(b.capacity, bool)
                    for lk in lkeys:
                        kv = b.column(lk).validity
                        if kv is not None:
                            key_valid = key_valid & kv
                    keep = ~matched & (key_valid | (t.n_rows == 0))
                else:
                    # NOT EXISTS is a pure anti-join: a NULL correlation key
                    # simply never matches, keeping the row
                    keep = ~matched
                return b.with_live(b.live & keep)
            return b.with_live(b.live & matched)

        jfn = _node_jit(node, _ek("probe"), lambda: probe_fn)
        for pb in probe_stream:
            yield jfn(table, pb)
        return

    # residual path (correlated EXISTS with non-equi conjuncts, e.g. Q21):
    # full build table, chunked pair expansion, residual predicate on pairs,
    # per-probe-row ANY-reduction across chunks.
    lsyms = [n for n, _ in node.left.output]
    rsyms = [n for n, _ in node.right.output]
    pred = compile_predicate(node.residual)
    node.__dict__["_breaker_engine"] = "sort"
    node.__dict__["_breaker_engine_why"] = "residual semijoin"
    table = _node_jit(node, "build", lambda: build_side, static_argnames=("key_names",))(
        right_in, rkeys
    )

    def chain_align(pb):
        pb = chain(pb)
        pba = align_probe_strings(pb, lkeys, table, rkeys)
        return pb, pba

    # _shared=False: chain_align closes over THIS query's build table (its
    # string dictionaries become trace constants via align_probe_strings)
    chain_j = _node_jit(node, "chain_align", lambda: chain_align,
                        _shared=False)
    counts_fn = _node_jit(
        node, "counts", lambda: lambda t, pba: probe_counts(t, pba, lkeys, rkeys)
    )

    def exists_fn(t, pb, pba, lo, counts, offsets, base, out_cap):
        pr, bi, ol = probe_expand(
            t, pba, lkeys, rkeys, lo, counts, offsets, base, out_cap
        )
        pair = gather_join_output(pb, t, pr, bi, ol, lsyms, rsyms)
        ok = pred(pair) & pair.live
        return (
            jnp.zeros(pb.capacity, dtype=jnp.int32)
            .at[pr]
            .max(ok.astype(jnp.int32), mode="drop")
            .astype(bool)
        )

    jexists = _node_jit(node, "exists", lambda: exists_fn, static_argnames=("out_cap",))
    for pb_raw in probe_stream:
        pb, pba = chain_j(pb_raw)
        lo, counts, offsets, total, _, _ovf = counts_fn(table, pba)
        # chunk 0 dispatches while `total` travels to the host (see
        # _join_probe — same round-trip overlap)
        try:
            total.copy_to_host_async()
        except Exception:
            pass
        out_cap = ctx.config.join_out_capacity or pb.capacity
        exists_acc = jexists(table, pb, pba, lo, counts, offsets, 0, out_cap)
        tot = int(total)
        base = out_cap
        while base < tot:
            exists_acc = exists_acc | jexists(
                table, pb, pba, lo, counts, offsets, base, out_cap
            )
            base += out_cap
        keep = ~exists_acc if node.negated else exists_acc
        yield pb.with_live(pb.live & keep)


# -- set operations ---------------------------------------------------------


def _align_setop_dicts(node: SetOp, batches: List[Batch]) -> List[Batch]:
    """Re-encode string columns of all batches against shared merged
    dictionaries so code equality == string equality (the DictionaryBlock
    id-canonicalization the reference does inside set-operation hashing).
    Thin wrapper over _unify_batch_dicts, which stamps a dict-less side
    with the merged dictionary too."""
    out = _unify_batch_dicts(batches)
    # a side whose string column carries no dictionary (all-NULL) still
    # needs the shared one for decode
    for i, t in enumerate(node.types):
        if not t.is_string:
            continue
        name = node.symbols[i]
        ds = [b.dicts.get(name) for b in out if b.dicts.get(name) is not None]
        if ds:
            out = [b if name in b.dicts else
                   Batch(b.names, b.types, b.columns, b.live,
                         {**b.dicts, name: ds[0]})
                   for b in out]
    return out


def _null_safe_encode(b: Batch) -> Tuple[Batch, List[str]]:
    """Rows as join keys with NULLs-equal semantics (SQL DISTINCT / set-op
    equality treats NULL = NULL): every column contributes a zero-filled
    value key plus a validity-bit key, so build_side/probe never null-kill
    and NULL cells compare equal. Long decimals contribute their hi limb."""
    names, types, cols = [], [], []
    for i, c in enumerate(b.columns):
        base = f"k{i}"
        v = (c.values if c.validity is None
             else jnp.where(c.validity, c.values, jnp.zeros_like(c.values)))
        names.append(base)
        types.append(b.types[i])
        cols.append(Column(v, None))
        names.append(base + "$v")
        types.append(BIGINT)
        vb = (jnp.ones(b.capacity, jnp.int8) if c.validity is None
              else c.validity.astype(jnp.int8))
        cols.append(Column(vb.astype(jnp.int64), None))
        if c.hi is not None:
            hv = (c.hi if c.validity is None
                  else jnp.where(c.validity, c.hi, jnp.zeros_like(c.hi)))
            names.append(base + "$hi")
            types.append(BIGINT)
            cols.append(Column(hv, None))
    return Batch(names, types, cols, b.live, {}), names


def _distinct_rows(b: Batch) -> Batch:
    """Keep one row per distinct tuple (NULLs equal): sort by all null-safe
    key encodings, keep the first row of each run. Preserves full rows
    (validity + hi limbs) — unlike grouped_merge, which rebuilds columns."""
    enc, _ = _null_safe_encode(b)
    n = b.capacity
    operands = [(~b.live).astype(jnp.int32)] + [c.values for c in enc.columns]
    perm = jnp.arange(n, dtype=jnp.int32)
    sorted_ops = jax.lax.sort(operands + [perm], num_keys=len(operands))
    sperm = sorted_ops[-1]
    sdead = sorted_ops[0]
    first = jnp.zeros(n, dtype=bool).at[0].set(True)
    for sk in sorted_ops[:-1]:
        first = first.at[1:].set(first[1:] | (sk[1:] != sk[:-1]))
    from presto_tpu.ops.sort import permute_batch

    out = permute_batch(b, sperm)
    return out.with_live((sdead == 0) & first)


def _execute_setop(node: SetOp, ctx: ExecContext) -> Iterator[Batch]:
    """UNION [ALL] / INTERSECT / EXCEPT executor (reference: UnionNode is
    pass-through concat; INTERSECT/EXCEPT lower to mark-joins over hashed
    rows — here a null-safe membership probe over the whole row)."""
    syms = node.symbols

    def renamed(child):
        for b in execute_node(child, ctx):
            yield b.rename(syms)

    if node.all and node.kind == "union":  # UNION ALL: streaming concat
        yield from renamed(node.left)
        yield from renamed(node.right)
        return

    lb = _collect_concat(renamed(node.left))
    rb = _collect_concat(renamed(node.right))
    if node.kind == "union":
        sides = [b for b in (lb, rb) if b is not None]
        if not sides:
            return
        sides = _align_setop_dicts(node, sides)
        merged = sides[0] if len(sides) == 1 else _concat2(sides[0], sides[1])
        yield _node_jit(node, "distinct", lambda: _distinct_rows)(merged)
        return

    # INTERSECT / EXCEPT
    if lb is None:
        return
    if rb is None:
        if node.kind == "except":
            out = (lb if node.all
                   else _node_jit(node, "distinct", lambda: _distinct_rows)(lb))
            yield out
        return
    lb, rb = _align_setop_dicts(node, [lb, rb])

    if node.all:
        # multiset semantics (INTERSECT ALL / EXCEPT ALL): per distinct
        # row, emit min(cl, cr) / max(cl - cr, 0) copies. Row counting on
        # the host over the null-safe encodings, then ONE device gather of
        # the replicated row indices (set ops are gathered single-task;
        # the reference's row-number-marked joins serve the same shape)
        yield _multiset_setop(node, lb, rb)
        return

    def membership(lb: Batch, rb: Batch):
        ld = _distinct_rows(lb)
        lenc, keys = _null_safe_encode(ld)
        renc, _ = _null_safe_encode(rb)
        table = build_side(renc, tuple(keys))
        _, matched = probe_unique(table, lenc, tuple(keys), tuple(keys))
        keep = matched if node.kind == "intersect" else ~matched
        return ld.with_live(ld.live & keep)

    yield _node_jit(node, "membership", lambda: membership)(lb, rb)


# -- window -----------------------------------------------------------------


def _multiset_setop(node: SetOp, lb: Batch, rb: Batch) -> Batch:
    live_l = np.asarray(lb.live)
    orig_idx = np.nonzero(live_l)[0]
    lenc, _ = _null_safe_encode(lb)
    renc, _ = _null_safe_encode(rb)

    def rows_of(enc: Batch, live):
        cols = [np.asarray(c.values)[live] for c in enc.columns]
        return np.stack(cols, axis=1) if cols else np.zeros((int(live.sum()), 0))

    lrows = rows_of(lenc, live_l)
    rrows = rows_of(renc, np.asarray(rb.live))
    uniq, first_pos, lcnt = np.unique(lrows, axis=0, return_index=True,
                                      return_counts=True)
    rcounts: dict = {}
    for row in map(tuple, rrows):
        rcounts[row] = rcounts.get(row, 0) + 1
    reps = np.empty(len(uniq), np.int64)
    for i, row in enumerate(map(tuple, uniq)):
        cr = rcounts.get(row, 0)
        reps[i] = (min(int(lcnt[i]), cr) if node.kind == "intersect"
                   else max(int(lcnt[i]) - cr, 0))
    out_idx = np.repeat(orig_idx[first_pos], reps)
    n = len(out_idx)
    cap = round_up_capacity(max(n, 1))
    idx = np.zeros(cap, np.int32)
    idx[:n] = out_idx
    jidx = jnp.asarray(idx)
    cols = [c.gather(jidx) for c in lb.columns]
    live = np.zeros(cap, bool)
    live[:n] = True
    return Batch(lb.names, lb.types, cols, jnp.asarray(live), lb.dicts)


def _execute_window(node: Window, ctx: ExecContext) -> Iterator[Batch]:
    """Pipeline breaker: materialize the input, sort once by
    (partition keys, order keys), compute every function in the node's spec
    as closed-form vector ops (ops/window.py), emit one batch with the
    window columns appended (reference: WindowOperator.java:47 over a
    PagesIndex — here one lax.sort + O(n) vector passes)."""
    acc = _collect_concat(execute_node(node.child, ctx))
    if acc is None:
        return
    compute = build_window_compute(node)
    yield _node_jit(node, "window", lambda: compute)(acc)


def build_window_compute(node: Window):
    """Traceable batch → batch window computation (shared by the streaming
    executor and the mesh executor, which traces it inside shard_map)."""
    from presto_tpu.ops import window as W
    from presto_tpu.types import DecimalType as _Dec

    child_types = dict(node.child.output)

    def compute(b: Batch) -> Batch:
        keys = []
        for pk in node.partition_keys:
            c = b.column(pk)
            keys.append(SortKey(c.values, c.validity))
        for oi in node.order_items:
            c = b.column(oi.symbol)
            nf = oi.nulls_first
            if nf is None:
                nf = not oi.ascending  # SQL default: NULLS LAST for ASC
            keys.append(SortKey(c.values, c.validity, not oi.ascending, nf))
        perm = sort_permutation(keys, b.live)
        sb = permute_batch(b, perm)

        part_cols = [
            (sb.column(pk).values, sb.column(pk).validity)
            for pk in node.partition_keys
        ]
        order_cols = [
            (sb.column(oi.symbol).values, sb.column(oi.symbol).validity)
            for oi in node.order_items
        ]
        wk = W.window_keys(part_cols, order_cols, sb.live)

        rng_kw = {"order_vals": None}
        if (any(f.frame and f.frame.startswith("range:") for f in node.funcs)
                and node.order_items):
            # RANGE value offsets: the single order key, ascending-ized
            # (negated for DESC), kept in its NATIVE domain — int64 for
            # integral/decimal/date keys so boundary comparisons are
            # exact past 2^53; decimals compare unscaled with the OFFSET
            # scaled by 10^scale instead (see range_frame_bounds)
            oi = node.order_items[0]
            oc = sb.column(oi.symbol)
            ot = child_types.get(oi.symbol)
            ov = oc.values
            if jnp.issubdtype(ov.dtype, jnp.floating):
                ov = ov.astype(jnp.float64)
            else:
                ov = ov.astype(jnp.int64)
            if not oi.ascending:
                ov = -ov  # NaN survives negation; the kernel masks it
            nf = oi.nulls_first
            if nf is None:
                nf = not oi.ascending
            rng_kw = {
                "order_vals": ov,
                "order_valid": oc.validity,
                "nulls_first": nf,
                "offset_scale": 10 ** ot.scale if isinstance(ot, _Dec) else 1,
            }

        out = sb
        for f in node.funcs:
            if f.fn == "row_number":
                v, valid = W.row_number(wk)
            elif f.fn == "rank":
                v, valid = W.rank(wk)
            elif f.fn == "dense_rank":
                v, valid = W.dense_rank(wk)
            elif f.fn == "percent_rank":
                v, valid = W.percent_rank(wk)
            elif f.fn == "cume_dist":
                v, valid = W.cume_dist(wk)
            elif f.fn == "ntile":
                v, valid = W.ntile(wk, f.param)
            elif f.fn in ("lag", "lead", "first_value", "last_value", "nth_value"):
                c = sb.column(f.arg)
                bounded = f.frame is not None and f.frame.startswith(
                    ("rows:", "range:"))
                if f.fn == "lag":
                    v, valid = W.lag(wk, c.values, c.validity,
                                     f.param if f.param is not None else 1,
                                     f.default)
                elif f.fn == "lead":
                    v, valid = W.lead(wk, c.values, c.validity,
                                      f.param if f.param is not None else 1,
                                      f.default)
                elif bounded:
                    v, valid = W.value_over_frame(
                        wk, f.fn, c.values, c.validity, f.frame,
                        f.param if f.param is not None else 1, **rng_kw)
                elif f.fn == "first_value":
                    v, valid = W.first_value(wk, c.values, c.validity)
                elif f.fn == "last_value":
                    v, valid = W.last_value(wk, c.values, c.validity)
                else:
                    v, valid = W.nth_value(wk, c.values, c.validity, f.param)
            elif f.fn in ("sum", "avg", "min", "max", "count"):
                bounded = f.frame is not None and f.frame.startswith(
                    ("rows:", "range:"))
                if not node.order_items:
                    frame = "whole"
                elif f.frame == "rows_unbounded_current":
                    frame = "rows"
                else:
                    frame = "range"
                if bounded and f.arg is None:
                    v, valid = W.agg_window_bounded(
                        wk, "count", jnp.zeros(sb.capacity, jnp.int64), None,
                        f.frame, False, **rng_kw)
                elif f.arg is None:
                    v, valid = W.agg_window(
                        wk, "count", jnp.zeros(sb.capacity, jnp.int64), None,
                        frame, False,
                    )
                elif bounded:
                    c = sb.column(f.arg)
                    vals = c.values
                    arg_t = child_types.get(f.arg)
                    is_float = jnp.issubdtype(vals.dtype, jnp.floating)
                    if f.fn == "avg" and not is_float:
                        scale = arg_t.scale if isinstance(arg_t, _Dec) else 0
                        vals = vals.astype(jnp.float64) / (10.0 ** scale)
                        is_float = True
                    v, valid = W.agg_window_bounded(
                        wk, f.fn, vals, c.validity, f.frame, is_float,
                        **rng_kw)
                else:
                    c = sb.column(f.arg)
                    vals = c.values
                    arg_t = child_types.get(f.arg)
                    is_float = jnp.issubdtype(vals.dtype, jnp.floating)
                    if f.fn == "avg" and not is_float:
                        # avg computes in double (builder types avg → DOUBLE);
                        # decimals are unscaled ints — rescale on conversion
                        scale = arg_t.scale if isinstance(arg_t, _Dec) else 0
                        vals = vals.astype(jnp.float64) / (10.0 ** scale)
                        is_float = True
                    v, valid = W.agg_window(
                        wk, f.fn, vals, c.validity, frame, is_float
                    )
            else:
                raise NotImplementedError(f"window function {f.fn}")
            dict_ = None
            if f.arg is not None and f.type.is_string:
                dict_ = sb.dict_of(f.arg)
            out = out.with_column(
                f.symbol, f.type,
                Column(v.astype(f.type.dtype), valid), dictionary=dict_,
            )
        return out

    return compute


# -- sort / limit -----------------------------------------------------------


def _sort_keys(node: Sort, b: Batch) -> List[SortKey]:
    keys = []
    for k in node.keys:
        c = b.column(k.symbol)
        nulls_first = k.nulls_first
        if nulls_first is None:
            nulls_first = not k.ascending  # SQL default: NULLS LAST for ASC
        if c.hi is not None:
            # long decimal sorts lexicographically by (hi, lo): lo is the
            # canonical nonnegative low limb, so per-limb monotone encoding
            # composes into the int128 order
            keys.append(SortKey(c.hi, c.validity, not k.ascending, nulls_first))
        keys.append(SortKey(c.values, c.validity, not k.ascending, nulls_first))
    return keys


def _topn_step(node: Sort) -> Callable:
    """Traceable TopN stepping closure (chain → merge → sort → truncate),
    memoized on the node so the executor and the install-time breaker
    warmers hand _node_jit the SAME function object (one trace, one shared
    program). Derives everything from the node and its collapsed child
    chain — no runtime data captured."""
    memo = node.__dict__.get("_topn_step")
    if memo is not None:
        return memo
    _, chain0 = collapse_chain(node.child)
    chain = chain0 or (lambda b: b)
    cap = round_up_capacity(node.limit)

    def topn_step(acc: Optional[Batch], b: Batch):
        b = chain(b)
        if acc is not None:
            acc, b = _unify_batch_dicts([acc, b])
            merged = _concat2(acc, b)
        else:
            merged = b
        out = sort_batch(merged, _sort_keys(node, merged), limit=node.limit)
        return _truncate(out, cap)

    node.__dict__["_topn_step"] = topn_step
    return topn_step


def _execute_sort(node: Sort, ctx: ExecContext) -> Iterator[Batch]:
    in_stream, chain = _fused_child(node.child, ctx)
    if node.limit is not None:
        acc: Optional[Batch] = None
        topn_step = _topn_step(node)

        # acc is threaded linearly (the previous acc is dead once the step
        # returns, and only the final one is yielded), so its buffers are
        # donated for in-place update instead of double-buffering the heap
        _topn_kw = ({"donate_argnums": (0,)}
                    if ctx.config.donate_stepping else {})
        jstep = _node_jit(node, "topn", lambda: topn_step, **_topn_kw)
        frag_why = _fragment_eligibility(node, ctx.config)
        node.__dict__["_fragment_fusion"] = (
            "fused" if frag_why is None else frag_why)
        if frag_why is None:
            # fused fragment: fold the TopN step over stacked windows
            # on-device — the heap never overflows (capacity is the LIMIT)
            # so there is no confirm/replay protocol to thread through
            jfstep = _node_jit(
                node, "fragment_topn",
                lambda: _fragment_jit.topn_stepper(topn_step, False),
                **_topn_kw)
            jfstep0 = _node_jit(
                node, "fragment_topn0",
                lambda: _fragment_jit.topn_stepper(topn_step, True))
            src = _fragment_jit.WindowSource(
                in_stream, ctx.config.fragment_window,
                bucket=ctx.config.shape_bucketing != "off",
                on_window=_inflight_window_hook(node, ctx))
            try:
                for item in src:
                    if isinstance(item, _fragment_jit.Window):
                        t0 = time.time()
                        acc = (jfstep0(item.stacked) if acc is None
                               else jfstep(acc, item.stacked))
                        _record_fragment_dispatch(node, ctx, True, item.k)
                        if ctx.tracer.enabled:
                            ctx.tracer.record(
                                "fragment_step", "fragment_step", t0,
                                time.time(), batches=item.k,
                                width=item.width)
                    else:
                        acc = jstep(acc, item)
                        _record_fragment_dispatch(node, ctx, False)
            finally:
                src.close()
        else:
            for raw in in_stream:
                acc = jstep(acc, raw)
                _record_fragment_dispatch(node, ctx, False)
        if acc is not None:
            yield acc
        return

    jchain = _node_jit(node, "chain", lambda: chain)
    full = _collect_concat(jchain(b) for b in in_stream)
    if full is None:
        return
    yield _node_jit(node, "sort", lambda: (lambda b: sort_batch(b, _sort_keys(node, b))))(full)


def _concat2(a: Batch, b: Batch) -> Batch:
    caps = [a.capacity, b.capacity]
    cols = [
        concat_columns([a.columns[i], b.columns[i]], caps)
        for i in range(len(a.names))
    ]
    dicts = dict(a.dicts)
    dicts.update(b.dicts)
    return Batch(a.names, a.types, cols, jnp.concatenate([a.live, b.live]), dicts)


def _truncate(b: Batch, cap: int) -> Batch:
    cols = [slice_column(c, cap) for c in b.columns]
    return Batch(b.names, b.types, cols, b.live[:cap], b.dicts)


# ---------------------------------------------------------------------------
# plan entry


def bind_scalar_subqueries(qp: QueryPlan, ctx: ExecContext) -> None:
    """Execute the plan's uncorrelated scalar subqueries (each gathers to
    one value via the local streaming engine) and bind them as Constants —
    shared by run_plan, the coordinator and the mesh executor so the
    0-row/multi-row semantics can never diverge between engines."""
    if not qp.scalar_subqueries:
        return
    bindings = {}
    for sym, sub in qp.scalar_subqueries.items():
        sub_out = run_plan(sub, ctx)
        vals = sub_out.to_pydict(decode_strings=False)[sub_out.names[0]]
        if len(vals) != 1:
            raise RuntimeError(f"scalar subquery returned {len(vals)} rows")
        bindings[sym] = Constant(sub_out.types[0], vals[0], raw=True)
    _bind_plan_params(qp.root, bindings)


# breaker children pulled through _fused_child (their chain fuses into the
# breaker's own stepping programs — no separate "down" program exists for
# them, so precompiling one would be wasted work)
_FUSED_CHILD_SIDES = {
    Aggregate: (0,), Sort: (0,), Unnest: (0,),
    HashJoin: (0,), SemiJoin: (0,), NestedLoopJoin: (0,), IndexJoin: (0,),
}


def _scan_warm_cap(scan: TableScan, ctx: ExecContext) -> Optional[int]:
    """Eligibility + capacity for fabricating this scan's runtime batch
    structure ahead of the stream. VARCHAR columns ARE warmable when the
    table handle carries their (identity-stable) dictionary — the batch
    codes against that same object at run time, so the fabricated treedef
    matches. Decimals past 18 digits (hi-limb plane) and types without a
    static dtype stay unwarmable: their plane layout depends on decoded
    data."""
    from presto_tpu.types import DecimalType as _Dec

    if not scan.assignments:
        return None
    types = dict(scan.output)
    try:
        handle = ctx.catalog.connectors[scan.catalog].get_table(scan.table)
        nrows = int(handle.row_count or 0)
    except Exception:
        return None
    for sym, colname in scan.assignments.items():
        t = types[sym]
        if isinstance(t, _Dec) and t.precision > 18:
            return None
        try:
            t.dtype
        except Exception:
            return None
        if getattr(t, "is_string", False):
            try:
                if handle.column(colname).dictionary is None:
                    return None
            except Exception:
                return None
    return round_up_capacity(min(nrows, ctx.config.batch_rows) or 1)


def _fabricate_scan_batch(scan: TableScan, cap: int,
                          ctx: ExecContext) -> Optional[Batch]:
    """Zero-filled batch with the same pytree STRUCTURE runtime scan
    batches will have: per-column dtype, validity-plane presence (stats
    null_fraction hint), and the handle's own Dictionary objects (treedef
    identity — Dictionary equality is `is`)."""
    types = dict(scan.output)
    try:
        handle = ctx.catalog.connectors[scan.catalog].get_table(scan.table)
    except Exception:
        return None
    names, btypes, cols, dicts = [], [], [], {}
    for sym, colname in scan.assignments.items():
        t = types[sym]
        try:
            info = handle.column(colname)
        except Exception:
            info = None
        st = info.stats if info is not None else None
        validity = (jnp.ones(cap, dtype=bool)
                    if st is not None and (st.null_fraction or 0.0) > 0.0
                    else None)
        d = info.dictionary if info is not None else None
        if getattr(t, "is_string", False) and d is None:
            return None
        if d is not None:
            dicts[sym] = d
        names.append(sym)
        btypes.append(t)
        cols.append(Column(jnp.zeros(cap, t.dtype), validity))
    return Batch(names, btypes, cols, jnp.zeros(cap, dtype=bool), dicts)


def _chain_warmers(root: PlanNode, ctx: ExecContext) -> List[Callable]:
    """Warm tasks for ahead-of-stream precompilation: the scan-side fused
    chain programs execute_node will jit under key "down", plus the
    breaker step / fused fragment-step programs of Aggregate and TopN
    nodes whose collapsed child base is a warmable TableScan (their chain
    fuses INTO the stepping programs, so the breaker warm is the only way
    those chains precompile). Best-effort by contract: a missed warm only
    means the compile happens on batch 0, as it did before the compile
    plane existed; a structurally-wrong fabrication compiles one unused
    specialization."""
    tasks: List[Callable] = []

    def breaker_scan(n: PlanNode) -> Optional[Tuple[TableScan, int]]:
        try:
            base, _ = collapse_chain(n.child)
        except Exception:
            return None
        if not isinstance(base, TableScan):
            return None
        cap = _scan_warm_cap(base, ctx)
        return None if cap is None else (base, cap)

    def visit(n: PlanNode, top: bool):
        if isinstance(n, (Filter, Project)):
            base, down = collapse_chain(n)
            if top and down is not None and isinstance(base, TableScan):
                cap = _scan_warm_cap(base, ctx)
                if cap is not None:
                    tasks.append(partial(_warm_down_chain, n, down, base, cap))
            visit(base, False)
            return
        if (isinstance(n, Aggregate)
                and not any(a.fn in _NON_DECOMPOSABLE_FNS for a in n.aggs)):
            hit = breaker_scan(n)
            if hit is not None:
                tasks.append(partial(_warm_agg_breaker, n, *hit, ctx))
        elif isinstance(n, Sort) and n.limit is not None:
            hit = breaker_scan(n)
            if hit is not None:
                tasks.append(partial(_warm_topn_breaker, n, *hit, ctx))
        fused = _FUSED_CHILD_SIDES.get(type(n), ())
        for i, c in enumerate(n.children()):
            visit(c, i not in fused)

    visit(root, True)
    return tasks


def _warm_down_chain(node: PlanNode, down, scan: TableScan, cap: int,
                     ctx: Optional[ExecContext] = None) -> None:
    if ctx is not None:
        zb = _fabricate_scan_batch(scan, cap, ctx)
    else:
        types = dict(scan.output)
        syms = list(scan.assignments.keys())
        zb = Batch(syms, [types[s] for s in syms],
                   [Column(jnp.zeros(cap, types[s].dtype), None)
                    for s in syms],
                   jnp.zeros(cap, bool), {})
    if zb is None:
        return
    out = _node_jit(node, "down", lambda: down)(zb)
    jax.block_until_ready(out.live)


def _warm_agg_breaker(node: Aggregate, scan: TableScan, scan_cap: int,
                      ctx: ExecContext) -> None:
    """Warm the Aggregate breaker's step/step0 (and, when the fragment
    fuses, fragment_step/fragment_step0) programs at the runtime presize
    fingerprint. The builders come from the SAME memoized _agg_steps
    closures and _node_jit keys the executor will use, so the warm and
    the run share one trace and one compiled program. Modes whose ingest
    never uses these programs (grace-from-start, radix engagement,
    grouped-execution sweeps) are skipped rather than guessed at."""
    if _grouped_execution_lifespans(node):
        return
    cap, ceiling, can_spill, grace_from_start = _agg_presize(node, ctx)
    if grace_from_start:
        return
    # same engine chooser as the run (no counter bump: warming is not a
    # dispatch) so the warm compiles the programs the run will use
    engine = _breaker_engine_choice(node, ctx, record=False)
    _ek = lambda k: _engine_key(k, engine)  # noqa: E731
    steps = _agg_steps(node, engine)
    merge_step = steps.merge_step
    key_syms = steps.key_syms
    if (key_syms and ctx.config.radix_partitions > 1
            and (ctx.config.join_spill_budget_bytes is not None
                 or cap > ctx.config.agg_capacity)):
        return  # radix ingest uses the prechained step family instead
    zb = _fabricate_scan_batch(scan, scan_cap, ctx)
    if zb is None:
        return
    _step_jit_kw = {}
    if ctx.config.donate_stepping and not key_syms:
        _step_jit_kw["donate_argnums"] = (0,)
    jit_step = _node_jit(node, _ek("step"), lambda: (lambda acc, b, cap: merge_step(acc, b, cap)), static_argnums=(2,), **_step_jit_kw)
    jit_step0 = _node_jit(node, _ek("step0"), lambda: (lambda b, cap: merge_step(None, b, cap)), static_argnums=(1,))
    acc, _ = jit_step0(zb, cap)
    acc, _ = jit_step(acc, zb, cap)
    if _fragment_eligibility(node, ctx.config) is None:
        stacked = _fragment_jit.stack_batches(
            [zb] * max(2, ctx.config.fragment_window))
        jit_frag_step = _node_jit(
            node, _ek("fragment_step"),
            lambda: _fragment_jit.scan_stepper(merge_step, False),
            static_argnums=(2,), **_step_jit_kw)
        jit_frag_step0 = _node_jit(
            node, _ek("fragment_step0"),
            lambda: _fragment_jit.scan_stepper(merge_step, True),
            static_argnums=(1,))
        facc, _ = jit_frag_step0(stacked, cap)
        facc, _ = jit_frag_step(facc, stacked, cap)
        jax.block_until_ready(facc.live)
    jax.block_until_ready(acc.live)


def _warm_topn_breaker(node: Sort, scan: TableScan, scan_cap: int,
                       ctx: ExecContext) -> None:
    """Warm the TopN breaker's stepping programs (per-batch and, when the
    fragment fuses, the stacked-window variants) from a fabricated scan
    batch — same memoized _topn_step closure and _node_jit keys as the
    executor."""
    zb = _fabricate_scan_batch(scan, scan_cap, ctx)
    if zb is None:
        return
    topn_step = _topn_step(node)
    _topn_kw = ({"donate_argnums": (0,)}
                if ctx.config.donate_stepping else {})
    jstep = _node_jit(node, "topn", lambda: topn_step, **_topn_kw)
    acc = jstep(None, zb)
    acc = jstep(acc, zb)
    if _fragment_eligibility(node, ctx.config) is None:
        stacked = _fragment_jit.stack_batches(
            [zb] * max(2, ctx.config.fragment_window))
        jfstep = _node_jit(
            node, "fragment_topn",
            lambda: _fragment_jit.topn_stepper(topn_step, False),
            **_topn_kw)
        jfstep0 = _node_jit(
            node, "fragment_topn0",
            lambda: _fragment_jit.topn_stepper(topn_step, True))
        facc = jfstep0(stacked)
        facc = jfstep(facc, stacked)
        jax.block_until_ready(facc.live)
    jax.block_until_ready(acc.live)


def install_plan_programs(root: PlanNode, ctx: ExecContext) -> None:
    """Compile-plane entry point for a bound, fully-rewritten plan: stamp
    every node's structural program namespace (so _node_jit shares
    programs process-wide) and, when configured, kick off ahead-of-stream
    precompilation so scan-chain compiles overlap host decode. Call after
    every structure-mutating pass (subquery binding, colocation tagging,
    fragment decode)."""
    _programs.install_plan(root, ctx.config)
    if getattr(ctx.config, "devprof", "off") == "on":
        from presto_tpu.obs import devprof as _devprof

        _devprof.activate()
    try:
        _mark_fragment_fusion(root, ctx.config)
    except Exception:
        pass  # cosmetic EXPLAIN marker; the executor re-stamps on run
    try:
        _mark_breaker_engines(root, ctx)
    except Exception:
        pass  # cosmetic EXPLAIN marker; the executor re-stamps on run
    if _farm.enabled(ctx.config):
        try:
            _farm.record_plan(root, ctx)
        except Exception:
            pass  # corpus write is advisory; never fail an install on it
    if ctx.config.precompile_workers > 0:
        warmers = _chain_warmers(root, ctx)
        if _farm.enabled(ctx.config):
            warmers = _farm.wrap_claims(warmers)
        _programs.submit_warmers(warmers, ctx.config.precompile_workers)


def _mark_breaker_engines(root: PlanNode, ctx: "ExecContext") -> None:
    """Stamp the CBO's breaker-engine verdict (sort | hash + rationale)
    on every engine-dimensioned breaker so EXPLAIN (without ANALYZE)
    already shows it; the executors re-stamp on run (adding per-query
    gates like a build-batch dtype deviation) and bump the dispatch
    counters there."""

    def visit(n: PlanNode):
        if isinstance(n, (Aggregate, HashJoin, SemiJoin)):
            _breaker_engine_choice(n, ctx, record=False)
        for c in n.children():
            visit(c)

    visit(root)


def _mark_fragment_fusion(root: PlanNode, config: ExecConfig) -> None:
    """Stamp the static fragment-fusion eligibility verdict on every
    breaker so EXPLAIN (without ANALYZE) already shows which fragments
    will fuse; executors overwrite with the runtime decision (which adds
    per-query gates like grace-from-start)."""

    def visit(n: PlanNode):
        if isinstance(n, (Aggregate, Sort)):
            why = _fragment_eligibility(n, config)
            n.__dict__["_fragment_fusion"] = (
                "fused" if why is None else why)
        for c in n.children():
            visit(c)

    visit(root)


def run_plan(qp: QueryPlan, ctx: ExecContext) -> Batch:
    """Execute a QueryPlan to a single host-collectable Batch."""
    try:
        with _obs_trace.use(ctx.tracer), ctx.tracer.span("query", "query"):
            if getattr(ctx.config, "devprof", "off") != "on":
                return _run_plan_inner(qp, ctx)
            # devprof plane: HBM watermarks at the query span boundaries
            # plus a ledger-vs-device reconciliation once the query's pool
            # peak is final (obs/devprof.py; activate happens at plan
            # install)
            from presto_tpu.obs import devprof as _devprof

            _devprof.activate()
            _devprof.sample_hbm(tag="query_start")
            try:
                return _run_plan_inner(qp, ctx)
            finally:
                _devprof.sample_hbm(tag="query_end")
                try:
                    _devprof.reconcile(ctx.memory_pool, plane="worker",
                                       site="local_query")
                except Exception:
                    pass
    finally:
        # spill-file leak guard: whatever the operator generators left
        # open (mid-spill failure, abandoned iterator) is closed+unlinked
        ctx.cleanup_spill()


def _run_plan_inner(qp: QueryPlan, ctx: ExecContext) -> Batch:
    bind_scalar_subqueries(qp, ctx)

    # local grouped execution: mark bucket-colocated joins so the executor
    # sweeps them lifespan-by-lifespan (the fragmenter does this for the
    # distributed path); tagged once — cached plans skip the re-walk
    if not qp.__dict__.get("_colocated_tagged"):
        from presto_tpu.plan.fragmenter import tag_colocated_joins

        tag_colocated_joins(qp.root, ctx.catalog)
        qp.__dict__["_colocated_tagged"] = True

    # stamp structural program namespaces once the plan is fully bound
    # (subqueries bound, colocation tagged); re-stamped only when the
    # config's program-relevant fields change
    cfg_fp = _programs.config_fingerprint(ctx.config)
    if qp.__dict__.get("_programs_installed") != cfg_fp:
        install_plan_programs(qp.root, ctx)
        qp.__dict__["_programs_installed"] = cfg_fp

    out_node = qp.root
    batches = list(execute_node(out_node.child, ctx))
    _hbo_record_scans(qp.root, ctx)
    merged = _collect_concat(iter(batches))
    if merged is None:
        types = dict(out_node.child.output)
        merged = Batch(
            out_node.symbols,
            [types[s] for s in out_node.symbols],
            [Column(jnp.zeros(128, types[s].dtype), None) for s in out_node.symbols],
            jnp.zeros(128, bool),
            {},
        )
    merged = merged.select(out_node.symbols).rename(out_node.names)
    out = _JIT_COMPACT(merged)
    cfg = ctx.config
    if (cfg.max_compiled_shapes or cfg.max_compiled_shapes_scan
            or cfg.max_compiled_shapes_breaker):
        from presto_tpu.analysis.recompile import enforce

        enforce(qp.root, cfg.max_compiled_shapes,
                scan_budget=cfg.max_compiled_shapes_scan,
                breaker_budget=cfg.max_compiled_shapes_breaker)
    return out


def _bind_plan_params(node: PlanNode, bindings):
    if isinstance(node, Filter):
        node.predicate = substitute_params(node.predicate, bindings)
    elif isinstance(node, Project):
        node.exprs = [(s, substitute_params(e, bindings)) for s, e in node.exprs]
    elif isinstance(node, HashJoin) and node.residual is not None:
        node.residual = substitute_params(node.residual, bindings)
    for c in node.children():
        _bind_plan_params(c, bindings)
