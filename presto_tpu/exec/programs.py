"""Process-wide compile plane: structural program cache + precompilation.

Reference: the reference engine treats generated code as a shared cached
artifact — ExpressionCompiler / PageFunctionCompiler generated classes are
keyed by expression structure and reused across every execution of the
same plan shape. `_node_jit` (exec/runtime.py) used to key programs on the
plan-node *object*, so identical filter chains, probe programs and agg
steppers re-traced and re-compiled per node, per fragment, per concurrent
task in the shared-process cluster, and per query. This module gives the
runtime the missing process-wide layer:

- ``install_plan`` stamps every node of a bound plan with a *structural
  namespace*: sha256 over the plan codec JSON of the node's subtree (the
  canonical wire encoding — fused chains, constants, key symbols and
  child schemas included) plus a fingerprint of the program-relevant
  ExecConfig fields. Two nodes (in one plan, two tasks, or two queries)
  whose subtrees and configs encode identically share a namespace.
- ``entry_for`` resolves (namespace, node kind, program key, jit kwargs)
  to ONE process-wide :class:`ProgramEntry` holding the ``jax.jit``
  wrapper, so the underlying program traces and compiles exactly once
  per structural identity; per-node ``_jit_stats`` stay per-node views
  (EXPLAIN ANALYZE and the recompile guard keep node attribution).
- compile accounting moved here under a per-entry lock fixes the
  ``_cache_size()`` before/after race of the old wrapper: concurrent
  callers claim the cache-size delta exactly once.
- ``warm_chain_programs`` precompiles scan-side fused chain programs
  ahead of the stream on a small thread pool, so trace+compile overlaps
  host-side scan decode instead of serializing in front of batch 0.

Nodes NOT stamped (hand-built nodes in tests, runtime shims, nodes whose
builders capture runtime data) fall back to a private per-node entry with
the same locked accounting — sharing is opt-in via the stamp.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

# ExecConfig fields that never change what a traced program computes —
# excluded from the config fingerprint so toggling observability or
# host-side policy knobs does not fork the program cache. Everything NOT
# listed here is conservatively part of the structural identity (e.g.
# radix_partitions is baked into split closures, batch_rows shapes the
# merging-output rebucketing).
_VOLATILE_CONFIG_FIELDS = frozenset({
    "collect_stats", "tracing", "memory_pool_bytes", "spill_dir",
    "scan_prefetch", "query_retry_count", "execution_policy",
    "recoverable_grouped_execution", "phase_wait_timeout_s",
    "split_affinity", "max_compiled_shapes", "max_compiled_shapes_scan",
    "max_compiled_shapes_breaker", "precompile_workers",
    # fragment fusion selects WHICH programs dispatch (fused window vs
    # per-batch), never what any one program computes; window width only
    # shapes the stacked inputs, which jit keys on dynamically
    "fragment_fusion", "fragment_window",
    # hbo picks BETWEEN programs (engine keys fork via the @h suffix) and
    # adjusts capacities (static args), never what one program computes
    "hbo",
    # devprof observes compiles and samples device memory; profile wraps
    # a query in a jax.profiler capture — neither changes any program
    "devprof", "profile",
    # the result cache elides whole executions; any program that DOES run
    # computes exactly what it would with the cache off
    "result_cache",
    # shape bucketing changes WHICH avals reach a program (padding with
    # dead lanes), never what the program computes per aval — jit keys
    # on the shapes dynamically; the farm only pre-runs the same
    # programs the live path would compile
    "shape_bucketing", "compile_farm",
    # adaptive picks BETWEEN programs mid-run: a flipped breaker engine
    # forks program keys via the @h suffix and grown capacities are
    # static args — no one program ever computes differently under it
    "adaptive",
})

# env vars that change what a traced program COMPUTES (not where
# artifacts live or how many workers warm them) and therefore fork the
# config fingerprint: PRESTO_TPU_PALLAS selects the Pallas direct-merge
# kernel inside the grouped-merge dispatch, under what would otherwise
# be the same program key. Every other PRESTO_TPU_* knob is
# cache-volatile — the knob-flow pass (analysis/knob_flow.py) enforces
# that every env read is declared in exactly one of the two classes.
_FINGERPRINTED_ENVS = ("PRESTO_TPU_PALLAS",)

# program cache bound: one entry is one (structure, program key) identity;
# a TPC-H query compiles ~10-60 of them, so 512 holds many live plans
# before LRU eviction (an evicted entry keeps working for nodes already
# holding its wrapper — it just stops being shared with new nodes)
_MAX_ENTRIES = 512


class ProgramEntry:
    """One structurally-keyed program: the jit wrapper + locked compile
    accounting shared by every node that maps to it."""

    __slots__ = ("jfn", "lock", "seen_cache_size", "compiles",
                 "compile_wall_s", "calls", "fp", "restored", "statics",
                 "ready")

    def __init__(self, jfn, fp: Optional[str] = None,
                 statics: tuple = ((), ())):
        self.jfn = jfn
        # set once artifact restore has run (or was skipped): a caller
        # racing the creating thread waits on this instead of paying a
        # fresh trace while the restored program is mid-deserialize.
        # None = no restore will happen (private entry / no persist dir)
        self.ready = None
        # (static_argnums, static_argnames) of the jit: a jax.export
        # artifact bakes statics into the program, so its call signature
        # is the DYNAMIC args only — the restored-call path must drop
        # these positions/names before dispatching
        self.statics = statics
        # registry key for shared entries (None = private): the devprof
        # plane keys its per-program cost/memory analysis on this
        self.fp = fp
        self.lock = threading.Lock()
        # last observed jfn._cache_size(): compile detection claims the
        # delta under the lock, so two concurrent callers never double-
        # or under-count (the race the per-call before/after pattern had)
        self.seen_cache_size = 0   # shared: guarded-by(self.lock)
        self.compiles = 0          # shared: guarded-by(self.lock)
        self.compile_wall_s = 0.0  # shared: guarded-by(self.lock)
        self.calls = 0             # shared: guarded-by(self.lock)
        # avals-key → callable restored from a persisted jax.export
        # artifact (warm restart skips re-trace); None until populated
        self.restored = None       # shared: guarded-by(self.lock)


_lock = threading.Lock()
_entries: "OrderedDict[str, ProgramEntry]" = OrderedDict()  # shared: guarded-by(_lock)
_counters: Dict[str, int] = {  # shared: guarded-by(_lock)
    # structural lookups that found an existing shared program
    "hits": 0,
    # structural lookups that created a new shared program entry
    "misses": 0,
    # XLA trace+compile events observed through any entry (shared or
    # private) — the process-wide "how much compiling happened" truth
    "compiles": 0,
    # programs restored from PRESTO_TPU_CACHE_DIR persisted artifacts
    # (warm restart skipped their re-trace)
    "restored": 0,
    # restored split (the honest contract made precise): _executable
    # means the XLA persistent compilation cache is armed, so the first
    # call's backend compile is served from disk; _retrace means the
    # restored StableHLO still re-pays backend compilation
    "restored_executable": 0,
    "restored_retrace": 0,
    # persisted artifacts eagerly deserialized + executed once at farm
    # boot, so their backend compile is paid before traffic arrives (the
    # CPU backend bypasses the persistent executable cache — see
    # presto_tpu/__init__.py — which would otherwise leave that cost on
    # the first live call of every restored program)
    "prewarmed": 0,
}
_trace_wall_s = [0.0]  # shared: guarded-by(_lock)


def config_fingerprint(config) -> str:  # fp: key(program-ns) covers(config, plan-structure, env:PRESTO_TPU_PALLAS)
    """Stable digest of the program-relevant ExecConfig fields plus the
    program-affecting env knobs (_FINGERPRINTED_ENVS)."""
    import dataclasses

    items = []
    for f in dataclasses.fields(config):
        if f.name in _VOLATILE_CONFIG_FIELDS:
            continue
        items.append((f.name, repr(getattr(config, f.name, None))))
    for env in _FINGERPRINTED_ENVS:
        items.append((f"env:{env}", os.environ.get(env, "")))
    return hashlib.sha256(repr(sorted(items)).encode()).hexdigest()[:16]


def structural_fingerprint(node, config=None) -> Optional[str]:
    """sha256 namespace for one plan node: the codec's canonical JSON of
    its subtree (survives a wire round trip because strip_runtime_state
    keeps plans runtime-state-free) plus the config fingerprint. None
    when the subtree has no codec encoding."""
    from presto_tpu.plan.codec import CodecError, canonical_node_json

    try:
        doc = canonical_node_json(node)
    except (CodecError, TypeError, ValueError):
        return None
    h = hashlib.sha256(doc.encode())
    if config is not None:
        h.update(config_fingerprint(config).encode())
    return h.hexdigest()


def install_plan(root, config) -> int:  # fp: uses-key(program-ns)
    """Stamp every node under `root` with its structural namespace
    (``_program_ns``) so `_node_jit` routes programs through the shared
    cache. Call AFTER scalar-subquery binding and colocation tagging —
    both mutate plan structure the fingerprint must cover. Underscore
    attrs are stripped by the plan codec / strip_runtime_state, so stamps
    never travel on the wire. Returns the number of nodes stamped."""
    cfg_fp = config_fingerprint(config)
    stamped = 0

    def walk(n):
        nonlocal stamped
        ns = structural_fingerprint(n)
        if ns is not None:
            n.__dict__["_program_ns"] = ns + cfg_fp
            stamped += 1
        for c in n.children():
            walk(c)

    walk(root)
    return stamped


def _as_tuple(v) -> tuple:
    if v is None:
        return ()
    if isinstance(v, (int, str)):
        return (v,)
    return tuple(v)


def entry_for(ns: Optional[str], node_kind: str, key: str,
              jit_kwargs: dict, make: Callable[[], object]) -> ProgramEntry:
    """The shared ProgramEntry for (namespace, kind, program key, jit
    kwargs), creating it with `make()` on first use. ns None → a private
    unregistered entry (per-node semantics, shared accounting fix)."""
    if ns is None:
        return ProgramEntry(make())
    fp = f"{ns}|{node_kind}|{key}|{sorted(jit_kwargs.items())!r}"
    statics = (_as_tuple(jit_kwargs.get("static_argnums")),
               _as_tuple(jit_kwargs.get("static_argnames")))
    created = None
    with _lock:
        e = _entries.get(fp)
        if e is not None:
            _entries.move_to_end(fp)
            _counters["hits"] += 1
            return e
        # constructing jax.jit() is cheap (no trace happens here), so the
        # critical section stays small even on a miss
        e = created = _entries[fp] = ProgramEntry(make(), fp=fp,
                                                  statics=statics)
        if _persist_dir() is not None:
            e.ready = threading.Event()
        _counters["misses"] += 1
        while len(_entries) > _MAX_ENTRIES:
            _entries.popitem(last=False)
    # file IO stays outside the registry lock; a racing caller that grabs
    # the entry before restore lands just falls through to jfn
    _restore_programs(created)
    return e


# -- persisted programs (warm restart skips re-trace) ------------------------
#
# The structural namespace is a stable cross-process key, so a compiled
# program's jax.export artifact can be written once and re-loaded by a
# fresh process. The honest contract on CPU (and anywhere XLA executables
# don't persist): deserialization skips Python re-TRACE; backend
# compilation of the restored StableHLO still happens on first call.
# Everything is best-effort and double-gated (cache dir set AND
# PRESTO_TPU_PROGRAM_PERSIST=1) so the default path has zero overhead.


def _persist_dir() -> Optional[str]:
    import os

    d = os.environ.get("PRESTO_TPU_CACHE_DIR")
    if not d or os.environ.get("PRESTO_TPU_PROGRAM_PERSIST") != "1":
        return None
    return os.path.join(d, "programs")


_compilation_cache_state = [None]  # shared: guarded-by(_lock); None=untried


def enable_compilation_cache() -> bool:
    """Arm the XLA persistent compilation cache under the same
    PRESTO_TPU_CACHE_DIR umbrella as the jax.export artifacts, so a
    restored program's first call fetches its backend executable from
    disk instead of re-compiling the StableHLO. Idempotent and
    best-effort: where jax/the backend doesn't support it the restore
    path keeps working and reports honestly as ``restored_retrace``."""
    import os

    d = _persist_dir()
    if d is None:
        return False
    with _lock:
        if _compilation_cache_state[0] is not None:
            return _compilation_cache_state[0]
    ok = False
    try:
        import jax

        cache_dir = os.path.join(os.path.dirname(d), "xla_cache")
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # engine programs are often tiny (CPU lowers them in ms); persist
        # everything so the compile-tail win doesn't depend on thresholds
        try:
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0)
        except Exception:
            pass
        try:
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", 0)
        except Exception:
            pass
        ok = True
    except Exception:
        ok = False
    with _lock:
        # racing enablers run the same idempotent jax.config updates;
        # last writer records the same verdict
        _compilation_cache_state[0] = ok  # lint: allow(check-then-act)
    return ok


def compilation_cache_active() -> bool:
    with _lock:
        return bool(_compilation_cache_state[0])


_pytree_serialization_ready = False  # shared: guarded-by(_pytree_ser_lock)
_pytree_ser_lock = threading.Lock()


def _ensure_pytree_serialization() -> None:
    """jax.export serializes the calling-convention pytrees; Batch/Column
    are custom nodes and need a one-time serialization registration. Their
    auxdata (names, types, dictionary pages) is plain static metadata, so
    pickle round-trips it."""
    global _pytree_serialization_ready
    # dedicated lock, and the registrations happen INSIDE it: a second
    # caller (concurrent farm boot worker) must block until every node
    # type is registered, or its deserialize sees "unregistered type"
    # and silently downgrades restore to a re-compile
    with _pytree_ser_lock:
        if _pytree_serialization_ready:
            return
        # the flag latches only on FULL success: a registration attempt
        # can lose an import race against a thread lazily importing an
        # ops module (importlib raises on cross-thread circular waits),
        # and latching a partial registration would permanently break
        # deserialization of every artifact carrying the missing type
        _pytree_serialization_ready = _register_pytree_serialization()


def _register_pytree_serialization() -> bool:
    try:
        import pickle

        from jax import export as jax_export

        from presto_tpu.batch import Batch, Column

        def reg(fn, cls, name, **kw):
            try:
                fn(cls, serialized_name=name, **kw)
            except ValueError:
                pass  # already registered by an earlier partial attempt

        reg(jax_export.register_pytree_node_serialization,
            Batch, "presto_tpu.batch.Batch",
            serialize_auxdata=pickle.dumps,
            deserialize_auxdata=pickle.loads)
        reg(jax_export.register_pytree_node_serialization,
            Column, "presto_tpu.batch.Column",
            serialize_auxdata=pickle.dumps,
            deserialize_auxdata=pickle.loads)
        # operator-state NamedTuples that cross program boundaries (join
        # build tables, agg accumulators, sort keys, window boundary
        # structures)
        ok = True
        for mod, names in (
                ("presto_tpu.ops.join",
                 ("BuildTable", "HashJoinTable", "MwSpec")),
                ("presto_tpu.ops.grouping", ("StateCol", "KeyCol")),
                ("presto_tpu.ops.sort", ("SortKey",)),
                ("presto_tpu.ops.window", ("WindowKeys",)),
                ("presto_tpu.expr.geo", ("Geom", "GeomVal")),
                ("presto_tpu.expr.structural", ("StructVal",))):
            try:
                import importlib

                m = importlib.import_module(mod)
                for name in names:
                    reg(jax_export.register_namedtuple_serialization,
                        getattr(m, name), f"{mod}.{name}")
            except Exception:
                ok = False  # import race / missing module: retry later
        return ok
    except Exception:
        return False


def _avals_key(args, kw) -> str:
    """16-hex digest of the call's abstract signature (tree structure +
    leaf shapes/dtypes) — one persisted artifact per traced shape."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kw))
    # repr(treedef) renders Batch aux, including Dictionary objects —
    # Dictionary.__repr__ is content-addressed precisely so this key is
    # stable across processes (artifact restore depends on it)
    sig = [repr(treedef)]
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            sig.append(f"{tuple(leaf.shape)}:{leaf.dtype}")
        else:
            # non-array leaf (a static: capacity int, key-name string,
            # ...) — its VALUE selects the program, not just its type
            sig.append(f"{type(leaf).__name__}={leaf!r}")
    return hashlib.sha256("|".join(sig).encode()).hexdigest()[:16]


def _artifact_prefix(fp: str) -> str:
    return hashlib.sha256(fp.encode()).hexdigest()[:24]


def _persist_program(entry: ProgramEntry, args, kw) -> None:
    """Serialize the program that just compiled for these args. Failures
    (unexportable closure, read-only dir, no jax.export) are swallowed —
    persistence is an optimization, never a correctness dependency."""
    import os

    d = _persist_dir()
    if d is None or entry.fp is None:
        return
    _ensure_pytree_serialization()
    enable_compilation_cache()
    try:
        # submodule: not reachable as an attribute on older jax
        from jax import export as jax_export

        data = jax_export.export(entry.jfn)(*args, **kw).serialize()
        os.makedirs(d, exist_ok=True)
        path = os.path.join(
            d, _artifact_prefix(entry.fp) + "." + _avals_key(args, kw)
            + ".jaxexp")
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except Exception:
        pass


def _restored_caller(exp):
    """Call an Exported through its own in_tree. Exported.call compares
    the invocation treedef against the serialized one by EQUALITY, and
    Batch aux carries identity-compared objects (Dictionary), so a
    deserialized artifact would never match live args directly. The live
    call's avals key already proved the structures agree (same repr), so
    re-threading the live leaves through exp.in_tree is sound — and makes
    the flatten/compare inside exp.call a tautology. A genuine structure
    drift surfaces as a leaf-count mismatch here, which the restored-call
    path catches and routes to a fresh trace."""

    def call(*args, **kw):
        import jax

        leaves = jax.tree_util.tree_leaves((args, kw))
        if len(leaves) != exp.in_tree.num_leaves:
            # statics the caller could not strip (static_argnames bound
            # POSITIONALLY still count as static to jit) flatten to
            # python scalars/strings; the exported program baked them.
            # Keep the array leaves — a residual mismatch raises in
            # unflatten and routes the call to a fresh trace.
            leaves = [l for l in leaves
                      if hasattr(l, "shape") and hasattr(l, "dtype")]
        a2, k2 = jax.tree_util.tree_unflatten(exp.in_tree, leaves)
        return exp.call(*a2, **k2)

    call._exported = exp
    return call


# artifact filename → restored caller, shared process-wide so every
# entry restoring the same artifact — and the boot prewarm pass — reuse
# ONE Exported object. jax caches the backend executable on that object,
# so the compile happens once per process no matter how many entries
# (fragment/final variants of the same structure) restore the file.
_artifact_cache: "OrderedDict[str, Any]" = OrderedDict()  # shared: guarded-by(_artifact_lock)
_artifact_lock = threading.Lock()
_MAX_ARTIFACTS = 1024


def _artifact_caller(d: str, fn: str):
    import os

    with _artifact_lock:
        c = _artifact_cache.get(fn)
        if c is not None:
            _artifact_cache.move_to_end(fn)
            return c
    from jax import export as jax_export

    with open(os.path.join(d, fn), "rb") as f:
        c = _restored_caller(jax_export.deserialize(f.read()))
    with _artifact_lock:
        # a racer may have deserialized the same file: keep the first
        # published caller so its warmed executable is the one reused
        hit = _artifact_cache.get(fn)
        if hit is not None:
            return hit
        # membership re-validated two lines up inside THIS critical
        # section; the first-section probe was only a fast path
        _artifact_cache[fn] = c  # lint: allow(check-then-act)
        while len(_artifact_cache) > _MAX_ARTIFACTS:
            _artifact_cache.popitem(last=False)  # lint: allow(check-then-act)
    return c


def prewarm_artifacts(threads: int = 2,
                      limit: Optional[int] = None) -> int:
    """Deserialize every persisted artifact and execute it once on
    zero-filled inputs, forcing its backend compile NOW (boot) instead of
    on the first live call. Lazy restore alone is not enough: entries are
    created lazily by traffic, so a farm boot that only warms corpus-plan
    programs leaves the fragment/final/sort variants paying their XLA
    backend compile on the query path (measured: ~8 s first-query compile
    segment on a fully-restored boot). The zero-filled call is safe — the
    programs are pure array code — and its output is discarded. Returns
    the number of artifacts warmed; failures are skipped (best-effort,
    same contract as restore)."""
    import os

    d = _persist_dir()
    if d is None:
        return 0
    _ensure_pytree_serialization()
    enable_compilation_cache()
    try:
        files = sorted(fn for fn in os.listdir(d)
                       if fn.endswith(".jaxexp"))
    except OSError:
        return 0
    if limit is not None:
        files = files[:limit]

    def warm_one(fn: str) -> bool:
        try:
            import jax
            import jax.numpy as jnp

            exp = _artifact_caller(d, fn)._exported
            zeros = [jnp.zeros(a.shape, a.dtype) for a in exp.in_avals]
            a2, k2 = jax.tree_util.tree_unflatten(exp.in_tree, zeros)
            jax.block_until_ready(exp.call(*a2, **k2))
            return True
        except Exception:
            return False

    if threads > 1 and len(files) > 1:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=threads,
                                thread_name_prefix="prewarm") as ex:
            n = sum(1 for ok in ex.map(warm_one, files) if ok)
    else:
        n = sum(1 for fn in files if warm_one(fn))
    with _lock:
        _counters["prewarmed"] += n
    return n


def _restore_programs(entry: Optional[ProgramEntry]) -> None:
    """Load every persisted artifact matching a fresh entry's fingerprint
    so its first call per shape dispatches without re-tracing."""
    if entry is None or entry.fp is None:
        return
    try:
        _restore_programs_inner(entry)
    finally:
        if entry.ready is not None:
            entry.ready.set()


def _restore_programs_inner(entry: ProgramEntry) -> None:
    import os

    d = _persist_dir()
    if d is None:
        return
    _ensure_pytree_serialization()
    # armed BEFORE the restored program's first call, so its backend
    # compile is a persistent-cache fetch (restored_executable) instead
    # of a silent re-pay
    executable = enable_compilation_cache()
    try:
        from jax import export as jax_export

        prefix = _artifact_prefix(entry.fp) + "."
        restored = {}
        for fn in os.listdir(d):
            if not (fn.startswith(prefix) and fn.endswith(".jaxexp")):
                continue
            akey = fn[len(prefix):-len(".jaxexp")]
            try:
                # shared artifact cache: a boot prewarm (or a sibling
                # entry restoring the same file) already paid the
                # deserialize + backend compile — reuse that object
                restored[akey] = _artifact_caller(d, fn)
            except Exception:
                continue  # one corrupt artifact must not void the rest
        if not restored:
            return
        with entry.lock:
            entry.restored = restored
        with _lock:
            _counters["restored"] += len(restored)
            _counters["restored_executable" if executable
                      else "restored_retrace"] += len(restored)
    except Exception:
        pass


def record_compiles(delta: int, wall_s: float) -> None:
    """Process counters + trace-wall histogram for compile events claimed
    by an entry (called under that entry's lock)."""
    with _lock:
        _counters["compiles"] += int(delta)
        _trace_wall_s[0] += float(wall_s)
    try:
        from presto_tpu.obs import metrics as _obs_metrics

        _obs_metrics.COMPILE_TRACE_WALL.observe(wall_s, plane="worker")
    except Exception:
        pass


def wrap(entry: ProgramEntry, node_stats: Dict[str, float],
         node_kind: str, key: str):
    """Call-through wrapper binding one node's stats view to a (possibly
    shared) entry. Compile events are detected via jit-cache-size growth
    and claimed under the entry lock — exact under concurrency — and
    attributed to the node whose call triggered them."""
    from presto_tpu.obs import devprof as _devprof
    from presto_tpu.obs import trace as _obs_trace

    jfn = entry.jfn

    def wrapped(*args, **kw):
        ev = entry.ready
        if ev is not None and not ev.is_set():
            # restore in flight on the creating thread: waiting beats
            # paying a duplicate trace for a program that is about to
            # land deserialized (bounded — restore never blocks forever)
            ev.wait(30.0)
        r = entry.restored
        if r:
            fn = r.get(_avals_key(args, kw))
            if fn is not None:
                try:
                    # the exported artifact baked the statics in: call
                    # with the dynamic args only
                    nums, names = entry.statics
                    dyn = (tuple(a for i, a in enumerate(args)
                                 if i not in nums) if nums else args)
                    dkw = ({k: v for k, v in kw.items()
                            if k not in names} if names else kw)
                    return fn(*dyn, **dkw)
                except Exception:
                    pass  # shape/layout drift: fall through to jfn
        try:
            t0 = time.perf_counter()
            w0 = time.time()
            out = jfn(*args, **kw)
            dt = time.perf_counter() - t0
            cur = jfn._cache_size()
        except AttributeError:
            return jfn(*args, **kw)
        with entry.lock:
            entry.calls += 1
            delta = cur - entry.seen_cache_size
            if delta > 0:
                entry.seen_cache_size = cur
                entry.compiles += delta
                entry.compile_wall_s += dt
                node_stats["compiles"] += delta
                node_stats["compile_wall_s"] += dt
                # distinct-bucket accounting (analysis/recompile.py):
                # the avals key IS the post-bucketing shape signature,
                # so the recompile budget charges once per bucket even
                # when an entry re-creation replays a shape
                try:
                    shapes = node_stats.setdefault("shapes", {})
                    ak = _avals_key(args, kw)
                    shapes[ak] = int(shapes.get(ak, 0)) + delta
                except Exception:
                    pass
            else:
                delta = 0
        if delta > 0:
            record_compiles(delta, dt)
            _persist_program(entry, args, kw)
            tr = _obs_trace.current()
            if tr.enabled:
                tr.record("compile", "compile", w0, w0 + dt,
                          node=node_kind, key=key)
            if _devprof.active():
                # the program just compiled for these concrete args:
                # lower once more for its XLA cost/memory analysis
                try:
                    _devprof.on_compile(entry, node_kind, key, args, kw,
                                        node_stats=node_stats)
                except Exception:
                    pass
        if _devprof.active():
            _devprof.on_call(entry, node_kind, key, args, kw,
                             node_stats=node_stats)
        return out

    wrapped._entry = entry  # introspection hook for tests / EXPLAIN
    return wrapped


# -- ahead-of-stream precompilation -----------------------------------------

_warm_pools: List[object] = []  # shared: guarded-by(_warm_pools_lock)
_warm_pools_lock = threading.Lock()


def submit_warmers(tasks: List[Callable[[], None]], workers: int) -> int:
    """Run `tasks` concurrently on a short-lived thread pool without
    blocking the caller (compile overlaps scan decode / exchange warm-up).
    Failures are swallowed — warming is best-effort by contract."""
    if not tasks or workers <= 0:
        return 0
    from concurrent.futures import ThreadPoolExecutor

    pool = ThreadPoolExecutor(max_workers=min(workers, len(tasks)),
                              thread_name_prefix="precompile")

    def safe(fn):
        try:
            fn()
        except Exception:
            pass

    for t in tasks:
        pool.submit(safe, t)
    pool.shutdown(wait=False)
    with _warm_pools_lock:
        _warm_pools.append(pool)
        del _warm_pools[:-8]
    return len(tasks)


def drain_warmers() -> None:
    """Block until every outstanding warm task finished (tests/bench)."""
    with _warm_pools_lock:
        pools = list(_warm_pools)
        _warm_pools.clear()
    for p in pools:
        p.shutdown(wait=True)


# -- introspection / metrics -------------------------------------------------


def snapshot() -> Dict[str, float]:
    with _lock:
        return {"entries": len(_entries), **_counters,
                "trace_wall_s": _trace_wall_s[0]}


def entries() -> List[ProgramEntry]:
    """Live shared entries (CI/tests: per-entry calls/compiles introspection)."""
    with _lock:
        return list(_entries.values())


def reset(counters_only: bool = True) -> None:
    """Test/CI hook. counters_only=False also drops the shared entries
    (forces cold-cache behavior for the next plan install)."""
    with _lock:
        for k in _counters:
            _counters[k] = 0
        _trace_wall_s[0] = 0.0
        if not counters_only:
            _entries.clear()
    if not counters_only:
        with _artifact_lock:
            _artifact_cache.clear()


def metric_rows(labels: Optional[Dict[str, str]] = None) -> List[Tuple]:
    """Counter rows for server.metrics.render_metrics — process-wide, so
    callers label the exposing plane (same discipline as scan counters)."""
    snap = snapshot()
    return [
        ("presto_tpu_compile_cache_hits_total",
         "program-cache lookups served by an already-built shared program",
         snap["hits"], labels, "counter"),
        ("presto_tpu_compile_cache_misses_total",
         "program-cache lookups that created a new shared program entry",
         snap["misses"], labels, "counter"),
        ("presto_tpu_compile_events_total",
         "XLA trace+compile events observed across all node programs",
         snap["compiles"], labels, "counter"),
        ("presto_tpu_compile_cache_entries",
         "live shared program entries", snap["entries"], labels, "gauge"),
    ] + ([
        # rendered only once a warm restart actually restored something,
        # so the default scrape stays bit-for-bit
        ("presto_tpu_compile_programs_restored_total",
         "programs restored from persisted artifacts (re-trace skipped)",
         snap["restored"], labels, "counter"),
        ("presto_tpu_compile_programs_restored_executable_total",
         "restored programs whose backend compile is served from the "
         "XLA persistent compilation cache",
         snap.get("restored_executable", 0), labels, "counter"),
        ("presto_tpu_compile_programs_restored_retrace_total",
         "restored programs that still re-pay backend compilation "
         "(persistent compilation cache unavailable)",
         snap.get("restored_retrace", 0), labels, "counter"),
    ] if snap.get("restored") else [])
