"""Ahead-of-traffic compile farm: boot-time program pre-arming, a
persisted plan corpus, inflight compile claims, and speculative
queue-wait precompilation.

Reference: the reference engine never shows a user its codegen cost —
ExpressionCompiler / PageFunctionCompiler classes live in a process-wide
generated-bytecode cache that is warm by the time traffic arrives, and a
restarted coordinator re-fills it from the steady drizzle of production
queries long before any latency-sensitive tenant notices. Our XLA analog
(exec/programs.py) made programs *shareable*; this module moves their
compilation off the query's critical path entirely:

- **plan corpus** (``farm_corpus.jsonl`` under ``PRESTO_TPU_CACHE_DIR``):
  structural fingerprints are one-way hashes, so pre-arming needs the
  plans themselves. Every installed plan (LocalRunner roots, worker
  fragment roots) appends its codec canonical JSON once, keyed by the
  root's structural sha; a ``sql`` record maps each statement's digest to
  its fragment fingerprints for queue-wait speculation. Same append +
  ``fcntl.flock`` discipline as the HBO history file; corrupt or
  tombstoned lines are skipped, never fatal.
- **boot farm**: a bounded worker pool decodes the corpus (HBO-observed
  fingerprints first — ``hbo_history.jsonl`` is the traffic oracle),
  stamps program namespaces, and runs the SAME chain warmers the live
  path uses, so trace + backend compile happen before the coordinator
  reports ready. Persisted ``jax.export`` artifacts and the XLA
  persistent compilation cache are picked up through the ordinary
  ``entry_for`` restore path.
- **inflight claims**: every warm task claims ``(program namespace,
  warmer)`` in a process-wide map before compiling; a concurrent farm
  worker or live-query warmer that loses the claim WAITS on the winner
  instead of double-compiling (the PR 12 check-then-act discipline,
  applied to compilation).
- **speculative queue-wait precompile**: while a query sits in its
  resource-group queue, the farm compiles the corpus plans recorded for
  its statement digest; the compile delta is charged to the group's
  compile budget (never to the query's own terminal delta — the query
  manager nets farm-attributed compiles out).

Everything is gated: ``PRESTO_TPU_FARM=1`` arms the process (boot), the
``compile_farm`` session property arms recording/speculation per query.
Off means off — no corpus IO, no claims, no metric families.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

_CORPUS_FILE = "farm_corpus.jsonl"
# bound the number of corpus plans one boot will arm (a runaway corpus
# must not turn boot into an unbounded compile storm)
_DEFAULT_BOOT_LIMIT = 256
_DEFAULT_WORKERS = 2
# a claim loser waits for the winner's compile this long before giving
# up and compiling anyway (correctness never depends on the claim)
_CLAIM_WAIT_S = 120.0

_lock = threading.Lock()
_counters: Dict[str, int] = {  # shared: guarded-by(_lock)
    # corpus plans appended by this process
    "recorded": 0,
    # corpus plans armed (decoded + warmers ran) at boot
    "boot_armed": 0,
    # corpus lines skipped at load (corrupt / tombstoned / undecodable)
    "skipped": 0,
    # speculative precompile launches (one per queued query with a
    # corpus match)
    "speculations": 0,
    # speculations skipped because the group's compile budget was dry
    "speculations_budget_denied": 0,
    # warm tasks that lost an inflight claim and waited on the winner
    "claims_contended": 0,
    # XLA compile events attributed to farm work (boot + speculation);
    # the query manager subtracts these from live-query budget deltas
    "farm_compiles": 0,
}
_boot_wall_s = [0.0]  # shared: guarded-by(_lock)
# fp24 → "armed" (boot) | "live" (queue-wait speculation)
_status: Dict[str, str] = {}  # shared: guarded-by(_lock)
# inflight compile claims: claim key → Event set when the winner finished
_claims: Dict[str, threading.Event] = {}  # shared: guarded-by(_lock)
# root fingerprints already appended by this process (dedups corpus IO)
_recorded_fps: set = set()  # shared: guarded-by(_lock)
_recorded_sqls: set = set()  # shared: guarded-by(_lock)
# parsed corpus cache: (mtime, size) → {"plans": {...}, "sql": {...}}
_corpus_cache: List[Any] = [None, None]  # shared: guarded-by(_lock)
_pool = None  # shared: guarded-by(_lock)
_futures: List[Any] = []  # shared: guarded-by(_lock)


def enabled(config=None) -> bool:
    """Process-level arming (PRESTO_TPU_FARM=1) or per-session arming
    (compile_farm=on). config=None asks only about the process."""
    if os.environ.get("PRESTO_TPU_FARM") == "1":
        return True
    return (config is not None
            and getattr(config, "compile_farm", "off") == "on")


def corpus_path() -> Optional[str]:
    d = os.environ.get("PRESTO_TPU_CACHE_DIR")
    if not d:
        return None
    return os.path.join(d, _CORPUS_FILE)


def _fp24(root) -> Optional[str]:  # fp: key(farm-corpus) covers(plan-structure, config)
    """Config-free structural fingerprint of a plan root — the farm's
    status/corpus key (matches the HBO fingerprint's structural half).
    The key covers config even though the sha is config-free because
    every corpus record CARRIES the recording process's non-volatile
    config (`cfg`, see record_plan) and the armers warm under it —
    programs land in the same `_program_ns` the recorded traffic used,
    not whatever config the booting process happens to hold."""
    from presto_tpu.exec.programs import structural_fingerprint

    fp = structural_fingerprint(root)
    return fp[:24] if fp else None


def _cfg_doc(config) -> Dict[str, Any]:
    """JSON-safe dump of the non-volatile (program-relevant) ExecConfig
    fields — exactly the set config_fingerprint hashes, so a corpus
    record pins the program identity its plan compiled under."""
    import dataclasses

    from presto_tpu.exec.programs import _VOLATILE_CONFIG_FIELDS

    out: Dict[str, Any] = {}
    for f in dataclasses.fields(config):
        if f.name in _VOLATILE_CONFIG_FIELDS:
            continue
        v = getattr(config, f.name, None)
        if isinstance(v, tuple):
            v = list(v)
        if v is None or isinstance(v, (bool, int, float, str, list)):
            out[f.name] = v
    return out


def _cfg_restore(config, doc) -> Any:
    """The recorded config, reconstructed over the ambient one: known
    fields are replaced (JSON lists back to tuples — JSON has no
    tuples, so any list in a cfg doc started as one), unknown fields
    (older/newer writer) are ignored."""
    import dataclasses

    if not isinstance(doc, dict) or not doc:
        return config
    known = {f.name for f in dataclasses.fields(config)}
    fixed = {k: (tuple(v) if isinstance(v, list) else v)
             for k, v in doc.items() if k in known}
    try:
        return dataclasses.replace(config, **fixed)
    except (TypeError, ValueError):
        return config


def _sql_sha(sql: str) -> str:
    return hashlib.sha256(sql.strip().encode()).hexdigest()[:16]


# -- corpus -------------------------------------------------------------------


def _append(rec: Dict[str, Any]) -> bool:
    """One O_APPEND JSONL write under the cross-process flock (same
    discipline as obs/runstats.py — one line is one atomic record)."""
    path = corpus_path()
    if path is None:
        return False
    from presto_tpu.obs.runstats import _flock, _funlock

    data = (json.dumps(rec, sort_keys=True) + "\n").encode()
    lk = _flock(path, exclusive=True)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            os.write(fd, data)
        finally:
            os.close(fd)
        return True
    except OSError:
        return False
    finally:
        _funlock(lk)


def record_plan(root, ctx) -> bool:
    """Append this root's codec JSON to the corpus (once per process per
    fingerprint). Called from install_plan_programs — LocalRunner plan
    roots and worker fragment roots both land here, so the corpus holds
    exactly the trees whose programs actually compiled."""
    from presto_tpu.plan.codec import CodecError, canonical_node_json

    fp = _fp24(root)
    if fp is None:
        return False
    with _lock:
        if fp in _recorded_fps:
            return False
        _recorded_fps.add(fp)
    try:
        doc = json.loads(canonical_node_json(root))
    except (CodecError, TypeError, ValueError):
        return False
    ok = _append({"v": 1, "kind": "plan", "fp": fp, "plan": doc,
                  "cfg": _cfg_doc(ctx.config),
                  "ts": round(time.time(), 3)})
    if ok:
        with _lock:
            _counters["recorded"] += 1
    return ok


def record_sql(sql: str, roots) -> bool:
    """Map a statement digest to its plan fingerprints (queue-wait
    speculation resolves future submissions of the same SQL through
    this record — the raw SQL itself never touches the cache dir)."""
    if not sql:
        return False
    sha = _sql_sha(sql)
    with _lock:
        if sha in _recorded_sqls:
            return False
        _recorded_sqls.add(sha)
    fps = [fp for fp in (_fp24(r) for r in roots) if fp]
    if not fps:
        return False
    return _append({"v": 1, "kind": "sql", "sql": sha, "fps": fps,
                    "ts": round(time.time(), 3)})


def load_corpus() -> Dict[str, Dict[str, Any]]:
    """Parse the corpus (last line wins per key; corrupt lines counted
    and skipped; ``deleted`` tombstones drop their key). Cached on the
    file's (mtime, size) so queue-wait speculation stays cheap."""
    path = corpus_path()
    empty: Dict[str, Dict[str, Any]] = {"plans": {}, "sql": {},
                                        "cfgs": {}}
    if path is None or not os.path.exists(path):
        return empty
    try:
        st = os.stat(path)
        stamp = (st.st_mtime_ns, st.st_size)
    except OSError:
        return empty
    with _lock:
        if _corpus_cache[0] == stamp and _corpus_cache[1] is not None:
            return _corpus_cache[1]
    from presto_tpu.obs.runstats import _flock, _funlock

    plans: Dict[str, Any] = {}
    sqls: Dict[str, Any] = {}
    cfgs: Dict[str, Any] = {}
    skipped = 0
    lk = _flock(path, exclusive=False)
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                    kind = rec["kind"]
                    if kind == "plan":
                        fp = str(rec["fp"])
                        if rec.get("deleted"):
                            plans.pop(fp, None)
                            cfgs.pop(fp, None)
                        else:
                            plans[fp] = rec["plan"]
                            # pre-cfg records (older writers) arm with
                            # the ambient config, same as before
                            cfgs[fp] = rec.get("cfg") or {}
                    elif kind == "sql":
                        sqls[str(rec["sql"])] = [str(f)
                                                 for f in rec["fps"]]
                    else:
                        skipped += 1
                except (KeyError, TypeError, ValueError):
                    skipped += 1
    except OSError:
        return empty
    finally:
        _funlock(lk)
    corpus = {"plans": plans, "sql": sqls, "cfgs": cfgs}
    with _lock:
        # stamp-keyed memo: racing parsers store (stamp, corpus) as an
        # atomic pair, so a stale pair self-heals on the next stat probe
        _corpus_cache[0] = stamp  # lint: allow(check-then-act)
        _corpus_cache[1] = corpus  # lint: allow(check-then-act)
        _counters["skipped"] += skipped
    return corpus


def _hbo_observed_fps() -> set:
    """Structural fp24 prefixes present in the HBO history — the farm's
    arming priority (observed traffic compiles first)."""
    from presto_tpu.obs import runstats as _runstats

    path = _runstats.history_path()
    out: set = set()
    if path is None or not os.path.exists(path):
        return out
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as fh:
            for line in fh:
                try:
                    fp = json.loads(line).get("fp")
                except (TypeError, ValueError):
                    continue
                if isinstance(fp, str) and len(fp) >= 24:
                    out.add(fp[:24])
    except OSError:
        pass
    return out


def artifact_count() -> int:
    """Persisted jax.export artifacts under the cache dir (boot report)."""
    d = os.environ.get("PRESTO_TPU_CACHE_DIR")
    if not d:
        return 0
    try:
        return sum(1 for fn in os.listdir(os.path.join(d, "programs"))
                   if fn.endswith(".jaxexp"))
    except OSError:
        return 0


# -- inflight claims ----------------------------------------------------------


def _claim(key: str) -> Tuple[bool, threading.Event]:
    with _lock:
        ev = _claims.get(key)
        if ev is not None:
            return False, ev
        ev = _claims[key] = threading.Event()
        return True, ev


def _run_claimed(key: Optional[str], fn: Callable[[], None]) -> bool:
    """Run `fn` under the inflight claim for `key`: the winner compiles,
    losers wait for it (bounded) and skip. Returns True when this caller
    actually ran `fn`."""
    if key is None:
        fn()
        return True
    won, ev = _claim(key)
    if not won:
        with _lock:
            _counters["claims_contended"] += 1
        ev.wait(_CLAIM_WAIT_S)
        return False
    try:
        fn()
    finally:
        ev.set()
    return True


def _task_claim_key(task) -> Optional[str]:
    """Claim key for one chain-warmer task (a functools.partial whose
    first arg is the plan node): program namespace + warmer identity.
    Unstamped nodes (no namespace) warm unclaimed — their programs are
    private, so there is nothing shared to double-compile."""
    try:
        node = task.args[0]
        ns = node.__dict__.get("_program_ns")
        name = getattr(task.func, "__name__", "warm")
    except (AttributeError, IndexError):
        return None
    if not ns:
        return None
    return f"{ns}|{name}"


def wrap_claims(tasks: List[Callable]) -> List[Callable]:
    """Wrap live-path warm tasks in the farm's inflight claims, so
    concurrent queries (and a booting farm) compile each shared program
    exactly once."""
    out = []
    for t in tasks:
        key = _task_claim_key(t)
        out.append(lambda t=t, key=key: _run_claimed(key, t))
    return out


# -- farm pool ----------------------------------------------------------------


def _get_pool(workers: int):
    from concurrent.futures import ThreadPoolExecutor

    global _pool
    with _lock:
        if _pool is None:
            _pool = ThreadPoolExecutor(
                max_workers=max(1, workers),
                thread_name_prefix="compile-farm")
        return _pool


def _submit(fn: Callable[[], None], workers: int):
    pool = _get_pool(workers)

    def safe():
        try:
            fn()
        except Exception:
            pass  # farm work is best-effort by contract

    fut = pool.submit(safe)
    with _lock:
        _futures.append(fut)
        del _futures[:-1024]
    return fut


def drain() -> None:
    """Block until every outstanding farm task finished (boot block=True,
    tests, benches)."""
    while True:
        with _lock:
            pending = [f for f in _futures if not f.done()]
        if not pending:
            return
        for f in pending:
            try:
                f.result(timeout=600.0)
            except Exception:
                pass


# -- arming -------------------------------------------------------------------


def _warm_tasks_for(root, catalog, config) -> List[Callable]:
    """Decode-side mirror of the live install path: stamp namespaces,
    then build the SAME chain-warmer tasks execute_node would jit."""
    from presto_tpu.exec import programs as _programs
    from presto_tpu.exec.runtime import ExecContext, _chain_warmers

    ctx = ExecContext(catalog, config)
    _programs.install_plan(root, config)
    return _chain_warmers(root, ctx)


def _run_entry(fp: str, doc, catalog, config, status: str,
               cfg=None) -> int:  # fp: uses-key(farm-corpus)
    """Arm one corpus plan: decode, install under the RECORDED config
    (`cfg`, falling back to the ambient one for pre-cfg records), run
    its warmers under inflight claims, attribute the compile delta to
    the farm. Returns warm tasks run (≥0), or -1 when the plan was
    skipped (undecodable / uninstallable) — skips never count as
    armed."""
    from presto_tpu.exec import programs as _programs
    from presto_tpu.obs import metrics as _obs_metrics
    from presto_tpu.plan.codec import CodecError, node_from_json

    try:
        root = node_from_json(doc)
    except (CodecError, KeyError, TypeError, ValueError):
        with _lock:
            _counters["skipped"] += 1
        return -1
    try:
        tasks = _warm_tasks_for(root, catalog, _cfg_restore(config, cfg))
    except Exception:
        with _lock:
            _counters["skipped"] += 1
        return -1
    ran = 0
    for t in tasks:
        key = _task_claim_key(t)
        t0 = time.perf_counter()
        c0 = _programs.snapshot()["compiles"]

        def run(t=t):
            t()

        try:
            if _run_claimed(key, run):
                ran += 1
                delta = _programs.snapshot()["compiles"] - c0
                wall = time.perf_counter() - t0
                with _lock:
                    # process-counter delta over-attributes under
                    # concurrency (a neighbor's compile lands in the
                    # window) — same documented tolerance as the group
                    # budget charge in querymanager._charge_compiles
                    if delta > 0:
                        _counters["farm_compiles"] += delta
                try:
                    _obs_metrics.FARM_WARM_WALL.observe(
                        wall, plane="worker")
                except Exception:
                    pass
        except Exception:
            pass
    with _lock:
        cur = _status.get(fp)
        if status == "armed" and cur is None:
            _status[fp] = "armed"
        elif status == "live":
            _status[fp] = "live"
    return ran


def boot(catalog, config=None, workers: Optional[int] = None,
         block: bool = True,
         limit: Optional[int] = None) -> int:  # fp: uses-key(farm-corpus)
    """Pre-arm the process-wide program cache from the persisted corpus.
    Returns the number of corpus plans armed. block=True (coordinator
    boot) waits for the pool — "ready" means warm."""
    if not enabled(config) or corpus_path() is None:
        return 0
    from presto_tpu.exec import programs as _programs
    from presto_tpu.exec.runtime import ExecConfig
    from presto_tpu.obs import events as _obs_events

    config = config or ExecConfig()
    workers = workers or int(
        os.environ.get("PRESTO_TPU_FARM_WORKERS", _DEFAULT_WORKERS))
    limit = limit or int(
        os.environ.get("PRESTO_TPU_FARM_LIMIT", _DEFAULT_BOOT_LIMIT))
    _programs.enable_compilation_cache()
    # register pytree serialization on THIS thread, before workers exist:
    # a worker registering mid-boot can lose an import race against
    # another worker's lazy ops import, and artifact restore would
    # silently downgrade to a re-compile for the affected types
    _programs._ensure_pytree_serialization()
    corpus = load_corpus()
    plans = corpus["plans"]
    if not plans:
        return 0
    observed = _hbo_observed_fps()
    # traffic-observed structures arm first; the rest in corpus order
    order = sorted(plans, key=lambda fp: (fp not in observed,))[:limit]
    t0 = time.perf_counter()
    c0 = _programs.snapshot()["compiles"]
    # artifact prewarm FIRST: every persisted program deserializes and
    # backend-compiles now, so (a) the warm pass below restores from the
    # shared artifact cache instead of re-tracing, and (b) traffic-path
    # entries created lazily later (fragment/final/sort variants the
    # fabricated warm pass never reaches) dispatch onto already-compiled
    # executables instead of paying XLA on the first live call
    prewarmed = 0
    try:
        prewarmed = _programs.prewarm_artifacts(threads=workers,
                                                limit=4 * limit)
    except Exception:
        pass
    armed = [0]
    armed_lock = threading.Lock()

    def arm(fp):
        if _run_entry(fp, plans[fp], catalog, config, "armed",
                      cfg=corpus["cfgs"].get(fp)) >= 0:
            with armed_lock:
                armed[0] += 1

    futs = [_submit(lambda fp=fp: arm(fp), workers) for fp in order]
    if block:
        for f in futs:
            try:
                f.result(timeout=600.0)
            except Exception:
                pass
    wall = time.perf_counter() - t0
    with _lock:
        _counters["boot_armed"] += armed[0]
        _boot_wall_s[0] += wall
    try:
        _obs_events.EVENTS.emit(
            "precompile_boot", armed=armed[0],
            corpus=len(plans), observed=len(observed),
            artifacts=artifact_count(), prewarmed=prewarmed,
            compiles=_programs.snapshot()["compiles"] - c0,
            wall_s=round(wall, 4), blocking=bool(block))
    except Exception:
        pass
    return armed[0]


def speculate(sql: str, catalog, config, group: Optional[str] = None,
              charge_fn: Optional[Callable[[int], None]] = None,
              budget_fn: Optional[Callable[[], Optional[int]]] = None,
              query_id: Optional[str] = None,
              workers: Optional[int] = None):  # fp: uses-key(farm-corpus)
    """Queue-wait precompile: while the query queues, compile the corpus
    plans recorded for its statement digest. The compile delta is charged
    to the resource group via `charge_fn`; a dry budget (`budget_fn`
    returning 0) skips the speculation — speculative warmth must not
    starve the group's live queries. Non-blocking; returns the submitted
    future (None = nothing to do)."""
    if not enabled(config) or not sql:
        return None
    corpus = load_corpus()
    fps = corpus["sql"].get(_sql_sha(sql)) or []
    plans = corpus["plans"]
    cfgs = corpus["cfgs"]
    todo = [(fp, plans[fp]) for fp in fps if fp in plans]
    if not todo:
        return None
    if budget_fn is not None:
        try:
            remaining = budget_fn()
        except Exception:
            remaining = None
        if remaining is not None and remaining <= 0:
            with _lock:
                _counters["speculations_budget_denied"] += 1
            return None
    from presto_tpu.exec import programs as _programs
    from presto_tpu.obs import events as _obs_events

    with _lock:
        _counters["speculations"] += 1
    workers = workers or int(
        os.environ.get("PRESTO_TPU_FARM_WORKERS", _DEFAULT_WORKERS))

    def run():
        c0 = _programs.snapshot()["compiles"]
        ran = 0
        for fp, doc in todo:
            ran += max(0, _run_entry(fp, doc, catalog, config, "live",
                                     cfg=cfgs.get(fp)))
        delta = _programs.snapshot()["compiles"] - c0
        if delta > 0 and charge_fn is not None:
            try:
                charge_fn(delta)
            except Exception:
                pass
        try:
            _obs_events.EVENTS.emit(
                "precompile_speculative", query_id=query_id, group=group,
                plans=len(todo), warmed=ran, compiles=max(0, delta))
        except Exception:
            pass

    return _submit(run, workers)


# -- status / introspection ---------------------------------------------------


def status_fp(fp: Optional[str]) -> str:
    """"armed" (boot pre-armed) | "live" (queue-wait speculation) |
    "miss" for one structural fingerprint."""
    if not fp:
        return "miss"
    with _lock:
        return _status.get(fp[:24], "miss")


def status_for(root) -> str:
    return status_fp(_fp24(root))


def mark_live(root) -> None:
    """Promote a root's status to "live" (its programs were warmed for a
    specific queued query, not just at boot)."""
    fp = _fp24(root)
    if fp:
        with _lock:
            _status[fp] = "live"


def farm_compiles() -> int:
    """Compile events attributed to farm work — the query manager nets
    these out of live-query budget deltas so boot/speculative compiles
    are never double-charged to an unlucky concurrent query."""
    with _lock:
        return _counters["farm_compiles"]


def armed() -> bool:
    """Any farm activity this process (metric families render only once
    armed, keeping default scrapes bit-for-bit)."""
    with _lock:
        return bool(_status) or any(_counters.values())


def snapshot() -> Dict[str, Any]:
    with _lock:
        return {**_counters, "boot_wall_s": round(_boot_wall_s[0], 6),
                "statuses": len(_status),
                "corpus_path": corpus_path() or ""}


def reset() -> None:
    """Test/CI hook: drop claims, statuses, counters and the corpus
    cache (the corpus FILE is the caller's to manage)."""
    global _pool
    with _lock:
        for k in _counters:
            _counters[k] = 0
        _boot_wall_s[0] = 0.0
        _status.clear()
        _claims.clear()
        _recorded_fps.clear()
        _recorded_sqls.clear()
        _corpus_cache[0] = _corpus_cache[1] = None
        _futures.clear()
        pool, _pool = _pool, None
    if pool is not None:
        pool.shutdown(wait=False)


def metric_rows(labels: Optional[Dict[str, str]] = None) -> List[Tuple]:
    """Counter rows for both metric planes — rendered only once the farm
    has done anything, so an unarmed scrape stays bit-for-bit."""
    if not armed():
        return []
    snap = snapshot()
    return [
        ("presto_tpu_farm_corpus_recorded_total",
         "plan-corpus entries appended by this process",
         snap["recorded"], labels, "counter"),
        ("presto_tpu_farm_boot_armed_total",
         "corpus plans pre-armed at farm boot",
         snap["boot_armed"], labels, "counter"),
        ("presto_tpu_farm_skipped_total",
         "corpus lines skipped (corrupt, tombstoned, undecodable)",
         snap["skipped"], labels, "counter"),
        ("presto_tpu_farm_speculations_total",
         "queue-wait speculative precompile launches",
         snap["speculations"], labels, "counter"),
        ("presto_tpu_farm_speculations_budget_denied_total",
         "speculations skipped because the group compile budget was dry",
         snap["speculations_budget_denied"], labels, "counter"),
        ("presto_tpu_farm_claims_contended_total",
         "warm tasks that lost an inflight compile claim and waited",
         snap["claims_contended"], labels, "counter"),
        ("presto_tpu_farm_compiles_total",
         "XLA compile events attributed to farm work (boot + speculation)",
         snap["farm_compiles"], labels, "counter"),
        ("presto_tpu_farm_boot_wall_seconds",
         "cumulative wall spent arming the program cache at boot",
         snap["boot_wall_s"], labels, "gauge"),
    ]
