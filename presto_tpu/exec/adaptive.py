"""In-run adaptive execution: act on drift telemetry within the query.

Reference: the robust dynamic hybrid hash join literature (arXiv:2112.02480)
and the hash-vs-sort crossover study (arXiv:2411.13245) — both show the
win comes from reacting to OBSERVED cardinality/duplication mid-operator
instead of trusting estimates. The HBO plane (obs/runstats.py) already
self-corrects ACROSS runs; this module closes the loop WITHIN one run:
the drift telemetry the engine already fetches (confirmed group counts,
traced lane maxima, per-partition byte footprints) feeds decisions the
same query still has time to act on.

Session property `adaptive` (ExecConfig.adaptive):
  off      strict no-op — no AdaptiveState is ever constructed, no
           decisions, no events, no metric families; pre-adaptive engine
           bit-for-bit.
  observe  decide-and-log: every decision point evaluates and records
           what it WOULD do (event, EXPLAIN annotation, doctor record)
           but never acts — replay ladders, lane boosts, spills proceed
           exactly as with off.
  on       act: engine flips between replay waves, forward-propagating
           presize/lane growth, device-radix partition growth, partial
           (largest-partition-first) revocation.

Action kinds (the {kind} label of presto_tpu_adaptive_actions_total and
the `kind` attr of `adaptive_action` events):
  engine_flip    breaker re-chose sort<->hash from the wave's observed
                 group count / duplication instead of replaying the loser
  presize_grow   agg table grew from a completed window's confirmed group
                 count BEFORE the next window overflowed
  lane_resize    mesh exchange lanes resized to the failed attempt's
                 observed per-lane maxima instead of the x2 boost ladder
  radix_grow     a device-radix partition split by the next hash bit when
                 its observed bytes blew the partition budget
  partial_revoke memory pressure spilled the largest resident partitions
                 instead of a whole operator's state

Every decision emits an `adaptive_action` event (kind, site fingerprint,
before -> after, trigger telemetry, acted flag) on the unified event
stream, stamps a short form onto the plan node for the EXPLAIN ANALYZE
``[adaptive: ...]`` annotation, and — when acted — bumps the labeled
counter family on both metric planes. Events carry the stream's monotonic
seq, so the action order of a run is deterministic and auditable.

Off-discipline: the counter family is armed the first time any non-off
AdaptiveState is constructed; adaptive=off sessions never arm it, so
their /v1/metrics scrapes stay bit-for-bit pre-adaptive.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

# process-wide acted-action counts by kind — the
# presto_tpu_adaptive_actions_total{kind} family (both planes render it)
_COUNTS: Dict[str, int] = {}
# recent decision records for the query doctor (bounded ring)
_RECENT: List[Dict[str, Any]] = []
_RECENT_MAX = 256
_ARMED = False
_LAST_MODE: Optional[str] = None
_LOCK = threading.Lock()

_HELP = ("in-run adaptive actions taken, by kind (engine_flip, "
         "presize_grow, lane_resize, radix_grow, partial_revoke)")


def armed() -> bool:
    """Has any non-off adaptive session ever registered? Gates the metric
    family so adaptive=off scrapes stay bit-for-bit pre-adaptive."""
    return _ARMED


def last_mode() -> Optional[str]:
    """Mode of the most recent AdaptiveState ("observe"/"on"), or None if
    none was ever constructed — the query doctor uses this to explain WHY
    an action did or did not fire."""
    return _LAST_MODE


def metric_rows(labels: Dict[str, str]) -> List[tuple]:
    """(name, help, value, labels, type) rows for /v1/metrics — empty
    until armed, one row per action kind after."""
    if not _ARMED:
        return []
    with _LOCK:
        return [("presto_tpu_adaptive_actions_total", _HELP, v,
                 {**labels, "kind": k}, "counter")
                for k, v in sorted(_COUNTS.items())]


def recent_decisions(query_id: Optional[str] = None) -> List[Dict[str, Any]]:
    """Decision records (acted or not), newest last. With a query_id,
    records stamped for that query only, falling back to unstamped
    records (LocalRunner paths have no query id)."""
    with _LOCK:
        recs = list(_RECENT)
    if query_id:
        mine = [r for r in recs if r.get("query_id") == query_id]
        if mine:
            return mine
    return recs


def reset() -> None:
    """Test hook: forget every count/record and disarm the family."""
    global _ARMED, _LAST_MODE
    with _LOCK:
        _COUNTS.clear()
        _RECENT.clear()
        _ARMED = False
        _LAST_MODE = None


class AdaptiveState:
    """Per-query adaptation controller, held as ``ctx.adaptive`` (None
    when the session property is off — every call site guards on that,
    keeping off a strict no-op).

    ``decide()`` is the single funnel every adaptation goes through: it
    records the decision, emits the event, stamps the EXPLAIN annotation
    and returns whether the caller should ACT (mode == "on"). Acting call
    sites therefore read as ``if ctx.adaptive.decide(...): <act>``, and
    observe mode exercises the full decision path with zero behavior
    change."""

    def __init__(self, mode: str, query_id: str = ""):
        global _ARMED, _LAST_MODE
        if mode not in ("observe", "on"):
            raise ValueError(f"adaptive mode must be observe|on, got {mode!r}")
        self.mode = mode
        self.query_id = query_id or None
        self.actions: List[Dict[str, Any]] = []  # this query's decisions
        self.acted_count = 0
        self.decided_count = 0
        # obs/inflight.TaskInflight handle (set by the worker's task
        # wiring alongside ctx.inflight): each decision lands in the
        # mid-flight heartbeat as an adaptive.<kind> operator record
        self.inflight = None
        with _LOCK:
            _ARMED = True
            _LAST_MODE = mode

    def decide(self, kind: str, node=None, site: Optional[str] = None,
               before: Any = None, after: Any = None, detail: str = "",
               **trigger: Any) -> bool:
        """Record one adaptation decision; True = caller should act.

        ``detail`` is the short human form for the EXPLAIN annotation
        (e.g. "flip sort->hash"); ``trigger`` carries the telemetry that
        fired the decision (observed groups, lane max, bytes...)."""
        acted = self.mode == "on"
        self.decided_count += 1
        if acted:
            self.acted_count += 1
        rec = {
            "kind": kind, "site": site, "before": before, "after": after,
            "acted": acted, "mode": self.mode, "detail": detail,
            "query_id": self.query_id, **trigger,
        }
        self.actions.append(rec)
        with _LOCK:
            if acted:
                _COUNTS[kind] = _COUNTS.get(kind, 0) + 1
            _RECENT.append(rec)
            del _RECENT[:-_RECENT_MAX]
        if node is not None:
            ann = detail or f"{kind} {before}->{after}"
            if not acted:
                ann = f"would {ann}"
            node.__dict__.setdefault("_adaptive_actions", []).append(ann)
        if self.inflight is not None:
            try:
                self.inflight.publish(
                    f"adaptive.{kind}", windows=1,
                    adaptiveActions=self.acted_count,
                    adaptiveLast=(("" if acted else "would ") + detail))
            except Exception:
                pass
        try:
            from presto_tpu.obs.events import EVENTS

            EVENTS.emit("adaptive_action", query_id=self.query_id,
                        action=kind, site=site, before=before, after=after,
                        acted=acted, mode=self.mode, detail=detail,
                        **{k: v for k, v in trigger.items()})
        except Exception:
            pass
        return acted
