"""Whole-fragment device residency: stack a window of scan batches and fold
the breaker's merge step over it inside ONE compiled XLA program.

The per-batch driver loop costs a host→device dispatch per operator per
batch — on a tunneled TPU that is ~35-50 ms of transport per round trip
while the chip does microseconds of work (BENCH_NOTES.md round-5 roofline:
Q1 SF1 runs ~700× above the HBM floor on dispatch latency alone). This
module removes the loop from the host: consecutive same-structure batches
are stacked along a new leading axis (a "window"), and a `lax.scan` inside
the breaker's own jitted stepping program iterates the window on-device.
A fragment then costs O(ceil(batches / window)) dispatches instead of
O(batches × operators).

Pieces (mechanism only — eligibility gating and the program keys live in
exec/runtime.py, which owns the plan/breaker knowledge):

- ``batch_struct_key``: the stacking-compatibility key. Two batches stack
  iff their pytrees are structurally identical — same column names/types,
  same dictionary OBJECTS (Dictionary equality is identity, so one
  treedef match guarantees `_unify_batch_dicts` no-ops inside the traced
  scan body), same validity/limb presence, same leaf shapes and dtypes.
- ``iter_windows``: groups a batch stream into stacked windows of at most
  `width` batches. Ragged tails pad with DEAD copies of the last real
  batch (live mask zeroed — dead rows contribute nothing to a group merge
  or a TopN heap) up to the next power of two, so the compiled window
  shapes stay bounded: {2, 4, ..., width} plus the per-batch single path.
- ``WindowSource``: the async producer. A host thread pulls the (already
  decode-prefetched) scan stream, stacks windows, and stages them in a
  depth-1 queue — the device-side double buffer: window k+1 is stacked
  and its device work dispatched while the consumer's fused step for
  window k is still executing. ``drain()`` recovers every pulled-but-
  undispatched batch for the grace-spill path.
- ``scan_stepper`` / ``topn_stepper``: builders for the fused stepping
  functions runtime.py hands to `_node_jit` (one shared program per plan
  structure via exec/programs.py).

Everything here is kernel code for the analysis plane: the module is part
of the kernel linter's jit-rooted scope (analysis/kernel_lint.py).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from presto_tpu.batch import Batch


class Window:
    """A stacked window of `k` real batches (padded to `width` = k rounded
    up to a power of two). `stacked` is a Batch pytree whose every leaf
    carries a leading [width] axis; `first` is the untouched first real
    batch (host-side handle kept for structure-sensitive fallbacks)."""

    __slots__ = ("stacked", "k", "width", "first")

    def __init__(self, stacked: Batch, k: int, width: int, first: Batch):
        self.stacked = stacked
        self.k = k
        self.width = width
        self.first = first


WindowItem = Union[Batch, Window]


def batch_struct_key(b: Batch):
    """Hashable stacking-compatibility key: treedef (names, types, dict
    identities, optional-plane presence) + per-leaf (shape, dtype)."""
    leaves, treedef = jax.tree_util.tree_flatten(b)
    return treedef, tuple((tuple(l.shape), str(l.dtype)) for l in leaves)


def stack_batches(batches: List[Batch]) -> Batch:
    """Stack K structurally-identical batches into one Batch whose leaves
    carry a leading [K] axis (the aux — names/types/dicts — is shared, so
    every `lax.scan` slice sees the SAME dictionary objects)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *batches)


def unstack_batch(stacked: Batch, k: int) -> List[Batch]:
    """The first `k` (real) slices of a stacked window as plain batches —
    the grace-overflow handler spills per-batch."""
    return [jax.tree_util.tree_map(lambda x, i=i: x[i], stacked)
            for i in range(k)]


def dead_like(b: Batch) -> Batch:
    """A structural clone of `b` with every row dead — window tail padding.
    Chain filters AND into the zero live mask, group merges and TopN sorts
    count only live rows, so padding slices are provably inert."""
    return b.with_live(jnp.zeros_like(b.live))


def window_device_bytes(w: Window) -> int:
    """Device footprint of a stacked window (for spill accounting)."""
    return sum(l.size * l.dtype.itemsize
               for l in jax.tree_util.tree_leaves(w.stacked))


def _pow2_at_least(n: int) -> int:
    return 1 << max(n - 1, 0).bit_length()


def iter_windows(stream: Iterable[Batch], width: int,
                 bucket: bool = False) -> Iterator[WindowItem]:
    """Group CONSECUTIVE same-structure batches into stacked windows of at
    most `width`; a batch whose structure differs from its predecessors
    flushes the pending group first (order is always preserved). Lone
    batches pass through unstacked — padding a single to width would spend
    width× the compute to save zero dispatches. ``bucket``
    (shape_bucketing=pow2) pads every MULTI-batch flush to the full
    window width, collapsing the partial-window pow2 ladder to one
    stacked shape per structure."""
    # host generator, never traced: width is a plain Python int
    bw = _pow2_at_least(int(width)) if bucket else 0  # lint: allow(host-sync)
    pending: List[Batch] = []
    key = None
    for b in stream:
        k = batch_struct_key(b)
        if pending and k != key:
            yield _flush(pending, bw)
            pending = []
        key = k
        pending.append(b)
        if len(pending) >= width:
            yield _flush(pending, bw)
            pending = []
    if pending:
        yield _flush(pending, bw)


def _flush(pending: List[Batch], bucket_width: int = 0) -> WindowItem:
    k = len(pending)
    if k == 1:
        return pending[0]
    # host-side stacking decision: bucket_width is a plain Python int
    width = max(_pow2_at_least(k), int(bucket_width))  # lint: allow(host-sync)
    padded = pending + [dead_like(pending[-1])] * (width - k)
    w = Window(stack_batches(padded), k, width, pending[0])
    from presto_tpu.obs import devprof as _devprof

    if _devprof.active():
        # device-residency accounting: the fused path's staging
        # high-water is the stacked window, not a single batch
        _devprof.note_staging(window_device_bytes(w))
    return w


_SENTINEL = object()


class WindowSource:
    """Async window producer: a host thread pulls the scan stream (itself
    fed by the decode-prefetch producer), stacks windows, and stages them
    in a depth-1 queue. `jnp.stack` dispatches asynchronously, so window
    k+1's device staging overlaps the consumer's in-flight fused step for
    window k — a device-side double buffer with exactly one window in
    flight and one staged.

    ``drain()`` stops the producer and returns every batch it pulled from
    the stream but the consumer never received (staged windows unstacked
    back to their real batches, plus the partial pending group) — the
    grace-overflow path hands these to the spill partitioner so no input
    is lost when the consumer abandons the window loop mid-stream."""

    def __init__(self, stream: Iterable[Batch], width: int,
                 bucket: bool = False,
                 on_window: Optional[Callable[[int, int], None]] = None):
        self._stream = iter(stream)
        # window-boundary telemetry hook (obs/inflight publish): called
        # (k, width) from the producer thread after each staged flush —
        # host-side counts only, never a device sync. None = no-op.
        self._on_window = on_window
        # host-side producer config, not traced code (the module-wide
        # kernel scope is for the stepper builders below)
        self._width = max(2, int(width))  # lint: allow(host-sync)
        # shape_bucketing=pow2: partial windows pad to the full width so
        # the fused stepper sees exactly one stacked shape per structure
        self._bucket_w = _pow2_at_least(self._width) if bucket else 0
        self._q: "queue.Queue" = queue.Queue(maxsize=1)
        self._stop = threading.Event()
        self._exc: Optional[BaseException] = None
        self._pending: List[Batch] = []
        self._thread = threading.Thread(
            target=self._produce, name="fragment-window-producer", daemon=True)
        self._thread.start()

    def _produce(self):
        pending = self._pending
        key = None
        bw = self._bucket_w
        try:
            for b in self._stream:
                k = batch_struct_key(b)
                if pending and k != key:
                    if not self._put(_flush(list(pending), bw)):
                        return
                    del pending[:]
                key = k
                pending.append(b)
                if len(pending) >= self._width:
                    if not self._put(_flush(list(pending), bw)):
                        return
                    del pending[:]
                if self._stop.is_set():
                    return
            if pending and self._put(_flush(list(pending), bw)):
                del pending[:]
        except BaseException as e:  # propagated to the consumer
            self._exc = e
        finally:
            self._put(_SENTINEL, force=True)

    def _put(self, item, force: bool = False) -> bool:
        if item is not _SENTINEL and self._on_window is not None:
            k, width = (item.k, item.width) if isinstance(item, Window) \
                else (1, 1)
            try:
                self._on_window(k, width)
            except Exception:
                # telemetry must never kill the producer thread
                pass
        while True:
            stopped = self._stop.is_set()
            if stopped and not force:
                return False
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                if stopped and force:
                    # nobody will consume after a stop — drop the sentinel
                    # rather than spin against a full queue under join()
                    return False

    def __iter__(self) -> Iterator[WindowItem]:
        while True:
            item = self._q.get()
            if item is _SENTINEL:
                if self._exc is not None:
                    exc, self._exc = self._exc, None
                    raise exc
                return
            yield item

    def close(self):
        self._stop.set()
        self._thread.join(timeout=30.0)

    def drain(self) -> List[Batch]:
        """Stop the producer and recover its pulled-but-undelivered batches
        in stream order: staged queue items first, then the partial group."""
        self._stop.set()
        self._thread.join(timeout=30.0)
        rest: List[Batch] = []
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is _SENTINEL:
                continue
            if isinstance(item, Window):
                rest.extend(unstack_batch(item.stacked, item.k))
            else:
                rest.append(item)
        rest.extend(self._pending)
        del self._pending[:]
        return rest


# ---------------------------------------------------------------------------
# fused stepping-function builders (runtime.py jits these via _node_jit)


def _split_first(stacked: Batch) -> Tuple[Batch, Batch]:
    first = jax.tree_util.tree_map(lambda x: x[0], stacked)
    rest = jax.tree_util.tree_map(lambda x: x[1:], stacked)
    return first, rest


def scan_stepper(merge_step: Callable, first: bool) -> Callable:
    """Fused aggregate fragment step: fold `merge_step` (acc, batch, cap)
    -> (acc, n_groups) over a stacked window via `lax.scan`, returning the
    window's final accumulator and its MAX group count (the one scalar the
    host confirms per window instead of per batch). The first slice is
    peeled outside the scan so the carry is seeded with the step's own
    output structure — `merge_step` is a structural fixed point (its
    output feeds its input) only from the second application on.

    `first=True` builds the no-incoming-accumulator variant (window 0)."""

    def fold(acc0, stacked: Batch, cap: int):
        first_b, rest = _split_first(stacked)
        acc, ng = merge_step(acc0, first_b, cap)

        def body(carry, b):
            a, mx = carry
            out, n = merge_step(a, b, cap)
            return (out, jnp.maximum(mx, n)), None

        (acc, ng), _ = jax.lax.scan(body, (acc, ng), rest)
        return acc, ng

    if first:
        def fragment_step0(stacked: Batch, cap: int):
            return fold(None, stacked, cap)

        return fragment_step0

    def fragment_step(acc, stacked: Batch, cap: int):
        return fold(acc, stacked, cap)

    return fragment_step


def topn_stepper(topn_step: Callable, first: bool) -> Callable:
    """Fused TopN fragment step: fold `topn_step` (acc, batch) -> acc over
    a stacked window. TopN never overflows (the heap capacity is the
    query's LIMIT), so the carry is just the accumulator."""

    def fold(acc0, stacked: Batch):
        first_b, rest = _split_first(stacked)
        acc = topn_step(acc0, first_b)

        def body(a, b):
            return topn_step(a, b), None

        acc, _ = jax.lax.scan(body, acc, rest)
        return acc

    if first:
        def fragment_topn0(stacked: Batch):
            return fold(None, stacked)

        return fragment_topn0

    def fragment_topn(acc, stacked: Batch):
        return fold(acc, stacked)

    return fragment_topn
