from presto_tpu.exec.runner import LocalRunner, ExecConfig

__all__ = ["LocalRunner", "ExecConfig"]
