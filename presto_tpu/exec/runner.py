"""LocalRunner — single-process query runner.

Analog of the reference's LocalQueryRunner
(presto-main/.../testing/LocalQueryRunner.java:218): full
parse → analyze/plan → optimize → execute in-process, no RPC. The
workhorse for tests and single-chip benchmarks.
"""

from __future__ import annotations

from typing import Optional

from presto_tpu.connector import Catalog
from presto_tpu.exec.runtime import ExecConfig, ExecContext, run_plan
from presto_tpu.plan.builder import plan_query
from presto_tpu.plan.nodes import QueryPlan, plan_to_string
from presto_tpu.plan.optimizer import optimize


class LocalRunner:
    def __init__(self, catalog: Catalog, config: Optional[ExecConfig] = None):
        self.catalog = catalog
        self.config = config or ExecConfig()

    def plan(self, sql: str) -> QueryPlan:
        return optimize(plan_query(sql, self.catalog))

    def explain(self, sql: str) -> str:
        return plan_to_string(self.plan(sql).root)

    def run_batch(self, sql: str):
        qp = self.plan(sql)
        ctx = ExecContext(self.catalog, self.config)
        return run_plan(qp, ctx)

    def run(self, sql: str):
        """Execute and return a pandas DataFrame (host materialization)."""
        return self.run_batch(sql).to_pandas()
