"""LocalRunner — single-process query runner.

Analog of the reference's LocalQueryRunner
(presto-main/.../testing/LocalQueryRunner.java:218): full
parse → analyze/plan → optimize → execute in-process, no RPC. The
workhorse for tests and single-chip benchmarks.
"""

from __future__ import annotations

from typing import Optional

from presto_tpu.connector import Catalog
from presto_tpu.exec.runtime import ExecConfig, ExecContext, run_plan
from presto_tpu.plan.builder import plan_query
from presto_tpu.plan.nodes import QueryPlan, plan_to_string
from presto_tpu.plan.optimizer import optimize


class LocalRunner:
    def __init__(self, catalog: Catalog, config: Optional[ExecConfig] = None):
        self.catalog = catalog
        self.config = config or ExecConfig()
        # prepared-plan cache: repeated executions of the same SQL reuse the
        # plan objects and therefore every per-node compiled XLA program
        # (Presto analog: ExpressionCompiler/PageFunctionCompiler caches).
        # Plans with scalar subqueries mutate during param binding → not
        # cacheable.
        self._plan_cache = {}

    def plan(self, sql: str) -> QueryPlan:
        qp = self._plan_cache.get(sql)
        if qp is not None:
            return qp
        qp = optimize(plan_query(sql, self.catalog))
        if not qp.scalar_subqueries:
            self._plan_cache[sql] = qp
        return qp

    def explain(self, sql: str) -> str:
        return plan_to_string(self.plan(sql).root)

    def run_batch(self, sql: str):
        qp = self.plan(sql)
        ctx = ExecContext(self.catalog, self.config)
        return run_plan(qp, ctx)

    def run(self, sql: str):
        """Execute and return a pandas DataFrame (host materialization)."""
        return self.run_batch(sql).to_pandas()

    def explain_analyze(self, sql: str) -> str:
        """Execute with per-operator stats and render the annotated plan
        (reference: EXPLAIN ANALYZE via ExplainAnalyzeOperator)."""
        import dataclasses as _dc

        qp = self.plan(sql)
        cfg = _dc.replace(self.config, collect_stats=True)
        ctx = ExecContext(self.catalog, cfg)
        run_plan(qp, ctx)
        return plan_to_string(qp.root, node_stats=ctx.node_stats)
