"""LocalRunner — single-process query runner.

Analog of the reference's LocalQueryRunner
(presto-main/.../testing/LocalQueryRunner.java:218): full
parse → analyze/plan → optimize → execute in-process, no RPC. The
workhorse for tests and single-chip benchmarks.
"""

from __future__ import annotations

from typing import Optional

from presto_tpu.connector import Catalog
from presto_tpu.exec.runtime import ExecConfig, ExecContext, run_plan
from presto_tpu.plan.builder import plan_query
from presto_tpu.plan.nodes import QueryPlan, plan_to_string
from presto_tpu.plan.optimizer import optimize


def _ddl_nodes():
    from presto_tpu.sql import ast as _ast

    return (_ast.CreateTableAs, _ast.Insert, _ast.DropTable,
            _ast.CreateTable, _ast.CreateView, _ast.DropView,
            _ast.Delete, _ast.Truncate)


_DDL_NODES = None  # populated lazily (ast import cycle safety)


def is_ddl(stmt) -> bool:
    global _DDL_NODES
    if _DDL_NODES is None:
        _DDL_NODES = _ddl_nodes()
    return isinstance(stmt, _DDL_NODES)


def execute_data_definition(stmt, catalog: Catalog, run_query_fn):
    """CTAS / INSERT / DROP executed engine-side (reference: the ~35
    execution/*Task.java DDL classes + the TableWriter → TableFinish
    operator chain returning a rows-written count). `run_query_fn` executes
    the source query AST to a result Batch — local or distributed."""
    import jax.numpy as jnp
    import numpy as np

    from presto_tpu.batch import Batch, Column
    from presto_tpu.sql import ast as _ast
    from presto_tpu.types import BIGINT

    def _count_batch(rows: int) -> Batch:
        vals = np.zeros(128, np.int64)
        vals[0] = rows
        live = np.zeros(128, bool)
        live[0] = True
        return Batch(["rows"], [BIGINT],
                     [Column(jnp.asarray(vals), None)], jnp.asarray(live), {})

    if isinstance(stmt, _ast.CreateView):
        name = stmt.name[-1]
        if name in catalog.views and not stmt.or_replace:
            raise ValueError(f"view already exists: {name}")
        catalog.views[name] = stmt.query
        return _count_batch(0)
    if isinstance(stmt, _ast.DropView):
        if stmt.name[-1] not in catalog.views and not stmt.if_exists:
            raise KeyError(f"view not found: {stmt.name[-1]}")
        catalog.views.pop(stmt.name[-1], None)
        return _count_batch(0)

    conn, tname = catalog.connector_for(stmt.name)
    if isinstance(stmt, _ast.DropTable):
        conn.drop_table(tname, if_exists=stmt.if_exists)
        return _count_batch(0)
    if isinstance(stmt, _ast.CreateTable):
        from presto_tpu.types import parse_type

        if stmt.properties:
            raise ValueError(
                "table properties are only supported on CREATE TABLE AS")
        from presto_tpu.types import GEOMETRY

        cols = [(c, parse_type(t)) for c, t in stmt.columns]
        if any(t is GEOMETRY for _, t in cols):
            raise ValueError(
                "GEOMETRY columns cannot be stored — keep WKT varchar and "
                "parse with ST_GeometryFromText")
        conn.create_empty(tname, cols, if_not_exists=stmt.if_not_exists)
        return _count_batch(0)
    if isinstance(stmt, _ast.Truncate):
        before = int(conn.get_table(tname).row_count or 0)
        conn.truncate_table(tname)
        return _count_batch(before)
    if isinstance(stmt, _ast.Delete):
        # rewrite: keep the rows where the predicate is NOT TRUE
        # (DeleteNode → connector rewrite; NULL predicates keep the row)
        before = int(conn.get_table(tname).row_count or 0)
        if stmt.where is None:
            conn.truncate_table(tname)
            return _count_batch(before)
        keep = _ast.UnaryOp("not", _ast.FunctionCall(
            "coalesce", [stmt.where, _ast.Literal(False, "boolean")]))
        q = _ast.Query(
            select=[_ast.SelectItem(_ast.Star(), None)],
            from_=_ast.Table(stmt.name), where=keep)
        remaining = run_query_fn(q)
        conn.replace_table_from(tname, [remaining])
        after = int(conn.get_table(tname).row_count or 0)
        return _count_batch(before - after)

    result = run_query_fn(stmt.query)
    if isinstance(stmt, _ast.CreateTableAs):
        n = conn.create_table_from(tname, [result],
                                   if_not_exists=stmt.if_not_exists,
                                   properties=stmt.properties or None)
    else:
        n = conn.insert_into(tname, [result])
    return _count_batch(n)


class LocalRunner:
    def __init__(self, catalog: Catalog, config: Optional[ExecConfig] = None):
        self.catalog = catalog
        self.config = config or ExecConfig()
        # prepared-plan cache: repeated executions of the same SQL reuse the
        # plan objects and therefore every per-node compiled XLA program
        # (Presto analog: ExpressionCompiler/PageFunctionCompiler caches).
        # Plans with scalar subqueries mutate during param binding → not
        # cacheable.
        self._plan_cache = {}
        # ExecContext.stats of the most recent run (scan pruning/selective
        # counters and friends) — the local analog of query-info stats
        self.last_stats: dict = {}
        # Tracer of the most recent run (config.tracing) — the local analog
        # of the coordinator's /v1/query/{id}/trace
        self.last_trace = None

    def _new_ctx(self, cfg: Optional[ExecConfig] = None) -> ExecContext:
        from presto_tpu.obs import trace as _obs_trace

        ctx = ExecContext(self.catalog, cfg or self.config)
        if getattr(ctx.config, "tracing", True):
            ctx.tracer = _obs_trace.Tracer()
            self.last_trace = ctx.tracer
        return ctx

    def _optimize(self, qp: QueryPlan) -> QueryPlan:
        """optimize() + the config-gated multiway collapse — the collapse
        runs at plan-install time, not inside optimize(), because the
        verdict depends on the session's join_mode/hbo settings."""
        qp = optimize(qp, self.catalog)
        from presto_tpu.plan.multiway import apply_join_mode

        apply_join_mode(qp, self.catalog, self.config)
        return qp

    def plan(self, sql: str) -> QueryPlan:
        qp = self._plan_cache.get(sql)
        if qp is not None:
            return qp
        qp = self._optimize(plan_query(sql, self.catalog))
        if not qp.scalar_subqueries and qp.cacheable:
            self._plan_cache[sql] = qp
        return qp

    def explain(self, sql: str) -> str:
        qp = self.plan(sql)
        try:
            from presto_tpu.exec.runtime import (_mark_breaker_engines,
                                                 _mark_fragment_fusion)

            _mark_fragment_fusion(qp.root, self.config)
            _mark_breaker_engines(qp.root, ExecContext(self.catalog,
                                                       self.config))
        except Exception:
            pass  # cosmetic markers; the executor re-stamps on run
        return plan_to_string(qp.root)

    def run_batch(self, sql: str):
        from presto_tpu.sql import ast as _ast
        from presto_tpu.sql.parser import parse_sql

        qp = self._plan_cache.get(sql)  # cached plans are never DDL
        if qp is None:
            stmt = parse_sql(sql)
            if is_ddl(stmt):
                return execute_data_definition(stmt, self.catalog,
                                               self._run_query_ast)
            qp = self._optimize(plan_query(stmt, self.catalog))
            if not qp.scalar_subqueries and qp.cacheable:
                self._plan_cache[sql] = qp
        from presto_tpu.exec import farm as _farm

        if _farm.enabled(self.config):
            try:
                # statement→fingerprint corpus record, so queue-wait
                # speculation can resolve future submissions of this SQL
                _farm.record_sql(sql, [qp.root])
            except Exception:
                pass
        ctx = self._new_ctx()
        out = run_plan(qp, ctx)
        self.last_stats = ctx.stats
        return out

    def _run_query_ast(self, q):
        qp = self._optimize(plan_query(q, self.catalog))
        ctx = self._new_ctx()
        out = run_plan(qp, ctx)
        self.last_stats = ctx.stats
        return out

    def run(self, sql: str):
        """Execute and return a pandas DataFrame (host materialization)."""
        return self.run_batch(sql).to_pandas()

    def explain_analyze(self, sql: str) -> str:
        """Execute with per-operator stats and render the annotated plan
        (reference: EXPLAIN ANALYZE via ExplainAnalyzeOperator)."""
        import dataclasses as _dc

        qp = self.plan(sql)
        cfg = _dc.replace(self.config, collect_stats=True)
        ctx = self._new_ctx(cfg)
        run_plan(qp, ctx)
        self.last_stats = ctx.stats
        return plan_to_string(qp.root, node_stats=ctx.node_stats)
