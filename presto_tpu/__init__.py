"""presto_tpu — a TPU-native distributed SQL query engine.

A from-scratch re-design of the capability surface of Presto (reference:
oerling/presto, the "Aria" fork of prestodb 0.227) for TPU hardware:

- SQL frontend (lexer/parser/analyzer)             ~ presto-parser, sql/analyzer
- Logical planner + optimizer + fragmenter         ~ sql/planner
- Columnar execution on fixed-shape device batches ~ operator/* over Page/Block
- XLA-jitted fused pipelines (scan-filter-project-agg) ~ presto-bytecode codegen
- Distributed exchanges via jax.sharding + all_to_all  ~ execution/buffer + ExchangeClient
- TPC-H connector + parquet storage                ~ presto-tpch, presto-orc/hive

Architecture stance (NOT a port): Presto compensates for the JVM with runtime
bytecode generation and flat long[] hash tables; we compensate for XLA's
static-shape world with fixed-capacity column batches, validity + live-row
masks instead of selection vectors, sort-based grouping instead of
pointer-chasing hash tables, and host-precomputed dictionary lookup tables
instead of on-device string processing.
"""

import os as _os

import jax

# A SQL engine needs 64-bit integers (BIGINT, DECIMAL-as-scaled-int64) and
# float64 (DOUBLE). TPU emulates both; hot money arithmetic uses int64.
jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: the engine's per-plan-node programs
# include multi-operand int64 sorts whose TPU compiles run 10-50 s each;
# caching them on disk cuts warm-up to ~0.2 s across processes and rounds
# (reference analog: Presto's generated-class caches are per-JVM; XLA's
# serialized executables survive restarts). Opt out / relocate via
# PRESTO_TPU_COMPILE_CACHE ("" disables).
#
# The directory is keyed by a CPU-capability fingerprint: XLA:CPU AOT
# executables bake in the COMPILING host's feature set, and loading one
# on a host without those features SIGSEGVs/SIGILLs (observed: a cache
# written on an amx-avx512 box crashed the whole test suite after the
# machine changed between rounds).


def _machine_tag() -> str:
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    import hashlib

                    return hashlib.sha1(line.encode()).hexdigest()[:10]
    except OSError:
        pass
    import platform

    return platform.machine()


# PRESTO_TPU_CACHE_DIR is the documented umbrella knob for the compile
# plane's on-disk state; PRESTO_TPU_COMPILE_CACHE stays as the specific
# (and overriding) name. Either set to "" disables.
_cache_dir = _os.environ.get("PRESTO_TPU_COMPILE_CACHE")
if _cache_dir is None:
    _cache_dir = _os.environ.get("PRESTO_TPU_CACHE_DIR")
    if _cache_dir:
        _cache_dir = _os.path.join(_cache_dir, f"xla_{_machine_tag()}")
if _cache_dir is None:
    _cache_dir = _os.path.join(_os.path.expanduser("~"), ".cache",
                               f"presto_tpu_xla_{_machine_tag()}")
if _cache_dir:
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

    # XLA:CPU executable (de)serialization SEGFAULTS on this host/jaxlib
    # (reproduced three times: twice in put_executable_and_time — once
    # even under a process-wide lock, ruling out a pure thread race —
    # and once in the deserialize path; always on the big multi-operand
    # sort programs the engine compiles). The persistent cache therefore
    # BYPASSES the cpu backend: callers see a plain miss and compile
    # in-process (the per-process jit caches still dedupe), while TPU —
    # where 10-50 s compiles make the cache worth having — keeps it,
    # serialized through one lock. Best-effort: silently skipped if
    # jax's internals move.
    try:
        import threading as _threading

        from jax._src import compilation_cache as _cc

        _cc_lock = _threading.Lock()
        _orig_cc_get = _cc.get_executable_and_time
        _orig_cc_put = _cc.put_executable_and_time

        def _cc_platform(a, k):
            for x in list(a) + list(k.values()):
                p = getattr(x, "platform", None)
                if isinstance(p, str):
                    return p
            return None

        def _guarded_cc_get(*a, **k):
            if _cc_platform(a, k) == "cpu":
                return None, None  # plain miss: compile in-process
            with _cc_lock:
                return _orig_cc_get(*a, **k)

        def _guarded_cc_put(*a, **k):
            if _cc_platform(a, k) == "cpu":
                return None
            with _cc_lock:
                return _orig_cc_put(*a, **k)

        _cc.get_executable_and_time = _guarded_cc_get
        _cc.put_executable_and_time = _guarded_cc_put

        # CONCURRENT XLA:CPU compiles from multiple threads also
        # segfault on this host (reproduced in backend_compile_and_load
        # once the cache paths were bypassed; the same programs compile
        # fine serially — e.g. every warm-cache suite run). Serialize
        # compilation through the same lock: concurrent compiles only
        # ever happen in the in-process multi-worker cluster, where the
        # per-process jit caches already dedupe most of them.
        from jax._src import compiler as _compiler

        _orig_bcl = _compiler.backend_compile_and_load

        def _locked_bcl(*a, **k):
            with _cc_lock:
                return _orig_bcl(*a, **k)

        _compiler.backend_compile_and_load = _locked_bcl
    except Exception:  # pragma: no cover
        pass

__version__ = "0.1.0"

from presto_tpu.types import (  # noqa: E402
    BOOLEAN,
    BIGINT,
    INTEGER,
    DOUBLE,
    REAL,
    DATE,
    VARCHAR,
    DecimalType,
    Type,
)
from presto_tpu.batch import Batch, Column  # noqa: E402

__all__ = [
    "BOOLEAN",
    "BIGINT",
    "INTEGER",
    "DOUBLE",
    "REAL",
    "DATE",
    "VARCHAR",
    "DecimalType",
    "Type",
    "Batch",
    "Column",
]
