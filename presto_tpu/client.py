"""Statement client — the HTTP polling loop shared by CLI and DBAPI.

Reference: presto-client StatementClientV1.java:87,340-352 (`advance()`
follows `nextUri` until absent; session mutations arrive via
X-Presto-Set-Session / X-Presto-Clear-Session response headers and are
client-carried on subsequent requests — the server is stateless).
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Any, Dict, Iterator, List, Optional


class QueryError(RuntimeError):
    def __init__(self, message: str, error_name: str = "", error_type: str = ""):
        super().__init__(message)
        self.error_name = error_name
        self.error_type = error_type


class StatementClient:
    """One statement's lifecycle: submit → poll nextUri → rows."""

    def __init__(self, server: str, sql: str, session: "ClientSession"):
        self.server = server.rstrip("/")
        self.sql = sql
        self.session = session
        self.columns: Optional[List[dict]] = None
        self.query_id: Optional[str] = None
        self.stats: Dict[str, Any] = {}
        self.progress_uri: Optional[str] = None
        self._next_uri: Optional[str] = None
        self._current_data: List[list] = []
        self._error: Optional[dict] = None
        self._submit()

    def _headers(self) -> Dict[str, str]:
        h = {"X-Presto-User": self.session.user, "Content-Type": "text/plain"}
        if self.session.source:
            h["X-Presto-Source"] = self.session.source
        if self.session.catalog:
            h["X-Presto-Catalog"] = self.session.catalog
        if self.session.schema:
            h["X-Presto-Schema"] = self.session.schema
        if self.session.properties:
            from urllib.parse import quote

            # values are URL-encoded: a comma inside a value must survive
            # the comma-separated pair list (reference protocol does the same)
            h["X-Presto-Session"] = ",".join(
                f"{k}={quote(str(v))}" for k, v in self.session.properties.items()
            )
        return h

    def _apply_response_headers(self, headers):
        sets = headers.get_all("X-Presto-Set-Session") if hasattr(
            headers, "get_all") else None
        for item in sets or []:
            if "=" in item:
                k, v = item.split("=", 1)
                self.session.properties[k.strip()] = v.strip()
        clears = headers.get_all("X-Presto-Clear-Session") if hasattr(
            headers, "get_all") else None
        for item in clears or []:
            self.session.properties.pop(item.strip(), None)

    def _consume(self, payload: dict):
        self.query_id = payload.get("id") or self.query_id
        self.stats = payload.get("stats", {})
        if payload.get("columns") and self.columns is None:
            self.columns = payload["columns"]
        self._current_data = payload.get("data") or []
        self._next_uri = payload.get("nextUri")
        # present only when the server tracks this query's lifecycle
        # (session lifecycle=on); pollable even after the query finishes
        self.progress_uri = payload.get("progressUri") or self.progress_uri
        self._error = payload.get("error")

    def _submit(self):
        req = urllib.request.Request(
            f"{self.server}/v1/statement",
            data=self.sql.encode(), method="POST", headers=self._headers(),
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            self._apply_response_headers(r.headers)
            self._consume(json.loads(r.read()))

    def _advance(self) -> bool:
        if self._next_uri is None:
            return False
        backoff = 0.0
        while True:
            try:
                with urllib.request.urlopen(self._next_uri, timeout=60) as r:
                    self._apply_response_headers(r.headers)
                    self._consume(json.loads(r.read()))
                return True
            except urllib.error.URLError:
                backoff = min((backoff or 0.05) * 2, 1.0)
                time.sleep(backoff)
                if backoff >= 1.0:
                    raise

    def rows(self) -> Iterator[list]:
        while True:
            if self._error:
                raise QueryError(
                    self._error.get("message", "query failed"),
                    self._error.get("errorName", ""),
                    self._error.get("errorType", ""),
                )
            yield from self._current_data
            self._current_data = []
            if not self._advance():
                return

    def progress(self) -> Optional[dict]:
        """Fetch the server's live progress estimate (fraction complete,
        HBO/fragment provenance, lifecycle segments). None when the server
        exposed no progressUri (lifecycle=off) or the fetch fails."""
        if not self.progress_uri:
            return None
        try:
            with urllib.request.urlopen(self.progress_uri, timeout=10) as r:
                return json.loads(r.read())
        except Exception:
            return None

    def cancel(self):
        if self.query_id:
            req = urllib.request.Request(
                f"{self.server}/v1/statement/{self.query_id}", method="DELETE"
            )
            try:
                urllib.request.urlopen(req, timeout=10).read()
            except Exception:
                pass


class ClientSession:
    """Client-carried session state (user, default catalog/schema, property
    overrides accumulated from SET SESSION responses)."""

    def __init__(self, user: str = "user", source: str = "presto-tpu-client",
                 catalog: Optional[str] = None, schema: Optional[str] = None):
        self.user = user
        self.source = source
        self.catalog = catalog
        self.schema = schema
        self.properties: Dict[str, str] = {}


def execute(server: str, sql: str,
            session: Optional[ClientSession] = None) -> tuple:
    """One-shot helper: (column names, rows)."""
    client = StatementClient(server, sql, session or ClientSession())
    rows = list(client.rows())
    cols = [c["name"] for c in (client.columns or [])]
    return cols, rows
