"""Memory accounting: context tree + pools + revocation.

Reference: presto-memory-context (AggregatedMemoryContext /
LocalMemoryContext), memory/MemoryPool.java + QueryContext.java (reserve /
free with blocking), execution/MemoryRevokingScheduler.java:46 (when a pool
crosses a threshold, ask revocable operators to spill down to a target).

TPU-native shape: the scarce resource is HBM. Batches are fixed-capacity
device arrays, so accounting is exact: capacity × itemsize summed over
columns. Execution is synchronous per batch, so revocation is synchronous
too — a reserve() that crosses the threshold invokes registered revokers
(spillable aggregations / join builds) inline until usage drops below the
target, then proceeds; if nothing can be revoked and the limit is exceeded,
the query fails with EXCEEDED_MEMORY_LIMIT (the per-node slice of the
cluster OOM-killer policy).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional


class ExceededMemoryLimit(RuntimeError):
    pass


class MemoryPool:
    """A worker's query memory pool (MemoryPool.java analog)."""

    def __init__(self, limit_bytes: Optional[int] = None,
                 revoke_threshold: float = 0.9, revoke_target: float = 0.5):
        self.limit = limit_bytes
        self.reserved = 0  # shared: guarded-by(self._lock)
        self.peak = 0  # shared: guarded-by(self._lock)
        self.revoke_threshold = revoke_threshold
        self.revoke_target = revoke_target
        self._lock = threading.Lock()
        # revocable-state owners: fn(bytes_to_free) -> bytes actually freed
        self._revokers: List[Callable[[int], int]] = []

    def add_revoker(self, fn: Callable[[int], int]):
        with self._lock:
            self._revokers.append(fn)

    def remove_revoker(self, fn: Callable[[int], int]):
        with self._lock:
            try:
                self._revokers.remove(fn)
            except ValueError:
                pass

    def add_partial_revoker(self, owner) -> Callable[[int], int]:
        """Register a PARTITION-GRANULAR revocable-state owner (the
        adaptive partial-revocation protocol): `owner` exposes
        ``partition_sizes() -> [(pid, bytes)]`` and
        ``revoke_partition(pid) -> estimated bytes`` — the latter MARKS
        the partition (honored at the owner's next batch boundary, same
        deferred contract as flag revokers). The owner is wrapped into
        the ordinary revoker list so reserve()-inline pressure reaches
        it too, but with largest-partition-first selection instead of
        whole-operator revocation. Returns the wrapper; pass it to
        ``remove_revoker`` on operator teardown."""

        def fn(want):
            self._mark_partial([owner], int(want))
            return 0  # freeing is deferred to the owner's batch boundary

        fn._partial_owner = owner
        with self._lock:
            self._revokers.append(fn)
        return fn

    @staticmethod
    def _mark_partial(owners, want: int) -> int:
        """Largest-partition-first marking across `owners` until the
        estimated freed bytes cover `want` (want <= 0 sheds exactly one
        partition — the largest). Returns partitions marked."""
        ranked = []
        for o in owners:
            try:
                ranked.extend((int(b), o, pid)
                              for pid, b in o.partition_sizes())
            except Exception:
                continue
        ranked.sort(key=lambda t: -t[0])
        est = 0
        marked = 0
        for b, o, pid in ranked:
            try:
                est += int(o.revoke_partition(pid))
            except Exception:
                continue
            marked += 1
            if want <= 0 or est >= want:
                break
        return marked

    def request_partial_revoke(self, want_bytes: int = 0) -> int:
        """Out-of-band PARTIAL revoke: shed the largest partitions across
        every partition-granular owner instead of signaling whole
        operators. Returns partitions marked — 0 when no partial owners
        are registered, which callers (ClusterMemoryManager's enforce
        ladder) treat as "fall through to whole-operator revoke"."""
        with self._lock:
            owners = [fn._partial_owner for fn in self._revokers
                      if hasattr(fn, "_partial_owner")]
        if not owners:
            return 0
        return self._mark_partial(owners, int(want_bytes))

    def reserve(self, bytes_: int, tag: str = "") -> None:
        if bytes_ <= 0:
            return
        if self.limit is not None:
            with self._lock:
                projected = self.reserved + bytes_
                over_threshold = projected > self.limit * self.revoke_threshold
                revokers = list(self._revokers) if over_threshold else []
            if revokers:
                # MemoryRevokingScheduler: revoke until usage ≤ target
                target = int(self.limit * self.revoke_target)
                before, t0 = self.reserved, time.time()
                for fn in revokers:
                    if self.reserved + bytes_ <= target:
                        break
                    try:
                        fn(self.reserved + bytes_ - target)
                    except Exception:
                        pass
                self._trace_revoke(before, bytes_, target, t0)
            with self._lock:
                if self.reserved + bytes_ > self.limit:
                    raise ExceededMemoryLimit(
                        f"Query exceeded per-node memory limit of "
                        f"{self.limit} bytes (requested {bytes_} for {tag}, "
                        f"reserved {self.reserved})"
                    )
                self.reserved += bytes_
                self.peak = max(self.peak, self.reserved)
        else:
            with self._lock:
                self.reserved += bytes_
                self.peak = max(self.peak, self.reserved)

    def _trace_revoke(self, before: int, requested: int, target: int,
                      t0: float) -> None:
        """Memory pressure as a structured trace event: a reserve()
        crossed the revoke threshold and asked revokers to spill. Rides
        the thread-local tracer (no-op when tracing is off)."""
        try:
            from presto_tpu.obs import trace as _obs_trace

            tr = _obs_trace.current()
            if tr.enabled:
                tr.record("memory_revoke", "memory_revoke", t0, time.time(),
                          reserved_before=int(before),
                          reserved_after=int(self.reserved),
                          requested=int(requested), target=int(target),
                          limit=int(self.limit or 0))
        except Exception:
            pass

    def request_revoke(self, want_bytes: int = 0) -> int:
        """Out-of-band revoke signal (MemoryRevokingScheduler's
        requestMemoryRevoking, as opposed to the reserve()-inline path):
        ask every registered revocable-state owner to shed state. Flag-based
        revokers mark themselves and spill at their next batch boundary.
        Returns the number of revokers signaled."""
        with self._lock:
            revokers = list(self._revokers)
        for fn in revokers:
            try:
                fn(int(want_bytes))
            except Exception:
                pass
        return len(revokers)

    def free(self, bytes_: int) -> None:
        if bytes_ <= 0:
            return
        with self._lock:
            self.reserved = max(0, self.reserved - bytes_)

    def info(self) -> dict:
        with self._lock:
            return {"reservedBytes": self.reserved, "peakBytes": self.peak,
                    "limitBytes": self.limit}


class QueryScopedPool:
    """Per-query view over a worker's shared MemoryPool (QueryContext
    analog): forwards reserve/free to the node pool while tracking this
    query's own reservation, so the worker can report per-query bytes to
    the coordinator's ClusterMemoryManager (MemoryPoolInfo's
    queryMemoryReservations)."""

    def __init__(self, pool: MemoryPool, query_id: str = ""):
        self.pool = pool
        self.query_id = query_id
        self.query_reserved = 0  # this query's slice of the node pool
        self.peak = 0
        self._lock = threading.Lock()
        # surface the node pool's limit/revoker machinery unchanged
        self.limit = pool.limit
        self.revoke_threshold = pool.revoke_threshold
        self.revoke_target = pool.revoke_target

    @property
    def reserved(self) -> int:
        # NODE-wide reservation: spill/revoke decisions must see pressure
        # from every query sharing the pool, not just this one
        return self.pool.reserved

    def add_revoker(self, fn):
        self.pool.add_revoker(fn)

    def remove_revoker(self, fn):
        self.pool.remove_revoker(fn)

    def add_partial_revoker(self, owner):
        return self.pool.add_partial_revoker(owner)

    def request_partial_revoke(self, want_bytes: int = 0) -> int:
        return self.pool.request_partial_revoke(want_bytes)

    def reserve(self, bytes_: int, tag: str = "") -> None:
        self.pool.reserve(bytes_, tag or self.query_id)
        with self._lock:
            self.query_reserved += max(bytes_, 0)
            self.peak = max(self.peak, self.query_reserved)

    def free(self, bytes_: int) -> None:
        self.pool.free(bytes_)
        with self._lock:
            self.query_reserved = max(0, self.query_reserved - max(bytes_, 0))

    def info(self) -> dict:
        return self.pool.info()


class LocalMemoryContext:
    """One operator's accounting slot (LocalMemoryContext.java): setBytes
    semantics — the delta flows to the pool."""

    def __init__(self, pool: MemoryPool, tag: str = ""):
        self.pool = pool
        self.tag = tag
        self.bytes = 0

    def set_bytes(self, n: int):
        delta = n - self.bytes
        if delta > 0:
            self.pool.reserve(delta, self.tag)
        else:
            self.pool.free(-delta)
        self.bytes = n

    def close(self):
        self.set_bytes(0)


class AggregatedMemoryContext:
    """Groups child contexts (task/query rollup —
    AggregatedMemoryContext.java)."""

    def __init__(self, pool: MemoryPool, tag: str = ""):
        self.pool = pool
        self.tag = tag
        self._children: List[LocalMemoryContext] = []

    def new_local(self, tag: str = "") -> LocalMemoryContext:
        c = LocalMemoryContext(self.pool, f"{self.tag}/{tag}")
        self._children.append(c)
        return c

    @property
    def bytes(self) -> int:
        return sum(c.bytes for c in self._children)

    def close(self):
        for c in self._children:
            c.close()


def batch_device_bytes(batch) -> int:
    """Exact device footprint of a Batch (static shapes make this precise)."""
    total = batch.live.shape[0]  # live mask: 1 byte/row
    for c in batch.columns:
        total += c.values.shape[0] * c.values.dtype.itemsize
        if c.validity is not None:
            total += c.validity.shape[0]
    return total
