"""Worker — the data-plane node: task CRUD over HTTP, fragment execution,
output buffers.

Reference surface:
- server/TaskResource.java:84 — `@Path("/v1/task")`: create/update (POST
  :126), status (GET :188), results by token (GET :245-247), ack (:304),
  abort (DELETE :317)
- execution/SqlTaskManager.java:84,351 + SqlTask / TaskStateMachine
- execution/SqlTaskExecution.java:82 — splits → pipeline → drivers
- server/GracefulShutdownHandler.java:43 — drain then exit on
  PUT /v1/info/state "SHUTTING_DOWN"

TPU-native shape: a task executes one plan fragment as a stream of
fixed-capacity device batches (exec/runtime); the task's sink serializes
output pages into an OutputBuffer partitioned for the consumer stage
(hash / broadcast / gather). Fragments arrive as JSON over the closed
plan-node vocabulary (plan/codec.py) — the TaskUpdateRequest JSON/Smile
codec analog; nothing on the wire can execute code.
"""

from __future__ import annotations

import dataclasses
import json
import re
import threading
import time
import traceback
from functools import lru_cache
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

import numpy as np

from presto_tpu.batch import Batch
from presto_tpu.connector import Catalog
from presto_tpu.exec.runtime import ExecConfig, ExecContext, execute_node
from presto_tpu.obs import trace as _obs_trace
from presto_tpu.ops.partition import partition_ids
from presto_tpu.plan.fragmenter import (
    OUT_BROADCAST,
    OUT_GATHER,
    OUT_HASH,
    OUT_RR,
    Fragment,
)
from presto_tpu.serde import serialize_batch
from presto_tpu.server.buffers import BufferFailed, OutputBuffer
from presto_tpu.server.exchange import ExchangeClient, encode_results_payload


@dataclasses.dataclass
class TaskUpdate:
    """POST /v1/task/{id} body (TaskUpdateRequest analog: fragment + split
    assignment + output buffer layout + upstream locations)."""

    fragment: Fragment
    task_index: int
    n_tasks: int
    n_out_partitions: int
    upstreams: Dict[int, List[str]]  # fragment_id -> result-buffer base URLs
    config: dict = dataclasses.field(default_factory=dict)
    # phased scheduling: build-phase tasks spool their output (no enqueue
    # back-pressure) because their consumers are created in a LATER phase
    # and cannot drain them yet (PhasedExecutionSchedule + the reference's
    # spooling broadcast buffers)
    spool: bool = False
    # coordinator-assigned split ordinals per table (soft-affinity
    # placement; None → static task_index::n_tasks striding), with the
    # coordinator's enumeration count so a drifted table (concurrent
    # INSERT) fails loudly instead of silently dropping splits
    split_assignment: Optional[Dict[str, List[int]]] = None
    split_counts: Optional[Dict[str, int]] = None


@lru_cache(maxsize=256)
def _jit_partition_ids(keys: tuple, n_parts: int):
    import jax

    return jax.jit(lambda b: partition_ids(b, keys, n_parts))


@lru_cache(maxsize=256)
def _jit_radix_ids(keys: tuple, n_radix: int):
    import jax

    from presto_tpu.ops.radix import radix_ids

    return jax.jit(lambda b: radix_ids(b, keys, n_radix))


class TaskExecutor:
    """Fair batch-granularity time slicing across concurrent tasks — the
    analog of TaskExecutor.java:78 + MultilevelSplitQueue.java:41. Each
    task thread must hold a run slot to compute its next batch; when
    demand exceeds `slots`, free slots go to the waiting tasks with the
    LEAST accumulated compute time (so short interactive queries are not
    starved behind long scans). The reference time-slices at split
    quanta; the batch boundary is this engine's natural quantum."""

    def __init__(self, slots: int = 4):
        self.slots = max(1, slots)
        self._running = 0
        self._cv = threading.Condition()
        self._acc: dict = {}       # task_id -> accumulated seconds
        self._waiting: list = []

    def register(self, task_id: str) -> "TaskLease":
        with self._cv:
            self._acc.setdefault(task_id, 0.0)
        return TaskLease(self, task_id)

    def unregister(self, task_id: str):
        with self._cv:
            self._acc.pop(task_id, None)

    def accumulated(self, task_id: str) -> float:
        with self._cv:
            return self._acc.get(task_id, 0.0)

    def _acquire(self, task_id: str):
        with self._cv:
            self._waiting.append(task_id)
            while True:
                if self._running < self.slots:
                    free = self.slots - self._running
                    most_deserving = sorted(
                        self._waiting, key=lambda t: self._acc.get(t, 0.0)
                    )[:free]
                    if task_id in most_deserving:
                        self._waiting.remove(task_id)
                        self._running += 1
                        return
                self._cv.wait(timeout=1.0)

    def _release(self, task_id: str, elapsed: float):
        with self._cv:
            self._running -= 1
            self._acc[task_id] = self._acc.get(task_id, 0.0) + elapsed
            self._cv.notify_all()


class TaskLease:
    """Context manager: one held section = one scheduling quantum."""

    def __init__(self, executor: TaskExecutor, task_id: str):
        self.executor = executor
        self.task_id = task_id
        self._t0 = 0.0

    def __enter__(self):
        self.executor._acquire(self.task_id)
        import time

        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        import time

        self.executor._release(self.task_id, time.monotonic() - self._t0)
        return False


class TaskExecution:
    """One task: fragment + splits in, pages out (SqlTaskExecution analog)."""

    def __init__(self, task_id: str, update: TaskUpdate, catalog: Catalog,
                 memory_pool=None, spill_manager=None, executor=None,
                 trace_token: Optional[str] = None, node_id: str = ""):
        self.task_id = task_id
        self.update = update
        self.catalog = catalog
        self.memory_pool = memory_pool
        self.spill_manager = spill_manager
        self.executor = executor
        self.node_id = node_id
        # trace token travels in the X-Presto-Tpu-Trace header, NOT the
        # TaskUpdate body — the codec vocabulary stays closed. Each task
        # records into its own tracer; the coordinator pulls the dump via
        # GET /v1/task/{id}/trace and stitches the query tree.
        self.tracer = _obs_trace.NOOP
        self._trace_parent: Optional[str] = None
        if trace_token and update.config.get("tracing", True):
            trace_id, parent = _obs_trace.parse_token(trace_token)
            self.tracer = _obs_trace.Tracer(trace_id=trace_id)
            self._trace_parent = parent
        self.state = "running"
        self.error: Optional[str] = None
        self.stats_report: Optional[list] = None  # per-operator rows
        # lifecycle plane (obs/lifecycle.py): count emitted rows/batches so
        # heartbeats carry live query progress; gated — lifecycle=off keeps
        # the pre-lifecycle sink path and heartbeat doc bit-for-bit
        self._count_progress = str(
            update.config.get("lifecycle", "on")).lower() == "on"
        self.rows_emitted = 0
        self.batches_emitted = 0
        # mid-flight telemetry plane (obs/inflight.py): a per-task
        # publisher operators heartbeat through at window boundaries;
        # gated — inflight=off keeps the task path bit-for-bit
        self._inflight = None
        if str(update.config.get("inflight", "off")).lower() == "on":
            from presto_tpu.obs import inflight as _obs_inflight

            m = _TASK_ID_RE.match(task_id)
            self._inflight = _obs_inflight.task(
                m.group(1) if m else task_id, task_id,
                fragment=int(m.group(2)) if m else 0)
        f = update.fragment
        self.buffer = OutputBuffer(
            update.n_out_partitions,
            broadcast=(f.output_partitioning == OUT_BROADCAST),
            # phased build tasks spool overflow to disk: their consumers
            # are created in a later phase, so back-pressure cannot drain
            spool_dir=(spill_manager.dir if update.spool and spill_manager
                       is not None else None),
        )
        self.created_at = time.time()
        self.finished_at: Optional[float] = None
        self._clients: List[ExchangeClient] = []
        self.thread = threading.Thread(
            target=self._run, daemon=True, name=f"task-{task_id}"
        )
        self.thread.start()

    def _remote_source_factory(self, fragment_id: int):
        urls = self.update.upstreams[fragment_id]
        client = ExchangeClient(urls)
        self._clients.append(client)
        if not self.tracer.enabled:
            return client.batches()
        return self._traced_exchange(client, fragment_id)

    def _traced_exchange(self, client: ExchangeClient, fragment_id: int):
        """Exchange pull with consumer-blocked time accounted: each next()
        wall goes to the exchange-wait histogram, and one exchange_wait
        span records the stream envelope with total blocked seconds."""
        from presto_tpu.obs import metrics as _obs_metrics

        it = client.batches()
        parent = self.tracer.current_parent()
        start = time.time()
        waited = 0.0
        try:
            while True:
                w0 = time.perf_counter()
                try:
                    b = next(it)
                except StopIteration:
                    break
                dt = time.perf_counter() - w0
                waited += dt
                _obs_metrics.EXCHANGE_WAIT.observe(dt, plane="worker")
                yield b
        finally:
            self.tracer.record("exchange_wait", "exchange_wait", start,
                               time.time(), parent_id=parent,
                               fragment=fragment_id,
                               wait_s=round(waited, 6))

    def _run(self):
        try:
            cfg = ExecConfig(**self.update.config)
            if self.tracer.enabled:
                from presto_tpu.obs import metrics as _obs_metrics

                # created_at → first execution work = schedule delay
                _obs_metrics.TASK_SCHEDULE_DELAY.observe(
                    max(0.0, time.time() - self.created_at),
                    plane="worker", node=self.node_id)
                with _obs_trace.use(self.tracer), self.tracer.span(
                        "task", "task", parent_id=self._trace_parent,
                        task_id=self.task_id, node=self.node_id):
                    self._run_inner(cfg)
            else:
                self._run_inner(cfg)
            self.buffer.set_no_more_pages()
            self.state = "finished"
            self.finished_at = time.time()
        except Exception as e:
            self.error = f"{type(e).__name__}: {e}\n{traceback.format_exc()}"
            self.state = "failed"
            self.finished_at = time.time()
            self.buffer.fail(self.error)
        finally:
            if self._inflight is not None:
                self._inflight.finish()
            for c in self._clients:
                c.close()

    def _run_inner(self, cfg: ExecConfig):
        ctx = ExecContext(self.catalog, cfg,
                          memory_pool=self.memory_pool,
                          spill_manager=self.spill_manager)
        try:
            self._run_with_ctx(cfg, ctx)
        finally:
            # spill-file leak guard: a task that failed or was canceled
            # mid-spill must not strand spill files on the worker's disk
            ctx.cleanup_spill()

    def _run_with_ctx(self, cfg: ExecConfig, ctx: ExecContext):
        ctx.tracer = self.tracer
        ctx.inflight = self._inflight
        if ctx.adaptive is not None:
            # adaptive decisions land in this task's mid-flight heartbeat
            # as adaptive.<kind> operator records, stamped with the query
            # so /doctor can attribute them
            ctx.adaptive.inflight = self._inflight
            if self._inflight is not None:
                ctx.adaptive.query_id = self._inflight.query_id
        ctx.task_index = self.update.task_index
        ctx.n_tasks = self.update.n_tasks
        ctx.split_assignment = self.update.split_assignment
        ctx.split_counts = self.update.split_counts
        ctx.remote_sources = self._remote_source_factory
        f = self.update.fragment
        # compile plane: stamp structural program namespaces so this task
        # shares compiled programs with every other task of this fragment
        # (and any other fragment whose nodes encode identically), and
        # kick off ahead-of-stream precompilation when configured — the
        # trace/compile overlaps scan decode instead of serializing in
        # front of the first batch
        from presto_tpu.exec.runtime import install_plan_programs

        install_plan_programs(f.root, ctx)
        sink = self._make_sink(f, cfg)
        stream = execute_node(f.root, ctx)
        # fair time slicing applies to LEAF fragments only: a task
        # with remote sources can block inside next() waiting for
        # producer pages, and holding a run slot while blocked would
        # deadlock the slot pool (the reference's splits yield when
        # blocked; the exchange iterator cannot)
        gated = (self.executor is not None
                 and not f.remote_sources())
        if gated:
            lease = self.executor.register(self.task_id)
            try:
                while True:
                    with lease:
                        try:
                            batch = next(stream)
                        except StopIteration:
                            break
                        sink(batch)
            finally:
                self.executor.unregister(self.task_id)
        else:
            for batch in stream:
                sink(batch)
        if getattr(cfg, "devprof", "off") == "on":
            # devprof plane: reconcile this task's pool slice against the
            # device watermark once the task's work is done
            try:
                from presto_tpu.obs import devprof as _devprof

                _devprof.reconcile(ctx.memory_pool, plane="worker",
                                   site="task")
            except Exception:
                pass
        if cfg.collect_stats:
            names = {}
            jstats = {}

            def walk(n):
                names[id(n)] = type(n).__name__
                js = getattr(n, "_jit_stats", None)
                if js:
                    jstats[id(n)] = js
                for c in n.children():
                    walk(c)

            walk(f.root)
            rows = []
            for nid, st in ctx.node_stats.items():
                row = {"node": names.get(nid, "?"), **st}
                js = jstats.get(nid)
                if js:
                    # per-jit-key compile events, summed for the operator:
                    # lets EXPLAIN ANALYZE split wall into compile vs
                    # execute per node
                    row["compiles"] = sum(v.get("compiles", 0)
                                          for v in js.values())
                    row["compile_wall_s"] = round(
                        sum(v.get("compile_wall_s", 0.0)
                            for v in js.values()), 6)
                    # devprof plane: XLA-analyzed device numbers, summed
                    # (flops/bytes) or maxed (footprint) per operator so
                    # the coordinator can render [peak/flops/bytes/ai]
                    flops = sum(v.get("flops", 0.0) for v in js.values())
                    byts = sum(v.get("bytes_accessed", 0.0)
                               for v in js.values())
                    peak = max((v.get("footprint_bytes", 0.0)
                                for v in js.values()), default=0.0)
                    if flops:
                        row["flops"] = flops
                    if byts:
                        row["bytes_accessed"] = byts
                    if peak:
                        row["peak_bytes"] = peak
                rows.append(row)
            rows += [{"node": k, "rows": v, "batches": 0, "wall_s": 0.0}
                     for k, v in ctx.stats.items()]
            self.stats_report = rows

    def _make_sink(self, f: Fragment, cfg):
        sink = self._make_sink_inner(f, cfg)
        if not self._count_progress and self._inflight is None:
            return sink

        def counting_sink(b: Batch, _sink=sink):
            # live-row accounting happens before the inner sink's own
            # serialize so a sink raise still leaves the rows visible
            rows = 0
            if self._count_progress:
                rows = int(np.asarray(b.live).sum())
                self.rows_emitted += rows
                self.batches_emitted += 1
            if self._inflight is not None:
                # rows ride along only when lifecycle already synced the
                # live count — inflight alone never adds a device sync
                self._inflight.publish("output", rows_out=rows, batches=1)
            _sink(b)

        return counting_sink

    def _make_sink_inner(self, f: Fragment, cfg):
        if f.output_partitioning == OUT_HASH and self.update.n_out_partitions > 1:
            pid_fn = _jit_partition_ids(
                tuple(f.output_keys), self.update.n_out_partitions
            )
            R = cfg.radix_partitions if f.radix_align else 0
            rid_fn = _jit_radix_ids(tuple(f.output_keys), R) if R > 1 else None

            def sink(b: Batch):
                # device-side hash, host-side scatter into per-consumer pages
                # (PartitionedOutputOperator.partitionPage:377 analog)
                pid = np.asarray(pid_fn(b))
                live = np.asarray(b.live)
                if rid_fn is None:
                    for p in range(self.update.n_out_partitions):
                        mask = live & (pid == p)
                        if mask.any():
                            self.buffer.enqueue(
                                p, serialize_batch(b.with_live(mask),
                                                   dict_refs=True))
                    return
                # partition-aligned exchange: the consumer breaker radix-
                # partitions on these same keys, so split each consumer's
                # page further by the radix id (top bits of the SAME 63-bit
                # hash whose modulo picked the consumer) and tag it — the
                # consumer routes the page straight to partition r with no
                # re-partition sort
                rid = np.asarray(rid_fn(b))
                keys = tuple(f.output_keys)
                for p in range(self.update.n_out_partitions):
                    pmask = live & (pid == p)
                    if not pmask.any():
                        continue
                    for r in np.unique(rid[pmask]):
                        self.buffer.enqueue(
                            p, serialize_batch(
                                b.with_live(pmask & (rid == r)),
                                radix=(int(r), R, keys), dict_refs=True))

            return sink

        if f.output_partitioning == OUT_RR and self.update.n_out_partitions > 1:
            n_parts = self.update.n_out_partitions
            state = {"next": self.update.task_index}  # stagger producers

            def sink(b: Batch):
                # page-level round robin (the reference's
                # ArbitraryOutputBuffer: any consumer may take a page;
                # deterministic rotation here keeps tasks balanced)
                if int(np.asarray(b.live).sum()) == 0:
                    return
                p = state["next"] % n_parts
                state["next"] += 1
                self.buffer.enqueue(p, serialize_batch(b, dict_refs=True))

            return sink

        def sink(b: Batch):
            # gather/broadcast: one serialized page, fanned out by the buffer
            if int(np.asarray(b.live).sum()) == 0:
                return
            page = serialize_batch(b, dict_refs=True)
            if f.output_partitioning == OUT_BROADCAST:
                self.buffer.enqueue(None, page)
            else:
                self.buffer.enqueue(0, page)

        return sink

    def abort(self):
        self.state = "aborted"
        for c in self._clients:
            c.close()
        for p in range(self.buffer.n_partitions):
            self.buffer.abort(p)

    def info(self) -> dict:
        out = {
            "taskId": self.task_id,
            "state": self.state,
            "error": self.error,
            "bufferedBytes": self.buffer.buffered_bytes(),
            "spooledBytes": self.buffer.spooled_bytes(),
        }
        if self.stats_report is not None:
            out["stats"] = self.stats_report
        if self._count_progress:
            out["rowsEmitted"] = self.rows_emitted
            out["batchesEmitted"] = self.batches_emitted
        return out


# task ids are "{query_id}.{fragment}.{index}[.r{retry}]" — the greedy
# query group absorbs any dots inside the query id itself
_TASK_ID_RE = re.compile(r"^(.+)\.(\d+)\.(\d+)(?:\.r\d+)?$")


class TaskManager:
    """SqlTaskManager analog: task registry keyed by task id."""

    def __init__(self, catalog: Catalog, memory_pool=None, spill_manager=None,
                 run_slots: int = 4, node_id: str = ""):
        from presto_tpu.memory import MemoryPool
        from presto_tpu.spiller import SpillManager

        self.catalog = catalog
        self.node_id = node_id
        self.memory_pool = memory_pool or MemoryPool(None)
        self.spill_manager = spill_manager or SpillManager()
        self.tasks: Dict[str, TaskExecution] = {}
        self.executor = TaskExecutor(run_slots)
        self._lock = threading.Lock()
        # query_id -> QueryScopedPool: per-query slice of the node pool,
        # reported to the coordinator's ClusterMemoryManager
        self._query_pools: Dict[str, "QueryScopedPool"] = {}

    def _pool_for_locked(self, task_id: str):
        """Caller holds self._lock: the lookup and the insert must share
        one critical section, or two tasks of the same query arriving
        concurrently fork the query's reservations across two pools and
        the coordinator's per-query memory view undercounts."""
        from presto_tpu.memory import QueryScopedPool

        # task ids are "{query_id}.{fragment}.{index}" (coordinator.execute)
        query_id = task_id.rsplit(".", 2)[0] if task_id.count(".") >= 2 \
            else task_id
        qp = self._query_pools.get(query_id)
        if qp is None:
            qp = self._query_pools[query_id] = QueryScopedPool(
                self.memory_pool, query_id)
        return qp

    def query_progress(self) -> Dict[str, dict]:
        """Live per-query progress over lifecycle-counting tasks: rows and
        batches emitted plus task/fragment completion, keyed by the attempt
        query id (the coordinator's lifecycle registry resolves attempt ->
        serving query via its alias map). Empty when no task counts, so
        the heartbeat doc stays bit-for-bit pre-lifecycle."""
        with self._lock:
            tasks = list(self.tasks.values())
        out: Dict[str, dict] = {}
        frag_states: Dict[str, Dict[int, List[str]]] = {}
        for t in tasks:
            if not getattr(t, "_count_progress", False):
                continue
            m = _TASK_ID_RE.match(t.task_id)
            qid = m.group(1) if m else t.task_id
            fid = int(m.group(2)) if m else 0
            d = out.setdefault(qid, {
                "rows": 0, "batches": 0, "tasksDone": 0, "tasksTotal": 0,
                "fragmentsDone": 0, "fragmentsTotal": 0})
            d["rows"] += t.rows_emitted
            d["batches"] += t.batches_emitted
            d["tasksTotal"] += 1
            if t.state != "running":
                d["tasksDone"] += 1
            frag_states.setdefault(qid, {}).setdefault(fid, []).append(
                t.state)
        for qid, fmap in frag_states.items():
            out[qid]["fragmentsTotal"] = len(fmap)
            out[qid]["fragmentsDone"] = sum(
                1 for states in fmap.values()
                if all(s != "running" for s in states))
        return out

    def query_inflight(self) -> Dict[str, dict]:
        """Per-task inflight telemetry docs keyed by attempt query id ->
        task id, for the heartbeat (`queryInflight`). Empty when no task
        publishes, so the heartbeat doc stays bit-for-bit pre-inflight."""
        with self._lock:
            tasks = list(self.tasks.values())
        out: Dict[str, dict] = {}
        for t in tasks:
            pub = getattr(t, "_inflight", None)
            if pub is None or not pub.ops:
                continue
            out.setdefault(pub.query_id, {})[t.task_id] = pub.doc()
        return out

    def query_memory(self) -> Dict[str, int]:
        """Live per-query reserved bytes (stale finished queries pruned)."""
        with self._lock:
            active = {t.task_id.rsplit(".", 2)[0]
                      if t.task_id.count(".") >= 2 else t.task_id
                      for t in self.tasks.values() if t.state == "running"}
            for qid in list(self._query_pools):
                if (qid not in active
                        and self._query_pools[qid].query_reserved == 0):
                    del self._query_pools[qid]
            return {qid: qp.query_reserved
                    for qid, qp in self._query_pools.items()}

    def update_task(self, task_id: str, update: TaskUpdate,
                    trace_token: Optional[str] = None) -> dict:
        with self._lock:
            t = self.tasks.get(task_id)
            if t is None:
                t = TaskExecution(task_id, update, self.catalog,
                                  self._pool_for_locked(task_id),
                                  self.spill_manager,
                                  executor=self.executor,
                                  trace_token=trace_token,
                                  node_id=self.node_id)
                self.tasks[task_id] = t
            return t.info()

    def get(self, task_id: str) -> Optional[TaskExecution]:
        return self.tasks.get(task_id)

    def abort_task(self, task_id: str):
        t = self.tasks.get(task_id)
        if t is not None:
            t.abort()

    def abort_all(self):
        for t in list(self.tasks.values()):
            t.abort()

    def has_running(self) -> bool:
        return any(t.state == "running" for t in self.tasks.values())


_TASK_RE = re.compile(r"^/v1/task/([^/]+)$")
_RESULTS_RE = re.compile(r"^/v1/task/([^/]+)/results/(\d+)/(\d+)$")
_ACK_RE = re.compile(r"^/v1/task/([^/]+)/results/(\d+)/(\d+)/ack$")
_BUFFER_RE = re.compile(r"^/v1/task/([^/]+)/results/(\d+)$")
_STATUS_RE = re.compile(r"^/v1/task/([^/]+)/status$")
_TRACE_RE = re.compile(r"^/v1/task/([^/]+)/trace$")
_DICT_RE = re.compile(r"^/v1/dict/([0-9a-f]{64})$")


class Worker:
    """A worker node: HTTP server + task manager + node lifecycle."""

    def __init__(self, catalog: Catalog, node_id: str = "worker-0",
                 port: int = 0, coordinator_url: Optional[str] = None,
                 memory_pool_bytes: Optional[int] = None,
                 spill_dir: Optional[str] = None,
                 revoke_threshold: float = 0.9, revoke_target: float = 0.5,
                 cluster_secret: Optional[str] = None, run_slots: int = 4,
                 tls=None):
        from presto_tpu.memory import MemoryPool
        from presto_tpu.spiller import SpillManager

        self.catalog = catalog
        self.node_id = node_id
        # Intra-cluster auth: mutating endpoints require the shared cluster
        # secret when one is configured; task bodies are JSON over the
        # closed plan-node vocabulary (plan/codec.py — TaskUpdateRequest
        # analog), so no code execution is reachable from the wire.
        self.cluster_secret = cluster_secret
        self.memory_pool = MemoryPool(memory_pool_bytes,
                                      revoke_threshold=revoke_threshold,
                                      revoke_target=revoke_target)
        self.spill_manager = SpillManager(spill_dir)
        self.task_manager = TaskManager(catalog, self.memory_pool,
                                        self.spill_manager,
                                        run_slots=run_slots,
                                        node_id=node_id)
        self.node_state = "active"   # active | shutting_down | shut_down
        # ahead-of-traffic farm boot: workers arm their own program cache
        # from the persisted corpus, but NON-blocking — a worker serves
        # tasks immediately and warms in the background (the coordinator
        # is the one whose "ready" must mean "warm"). Gated on
        # PRESTO_TPU_FARM=1 + PRESTO_TPU_CACHE_DIR, else a no-op.
        try:
            from presto_tpu.exec import farm as _farm_mod

            if _farm_mod.enabled():
                _farm_mod.boot(catalog, block=False)
        except Exception:
            pass
        worker = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def _json(self, obj, code=200):
                data = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _bytes(self, data: bytes, code=200):
                self.send_response(code)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _authorized(self) -> bool:
                if worker.cluster_secret is None:
                    return True
                return (self.headers.get("X-Presto-Cluster-Secret")
                        == worker.cluster_secret)

            def do_POST(self):
                m = _TASK_RE.match(self.path)
                if m:
                    if not self._authorized():
                        return self._json({"error": "unauthorized"}, 403)
                    n = int(self.headers.get("Content-Length", 0))
                    from presto_tpu.plan.codec import (
                        CodecError, task_update_from_json,
                    )

                    try:
                        update = task_update_from_json(
                            json.loads(self.rfile.read(n)))
                    except (CodecError, KeyError, TypeError, ValueError) as e:
                        return self._json({"error": f"bad task update: {e}"},
                                          400)
                    info = worker.task_manager.update_task(
                        m.group(1), update,
                        trace_token=self.headers.get(_obs_trace.TRACE_HEADER))
                    return self._json(info)
                if self.path == "/v1/memory/revoke":
                    # cluster ladder rung: the coordinator asks this node's
                    # spillable operator state to move to disk before any
                    # query gets killed for memory. Body {"partial": true}
                    # selects the adaptive partition-granular rung.
                    if not self._authorized():
                        return self._json({"error": "unauthorized"}, 403)
                    n = int(self.headers.get("Content-Length", 0))
                    partial = False
                    if n:
                        try:
                            partial = bool(json.loads(
                                self.rfile.read(n) or b"{}").get("partial"))
                        except (ValueError, AttributeError):
                            partial = False
                    return self._json(worker.revoke_spillable(partial))
                self._json({"error": "not found"}, 404)

            def do_GET(self):
                m = _RESULTS_RE.match(self.path)
                if m:
                    tid, buf, token = m.group(1), int(m.group(2)), int(m.group(3))
                    t = worker.task_manager.get(tid)
                    if t is None:
                        return self._json({"error": "no such task"}, 404)
                    try:
                        pages, next_token, complete = t.buffer.get(buf, token)
                        header = {"next_token": next_token, "complete": complete,
                                  "task_state": t.state, "error": None}
                    except BufferFailed as e:
                        header = {"next_token": token, "complete": True,
                                  "task_state": t.state, "error": str(e)}
                        pages = []
                    return self._bytes(encode_results_payload(header, pages))
                m = _ACK_RE.match(self.path)
                if m:
                    t = worker.task_manager.get(m.group(1))
                    if t is not None:
                        t.buffer.ack(int(m.group(2)), int(m.group(3)))
                    return self._json({"ok": True})
                m = _STATUS_RE.match(self.path)
                if m:
                    t = worker.task_manager.get(m.group(1))
                    if t is None:
                        return self._json({"error": "no such task"}, 404)
                    return self._json(t.info())
                m = _TRACE_RE.match(self.path)
                if m:
                    t = worker.task_manager.get(m.group(1))
                    if t is None:
                        return self._json({"error": "no such task"}, 404)
                    return self._json(t.tracer.to_json())
                m = _DICT_RE.match(self.path)
                if m:
                    # dictionary side channel: by-ref wire pages resolve
                    # their content here exactly once on an intern miss
                    from presto_tpu.serde import lookup_dictionary

                    vals = lookup_dictionary(m.group(1))
                    if vals is None:
                        return self._json(
                            {"error": "dictionary not interned"}, 404)
                    return self._json(vals)
                if self.path == "/v1/info":
                    return self._json({
                        "nodeId": worker.node_id,
                        "state": worker.node_state,
                        "uri": worker.url,
                        "coordinator": False,
                    })
                if self.path == "/v1/status":
                    return self._json(worker.status())
                if self.path == "/v1/metrics":
                    from presto_tpu.server.metrics import worker_metrics

                    body = worker_metrics(worker).encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                self._json({"error": "not found"}, 404)

            def do_DELETE(self):
                m = _TASK_RE.match(self.path)
                if m:
                    if not self._authorized():
                        return self._json({"error": "unauthorized"}, 403)
                    worker.task_manager.abort_task(m.group(1))
                    return self._json({"ok": True})
                m = _BUFFER_RE.match(self.path)
                if m:
                    t = worker.task_manager.get(m.group(1))
                    if t is not None:
                        t.buffer.abort(int(m.group(2)))
                    return self._json({"ok": True})
                self._json({"error": "not found"}, 404)

            def do_PUT(self):
                if self.path == "/v1/info/state":
                    if not self._authorized():
                        return self._json({"error": "unauthorized"}, 403)
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n) or b'""')
                    if body == "SHUTTING_DOWN":
                        worker.start_graceful_shutdown()
                        return self._json({"ok": True})
                    return self._json({"error": f"bad state {body}"}, 400)
                self._json({"error": "not found"}, 404)

        self.server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        scheme = "http"
        if tls is not None:
            from presto_tpu.server.tls import install_client_context, wrap_server

            scheme = wrap_server(self.server, tls)
            install_client_context(tls)
        self.port = self.server.server_address[1]
        self.url = f"{scheme}://127.0.0.1:{self.port}"
        self._serve_thread = threading.Thread(
            target=self.server.serve_forever, daemon=True,
            name=f"worker-http-{self.node_id}",
        )
        self._serve_thread.start()
        self._coordinator_url = coordinator_url
        self._announce_thread = None
        if coordinator_url:
            self._announce_thread = threading.Thread(
                target=self._announce_loop, args=(coordinator_url,), daemon=True
            )
            self._announce_thread.start()

    def revoke_spillable(self, partial: bool = False) -> dict:
        """Signal every revocable-state owner on this node's pool (hybrid
        hash join builds, grace-agg accumulators): each flags itself and
        spills at its next batch boundary. The out-of-band half of the
        memory contract — reserve()-inline revoking handles local pressure,
        this handles CLUSTER pressure relayed by the coordinator.

        ``partial=True`` is the adaptive rung: shed only the LARGEST
        partitions of partition-granular owners (adaptive radix
        aggregations) instead of whole operators — `partitionsRevoked`
        comes back 0 when no such owner is registered, and the caller
        falls through to the whole-operator rung."""
        if partial:
            n = self.memory_pool.request_partial_revoke()
            return {"nodeId": self.node_id, "revokersSignaled": 0,
                    "partitionsRevoked": n}
        n = self.memory_pool.request_revoke()
        return {"nodeId": self.node_id, "revokersSignaled": n}

    def status(self) -> dict:
        tasks = self.task_manager.tasks
        doc = {
            "nodeId": self.node_id,
            "state": self.node_state,
            "tasks": len(tasks),
            "runningTasks": sum(1 for t in tasks.values() if t.state == "running"),
            "memory": self.memory_pool.info(),
            "queryMemory": self.task_manager.query_memory(),
            "spilledBytes": self.spill_manager.total_spilled_bytes,
            "spillCount": self.spill_manager.spill_count,
        }
        progress = self.task_manager.query_progress()
        if progress:
            # lifecycle plane: live operator row counts ride the heartbeat
            # so the coordinator's progress endpoint sees mid-query state
            doc["queryProgress"] = progress
        inflight = self.task_manager.query_inflight()
        if inflight:
            # inflight plane: per-task operator watermarks ride the
            # heartbeat; the coordinator merges them per fragment (seq-
            # guarded, so the in-process cluster never double-counts)
            doc["queryInflight"] = inflight
        try:
            from presto_tpu.obs import devprof as _devprof

            if _devprof.active():
                # devprof plane: the device's own HBM accounting rides the
                # heartbeat so the coordinator rollup can reconcile the
                # ledger against real allocator numbers per node
                doc["deviceMemory"] = _devprof.device_memory_doc()
        except Exception:
            pass
        return doc

    def _announce_once(self):
        """One announcement PUT carrying this node's current state."""
        import urllib.request

        if not self._coordinator_url:
            return
        try:
            body = json.dumps({"nodeId": self.node_id, "uri": self.url,
                               "state": self.node_state}).encode()
            req = urllib.request.Request(
                f"{self._coordinator_url}/v1/announcement/{self.node_id}",
                data=body, method="PUT",
                headers={"Content-Type": "application/json"},
            )
            urllib.request.urlopen(req, timeout=5).read()
        except Exception:
            pass

    def _announce_loop(self, coordinator_url: str):
        """Service announcement (airlift discovery analog): re-announce
        periodically so the coordinator can expire dead nodes."""
        import time

        while self.node_state != "shut_down":
            self._announce_once()
            time.sleep(1.0)

    def start_graceful_shutdown(self):
        """Drain: stop accepting tasks, wait for running tasks, then stop
        (GracefulShutdownHandler.java:73)."""

        def drain():
            import time

            self.node_state = "shutting_down"
            # tell discovery immediately (don't wait for the next
            # announcement cycle) so scheduling stops routing here
            self._announce_once()
            while self.task_manager.has_running():
                time.sleep(0.1)
            self.close()
            self.node_state = "shut_down"

        threading.Thread(target=drain, daemon=True).start()

    def close(self):
        # stop announcing FIRST: a closed server that keeps announcing
        # would decay its failure score back under the exclusion threshold
        # and re-enter scheduling rotation as a black hole
        if self.node_state == "active":
            self.node_state = "shut_down"
        self.task_manager.abort_all()
        self.server.shutdown()
        self.server.server_close()
