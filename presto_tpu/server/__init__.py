"""Control plane: sessions, query lifecycle, admission, coordinator/worker
services (reference: presto-main server/ + execution/ packages)."""
