"""SHOW FUNCTIONS catalog — the function-registry listing
(reference: metadata/FunctionListBuilder + SHOW FUNCTIONS task).

The engine's dispatch is code (plan/builder._an_FunctionCall,
expr/compile._eval_call), so this module curates the user-visible
surface; tests assert the listing matches what actually analyzes."""

from __future__ import annotations

from typing import List, Tuple

_SCALAR = {
    "math": ["abs", "sqrt", "exp", "ln", "log", "log2", "log10", "power",
             "floor", "ceil", "ceiling", "round", "truncate", "sign",
             "mod", "pi", "e", "cbrt", "degrees", "radians", "greatest",
             "least", "width_bucket", "is_nan", "is_finite", "is_infinite",
             "sin", "cos", "tan", "asin", "acos", "atan", "atan2",
             "sinh", "cosh", "tanh"],
    "string": ["substr", "substring", "upper", "lower", "trim", "ltrim",
               "rtrim", "reverse", "replace", "lpad", "rpad", "split_part",
               "concat", "length", "strpos", "position", "codepoint",
               "starts_with", "ends_with", "contains", "levenshtein_distance",
               "hamming_distance", "split", "bit_length"],
    "regexp/json": ["regexp_like", "regexp_extract", "regexp_replace",
                    "regexp_split",
                    "json_extract_scalar", "json_extract", "json_array_get",
                    "json_array_length", "json_size", "json_format",
                    "json_parse", "json_array_contains", "is_json_scalar"],
    "url": ["url_extract_host", "url_extract_path", "url_extract_query",
            "url_extract_protocol", "url_extract_fragment", "url_encode",
            "url_decode"],
    "binary": ["md5", "sha1", "sha256", "sha512", "to_base64",
               "from_base64", "normalize", "to_hex", "from_hex",
               "to_utf8", "from_utf8"],
    "ip": ["ip_prefix", "ip_subnet_min", "ip_subnet_max", "ip_subnet_range",
           "is_subnet_of"],
    "tdigest": ["value_at_quantile", "values_at_quantiles",
                "quantile_at_value", "trimmed_mean", "scale_tdigest"],
    "hyperloglog": ["cardinality", "empty_approx_set"],
    "date": ["year", "month", "day", "quarter", "day_of_week", "dow",
             "day_of_year", "doy", "date_trunc", "date_diff", "date_add",
             "from_unixtime", "to_unixtime", "date_parse",
             "from_iso8601_date", "from_iso8601_timestamp"],
    "conditional": ["coalesce", "nullif", "if", "grouping"],
    "bitwise": ["bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
                "bitwise_left_shift", "bitwise_right_shift"],
    "array": ["cardinality", "element_at", "contains", "array_position",
              "array_min", "array_max", "array_sum", "array_average",
              "array_distinct", "array_sort", "slice", "sequence",
              "repeat", "concat", "array_union", "array_intersect",
              "array_except", "arrays_overlap", "array_remove"],
    "map": ["map", "map_keys", "map_values", "element_at", "cardinality",
            "map_concat"],
    "lambda": ["transform", "filter", "reduce", "any_match", "all_match",
               "none_match", "transform_values", "map_filter", "zip_with"],
    "geospatial": ["st_geometryfromtext", "st_point", "st_astext", "st_x",
                   "st_y", "st_contains", "st_within", "st_intersects",
                   "st_distance", "st_area", "st_perimeter", "st_length",
                   "st_npoints", "st_centroid", "st_xmin", "st_xmax",
                   "st_ymin", "st_ymax", "great_circle_distance"],
}

_AGGREGATE = ["count", "sum", "avg", "min", "max", "stddev", "stddev_pop",
              "stddev_samp", "variance", "var_pop", "var_samp", "covar_pop",
              "covar_samp", "corr", "geometric_mean", "bool_and", "bool_or",
              "every", "arbitrary", "any_value", "checksum", "count_if",
              "approx_distinct", "approx_percentile", "max_by", "min_by",
              "array_agg", "map_agg", "numeric_histogram", "tdigest_agg",
              "merge", "approx_set"]

_WINDOW = ["row_number", "rank", "dense_rank", "percent_rank", "cume_dist",
           "ntile", "lag", "lead", "first_value", "last_value", "nth_value"]


def list_functions() -> List[Tuple[str, str, str]]:
    out = []
    for kind, names in _SCALAR.items():
        for n in sorted(set(names)):
            out.append((n, "scalar", kind))
    for n in sorted(_AGGREGATE):
        out.append((n, "aggregate", ""))
    for n in sorted(_WINDOW):
        out.append((n, "window", ""))
    # registered (plugin/user) functions — presto_tpu/functions.py
    from presto_tpu.functions import registry

    out.extend(registry().list())
    return out
