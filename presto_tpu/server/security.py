"""Client security: password authentication + rule-based session defaults.

Reference modules: presto-password-authenticators (file/LDAP password
login via the PasswordAuthenticator SPI) and presto-session-property-
managers (FileSessionPropertyManager: JSON rules matching user/source
regexes to session property defaults). Both are file-configured here:

- password file: one `user:salt:sha256(salt || password)` line per user
  (create entries with PasswordAuthenticator.hash_entry)
- session property rules: JSON list of
  {"user": regex?, "source": regex?, "sessionProperties": {...}} —
  ALL matching rules apply in order, later rules override earlier ones,
  and explicit client-provided properties always win.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import re
import secrets
from typing import Dict, List, Optional


class AuthenticationError(Exception):
    pass


class PasswordAuthenticator:
    """File-based BASIC authentication (file format above; the analog of
    file-based PasswordAuthenticatorFactory)."""

    def __init__(self, path: Optional[str] = None, entries: Optional[dict] = None):
        self.users: Dict[str, tuple] = {}
        if entries:
            for user, (salt, digest) in entries.items():
                self.users[user] = (salt, digest)
        if path:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line or line.startswith("#"):
                        continue
                    user, salt, digest = line.split(":", 2)
                    self.users[user] = (salt, digest)

    # PBKDF2 work factor: a leaked password file must not be brute-forceable
    # at hash-cracking speed (the reference's file authenticator requires
    # bcrypt or PBKDF2 and rejects fast hashes).
    PBKDF2_ITERATIONS = 120_000

    @classmethod
    def hash_entry(cls, user: str, password: str) -> str:
        """One password-file line for `user` (PBKDF2-HMAC-SHA256)."""
        salt = secrets.token_hex(16)
        dk = hashlib.pbkdf2_hmac("sha256", password.encode(),
                                 salt.encode(), cls.PBKDF2_ITERATIONS)
        return f"{user}:{salt}:pbkdf2:{cls.PBKDF2_ITERATIONS}:{dk.hex()}"

    def check(self, user: str, password: str) -> bool:
        rec = self.users.get(user)
        if rec is None:
            return False
        salt, digest = rec
        if digest.startswith("pbkdf2:"):
            try:
                _, iters_s, hexdk = digest.split(":", 2)
                iters = int(iters_s)
            except ValueError:
                return False
            cand = hashlib.pbkdf2_hmac("sha256", password.encode(),
                                       salt.encode(), iters).hex()
            return hmac.compare_digest(cand, hexdk)
        # legacy single-round entries still verify (rotate via hash_entry)
        cand = hashlib.sha256((salt + password).encode()).hexdigest()
        return hmac.compare_digest(cand, digest)

    def authenticate(self, authorization: Optional[str]) -> str:
        """Authorization header → authenticated user (raises on failure)."""
        if not authorization or not authorization.startswith("Basic "):
            raise AuthenticationError("Basic authentication required")
        try:
            raw = base64.b64decode(authorization[6:]).decode()
            user, _, password = raw.partition(":")
        except Exception:
            raise AuthenticationError("malformed Authorization header")
        if not self.check(user, password):
            raise AuthenticationError("invalid credentials")
        return user


class SessionPropertyManager:
    """Rule-matched session property defaults
    (FileSessionPropertyManager analog)."""

    def __init__(self, path: Optional[str] = None,
                 rules: Optional[List[dict]] = None):
        if path:
            with open(path) as f:
                rules = json.load(f)
        self.rules = []
        for r in rules or []:
            self.rules.append({
                "user": re.compile(r["user"]) if r.get("user") else None,
                "source": re.compile(r["source"]) if r.get("source") else None,
                "props": dict(r.get("sessionProperties") or {}),
            })

    def defaults_for(self, user: str, source: str) -> Dict[str, str]:
        out: Dict[str, str] = {}
        for r in self.rules:
            if r["user"] is not None and not r["user"].fullmatch(user or ""):
                continue
            if r["source"] is not None and not r["source"].fullmatch(source or ""):
                continue
            out.update(r["props"])
        return out


class AccessDeniedError(Exception):
    """Structured authorization failure (reference: spi
    AccessDeniedException — surfaced as PERMISSION_DENIED)."""


class AccessControl:
    """Rule-based table/column authorization (reference:
    security/AccessControlManager.java dispatching to
    presto-plugin-toolkit's FileBasedAccessControl table rules).

    Rules are evaluated FIRST-MATCH; no matching rule denies (add a
    catch-all to open the rest, exactly like the reference's file-based
    connector access control):

        [{"user": "analyst.*", "catalog": "pq", "table": "events",
          "allowed_columns": ["region", "clicks"]},
         {"user": ".*", "privileges": "all"}]

    `privileges`: "all" | "none" (default "all" when the rule matches and
    no column list restricts it). `allowed_columns` whitelists columns;
    `denied_columns` blacklists. Regex fields default to match-anything.
    """

    def __init__(self, rules: Optional[List[dict]] = None,
                 path: Optional[str] = None):
        if path is not None:
            with open(path) as f:
                rules = json.load(f)
        self.rules = rules or []

    def _match(self, user: str, catalog: str, table: str) -> Optional[dict]:
        for r in self.rules:
            if re.fullmatch(r.get("user", ".*"), user) is None:
                continue
            if re.fullmatch(r.get("catalog", ".*"), catalog) is None:
                continue
            if re.fullmatch(r.get("table", ".*"), table) is None:
                continue
            return r
        return None

    def check_can_select(self, user: str, catalog: str, table: str,
                         columns) -> None:
        r = self._match(user, catalog, table)
        if r is None or r.get("privileges") == "none":
            raise AccessDeniedError(
                f"Access Denied: user {user!r} cannot select from "
                f"{catalog}.{table}")
        allowed = r.get("allowed_columns")
        denied = set(r.get("denied_columns") or ())
        for c in sorted(set(columns)):
            if (allowed is not None and c not in allowed) or c in denied:
                raise AccessDeniedError(
                    f"Access Denied: user {user!r} cannot select column "
                    f"{c!r} from {catalog}.{table}")
