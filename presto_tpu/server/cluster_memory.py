"""Cluster memory manager + low-memory killer.

Reference: memory/ClusterMemoryManager.java:92 (coordinator-side rollup of
every worker's pool via MemoryPoolInfo), :218 (process() — when the
cluster is out of memory, pick a victim with the configured
LowMemoryKiller and fail it), and the killer policies
TotalReservationLowMemoryKiller / TotalReservationOnBlockedNodesLowMemoryKiller.

TPU-native shape: workers already announce their status on a heartbeat;
the status document now carries per-query reserved bytes (HBM accounting
is exact — fixed-capacity device arrays). The coordinator aggregates
those reports here and, when the cluster is out of memory, fails the
query with the largest relevant reservation with a structured
CLUSTER_OUT_OF_MEMORY error while smaller queries keep running.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional


class NodeMemory:
    """One worker's last-reported memory state (MemoryPoolInfo analog)."""

    __slots__ = ("reserved", "limit", "queries", "at")

    def __init__(self, reserved: int, limit: Optional[int],
                 queries: Dict[str, int], at: float):
        self.reserved = reserved
        self.limit = limit
        self.queries = queries
        self.at = at

    @property
    def blocked(self) -> bool:
        """A node whose pool is (nearly) exhausted blocks further reserves
        (the reference's blocked-nodes signal for the OOM killer)."""
        return self.limit is not None and self.reserved >= 0.95 * self.limit


class ClusterMemoryManager:
    """Aggregates per-worker pool reports; kills the top memory hog when
    the cluster runs out of memory (ClusterMemoryManager.process analog).

    Kill policies (reference LowMemoryKiller implementations):
      total-reservation            victim = max Σ bytes across ALL nodes
      total-reservation-on-blocked victim = max Σ bytes across BLOCKED nodes
    A kill fires when the cluster-wide reservation exceeds `limit_bytes`,
    or when any worker pool is blocked (its local limit is the binding
    constraint) — each after `kill_delay_s` of sustained pressure, so a
    transient spike between heartbeats doesn't kill a healthy query.
    """

    def __init__(self, limit_bytes: Optional[int] = None,
                 policy: str = "total-reservation-on-blocked",
                 kill_delay_s: float = 1.0, stale_s: float = 30.0):
        if policy not in ("total-reservation",
                         "total-reservation-on-blocked", "none"):
            raise ValueError(f"unknown low-memory killer policy {policy!r}")
        self.limit_bytes = limit_bytes
        self.policy = policy
        self.kill_delay_s = kill_delay_s
        self.stale_s = stale_s
        self.kills = 0
        self._nodes: Dict[str, NodeMemory] = {}
        self._pressure_since: Optional[float] = None
        self._lock = threading.Lock()

    # -- ingest (called from the heartbeat prober) -------------------------

    def update_node(self, node_id: str, status: dict):
        mem = status.get("memory") or {}
        with self._lock:
            self._nodes[node_id] = NodeMemory(
                int(mem.get("reservedBytes") or 0),
                mem.get("limitBytes"),
                {str(q): int(b) for q, b in
                 (status.get("queryMemory") or {}).items()},
                time.monotonic(),
            )

    def drop_node(self, node_id: str):
        with self._lock:
            self._nodes.pop(node_id, None)

    # -- rollup ------------------------------------------------------------

    def _fresh_nodes(self) -> Dict[str, NodeMemory]:
        now = time.monotonic()
        return {nid: nm for nid, nm in self._nodes.items()
                if now - nm.at < self.stale_s}

    def info(self) -> dict:
        with self._lock:
            nodes = self._fresh_nodes()
            by_query: Dict[str, int] = {}
            for nm in nodes.values():
                for q, b in nm.queries.items():
                    by_query[q] = by_query.get(q, 0) + b
            return {
                "totalReservedBytes": sum(n.reserved for n in nodes.values()),
                "clusterLimitBytes": self.limit_bytes,
                "blockedNodes": [nid for nid, n in nodes.items() if n.blocked],
                "queryMemory": by_query,
                "lowMemoryKills": self.kills,
            }

    # -- enforcement -------------------------------------------------------

    def _candidates(self, nodes: Dict[str, NodeMemory],
                    blocked_only: bool) -> list:
        """Query ids ordered biggest-reservation-first."""
        by_query: Dict[str, int] = {}
        for nm in nodes.values():
            if blocked_only and not nm.blocked:
                continue
            for q, b in nm.queries.items():
                by_query[q] = by_query.get(q, 0) + b
        return [q for q, _ in sorted(by_query.items(),
                                     key=lambda kv: -kv[1])]

    def enforce(self, query_manager) -> Optional[str]:
        """One enforcement pass (call on the heartbeat cadence). Returns
        the killed query id, if any."""
        if self.policy == "none":
            return None
        with self._lock:
            nodes = self._fresh_nodes()
            total = sum(n.reserved for n in nodes.values())
            over_cluster = (self.limit_bytes is not None
                            and total > self.limit_bytes)
            blocked = [nid for nid, n in nodes.items() if n.blocked]
            under_pressure = over_cluster or bool(blocked)
            now = time.monotonic()
            if not under_pressure:
                self._pressure_since = None
                return None
            if self._pressure_since is None:
                self._pressure_since = now
                return None
            if now - self._pressure_since < self.kill_delay_s:
                return None
            blocked_only = (self.policy == "total-reservation-on-blocked"
                            and bool(blocked) and not over_cluster)
            candidates = self._candidates(nodes, blocked_only)
            if blocked_only:
                for q in self._candidates(nodes, blocked_only=False):
                    if q not in candidates:
                        candidates.append(q)
        # kill accounting happens only on a CONFIRMED kill: a stale victim
        # (worker still reporting a finished query) must not reset the
        # pressure timer or count as a kill — fall through to the next hog
        for victim in candidates:
            try:
                qe = query_manager.get(victim)
            except KeyError:
                continue
            if qe.done:
                continue
            qe.fail(
                "Query killed because the cluster is out of memory. "
                "Please try again in a few minutes.",
                error_type="CLUSTER_OUT_OF_MEMORY",
            )
            with self._lock:
                self._pressure_since = None
                self.kills += 1
            return victim
        return None
