"""Cluster memory manager + low-memory killer.

Reference: memory/ClusterMemoryManager.java:92 (coordinator-side rollup of
every worker's pool via MemoryPoolInfo), :218 (process() — when the
cluster is out of memory, pick a victim with the configured
LowMemoryKiller and fail it), and the killer policies
TotalReservationLowMemoryKiller / TotalReservationOnBlockedNodesLowMemoryKiller.

TPU-native shape: workers already announce their status on a heartbeat;
the status document now carries per-query reserved bytes (HBM accounting
is exact — fixed-capacity device arrays) plus the pool's peak and the
devprof plane's device memory doc. The coordinator aggregates those
reports here and, when the cluster is out of memory, fails the query
with the largest relevant reservation with a structured
CLUSTER_OUT_OF_MEMORY error while smaller queries keep running — and
dumps a forensics snapshot (every per-query reservation on every node at
kill time) as JSONL under PRESTO_TPU_CACHE_DIR so the kill is
diagnosable after the fact.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional


class NodeMemory:
    """One worker's last-reported memory state (MemoryPoolInfo analog)."""

    __slots__ = ("reserved", "peak", "limit", "queries", "device", "at",
                 "blocked_threshold")

    def __init__(self, reserved: int, limit: Optional[int],
                 queries: Dict[str, int], at: float,
                 peak: int = 0, device: Optional[dict] = None,
                 blocked_threshold: float = 0.95):
        self.reserved = reserved
        self.peak = peak
        self.limit = limit
        self.queries = queries
        self.device = device
        self.at = at
        self.blocked_threshold = blocked_threshold

    @property
    def blocked(self) -> bool:
        """A node whose pool is (nearly) exhausted blocks further reserves
        (the reference's blocked-nodes signal for the OOM killer). The
        threshold is the manager's `blocked_node_threshold` knob."""
        return (self.limit is not None
                and self.reserved >= self.blocked_threshold * self.limit)


class ClusterMemoryManager:
    """Aggregates per-worker pool reports; kills the top memory hog when
    the cluster runs out of memory (ClusterMemoryManager.process analog).

    Kill policies (reference LowMemoryKiller implementations):
      total-reservation            victim = max Σ bytes across ALL nodes
      total-reservation-on-blocked victim = max Σ bytes across BLOCKED nodes
    A kill fires when the cluster-wide reservation exceeds `limit_bytes`,
    or when any worker pool is blocked (its local limit is the binding
    constraint) — each after `kill_delay_s` of sustained pressure, so a
    transient spike between heartbeats doesn't kill a healthy query.
    `blocked_node_threshold` is the pool-fullness fraction at which a
    node counts as blocked (previously a hardcoded 0.95).
    """

    def __init__(self, limit_bytes: Optional[int] = None,
                 policy: str = "total-reservation-on-blocked",
                 kill_delay_s: float = 1.0, stale_s: float = 30.0,
                 blocked_node_threshold: float = 0.95,
                 forensics_dir: Optional[str] = None,
                 trace_registry=None):
        if policy not in ("total-reservation",
                         "total-reservation-on-blocked", "none"):
            raise ValueError(f"unknown low-memory killer policy {policy!r}")
        if not 0.0 < blocked_node_threshold <= 1.0:
            raise ValueError(
                f"blocked_node_threshold must be in (0, 1], got "
                f"{blocked_node_threshold!r}")
        self.limit_bytes = limit_bytes
        self.policy = policy
        self.kill_delay_s = kill_delay_s
        self.stale_s = stale_s
        self.blocked_node_threshold = blocked_node_threshold
        # OOM forensics sink: explicit dir, else the umbrella cache dir
        self.forensics_dir = forensics_dir
        # optional obs.trace.TraceRegistry: a kill stamps a memory_kill
        # span onto the victim's query trace
        self.trace_registry = trace_registry
        self.kills = 0  # shared: guarded-by(self._lock)
        self._nodes: Dict[str, NodeMemory] = {}
        self._pressure_since: Optional[float] = None  # shared: guarded-by(self._lock)
        self._lock = threading.Lock()
        # result-cache ledger hook (server/result_cache.py): when set,
        # cached-result bytes count toward cluster pressure and are
        # revoked BEFORE any query is killed
        self.result_cache = None
        # spillable-state hook: a callable () -> int that asks every
        # worker to revoke spillable operator state (join builds / agg
        # accumulators spill to disk at their next batch boundary) and
        # returns how many revokers were signaled. Second rung of the
        # revoke-before-kill ladder, after the free cache drop.
        self.spill_revoker = None
        self._spill_revoked_episode = False  # shared: guarded-by(self._lock)
        # adaptive partial-revocation hook: a callable () -> int that
        # asks workers to shed only the LARGEST partitions of
        # partition-granular owners (adaptive radix aggregations) and
        # returns partitions revoked. Tried BEFORE spill_revoker — cold
        # partitions leave while hot ones stay resident; 0 falls through
        # to the whole-operator rung in the same pass.
        self.partial_revoker = None
        self._partial_revoked_episode = False  # guarded-by(self._lock)

    # -- ingest (called from the heartbeat prober) -------------------------

    def update_node(self, node_id: str, status: dict):
        mem = status.get("memory") or {}
        with self._lock:
            self._nodes[node_id] = NodeMemory(
                int(mem.get("reservedBytes") or 0),
                mem.get("limitBytes"),
                {str(q): int(b) for q, b in
                 (status.get("queryMemory") or {}).items()},
                time.monotonic(),
                peak=int(mem.get("peakBytes") or 0),
                device=status.get("deviceMemory"),
                blocked_threshold=self.blocked_node_threshold,
            )

    def drop_node(self, node_id: str):
        with self._lock:
            self._nodes.pop(node_id, None)

    # -- rollup ------------------------------------------------------------

    def _fresh_nodes(self) -> Dict[str, NodeMemory]:
        now = time.monotonic()
        return {nid: nm for nid, nm in self._nodes.items()
                if now - nm.at < self.stale_s}

    def _cache_doc(self) -> Optional[dict]:
        """Result-cache slice of the ledger, or None until the cache has
        been consulted (off-discipline: pre-cache docs stay bit-for-bit)."""
        rc = self.result_cache
        if rc is None or not rc.armed():
            return None
        c = rc.counters()
        return {"bytes": c["bytes"], "entries": c["entries"],
                "budgetBytes": c["budget_bytes"],
                "evictions": c["evictions"]}

    def info(self) -> dict:
        cache_doc = self._cache_doc()
        with self._lock:
            nodes = self._fresh_nodes()
            by_query: Dict[str, int] = {}
            for nm in nodes.values():
                for q, b in nm.queries.items():
                    by_query[q] = by_query.get(q, 0) + b
            doc = {
                "totalReservedBytes": sum(n.reserved for n in nodes.values()),
                "clusterLimitBytes": self.limit_bytes,
                "blockedNodes": [nid for nid, n in nodes.items() if n.blocked],
                "blockedNodeThreshold": self.blocked_node_threshold,
                "queryMemory": by_query,
                "lowMemoryKills": self.kills,
            }
            if cache_doc is not None:
                doc["resultCache"] = cache_doc
            return doc

    def memory_rollup(self) -> dict:
        """The `GET /v1/memory` document (MemoryPoolInfo rollup analog):
        per-node pools (reserved/peak/limit + device stats) + per-query
        slices + the cluster view."""
        cache_doc = self._cache_doc()
        with self._lock:
            nodes = self._fresh_nodes()
            node_docs = {}
            for nid, nm in sorted(nodes.items()):
                doc = {
                    "reservedBytes": nm.reserved,
                    "peakBytes": nm.peak,
                    "limitBytes": nm.limit,
                    "blocked": nm.blocked,
                    "queryMemory": dict(nm.queries),
                }
                if nm.device is not None:
                    doc["deviceMemory"] = nm.device
                node_docs[nid] = doc
            by_query: Dict[str, int] = {}
            for nm in nodes.values():
                for q, b in nm.queries.items():
                    by_query[q] = by_query.get(q, 0) + b
            cluster = {
                "totalReservedBytes": sum(
                    n.reserved for n in nodes.values()),
                "peakReservedBytes": sum(n.peak for n in nodes.values()),
                "clusterLimitBytes": self.limit_bytes,
                "blockedNodes": [nid for nid, n in nodes.items()
                                 if n.blocked],
                "blockedNodeThreshold": self.blocked_node_threshold,
                "lowMemoryKills": self.kills,
            }
            if cache_doc is not None:
                cluster["resultCache"] = cache_doc
            return {
                "cluster": cluster,
                "nodes": node_docs,
                "queryMemory": by_query,
            }

    # -- enforcement -------------------------------------------------------

    def _candidates(self, nodes: Dict[str, NodeMemory],
                    blocked_only: bool) -> list:
        """Query ids ordered biggest-reservation-first."""
        by_query: Dict[str, int] = {}
        for nm in nodes.values():
            if blocked_only and not nm.blocked:
                continue
            for q, b in nm.queries.items():
                by_query[q] = by_query.get(q, 0) + b
        return [q for q, _ in sorted(by_query.items(),
                                     key=lambda kv: -kv[1])]

    def _forensics_path(self) -> Optional[str]:
        d = self.forensics_dir or os.environ.get("PRESTO_TPU_CACHE_DIR")
        if not d:
            return None
        return os.path.join(d, "oom_forensics.jsonl")

    def _dump_forensics(self, victim: str, nodes: Dict[str, NodeMemory],
                        total: int, blocked: list) -> Optional[str]:
        """One JSONL record per kill: every per-query reservation on every
        node at kill time — the post-mortem the reference attaches to
        CLUSTER_OUT_OF_MEMORY. Best-effort by contract."""
        path = self._forensics_path()
        if not path:
            return None
        rec = {
            "event": "lowMemoryKill",
            "ts": time.time(),
            "victim": victim,
            "totalReservedBytes": total,
            "clusterLimitBytes": self.limit_bytes,
            "blockedNodes": blocked,
            "blockedNodeThreshold": self.blocked_node_threshold,
            "nodes": {
                nid: {
                    "reservedBytes": nm.reserved,
                    "peakBytes": nm.peak,
                    "limitBytes": nm.limit,
                    "blocked": nm.blocked,
                    "queryMemory": dict(nm.queries),
                    **({"deviceMemory": nm.device}
                       if nm.device is not None else {}),
                }
                for nid, nm in sorted(nodes.items())
            },
        }
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "a") as fh:
                fh.write(json.dumps(rec, default=str) + "\n")
            return path
        except OSError:
            return None

    def _trace_kill(self, victim: str, forensics: Optional[str],
                    total: int, blocked: list) -> None:
        """Stamp a memory_kill span on the victim's query trace so the
        kill shows up in /v1/query/{id}/trace and the slow-query log."""
        reg = self.trace_registry
        if reg is None:
            return
        try:
            tr = reg.get(victim)
            if tr is not None and tr.enabled:
                t = time.time()
                tr.record("memory_kill", "memory_kill", t, t,
                          reason="CLUSTER_OUT_OF_MEMORY",
                          total_reserved_bytes=int(total),
                          blocked_nodes=list(blocked),
                          forensics=forensics)
        except Exception:
            pass

    @staticmethod
    def _emit_event(kind: str, query_id: Optional[str] = None,
                    **attrs) -> None:
        """Ladder stages onto the unified /v1/events feed — the revoke
        order (cache → spillable state → kill) is auditable from the
        stream. Best-effort by contract."""
        try:
            from presto_tpu.obs.events import EVENTS

            EVENTS.emit(kind, query_id=query_id, **attrs)
        except Exception:
            pass

    def enforce(self, query_manager) -> Optional[str]:
        """One enforcement pass (call on the heartbeat cadence). Returns
        the killed query id, if any."""
        if self.policy == "none":
            return None
        # cached-result bytes are cluster-held memory too: they count
        # toward the limit (so holding results can create pressure) and
        # are the FIRST thing revoked when pressure sustains
        rc = self.result_cache
        cache_bytes = (rc.bytes_held()
                       if rc is not None and rc.armed() else 0)
        with self._lock:
            nodes = self._fresh_nodes()
            total = sum(n.reserved for n in nodes.values()) + cache_bytes
            over_cluster = (self.limit_bytes is not None
                            and total > self.limit_bytes)
            blocked = [nid for nid, n in nodes.items() if n.blocked]
            under_pressure = over_cluster or bool(blocked)
            now = time.monotonic()
            if not under_pressure:
                self._pressure_since = None
                self._spill_revoked_episode = False
                self._partial_revoked_episode = False
                return None
            if self._pressure_since is None:
                self._pressure_since = now
                return None
            if now - self._pressure_since < self.kill_delay_s:
                return None
            blocked_only = (self.policy == "total-reservation-on-blocked"
                            and bool(blocked) and not over_cluster)
            candidates = self._candidates(nodes, blocked_only)
            if blocked_only:
                for q in self._candidates(nodes, blocked_only=False):
                    if q not in candidates:
                        candidates.append(q)
        # revocation before eviction-by-kill: dropping cached results is
        # free (they can always be recomputed); a killed query is not.
        # Any bytes actually freed end the pass — the next heartbeat
        # re-evaluates pressure against the lighter cluster.
        if rc is not None and cache_bytes > 0:
            freed = rc.revoke_for_pressure()
            if freed > 0:
                with self._lock:
                    self._pressure_since = None
                return None
        # adaptive rung (before whole-operator revoke): shed only the
        # LARGEST partitions of partition-granular owners. One shot per
        # pressure episode, and a pass that revokes nothing falls
        # straight through to the whole-operator rung below — with no
        # partial owners registered this rung is invisible.
        pr = self.partial_revoker
        if pr is not None:
            with self._lock:
                palready = self._partial_revoked_episode
                self._partial_revoked_episode = True
            if not palready:
                try:
                    revoked = int(pr())
                except Exception:
                    revoked = 0
                if revoked > 0:
                    self._emit_event("partial_revoke_requested",
                                     partitions=revoked,
                                     totalReservedBytes=int(total),
                                     blockedNodes=list(blocked))
                    with self._lock:
                        self._pressure_since = None
                    return None
        # second rung: ask workers to revoke SPILLABLE OPERATOR STATE —
        # hybrid hash join builds and grace-agg accumulators move to disk
        # at their next batch boundary, which is graceful degradation, not
        # a failed query. One shot per pressure episode: a workload that
        # cannot actually shed state must not postpone the kill forever.
        sr = self.spill_revoker
        if sr is not None:
            with self._lock:
                already = self._spill_revoked_episode
                self._spill_revoked_episode = True
            if not already:
                try:
                    signaled = int(sr())
                except Exception:
                    signaled = 0
                if signaled > 0:
                    self._emit_event("spill_revoke_requested",
                                     revokers=signaled,
                                     totalReservedBytes=int(total),
                                     blockedNodes=list(blocked))
                    with self._lock:
                        # give the revokers one kill_delay_s worth of
                        # heartbeats to actually spill before re-arming
                        self._pressure_since = None
                    return None
        # kill accounting happens only on a CONFIRMED kill: a stale victim
        # (worker still reporting a finished query) must not reset the
        # pressure timer or count as a kill — fall through to the next hog
        for victim in candidates:
            try:
                qe = query_manager.get(victim)
            except KeyError:
                continue
            if qe.done:
                continue
            forensics = self._dump_forensics(victim, nodes, total, blocked)
            self._trace_kill(victim, forensics, total, blocked)
            self._emit_event("low_memory_kill", query_id=victim,
                             totalReservedBytes=int(total),
                             blockedNodes=list(blocked))
            qe.fail(
                "Query killed because the cluster is out of memory. "
                "Please try again in a few minutes.",
                error_type="CLUSTER_OUT_OF_MEMORY",
            )
            with self._lock:
                self._pressure_since = None
                self._spill_revoked_episode = False
                self._partial_revoked_episode = False
                self.kills += 1
            return victim
        return None
