"""Task output buffers — the producer side of the HTTP pull shuffle.

Reference: execution/buffer/OutputBuffer.java and its Partitioned/Broadcast
variants + ClientBuffer: pages are buffered per downstream consumer, fetched
by explicit token sequence numbers, retained until acknowledged, so a
consumer can re-fetch from any token (restart-safe, exactly-once delivery —
TaskResource.java:245-304).

Spool mode (phased execution): a build-phase task's consumers are created
in a LATER phase, so back-pressure can never drain — pages beyond the
memory cap overflow to an unlinked temp file instead of blocking (the
reference's spooling output buffers), and `get` reads them back
transparently by token.
"""

from __future__ import annotations

import os
import tempfile
import threading
from typing import List, Optional, Tuple


class _SharedPage:
    """One broadcast page shared by every partition queue. The buffer's
    byte accounting holds it exactly ONCE and releases it when the last
    consumer acks or aborts (BroadcastOutputBuffer's page refcounting) —
    counting per partition overstated buffered bytes N× and tripped
    back-pressure long before the buffer was actually full."""

    __slots__ = ("page", "refs")

    def __init__(self, page: bytes, refs: int):
        self.page = page
        self.refs = refs


class _PartitionBuffer:
    """Token-addressed page queue for one consumer. Entries are either hot
    bytes or ("d", offset, length) descriptors into the shared spool file."""

    def __init__(self):
        self.entries: List[object] = []
        self.base_token = 0          # token of entries[0]
        self.no_more = False
        self.aborted = False

    @property
    def end_token(self) -> int:
        return self.base_token + len(self.entries)


class OutputBuffer:
    """Pages per downstream partition with token/ack delivery.

    broadcast=True appends every page to all partitions (shared bytes —
    reference: BroadcastOutputBuffer page reference counting).
    spool_dir, when set, disables producer blocking: overflow pages go to
    disk (see module docstring).
    """

    def __init__(self, n_partitions: int, broadcast: bool = False,
                 max_buffered_bytes: int = 256 << 20,
                 spool_dir: Optional[str] = None):
        self.n_partitions = n_partitions
        self.broadcast = broadcast
        self._parts = [_PartitionBuffer() for _ in range(n_partitions)]
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._bytes = 0
        self._spooled_bytes = 0
        self._max_bytes = max_buffered_bytes
        self._spool_dir = spool_dir
        self._spool_f = None  # unlinked temp file: space frees on close
        self._failed: Optional[str] = None

    # -- producer ---------------------------------------------------------

    def _spool_page(self, page: bytes):
        if self._spool_f is None:
            fd, path = tempfile.mkstemp(prefix="outbuf-", suffix=".spool",
                                        dir=self._spool_dir)
            self._spool_f = os.fdopen(fd, "wb")
            os.unlink(path)  # invisible; space reclaimed when fd closes
        off = self._spool_f.tell()
        self._spool_f.write(page)
        self._spool_f.flush()
        self._spooled_bytes += len(page)
        return ("d", off, len(page))

    def _read_entry(self, entry) -> bytes:
        if isinstance(entry, bytes):
            return entry
        if isinstance(entry, _SharedPage):
            return entry.page
        _, off, length = entry
        return os.pread(self._spool_f.fileno(), length, off)

    def _release_entry(self, entry):
        # caller holds the lock
        if isinstance(entry, bytes):
            self._bytes -= len(entry)
        elif isinstance(entry, _SharedPage):
            entry.refs -= 1
            if entry.refs == 0:
                self._bytes -= len(entry.page)

    def enqueue(self, partition: Optional[int], page: bytes):
        """Append a page; partition=None broadcasts. Blocks for back-pressure
        when the buffer is full (OutputBufferMemoryManager's blocked future)
        — unless spooling, where overflow goes to disk instead."""
        with self._cond:
            if self._spool_dir is None:
                while self._bytes >= self._max_bytes and not self._all_aborted():
                    self._cond.wait(timeout=1.0)
            fanout = range(self.n_partitions) if (self.broadcast or partition is None) \
                else (partition,)
            targets = [p for p in fanout if not self._parts[p].aborted]
            entry: object = page
            if (self._spool_dir is not None
                    and self._bytes + len(page) > self._max_bytes):
                entry = self._spool_page(page)
            elif len(targets) > 1:
                entry = _SharedPage(page, len(targets))
                self._bytes += len(page)
            elif targets:
                self._bytes += len(page)
            for p in targets:
                self._parts[p].entries.append(entry)
            self._cond.notify_all()

    def set_no_more_pages(self):
        with self._cond:
            for pb in self._parts:
                pb.no_more = True
            self._cond.notify_all()

    def fail(self, message: str):
        with self._cond:
            self._failed = message
            for pb in self._parts:
                pb.no_more = True
            self._cond.notify_all()

    def _all_aborted(self) -> bool:
        return all(pb.aborted for pb in self._parts)

    def _maybe_release_spool(self):
        # caller holds the lock; drop the spool file once no partition can
        # ever read from it again
        if self._spool_f is None:
            return
        if all(pb.aborted or (pb.no_more and not pb.entries)
               for pb in self._parts):
            try:
                self._spool_f.close()
            except OSError:
                pass
            self._spool_f = None
            self._spooled_bytes = 0

    # -- consumer ---------------------------------------------------------

    def get(self, partition: int, token: int, max_bytes: int = 16 << 20,
            max_wait_s: float = 1.0) -> Tuple[List[bytes], int, bool]:
        """Pages from `token` on (long-poll up to max_wait_s).

        Returns (pages, next_token, complete). Re-fetching an unacked token
        returns the same pages (exactly-once via client-side dedup, like
        SerializedPage token semantics)."""
        with self._cond:
            pb = self._parts[partition]
            if self._failed is not None:
                raise BufferFailed(self._failed)
            deadline = max_wait_s
            while token >= pb.end_token and not pb.no_more and deadline > 0:
                step = min(deadline, 0.1)
                self._cond.wait(timeout=step)
                deadline -= step
                if self._failed is not None:
                    raise BufferFailed(self._failed)
            pages = []
            size = 0
            t = token
            if t < pb.base_token:
                t = pb.base_token  # already acked past this point
            while t < pb.end_token and size < max_bytes:
                page = self._read_entry(pb.entries[t - pb.base_token])
                pages.append(page)
                size += len(page)
                t += 1
            complete = pb.no_more and t >= pb.end_token
            return pages, t, complete

    def ack(self, partition: int, token: int):
        """Discard pages before `token` (client acknowledged receipt)."""
        with self._cond:
            pb = self._parts[partition]
            drop = min(max(token - pb.base_token, 0), len(pb.entries))
            for i in range(drop):
                self._release_entry(pb.entries[i])
            del pb.entries[:drop]
            pb.base_token += drop
            self._maybe_release_spool()
            self._cond.notify_all()

    def abort(self, partition: int):
        with self._cond:
            pb = self._parts[partition]
            pb.aborted = True
            for e in pb.entries:
                self._release_entry(e)
            pb.entries.clear()
            pb.no_more = True
            self._maybe_release_spool()
            self._cond.notify_all()

    def buffered_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def spooled_bytes(self) -> int:
        with self._lock:
            return self._spooled_bytes

    def is_finished(self) -> bool:
        with self._lock:
            return all(
                pb.aborted or (pb.no_more and not pb.entries) for pb in self._parts
            )


class BufferFailed(RuntimeError):
    pass
