"""Prometheus-style metrics rendering — the JMX-export analog.

Reference: the reference exposes engine internals over JMX MBeans
(presto-jmx connector + airlift jmx http endpoints); the cloud-native
equivalent is a /v1/metrics text exposition that scrapers ingest
directly. Metrics are derived on demand from the same status structures
the REST introspection serves — no separate collection machinery.
"""

from __future__ import annotations

from typing import Dict, List, Tuple


def _fmt(name: str, value, labels: Dict[str, str] | None = None) -> str:
    if labels:
        lab = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
        return f"{name}{{{lab}}} {value}"
    return f"{name} {value}"


def render_metrics(rows: List[Tuple[str, str, object, Dict[str, str]]]) -> str:
    """rows: (metric_name, help_text, value, labels). Renders one
    exposition document with # HELP/# TYPE headers per metric family."""
    seen = set()
    out = []
    for name, help_text, value, labels in rows:
        if name not in seen:
            seen.add(name)
            out.append(f"# HELP {name} {help_text}")
            out.append(f"# TYPE {name} gauge")
        out.append(_fmt(name, value, labels))
    return "\n".join(out) + "\n"


def worker_metrics(worker) -> str:
    st = worker.status()
    mem = st.get("memory") or {}
    lbl = {"node": st["nodeId"]}
    rows = [
        ("presto_tpu_worker_tasks", "registered tasks", st["tasks"], lbl),
        ("presto_tpu_worker_running_tasks", "running tasks",
         st["runningTasks"], lbl),
        ("presto_tpu_worker_memory_reserved_bytes", "pool reservation",
         mem.get("reserved", 0), lbl),
        ("presto_tpu_worker_memory_limit_bytes", "pool limit",
         mem.get("limit") or 0, lbl),
        ("presto_tpu_worker_spilled_bytes_total", "bytes spilled to disk",
         st["spilledBytes"], lbl),
        ("presto_tpu_worker_spill_count_total", "spill events",
         st["spillCount"], lbl),
    ]
    from presto_tpu.scan import metrics as scan_metrics

    rows.extend(scan_metrics.metric_rows(lbl))
    return render_metrics(rows)


def coordinator_metrics(coordinator) -> str:
    qm = coordinator.query_manager
    states: Dict[str, int] = {}
    for q in qm.queries():
        states[q.state] = states.get(q.state, 0) + 1
    rows = [
        ("presto_tpu_cluster_active_workers", "workers in rotation",
         len(coordinator.node_manager.active_nodes()), None),
        ("presto_tpu_cluster_total_workers", "workers known to discovery",
         len(coordinator.node_manager.nodes), None),
    ]
    for state, count in sorted(states.items()):
        rows.append(("presto_tpu_queries", "queries by state", count,
                     {"state": state}))
    rows.append(("presto_tpu_plan_cache_entries", "cached distributed plans",
                 len(coordinator._dplan_cache), None))
    from presto_tpu.scan import metrics as scan_metrics

    rows.extend(scan_metrics.metric_rows(None))
    return render_metrics(rows)


_UI_PAGE = """<!DOCTYPE html>
<html><head><title>presto-tpu</title><meta http-equiv="refresh" content="5">
<style>
 body {{ font-family: monospace; margin: 2em; background: #111; color: #ddd; }}
 h1 {{ color: #7ec8e3; }} h2 {{ color: #9a9; margin-top: 1.5em; }}
 table {{ border-collapse: collapse; width: 100%; }}
 th, td {{ text-align: left; padding: 4px 10px; border-bottom: 1px solid #333; }}
 th {{ color: #888; }}
 .RUNNING {{ color: #7ec8e3; }} .FINISHED {{ color: #8c8; }}
 .FAILED {{ color: #e88; }} .QUEUED {{ color: #cc8; }}
</style></head><body>
<h1>presto-tpu coordinator</h1>
<h2>cluster</h2><table>
<tr><th>node</th><th>uri</th><th>state</th><th>failure score</th></tr>
{nodes}
</table>
<h2>queries</h2><table>
<tr><th>query id</th><th>state</th><th>elapsed (s)</th><th>sql</th></tr>
{queries}
</table>
</body></html>
"""


def render_ui(coordinator) -> str:
    """Minimal live cluster/query page (the web-UI analog of
    presto-main's /ui query list) served at the coordinator root."""
    import html
    import time

    nodes = []
    for n in coordinator.node_manager.nodes.values():
        nodes.append(
            f"<tr><td>{html.escape(n.node_id)}</td>"
            f"<td>{html.escape(n.uri)}</td><td>{n.state}</td>"
            f"<td>{n.failure_score:.2f}</td></tr>")
    queries = []
    for q in sorted(coordinator.query_manager.queries(),
                    key=lambda q: q.create_time, reverse=True)[:50]:
        elapsed = (q.end_time or time.time()) - q.create_time
        queries.append(
            f'<tr><td>{html.escape(q.query_id)}</td>'
            f'<td class="{q.state}">{q.state}</td>'
            f"<td>{elapsed:.2f}</td>"
            f"<td>{html.escape((q.sql or '')[:160])}</td></tr>")
    return _UI_PAGE.format(nodes="\n".join(nodes) or "<tr><td>none</td></tr>",
                           queries="\n".join(queries)
                           or "<tr><td>none</td></tr>")
