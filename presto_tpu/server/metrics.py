"""Prometheus-style metrics rendering — the JMX-export analog.

Reference: the reference exposes engine internals over JMX MBeans
(presto-jmx connector + airlift jmx http endpoints); the cloud-native
equivalent is a /v1/metrics text exposition that scrapers ingest
directly. Metrics are derived on demand from the same status structures
the REST introspection serves — no separate collection machinery.

Exposition rules honored here (text format 0.0.4): HELP/TYPE once per
family, label values escaped (backslash, quote, newline), counter
families typed `counter`, and the histogram families from
presto_tpu.obs.metrics appended per plane so the in-process cluster
(coordinator + workers sharing one process) never double-exposes a
series.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

_ESCAPES = [("\\", "\\\\"), ('"', '\\"'), ("\n", "\\n")]


def _escape_label(value: object) -> str:
    s = str(value)
    for raw, esc in _ESCAPES:
        s = s.replace(raw, esc)
    return s


def _fmt(name: str, value, labels: Dict[str, str] | None = None) -> str:
    if labels:
        lab = ",".join(f'{k}="{_escape_label(v)}"'
                       for k, v in sorted(labels.items()))
        return f"{name}{{{lab}}} {value}"
    return f"{name} {value}"


def _row_type(name: str, explicit: Optional[str]) -> str:
    if explicit:
        return explicit
    # Prometheus naming convention: monotonic totals end in _total
    return "counter" if name.endswith("_total") else "gauge"


def render_metrics(rows: List[Tuple]) -> str:
    """rows: (metric_name, help_text, value, labels[, type]). Renders one
    exposition document with # HELP/# TYPE headers emitted once per
    metric family. The optional fifth element names the family type
    ("counter" / "gauge" / ...); absent, `*_total` names render as
    counters and everything else as gauges."""
    seen = set()
    out = []
    for row in rows:
        name, help_text, value, labels = row[0], row[1], row[2], row[3]
        mtype = _row_type(name, row[4] if len(row) > 4 else None)
        if name not in seen:
            seen.add(name)
            out.append(f"# HELP {name} {help_text}")
            out.append(f"# TYPE {name} {mtype}")
        out.append(_fmt(name, value, labels))
    return "\n".join(out) + "\n"


def worker_metrics(worker) -> str:
    st = worker.status()
    mem = st.get("memory") or {}
    lbl = {"node": st["nodeId"]}
    rows = [
        ("presto_tpu_worker_tasks", "registered tasks", st["tasks"], lbl),
        ("presto_tpu_worker_running_tasks", "running tasks",
         st["runningTasks"], lbl),
        ("presto_tpu_worker_memory_reserved_bytes", "pool reservation",
         mem.get("reserved", 0), lbl),
        ("presto_tpu_worker_memory_limit_bytes", "pool limit",
         mem.get("limit") or 0, lbl),
        ("presto_tpu_worker_spilled_bytes_total", "bytes spilled to disk",
         st["spilledBytes"], lbl),
        ("presto_tpu_worker_spill_count_total", "spill events",
         st["spillCount"], lbl),
    ]
    from presto_tpu.exec import programs as exec_programs
    from presto_tpu.obs import devprof as obs_devprof
    from presto_tpu.obs import metrics as obs_metrics
    from presto_tpu.obs import runstats as obs_runstats
    from presto_tpu.scan import metrics as scan_metrics

    # scan + compile + HBO counters are process-wide; the plane label keeps
    # the worker and coordinator expositions of a shared-process cluster
    # distinguishable (sum over planes double-counts — filter on one)
    rows.extend(scan_metrics.metric_rows({**lbl, "plane": "worker"}))
    rows.extend(exec_programs.metric_rows({**lbl, "plane": "worker"}))
    rows.extend(obs_runstats.metric_rows({**lbl, "plane": "worker"}))
    rows.extend(obs_devprof.metric_rows({**lbl, "plane": "worker"}))
    from presto_tpu.server import result_cache as _result_cache

    # result-cache families appear only once the cache has been consulted
    # (result_cache=off scrapes stay bit-for-bit pre-cache)
    rows.extend(_result_cache.CACHE.metric_rows({**lbl, "plane": "worker"}))
    from presto_tpu.exec import farm as _farm

    # compile-farm families appear only once the farm has done anything
    rows.extend(_farm.metric_rows({**lbl, "plane": "worker"}))
    from presto_tpu.exec import adaptive as _adaptive

    # adaptive-action families are armed-gated the same way: adaptive=off
    # everywhere leaves the scrape bit-for-bit pre-adaptive
    rows.extend(_adaptive.metric_rows({**lbl, "plane": "worker"}))
    return render_metrics(rows) + obs_metrics.render_histograms("worker")


def coordinator_metrics(coordinator) -> str:
    qm = coordinator.query_manager
    states: Dict[str, int] = {}
    for q in qm.queries():
        states[q.state] = states.get(q.state, 0) + 1
    rows = [
        ("presto_tpu_cluster_active_workers", "workers in rotation",
         len(coordinator.node_manager.active_nodes()), None),
        ("presto_tpu_cluster_total_workers", "workers known to discovery",
         len(coordinator.node_manager.nodes), None),
    ]
    for state, count in sorted(states.items()):
        rows.append(("presto_tpu_queries", "queries by state", count,
                     {"state": state}))
    rows.append(("presto_tpu_plan_cache_entries", "cached distributed plans",
                 len(coordinator._dplan_cache), None))
    from presto_tpu.exec import programs as exec_programs
    from presto_tpu.obs import devprof as obs_devprof
    from presto_tpu.obs import metrics as obs_metrics
    from presto_tpu.obs import runstats as obs_runstats
    from presto_tpu.scan import metrics as scan_metrics

    rows.extend(scan_metrics.metric_rows({"plane": "coordinator"}))
    rows.extend(exec_programs.metric_rows({"plane": "coordinator"}))
    rows.extend(obs_runstats.metric_rows({"plane": "coordinator"}))
    rows.extend(obs_devprof.metric_rows({"plane": "coordinator"}))
    from presto_tpu.server import result_cache as _result_cache

    # same armed-gating as the worker plane: no families until consulted
    rows.extend(_result_cache.CACHE.metric_rows({"plane": "coordinator"}))
    from presto_tpu.exec import farm as _farm

    rows.extend(_farm.metric_rows({"plane": "coordinator"}))
    from presto_tpu.exec import adaptive as _adaptive

    # armed-gated like the worker plane: adaptive=off scrapes bit-for-bit
    rows.extend(_adaptive.metric_rows({"plane": "coordinator"}))
    text = render_metrics(rows) + obs_metrics.render_histograms("coordinator")
    from presto_tpu.obs import lifecycle as obs_lifecycle

    # SLO families appear only once a lifecycle-tracked query has been
    # registered — lifecycle=off stays bit-for-bit identical to pre-SLO
    # expositions (no zeroed family declarations either).
    if obs_lifecycle.armed():
        slo_rows = obs_lifecycle.metric_rows({"plane": "coordinator"})
        text += (render_metrics(slo_rows) if slo_rows else "")
        text += obs_lifecycle.render_slo_histograms("coordinator")
    from presto_tpu.obs import inflight as obs_inflight

    # inflight families are likewise armed-gated: no query ever registered
    # (inflight=off everywhere) leaves the scrape family-free
    if obs_inflight.armed():
        inf_rows = obs_inflight.metric_rows({"plane": "coordinator"})
        text += (render_metrics(inf_rows) if inf_rows else "")
    return text


_UI_PAGE = """<!DOCTYPE html>
<html><head><title>presto-tpu</title><meta http-equiv="refresh" content="5">
<style>
 body {{ font-family: monospace; margin: 2em; background: #111; color: #ddd; }}
 h1 {{ color: #7ec8e3; }} h2 {{ color: #9a9; margin-top: 1.5em; }}
 table {{ border-collapse: collapse; width: 100%; }}
 th, td {{ text-align: left; padding: 4px 10px; border-bottom: 1px solid #333; }}
 th {{ color: #888; }}
 a {{ color: #7ec8e3; }}
 .RUNNING {{ color: #7ec8e3; }} .FINISHED {{ color: #8c8; }}
 .FAILED {{ color: #e88; }} .QUEUED {{ color: #cc8; }}
 .EXPIRED {{ color: #e8a; }} .CANCELED {{ color: #aaa; }}
</style></head><body>
<h1>presto-tpu coordinator</h1>
<h2>cluster</h2><table>
<tr><th>node</th><th>uri</th><th>state</th><th>failure score</th></tr>
{nodes}
</table>
<h2>queries</h2><table>
<tr><th>query id</th><th>state</th><th>elapsed (s)</th><th>sql</th></tr>
{queries}
</table>
</body></html>
"""


def render_ui(coordinator) -> str:
    """Minimal live cluster/query page (the web-UI analog of
    presto-main's /ui query list) served at the coordinator root."""
    import html
    import time

    nodes = []
    for n in coordinator.node_manager.nodes.values():
        nodes.append(
            f"<tr><td>{html.escape(n.node_id)}</td>"
            f"<td>{html.escape(n.uri)}</td><td>{n.state}</td>"
            f"<td>{n.failure_score:.2f}</td></tr>")
    queries = []
    for q in sorted(coordinator.query_manager.queries(),
                    key=lambda q: q.create_time, reverse=True)[:50]:
        elapsed = (q.end_time or time.time()) - q.create_time
        qid = html.escape(q.query_id)
        queries.append(
            f'<tr><td><a href="/ui/query/{qid}">{qid}</a></td>'
            f'<td class="{q.state}">{q.state}</td>'
            f"<td>{elapsed:.2f}</td>"
            f"<td>{html.escape((q.sql or '')[:160])}</td></tr>")
    return _UI_PAGE.format(nodes="\n".join(nodes) or "<tr><td>none</td></tr>",
                           queries="\n".join(queries)
                           or "<tr><td>none</td></tr>")


_QUERY_PAGE = """<!DOCTYPE html>
<html><head><title>presto-tpu query {qid}</title>
<style>
 body {{ font-family: monospace; margin: 2em; background: #111; color: #ddd; }}
 h1 {{ color: #7ec8e3; }} h2 {{ color: #9a9; margin-top: 1.5em; }}
 table {{ border-collapse: collapse; width: 100%; }}
 th, td {{ text-align: left; padding: 3px 10px; border-bottom: 1px solid #333; }}
 th {{ color: #888; }}
 a {{ color: #7ec8e3; }}
 .RUNNING {{ color: #7ec8e3; }} .FINISHED {{ color: #8c8; }}
 .FAILED {{ color: #e88; }} .QUEUED {{ color: #cc8; }}
 .EXPIRED {{ color: #e8a; }} .CANCELED {{ color: #aaa; }}
 pre {{ background: #1a1a1a; padding: 1em; overflow-x: auto; }}
 .bar {{ background: #2a6; height: 10px; display: inline-block; }}
 .pbar {{ background: #333; width: 400px; height: 14px; display: inline-block; }}
 .pfill {{ background: #7ec8e3; height: 14px; display: block; }}
</style></head><body>
<a href="/ui">&larr; queries</a>
<h1>query {qid}</h1>
<table>
<tr><th>state</th><td class="{state}">{state}</td></tr>
<tr><th>elapsed</th><td>{elapsed}</td></tr>
<tr><th>user</th><td>{user}</td></tr>
</table>
{progress}
<h2>sql</h2><pre>{sql}</pre>
<h2>trace spans</h2>
{trace}
<p><a href="/v1/query/{qid}/trace">raw trace JSON</a></p>
</body></html>
"""


def _render_span_rows(tree: list, total_s: float, depth: int = 0,
                      out: Optional[list] = None) -> list:
    import html as _html

    if out is None:
        out = []
    for node in tree:
        dur = node.get("durationS") or 0.0
        pct = (dur / total_s * 100.0) if total_s > 0 else 0.0
        width = max(1, int(pct * 2))
        attrs = node.get("attrs") or {}
        attr_s = " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
        indent = "&nbsp;" * (2 * depth)
        out.append(
            f"<tr><td>{indent}{_html.escape(node['name'])}</td>"
            f"<td>{_html.escape(node.get('kind', ''))}</td>"
            f"<td>{dur:.4f}</td>"
            f'<td><span class="bar" style="width:{width}px"></span>'
            f" {pct:.1f}%</td>"
            f"<td>{_html.escape(attr_s[:120])}</td></tr>")
        _render_span_rows(node.get("children") or [], total_s, depth + 1, out)
    return out


def render_query_page(coordinator, query_id: str) -> Optional[str]:
    """Per-query drill-down: state + sql + nested span table with
    percent-of-query bars. None when the query id is unknown."""
    import html
    import time

    q = None
    for cand in coordinator.query_manager.queries():
        if cand.query_id == query_id:
            q = cand
            break
    tracer = coordinator.trace_registry.get(query_id)
    if q is None and tracer is None:
        return None
    if q is not None:
        state, user, sql = q.state, q.user, q.sql or ""
        elapsed = f"{(q.end_time or time.time()) - q.create_time:.3f}s"
    else:
        state, user, sql, elapsed = "?", "?", "", "?"
    from presto_tpu.obs import lifecycle as obs_lifecycle

    progress_html = ""
    pdoc = obs_lifecycle.progress_doc(query_id, state=str(state))
    if pdoc is not None:
        frac = pdoc.get("fraction") or 0.0
        width = int(max(0.0, min(1.0, frac)) * 400)
        seg_rows = "".join(
            f"<tr><td>{html.escape(seg)}</td><td>{val:.4f}</td></tr>"
            for seg, val in (pdoc.get("segments") or {}).items())
        progress_html = (
            "<h2>progress</h2>"
            f'<p><span class="pbar"><span class="pfill" '
            f'style="width:{width}px"></span></span> '
            f"{frac * 100.0:.1f}% "
            f"({html.escape(str(pdoc.get('provenance')))})</p>"
            "<table><tr><th>segment</th><th>wall (s)</th></tr>"
            + seg_rows + "</table>")
    trace_html = "<p>no trace recorded</p>"
    if tracer is not None:
        doc = tracer.to_json()
        tree = doc.get("tree") or []
        total = max((n.get("durationS") or 0.0) for n in tree) if tree else 0.0
        rows = _render_span_rows(tree, total)
        if rows:
            trace_html = (
                "<table><tr><th>span</th><th>kind</th><th>wall (s)</th>"
                "<th>% of query</th><th>attrs</th></tr>"
                + "\n".join(rows) + "</table>")
    return _QUERY_PAGE.format(qid=html.escape(query_id),
                              state=html.escape(str(state)),
                              elapsed=html.escape(elapsed),
                              user=html.escape(str(user)),
                              progress=progress_html,
                              sql=html.escape(sql),
                              trace=trace_html)
