"""Multi-tenant result reuse: fingerprint-keyed semantic result cache.

Process-wide, memory-budgeted memoization of final query results and
materialized breaker-subplan results. The cache key composes the three
planes that already exist in the engine:

- the compile plane's structural sha256 of the bound plan (PR 5,
  ``exec/programs.structural_fingerprint``) — for a distributed plan the
  root fragment alone is NOT discriminating (RemoteSource leaves carry
  only fragment ids), so ``plan_fingerprint`` hashes every fragment root
  in fid order plus the output names;
- the HBO plane's catalog snapshot token (PR 10,
  ``obs/runstats.catalog_token``) — any INSERT/CTAS/DROP changes a row
  count or table list and the token, so stale entries can never hit;
- the result-relevant session fingerprint (catalog.schema name-resolution
  context). Engine knobs like ``breaker_engine`` deliberately do NOT key:
  they change how a result is computed, never what it is.

Admission is cost-aware: an entry's value is its observed execution wall
(floored by the HBO history wall when available) per byte held, so the
cache keeps what was expensive to compute and cheap to hold. Bytes are
charged to the PR 11 cluster memory ledger; under sustained pressure
``ClusterMemoryManager.enforce`` revokes cache entries (cheapest density
first) BEFORE killing queries.

Reference discipline: presto-main's semantic cache proposals and
Aria-style cycle elision — the cheapest query is the one never re-planned,
re-compiled, or re-executed.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "CACHE",
    "ResultCache",
    "batch_nbytes",
    "find_breaker_subplans",
    "plan_fingerprint",
    "query_key",
    "replace_child",
    "spliceable_output",
    "subplan_key",
]

_DEFAULT_BUDGET = 256 << 20  # bytes


def _env_budget() -> int:
    try:
        return int(os.environ.get("PRESTO_TPU_RESULT_CACHE_BYTES",
                                  _DEFAULT_BUDGET))
    except (TypeError, ValueError):
        return _DEFAULT_BUDGET


# -- key composition -------------------------------------------------------


def plan_fingerprint(dplan) -> Optional[str]:
    """Structural sha256 over ALL fragment roots of a DistributedPlan (fid
    order) plus the output names, memoized on the plan. Hashing only the
    root fragment would collide across queries whose differing scans live
    in leaf fragments behind RemoteSource placeholders."""
    sha = dplan.__dict__.get("_rc_sha")
    if sha is not None:
        return sha or None
    try:
        from presto_tpu.plan.codec import canonical_node_json

        parts = []
        for fid in sorted(dplan.fragments):
            parts.append(f"#{fid}:"
                         + canonical_node_json(dplan.fragments[fid].root))
        parts.append("|".join(dplan.output_names))
        sha = hashlib.sha256("\n".join(parts).encode()).hexdigest()
    except Exception:
        sha = ""
    dplan.__dict__["_rc_sha"] = sha
    return sha or None


def query_key(dplan, catalog, session_catalog: str = "",
              session_schema: str = "") -> Optional[str]:  # fp: key(result-cache) covers(plan-structure, catalog, session-schema)
    """Full-result cache key for a distributed plan, or None when the plan
    cannot be fingerprinted (codec-unsupported node). Deliberately
    config-free: a query's RESULT is config-invariant (config only picks
    programs/policies), so forking on config would just shred hit rates
    — the knob-flow contract records that decision."""
    sha = plan_fingerprint(dplan)
    if sha is None:
        return None
    from presto_tpu.obs.runstats import catalog_token

    return (sha + "/" + catalog_token(catalog) + "/"
            + f"{session_catalog or ''}.{session_schema or ''}")


def subplan_key(node, catalog) -> Optional[str]:
    """Cache key for a breaker subplan (a bound plan subtree). Subplan
    entries share the snapshot-token invalidation of query entries but
    live in their own key namespace."""
    try:
        from presto_tpu.exec.programs import structural_fingerprint

        sha = structural_fingerprint(node)
    except Exception:
        sha = None
    if sha is None:
        return None
    from presto_tpu.obs.runstats import catalog_token

    return sha + "/" + catalog_token(catalog) + "/subplan"


# -- batch accounting ------------------------------------------------------

_COL_SLOTS = ("values", "validity", "hi", "sizes", "evalid", "keys")


def batch_nbytes(batch) -> int:
    """Held-bytes estimate for a Batch: every array hanging off every
    column plus the live mask. Dictionary pages are shared engine-wide and
    are not charged to the entry."""
    total = 0
    try:
        for c in batch.columns:
            for slot in _COL_SLOTS:
                a = getattr(c, slot, None)
                total += int(getattr(a, "nbytes", 0) or 0)
        total += int(getattr(batch.live, "nbytes", 0) or 0)
    except Exception:
        pass
    return total


# -- subplan discovery / splicing ------------------------------------------

_SPLICE_TYPES = frozenset([
    "bigint", "integer", "smallint", "tinyint",
    "double", "real", "boolean", "varchar",
])


def spliceable_output(node) -> bool:
    """Only subtrees whose output round-trips losslessly through a memory
    table are splice candidates (decimals re-scale on ingest; structural
    types re-encode)."""
    try:
        out = node.output
    except Exception:
        return False
    if not out:
        return False
    return all(str(t) in _SPLICE_TYPES for _, t in out)


def find_breaker_subplans(root, limit: int = 4) -> List[Any]:
    """Topmost grouped Aggregates under ``root`` — the pipeline breakers
    whose materialized output is a natural reuse unit. Descent stops at a
    match (nested aggregates are covered by their ancestor's entry)."""
    from presto_tpu.plan.nodes import Aggregate

    found: List[Any] = []

    def walk(n):
        if len(found) >= limit:
            return
        if (isinstance(n, Aggregate) and n.step == "single"
                and n.group_keys and spliceable_output(n)):
            found.append(n)
            return
        for c in n.children():
            walk(c)

    walk(root)
    return found


def replace_child(root, old, new) -> bool:
    """Replace ``old`` (by identity) with ``new`` anywhere in the plan
    tree under ``root``, scanning dataclass fields and lists in place."""
    import dataclasses

    def fix(n) -> bool:
        if not dataclasses.is_dataclass(n):
            return False
        for f in dataclasses.fields(n):
            v = getattr(n, f.name, None)
            if v is old:
                setattr(n, f.name, new)
                return True
            if isinstance(v, list):
                for i, item in enumerate(v):
                    if item is old:
                        v[i] = new
                        return True
                    if fix(item):
                        return True
            elif fix(v):
                return True
        return False

    return fix(root)


# -- the cache -------------------------------------------------------------


class _Entry:
    __slots__ = ("key", "kind", "batch", "nbytes", "wall_s", "token",
                 "hits", "created", "on_evict")

    def __init__(self, key: str, kind: str, batch, nbytes: int,
                 wall_s: float, token: str,
                 on_evict: Optional[Callable[[], None]]):
        self.key = key
        self.kind = kind  # "query" | "subplan"
        self.batch = batch
        self.nbytes = nbytes
        self.wall_s = wall_s
        self.token = token
        self.hits = 0
        self.created = time.time()
        self.on_evict = on_evict

    @property
    def density(self) -> float:
        # value-per-byte: what was expensive to compute and cheap to hold
        # survives admission pressure
        return self.wall_s / float(max(1, self.nbytes))


class ResultCache:
    """Process-wide result cache. All mutation is under one lock; evict
    callbacks and event emission run outside it (they take other planes'
    locks)."""

    def __init__(self, budget_bytes: Optional[int] = None):
        self._lock = threading.Lock()
        self._budget = (budget_bytes if budget_bytes is not None
                        else _env_budget())
        self._entries: Dict[str, _Entry] = {}  # shared: guarded-by(self._lock)
        self._bytes = 0  # shared: guarded-by(self._lock)
        self._hits = 0  # shared: guarded-by(self._lock)
        self._misses = 0  # shared: guarded-by(self._lock)
        self._evictions = 0  # shared: guarded-by(self._lock)
        self._wall_saved_s = 0.0  # shared: guarded-by(self._lock)
        self._armed = False  # shared: guarded-by(self._lock)

    # -- discipline: ``off`` must stay bit-for-bit pre-PR. Nothing arms
    # the cache until a coordinator actually consults it with the session
    # knob on; until then metric_rows() contributes no families.

    def armed(self) -> bool:
        with self._lock:
            return self._armed

    def configure(self, budget_bytes: int) -> None:
        with self._lock:
            self._budget = int(budget_bytes)

    @property
    def budget_bytes(self) -> int:
        with self._lock:
            return self._budget

    def bytes_held(self) -> int:
        with self._lock:
            return self._bytes

    def counters(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "hits": self._hits,
                "misses": self._misses,
                "evictions": self._evictions,
                "bytes": self._bytes,
                "entries": len(self._entries),
                "wall_saved_s": round(self._wall_saved_s, 6),
                "budget_bytes": self._budget,
            }

    def reset(self) -> None:
        """Test hook: drop everything including counters and arming."""
        with self._lock:
            entries = list(self._entries.values())
            self._entries.clear()
            self._bytes = 0
            self._hits = 0
            self._misses = 0
            self._evictions = 0
            self._wall_saved_s = 0.0
            self._armed = False
        for e in entries:
            self._run_evict_cb(e)

    # -- lookup / admission ------------------------------------------------

    def lookup(self, key: Optional[str], query_id: Optional[str] = None):
        """Consult the cache; counts a hit or miss and emits a
        ``cache_hit`` event. Returns the cached batch or None."""
        if key is None:
            return None
        hit = None
        with self._lock:
            self._armed = True
            e = self._entries.get(key)
            if e is None:
                self._misses += 1
            else:
                e.hits += 1
                self._hits += 1
                self._wall_saved_s += e.wall_s
                hit = e
        if hit is not None:
            self._emit("cache_hit", query_id=query_id, key=key[:24],
                       cache_kind=hit.kind, bytes=hit.nbytes,
                       wall_saved_s=round(hit.wall_s, 6))
            return hit.batch
        return None

    def peek(self, key: Optional[str]) -> bool:
        """Non-mutating presence probe (EXPLAIN ANALYZE header): no
        counters, no events, no arming."""
        if key is None:
            return False
        with self._lock:
            return key in self._entries

    def admit(self, key: Optional[str], kind: str, batch, wall_s: float,
              token: str, nbytes: Optional[int] = None,
              on_evict: Optional[Callable[[], None]] = None,
              query_id: Optional[str] = None) -> bool:
        """Cost-aware admission. Rejects oversized entries outright;
        otherwise evicts strictly lower-density entries to make room and
        rejects the newcomer if room would cost denser residents."""
        if key is None or batch is None:
            return False
        nb = batch_nbytes(batch) if nbytes is None else int(nbytes)
        cand = _Entry(key, kind, batch, nb, max(0.0, float(wall_s)), token,
                      on_evict)
        evicted: List[_Entry] = []
        admitted = False
        with self._lock:
            self._armed = True
            if nb > self._budget:
                return False
            prev = self._entries.pop(key, None)
            if prev is not None:
                self._bytes -= prev.nbytes
                evicted.append(prev)
            need = self._bytes + nb - self._budget
            if need > 0:
                victims = self._pick_victims_locked(need, cand.density)
                if victims is None:
                    # rollback the same-key displacement; the resident
                    # population is denser than the newcomer
                    if prev is not None:
                        self._entries[key] = prev
                        self._bytes += prev.nbytes
                        evicted.clear()
                    return False
                for v in victims:
                    del self._entries[v.key]
                    self._bytes -= v.nbytes
                    evicted.append(v)
            self._entries[key] = cand
            self._bytes += nb
            self._evictions += len(evicted)
            admitted = True
        for e in evicted:
            self._run_evict_cb(e)
            self._emit("cache_evict", query_id=query_id, key=e.key[:24],
                       cache_kind=e.kind, bytes=e.nbytes, reason="admission")
        return admitted

    def _pick_victims_locked(self, need: int,
                             new_density: float) -> Optional[List[_Entry]]:
        victims: List[_Entry] = []
        freed = 0
        for e in sorted(self._entries.values(), key=lambda e: e.density):
            if freed >= need:
                break
            if e.density >= new_density:
                return None
            victims.append(e)
            freed += e.nbytes
        return victims if freed >= need else None

    # -- invalidation ------------------------------------------------------

    def flush(self, reason: str = "flush") -> int:
        """Drop every entry (explicit flush / DDL barrier)."""
        with self._lock:
            entries = list(self._entries.values())
            self._entries.clear()
            self._bytes = 0
            self._evictions += len(entries)
        for e in entries:
            self._run_evict_cb(e)
            self._emit("cache_evict", key=e.key[:24], cache_kind=e.kind,
                       bytes=e.nbytes, reason=reason)
        return len(entries)

    def flush_stale(self, token: str) -> int:
        """Drop entries whose snapshot token no longer matches the live
        catalog. Key mismatch already guarantees they can never hit; this
        reclaims their bytes eagerly after DDL."""
        stale: List[_Entry] = []
        with self._lock:
            for k in [k for k, e in self._entries.items()
                      if e.token != token]:
                e = self._entries.pop(k)
                self._bytes -= e.nbytes
                stale.append(e)
            self._evictions += len(stale)
        for e in stale:
            self._run_evict_cb(e)
            self._emit("cache_evict", key=e.key[:24], cache_kind=e.kind,
                       bytes=e.nbytes, reason="invalidated")
        return len(stale)

    def revoke_for_pressure(self, target_bytes: Optional[int] = None) -> int:
        """Memory-ledger revocation: free at least ``target_bytes``
        (default: everything), cheapest density first. Returns bytes
        freed. Called by ClusterMemoryManager.enforce BEFORE it considers
        killing queries."""
        revoked: List[_Entry] = []
        with self._lock:
            goal = self._bytes if target_bytes is None else int(target_bytes)
            freed = 0
            for e in sorted(self._entries.values(), key=lambda e: e.density):
                if freed >= goal:
                    break
                del self._entries[e.key]
                self._bytes -= e.nbytes
                freed += e.nbytes
                revoked.append(e)
            self._evictions += len(revoked)
        freed = 0
        for e in revoked:
            freed += e.nbytes
            self._run_evict_cb(e)
            self._emit("cache_evict", key=e.key[:24], cache_kind=e.kind,
                       bytes=e.nbytes, reason="memory_pressure")
        return freed

    # -- exposition --------------------------------------------------------

    def metric_rows(self, labels: Optional[Dict[str, str]] = None) -> List[Tuple]:
        """Prometheus rows for both metric planes. Empty until armed so a
        ``result_cache=off`` process scrapes bit-for-bit pre-PR."""
        with self._lock:
            if not self._armed:
                return []
            hits, misses = self._hits, self._misses
            evictions, nbytes = self._evictions, self._bytes
        return [
            ("presto_tpu_result_cache_hits_total",
             "Result cache hits", hits, labels, "counter"),
            ("presto_tpu_result_cache_misses_total",
             "Result cache misses", misses, labels, "counter"),
            ("presto_tpu_result_cache_evictions_total",
             "Result cache evictions (admission, invalidation, pressure)",
             evictions, labels, "counter"),
            ("presto_tpu_result_cache_bytes",
             "Bytes held by cached result batches", nbytes, labels, "gauge"),
        ]

    # -- internals ---------------------------------------------------------

    @staticmethod
    def _run_evict_cb(e: _Entry) -> None:
        cb = e.on_evict
        if cb is None:
            return
        try:
            cb()
        except Exception:
            pass

    @staticmethod
    def _emit(kind: str, query_id: Optional[str] = None, **attrs) -> None:
        try:
            from presto_tpu.obs.events import EVENTS

            EVENTS.emit(kind, query_id=query_id, **attrs)
        except Exception:
            pass


CACHE = ResultCache()
