"""Coordinator — control plane: discovery, node health, distributed
scheduling, result collection.

Reference surface:
- metadata/DiscoveryNodeManager.java + embedded airlift discovery: workers
  announce themselves; the coordinator tracks active nodes
- failureDetector/HeartbeatFailureDetector.java:77,225,360: periodic pings
  with a decaying failure-rate gate; failed nodes are excluded from
  scheduling
- execution/scheduler/SqlQueryScheduler.java:640,657 + SqlStageExecution +
  server/remotetask/HttpRemoteTask.java:336: stage-by-stage task creation
  over HTTP
- ClusterSizeMonitor: gate query start on minimum workers

TPU-native shape: fragments are scheduled one-task-per-worker (HASH/SOURCE)
or single-task (SINGLE); producers are created before consumers (ascending
fragment id = topological order), everything runs concurrently and streams
through the pull exchange.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional

from presto_tpu.batch import Batch
from presto_tpu.connector import Catalog
from presto_tpu.exec import farm as _farm
from presto_tpu.exec.runtime import ExecConfig
from presto_tpu.obs import events as _obs_events
from presto_tpu.obs import inflight as _obs_inflight
from presto_tpu.obs import lifecycle as _obs_lifecycle
from presto_tpu.obs import trace as _obs_trace
from presto_tpu.plan.fragmenter import (
    HASH,
    OUT_BROADCAST,
    SINGLE,
    SOURCE,
    DistributedPlan,
    strip_runtime_state,
)
from presto_tpu.server.exchange import ExchangeClient, ExchangeFailure
from presto_tpu.server.worker import TaskUpdate


class NodeInfo:
    def __init__(self, node_id: str, uri: str):
        self.node_id = node_id
        self.uri = uri
        self.last_seen = time.monotonic()
        # decayed failure counter (HeartbeatFailureDetector's
        # DecayCounter(0.1) moral equivalent)
        self.failure_score = 0.0
        self.state = "active"

    def record_success(self):
        self.last_seen = time.monotonic()
        self.failure_score *= 0.5

    def record_failure(self):
        self.failure_score = self.failure_score * 0.8 + 1.0

    @property
    def failed(self) -> bool:
        return self.failure_score > 4.0


class NodeManager:
    """Registry of announced worker nodes (DiscoveryNodeManager analog)."""

    def __init__(self, expire_s: float = 30.0):
        self.nodes: Dict[str, NodeInfo] = {}
        self._lock = threading.Lock()
        self.expire_s = expire_s

    def announce(self, node_id: str, uri: str, state: str = "active"):
        with self._lock:
            n = self.nodes.get(node_id)
            if n is None or n.uri != uri:
                n = NodeInfo(node_id, uri)
                self.nodes[node_id] = n
            else:
                n.record_success()
            # the worker's own announcement is authoritative for its state —
            # a restarted worker reusing node_id/uri returns to rotation
            n.state = "active" if state == "active" else "draining"

    def active_nodes(self) -> List[NodeInfo]:
        now = time.monotonic()
        with self._lock:
            return [
                n for n in self.nodes.values()
                if not n.failed and n.state == "active"
                and now - n.last_seen < self.expire_s
            ]

    def remove(self, node_id: str):
        with self._lock:
            self.nodes.pop(node_id, None)


class HeartbeatFailureDetector:
    """Background prober: GET /v1/status on every known node; nodes whose
    decayed failure score crosses the threshold are excluded from
    scheduling (HeartbeatFailureDetector.java:360 ping loop)."""

    def __init__(self, node_manager: NodeManager, interval_s: float = 2.0,
                 cluster_memory=None, query_manager=None):
        self.node_manager = node_manager
        self.interval_s = interval_s
        self.cluster_memory = cluster_memory
        self.query_manager = query_manager
        self._stop = threading.Event()
        self.thread = threading.Thread(target=self._loop, daemon=True,
                                       name="failure-detector")

    def start(self):
        self.thread.start()

    def _probe(self, n: NodeInfo):
        try:
            with urllib.request.urlopen(f"{n.uri}/v1/status", timeout=5) as r:
                status = json.loads(r.read())
            if status.get("state") in ("shutting_down", "shut_down"):
                n.state = "draining"
            else:
                n.record_success()
            if self.cluster_memory is not None:
                self.cluster_memory.update_node(n.node_id, status)
            progress = status.get("queryProgress")
            if progress:
                # lifecycle plane: fold the worker's live per-query row
                # counts into the progress registry (attempt ids resolve
                # through the registry's alias map)
                _obs_lifecycle.merge_worker_progress(n.node_id, progress)
            inflight = status.get("queryInflight")
            if inflight:
                # inflight plane: per-task operator watermarks, merged
                # per fragment (seq-guarded — in-process clusters whose
                # publishers already live in the registry are idempotent)
                _obs_inflight.merge_worker(n.node_id, inflight)
        except Exception:
            n.record_failure()

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            # concurrent probes: one hung worker must not stall detection of
            # the others (reference pings asynchronously per service)
            probes = [
                threading.Thread(target=self._probe, args=(n,), daemon=True)
                for n in list(self.node_manager.nodes.values())
            ]
            for t in probes:
                t.start()
            for t in probes:
                t.join(timeout=6)
            # cluster OOM enforcement rides the heartbeat cadence
            # (ClusterMemoryManager.process runs on its executor likewise)
            if self.cluster_memory is not None and self.query_manager is not None:
                try:
                    self.cluster_memory.enforce(self.query_manager)
                except Exception:
                    pass

    def stop(self):
        self._stop.set()


class ClusterSizeMonitor:
    def __init__(self, node_manager: NodeManager, min_workers: int = 1):
        self.node_manager = node_manager
        self.min_workers = min_workers

    def wait_for_minimum(self, timeout_s: float = 30.0):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if len(self.node_manager.active_nodes()) >= self.min_workers:
                return
            time.sleep(0.05)
        raise RuntimeError(
            f"insufficient active workers "
            f"({len(self.node_manager.active_nodes())} < {self.min_workers})"
        )


class QueryFailed(RuntimeError):
    """`retryable=True` marks failures caused by worker/transport loss
    (worth re-running on the surviving cluster); deterministic task errors
    stay non-retryable — the reference's RetryPolicy.QUERY makes the same
    distinction."""

    def __init__(self, msg: str, retryable: bool = False):
        super().__init__(msg)
        self.retryable = retryable


def compute_phases(frags) -> Dict[int, int]:
    """PhasedExecutionSchedule analog (execution/scheduler/
    PhasedExecutionSchedule.java): fragments feeding a join BUILD side get
    an earlier phase than the probe's fragment, so probe-side scans don't
    hold memory while the build is still assembling. Streaming producers
    (exchanges that pipeline: partial→final agg, sort inputs) share their
    consumer's phase. Returns fid → 0-based phase (ascending start order)."""
    from presto_tpu.plan.nodes import (
        HashJoin,
        NestedLoopJoin,
        RemoteSource,
        SemiJoin,
    )

    build_deps: Dict[int, set] = {fid: set() for fid in frags}
    stream_deps: Dict[int, set] = {fid: set() for fid in frags}

    def walk(n, fid, in_build):
        if isinstance(n, RemoteSource):
            (build_deps if in_build else stream_deps)[fid].add(n.fragment_id)
            return
        if isinstance(n, (HashJoin, SemiJoin, NestedLoopJoin)):
            walk(n.left, fid, in_build)
            walk(n.right, fid, True)  # build side
            return
        for c in n.children():
            walk(c, fid, in_build)

    for fid, f in frags.items():
        walk(f.root, fid, False)
    # consumers first (producers have lower fids — fragmenter numbers
    # topologically), so each fragment's phase is final before its deps'
    phase: Dict[int, int] = {}
    for fid in sorted(frags, reverse=True):
        phase.setdefault(fid, 0)
        for dep in stream_deps[fid]:
            phase[dep] = min(phase.get(dep, phase[fid]), phase[fid])
        for dep in build_deps[fid]:
            phase[dep] = min(phase.get(dep, phase[fid] - 1), phase[fid] - 1)
    lo = min(phase.values())
    return {fid: p - lo for fid, p in phase.items()}


def _fragment_scans(root) -> list:
    """All TableScan nodes of a fragment (split-placement candidates)."""
    from presto_tpu.plan.nodes import TableScan

    out = []

    def walk(n):
        if isinstance(n, TableScan):
            out.append(n)
        for c in n.children():
            walk(c)

    walk(root)
    return out


def _affinity_assign(table: str, n_splits: int,
                     worker_keys: List[str]) -> List[List[int]]:
    """Rendezvous-hash split placement with a balance cap (reference:
    scheduler/NodeScheduler.java + SimpleNodeSelector and the
    SOFT_AFFINITY NodeSelectionStrategy of connector split sources).

    Each split ranks every worker by fnv64(table:ordinal:worker) and
    lands on its best-ranked worker that still has capacity
    (cap = ⌈splits/workers⌉, the maxSplitsPerNode analog). The mapping is
    deterministic across queries AND coordinator restarts, so a worker
    keeps seeing the same splits — its device split cache turns that
    stability into scan locality. When a worker joins/leaves, only the
    splits hashed to it move (rendezvous minimal-disruption property)."""
    from presto_tpu.dictionary import fnv64

    k = len(worker_keys)
    cap = -(-n_splits // k) if n_splits else 0
    counts = [0] * k
    out: List[List[int]] = [[] for _ in range(k)]
    for j in range(n_splits):
        ranked = sorted(
            range(k),
            key=lambda w: fnv64(f"{table}:{j}:{worker_keys[w]}"),
            reverse=True)
        for w in ranked:
            if counts[w] < cap:
                out[w].append(j)
                counts[w] += 1
                break
    return out


class DistributedScheduler:
    """Schedules a DistributedPlan onto workers and streams the result
    (SqlQueryScheduler.schedule:657 analog). Policies
    (SystemSessionProperties EXECUTION_POLICY): "all-at-once" starts every
    stage immediately; "phased" creates each phase's tasks only after the
    previous phase's (join-build) tasks finished — see compute_phases."""

    def __init__(self, config: Optional[ExecConfig] = None,
                 cluster_secret: Optional[str] = None,
                 on_worker_lost=None, catalog=None):
        self.config = config or ExecConfig()
        self.cluster_secret = cluster_secret
        # notified with the NodeInfo of a worker found dead during task
        # placement/phase waits (the coordinator excludes it from rotation
        # immediately, like the pre-retry reprobe does)
        self.on_worker_lost = on_worker_lost
        # catalog access enables coordinator-side split placement
        # (soft-affinity scheduling); without it tasks fall back to the
        # static task_index::n_tasks striding
        self.catalog = catalog

    def _headers(self, extra: Optional[dict] = None) -> dict:
        h = dict(extra or {})
        if self.cluster_secret is not None:
            h["X-Presto-Cluster-Secret"] = self.cluster_secret
        return h

    def execute(self, query_id: str, dplan: DistributedPlan,
                workers: List[NodeInfo],
                config: Optional[ExecConfig] = None,
                stats_out: Optional[list] = None,
                tracer=None):
        """`stats_out`, when given, is filled with one
        (task_id, fragment_id, task_info_dict) per task after the result
        stream completes — the per-task stats rollup EXPLAIN ANALYZE
        renders (QueryStats/TaskStats introspection analog).

        `tracer` (obs.trace.Tracer) makes every task-create POST carry the
        query's trace token; after the stream completes the scheduler pulls
        each task's span dump and stitches query → stage → task."""
        config = config or self.config
        tracer = tracer or _obs_trace.NOOP
        trace_parent = tracer.current_parent()
        trace_hdrs = ({_obs_trace.TRACE_HEADER: tracer.token(trace_parent)}
                      if tracer.enabled else {})
        if not workers:
            raise QueryFailed("no active workers")
        frags = dplan.fragments
        # task counts per fragment (FIXED_HASH → one per worker; SINGLE → 1)
        n_tasks = {
            fid: 1 if f.partitioning == SINGLE else len(workers)
            for fid, f in frags.items()
        }
        # Recoverable grouped execution (reference:
        # SystemSessionProperties.java:69 recoverable_grouped_execution +
        # StageExecutionDescriptor + FixedSourcePartitionedScheduler):
        # a grouped SOURCE fragment (colocated bucketed join) is scheduled
        # ONE TASK PER LIFESPAN (task_index=b, n_tasks=B sweeps exactly
        # bucket b) in its own phase with spooled output; a worker lost
        # mid-phase re-runs only its UNFINISHED bucket tasks on survivors —
        # finished lifespans are never redone. Consumers launch only after
        # the gate, so a dead producer has contributed nothing downstream.
        grouped: Dict[int, int] = {}
        if getattr(config, "recoverable_grouped_execution", False):
            for fid, f in frags.items():
                # only fully self-contained fragments qualify: one with a
                # remote source would be forced into phase 0 BEFORE its
                # producers (broadcast build feeding the colocated join)
                if (f.partitioning == SOURCE and fid != dplan.root_fid
                        and not f.remote_sources()):
                    B = _fragment_lifespans(f.root)
                    if B:
                        grouped[fid] = B
                        n_tasks[fid] = B
        # consumer fragment of each producer (tree: exactly one consumer)
        consumer: Dict[int, int] = {}
        for fid, f in frags.items():
            for rs in f.remote_sources():
                consumer[rs.fragment_id] = fid
        # output partition count = consumer's task count
        n_out = {
            fid: n_tasks[consumer[fid]] if fid in consumer else 1
            for fid in frags
        }
        # soft-affinity split placement (NodeScheduler analog): for each
        # single-scan SOURCE fragment, enumerate the connector's splits
        # HERE and pin each ordinal to a worker by rendezvous hash. A
        # rescheduled task keeps its index → its ordinals, so coverage
        # survives worker loss. Multi-scan fragments (colocated bucket
        # joins) keep aligned task_index striding.
        # fid → per-task (ordinals-by-table, enumeration-count-by-table)
        split_assignments: Dict[int, List[tuple]] = {}
        if self.catalog is not None and getattr(config, "split_affinity",
                                                True):
            wkeys = [w.uri for w in workers]
            for fid, f in frags.items():
                if f.partitioning != SOURCE or fid in grouped:
                    continue
                scans = _fragment_scans(f.root)
                if len(scans) != 1:
                    continue
                scan = scans[0]
                try:
                    conn = self.catalog.connectors[scan.catalog]
                    handle = conn.get_table(scan.table)
                    nrows = int(handle.row_count or 0)
                    nsplits = max(1, -(-nrows // config.batch_rows))
                    n = len(conn.splits(handle, nsplits))
                except Exception:
                    continue  # non-enumerable here → static striding
                per_worker = _affinity_assign(scan.table, n, wkeys)
                split_assignments[fid] = [
                    ({scan.table: per_worker[i % len(workers)]},
                     {scan.table: n})
                    for i in range(n_tasks[fid])
                ]
        phased = getattr(config, "execution_policy",
                         "all-at-once") == "phased"
        phases = (compute_phases(frags) if phased
                  else {fid: 0 for fid in frags})
        if grouped:
            # grouped fragments run (and gate) first; everything else keeps
            # its relative phasing shifted after them
            phases = {fid: (0 if fid in grouped else phases[fid] + 1)
                      for fid in frags}
        last_phase = max(phases.values())

        task_urls: Dict[int, List[str]] = {}
        assignments = []  # (task_id, worker, fragment id, index, phase)
        for fid in sorted(frags):
            cnt = n_tasks[fid]
            urls = []
            for i in range(cnt):
                w = workers[i % len(workers)]
                tid = f"{query_id}.{fid}.{i}"
                assignments.append((tid, w, fid, i, phases[fid]))
                urls.append(f"{w.uri}/v1/task/{tid}")
            task_urls[fid] = urls

        def post_task(tid, w, fid, i):
            """Create the task on `w`, resolving upstream buffer URLs from
            the CURRENT task_urls (rescheduled producers re-point them)."""
            from presto_tpu.plan.codec import task_update_to_json

            f = frags[fid]
            upstreams = {
                rs.fragment_id: [
                    f"{u}/results/{i}" for u in task_urls[rs.fragment_id]
                ]
                for rs in f.remote_sources()
            }
            strip_runtime_state(f.root)
            sa = split_assignments.get(fid)
            update = TaskUpdate(
                fragment=f,
                task_index=i,
                n_tasks=n_tasks[fid],
                n_out_partitions=n_out[fid],
                upstreams=upstreams,
                config=_config_dict(config),
                # a build-phase task's consumers don't exist yet:
                # spool its output instead of blocking on back-pressure
                spool=phases[fid] < last_phase,
                split_assignment=None if sa is None else sa[i][0],
                split_counts=None if sa is None else sa[i][1],
            )
            body = json.dumps(task_update_to_json(update)).encode()
            req = urllib.request.Request(
                f"{w.uri}/v1/task/{tid}", data=body, method="POST",
                headers=self._headers({"Content-Type": "application/json",
                                       **trace_hdrs}),
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                info = json.loads(r.read())
            if info.get("state") == "failed":
                raise QueryFailed(info.get("error") or "task failed")

        created = []
        dead: set = set()

        def mark_dead(x):
            dead.add(id(x))
            x.record_failure()
            if self.on_worker_lost is not None:
                try:
                    self.on_worker_lost(x)
                except Exception:
                    pass

        def reschedule(tid, w, fid, i):
            """Re-run ONE lost task on a surviving worker, walking past
            survivors that also turn out dead."""
            mark_dead(w)
            attempt = int(tid.rsplit(".r", 1)[1]) + 1 if ".r" in tid else 1
            while True:
                survivors = [x for x in workers if id(x) not in dead]
                if not survivors:
                    # retryable: the query-level loop re-probes the cluster
                    # (pruning truly-dead nodes) before giving up
                    raise QueryFailed(
                        "no surviving workers to re-place lost tasks on",
                        retryable=True)
                if attempt > len(workers):
                    raise QueryFailed(f"task {tid} exhausted re-placement "
                                      f"retries")
                nw = survivors[i % len(survivors)]
                ntid = f"{query_id}.{fid}.{i}.r{attempt}"
                try:
                    post_task(ntid, nw, fid, i)
                except (urllib.error.URLError, OSError):
                    mark_dead(nw)
                    attempt += 1
                    continue
                task_urls[fid][i] = f"{nw.uri}/v1/task/{ntid}"
                created.append((ntid, nw))
                return ntid, nw

        completed = False
        try:
            # phase by phase; within a phase producers first (ascending fid
            # = topological order). All-at-once has exactly one phase.
            for ph in range(last_phase + 1):
                phase_tids = []
                for tid, w, fid, i, p in assignments:
                    if p != ph:
                        continue
                    try:
                        if id(w) in dead:
                            raise urllib.error.URLError("worker known dead")
                        post_task(tid, w, fid, i)
                        created.append((tid, w))
                        phase_tids.append((tid, w, fid, i))
                    except (urllib.error.URLError, OSError):
                        # creation-time loss: any task is re-placeable on a
                        # survivor BEFORE its consumers wire upstreams
                        # (producers post first — ascending fid order)
                        ntid, nw = reschedule(tid, w, fid, i)
                        phase_tids.append((ntid, nw, fid, i))
                if ph < last_phase:
                    # gate the next phase on this (build) phase finishing
                    self._wait_finished(
                        phase_tids,
                        timeout_s=getattr(config, "phase_wait_timeout_s",
                                          600.0),
                        on_lost=(reschedule if ph == 0 and grouped
                                 else None),
                        extra_headers=trace_hdrs)
            # stream the root fragment's single output buffer
            root_urls = [f"{u}/results/0" for u in task_urls[dplan.root_fid]]
            client = ExchangeClient(root_urls)
            if tracer.enabled:
                from presto_tpu.obs import metrics as _obs_metrics
            stream_w0 = time.time()
            waited = 0.0
            try:
                it = client.batches()
                while True:
                    w0 = time.monotonic()
                    try:
                        b = next(it)
                    except StopIteration:
                        break
                    dt = time.monotonic() - w0
                    waited += dt
                    if tracer.enabled:
                        _obs_metrics.EXCHANGE_WAIT.observe(
                            dt, plane="coordinator")
                    yield b
                completed = True
            finally:
                client.close()
                if tracer.enabled:
                    tracer.record("exchange_wait", "exchange_wait",
                                  stream_w0, time.time(),
                                  parent_id=trace_parent,
                                  fragment=dplan.root_fid,
                                  wait_s=round(waited, 6))
            if stats_out is not None:
                for tid, w in created:
                    try:
                        req = urllib.request.Request(
                            f"{w.uri}/v1/task/{tid}/status",
                            headers=self._headers(trace_hdrs))
                        with urllib.request.urlopen(req, timeout=10) as r:
                            info = json.loads(r.read())
                        m = _TID_RE.match(tid)
                        fid = int(m.group(2)) if m else -1
                        stats_out.append((tid, fid, info))
                    except Exception:
                        pass
            if tracer.enabled:
                self._collect_task_traces(tracer, created, trace_parent,
                                          trace_hdrs)
        except ExchangeFailure as e:
            raise QueryFailed(str(e), retryable=not e.task_error) from e
        finally:
            # abort on ANY early exit — including GeneratorExit when the
            # consumer abandons the stream (client disconnect / LIMIT) —
            # so worker tasks and buffers are always released
            if not completed:
                self._abort(created)

    def _collect_task_traces(self, tracer, created, trace_parent,
                             extra_headers):
        """Stitch the distributed trace: pull every created task's span
        dump (GET /v1/task/{id}/trace), group by fragment, synthesize one
        `stage` span per fragment (the envelope of its tasks' spans) hung
        off the query root, and re-parent each worker task root onto its
        stage span. Unreachable workers just leave a hole — the trace is
        best-effort by design."""
        by_fid: Dict[int, list] = {}
        for tid, w in created:
            m = _TID_RE.match(tid)
            fid = int(m.group(2)) if m else -1
            try:
                req = urllib.request.Request(
                    f"{w.uri}/v1/task/{tid}/trace",
                    headers=self._headers(extra_headers))
                with urllib.request.urlopen(req, timeout=10) as r:
                    doc = json.loads(r.read())
            except Exception:
                continue
            if doc.get("spans"):
                by_fid.setdefault(fid, []).append(doc)
        for fid in sorted(by_fid):
            docs = by_fid[fid]
            starts = [s["start"] for d in docs for s in d["spans"]]
            ends = [(s["end"] if s["end"] is not None else s["start"])
                    for d in docs for s in d["spans"]]
            stage = tracer.record(
                f"stage-{fid}", "stage", min(starts), max(ends),
                parent_id=trace_parent, fragment=fid, tasks=len(docs))
            for d in docs:
                parent_map = {}
                root = d.get("rootSpanId")
                if root:
                    parent_map[root] = stage.span_id
                tracer.absorb(d["spans"], parent_map)

    def _wait_finished(self, tasks, timeout_s: float = 600.0,
                       poll_s: float = 0.1, on_lost=None,
                       extra_headers: Optional[dict] = None):
        """Block until every (tid, worker, fid, index) task reached a
        terminal state (phased scheduling's stage-completion gate). A
        failed task fails the query immediately. With `on_lost` (recoverable
        grouped execution), a task whose worker stopped answering is handed
        back — on_lost re-runs that lifespan on a survivor and returns the
        replacement (tid, worker) to keep waiting on; deterministic task
        FAILURES still fail the query (they would fail identically on
        any node)."""
        deadline = time.monotonic() + timeout_s
        pending = list(tasks)
        while pending:
            still = []
            for tid, w, fid, i in pending:
                try:
                    req = urllib.request.Request(
                        f"{w.uri}/v1/task/{tid}/status",
                        headers=self._headers(extra_headers))
                    with urllib.request.urlopen(req, timeout=10) as r:
                        info = json.loads(r.read())
                except Exception as e:
                    if on_lost is not None:
                        ntid, nw = on_lost(tid, w, fid, i)
                        still.append((ntid, nw, fid, i))
                        continue
                    raise QueryFailed(
                        f"lost task {tid} while awaiting phase completion: "
                        f"{e}", retryable=True) from e
                state = info.get("state")
                if state == "failed":
                    raise QueryFailed(info.get("error") or f"task {tid} failed")
                if state not in ("finished", "aborted"):
                    still.append((tid, w, fid, i))
            pending = still
            if pending:
                if time.monotonic() > deadline:
                    raise QueryFailed(
                        f"phase did not complete within {timeout_s}s "
                        f"({len(pending)} tasks still running)")
                time.sleep(poll_s)

    def _abort(self, created):
        for tid, w in created:
            try:
                req = urllib.request.Request(
                    f"{w.uri}/v1/task/{tid}", method="DELETE",
                    headers=self._headers(),
                )
                urllib.request.urlopen(req, timeout=5).read()
            except Exception:
                pass


# task ids are "{query_id}.{fragment}.{index}" with an optional ".r{n}"
# retry suffix (reschedule) — rsplit misparses retried ids, this doesn't
_TID_RE = re.compile(r"^(.+)\.(\d+)\.(\d+)(?:\.r\d+)?$")


def _fragment_lifespans(node) -> int:
    """Bucket count of a grouped (colocated-join) fragment, else 0
    (StageExecutionDescriptor.isStageGroupedExecution analog)."""
    from presto_tpu.plan.nodes import HashJoin

    if isinstance(node, HashJoin) and node.colocated:
        return node.colocated
    for c in node.children():
        b = _fragment_lifespans(c)
        if b:
            return b
    return 0


def _config_dict(cfg: ExecConfig) -> dict:
    import dataclasses

    return dataclasses.asdict(cfg)


class Coordinator:
    """Discovery + health + scheduling service. Exposes the announcement
    endpoint over HTTP; the statement protocol lives in
    presto_tpu.server.protocol (mounted on the same server)."""

    def __init__(self, catalog: Catalog, port: int = 0,
                 config: Optional[ExecConfig] = None, min_workers: int = 1,
                 broadcast_threshold_rows: float = 1_000_000,
                 cluster_secret: Optional[str] = None,
                 authenticator=None, session_property_manager=None,
                 query_event_log: Optional[str] = None,
                 cluster_memory_limit_bytes: Optional[int] = None,
                 low_memory_killer: str = "total-reservation-on-blocked",
                 low_memory_kill_delay_s: float = 1.0,
                 blocked_node_threshold: float = 0.95,
                 access_control=None, tls=None,
                 slow_query_log: Optional[str] = None,
                 slow_query_threshold_s: float = 0.0,
                 events_log: Optional[str] = None):
        from presto_tpu.server.cluster_memory import ClusterMemoryManager
        from presto_tpu.server.protocol import StatementProtocol
        from presto_tpu.server.querymanager import (
            QueryManager,
            batch_to_result,
        )

        self.catalog = catalog
        self.config = config or ExecConfig()
        self.broadcast_threshold_rows = broadcast_threshold_rows
        # column-level authorization consulted on every execution
        # (security/AccessControlManager.java analog; None = allow all)
        self.access_control = access_control
        self.tls = tls
        self.node_manager = NodeManager()
        self.cluster_memory = ClusterMemoryManager(
            cluster_memory_limit_bytes, policy=low_memory_killer,
            kill_delay_s=low_memory_kill_delay_s,
            blocked_node_threshold=blocked_node_threshold)
        # semantic result cache (server/result_cache.py): process-wide;
        # its bytes ride the cluster memory ledger and are revoked under
        # pressure before any query is killed
        from presto_tpu.server import result_cache as _result_cache

        self.result_cache = _result_cache.CACHE
        self.cluster_memory.result_cache = self.result_cache
        # revoke-before-kill ladder, second rung: under sustained pressure
        # the manager asks every active worker to revoke spillable operator
        # state (join builds / agg accumulators spill at their next batch
        # boundary) before killing anything
        self.cluster_memory.spill_revoker = self._revoke_spillable_state
        # adaptive rung tried BEFORE whole-operator revoke: shed only the
        # largest partitions of partition-granular owners (adaptive radix
        # aggregations) so hot state stays resident under pressure
        self.cluster_memory.partial_revoker = self._revoke_partial_state
        self._cluster_secret = cluster_secret
        self.failure_detector = HeartbeatFailureDetector(
            self.node_manager, cluster_memory=self.cluster_memory)
        self.size_monitor = ClusterSizeMonitor(self.node_manager, min_workers)
        self.scheduler = DistributedScheduler(
            self.config, cluster_secret=cluster_secret,
            on_worker_lost=lambda n: self._probe_and_exclude(n),
            catalog=catalog)
        self._query_seq = 0
        self._lock = threading.Lock()
        # keyed by (sql, plan-affecting session property values)
        self._dplan_cache: Dict[tuple, DistributedPlan] = {}
        self._cached_sqls: set = set()  # sqls with any cached plan (non-DDL)
        self._http = None

        def execute_fn(session, sql):
            cfg = session.exec_config()
            return batch_to_result(self.run_batch(sql, cfg, session))

        self.query_manager = QueryManager(execute_fn)
        self.failure_detector.query_manager = self.query_manager
        # query-id → Tracer; /v1/query/{id}/trace and the UI drill-down
        # resolve from here (scheduler attempt ids alias to the same trace)
        self.trace_registry = _obs_trace.TraceRegistry()
        # a low-memory kill stamps a memory_kill span onto the victim's
        # trace (registry exists only now — created after the manager)
        self.cluster_memory.trace_registry = self.trace_registry
        # inflight plane: stall forensics get the victim's open span stack
        # and pool reservations; configure() never arms, so off sessions
        # stay bit-for-bit
        _obs_inflight.configure(
            span_provider=lambda qid: (
                tr.spans() if (tr := self.trace_registry.get(qid))
                is not None else None),
            pool_provider=lambda qid: (
                (self.cluster_memory.memory_rollup().get("queryMemory")
                 or {}).get(qid)))

        if events_log:
            # unified cluster event stream JSONL sink (/v1/events mirrors
            # the in-memory ring regardless)
            _obs_events.EVENTS.configure(path=events_log)

        def _lifecycle_complete(event: str, info):
            # FIRST in the listener chain: SLO histograms, objective
            # violations, and the latency-regression flag must exist
            # before _log_slow reads the annotation
            if event != "queryCompleted":
                return
            try:
                tr = self.trace_registry.get(info.query_id)
                _obs_lifecycle.complete(
                    info, spans=tr.spans() if tr is not None else None)
            except Exception:
                pass

        self.query_manager.listeners.append(_lifecycle_complete)

        def _record_latency(event: str, info):
            if event != "queryCompleted":
                return
            try:
                from presto_tpu.obs import metrics as _obs_metrics

                _obs_metrics.QUERY_LATENCY.observe(
                    max(0.0, (info.end_time or time.time())
                        - info.create_time),
                    plane="coordinator", state=info.state)
            except Exception:
                pass

        self.query_manager.listeners.append(_record_latency)
        if slow_query_log:
            from presto_tpu.obs.events import SlowQueryLogger

            slow = SlowQueryLogger(slow_query_log,
                                   threshold_s=slow_query_threshold_s)

            def _log_slow(event: str, info, _s=slow):
                if event != "queryCompleted":
                    return
                tr = self.trace_registry.get(info.query_id)
                mem = None
                try:
                    # devprof plane: fold the query's memory picture into
                    # the slow-query record — its cluster-ledger slice plus
                    # the device's own numbers when the plane is on
                    from presto_tpu.obs import devprof as _devprof

                    doc = {}
                    roll = self.cluster_memory.memory_rollup()
                    qb = (roll.get("queryMemory") or {}).get(info.query_id)
                    if qb:
                        doc["reservedBytes"] = qb
                    if _devprof.active():
                        doc["device"] = _devprof.device_memory_doc()
                        s = _devprof.summary()
                        if s.get("peak_program_footprint_bytes"):
                            doc["peakProgramFootprintBytes"] = \
                                s["peak_program_footprint_bytes"]
                    mem = doc or None
                except Exception:
                    mem = None
                extra = _obs_lifecycle.slow_log_annotation(info.query_id)
                try:
                    # inflight plane: doctor verdict + straggler docs ride
                    # the slow-query record when the plane saw the query
                    inf = _obs_inflight.slow_log_annotation(info.query_id)
                    if inf:
                        extra = {**(extra or {}), **inf}
                except Exception:
                    pass
                _s.log(info, tr.spans() if tr is not None else None,
                       memory=mem, extra=extra)

            self.query_manager.listeners.append(_log_slow)
        if query_event_log:
            # query-completion audit stream (reference: the EventListener
            # SPI's QueryCompletedEvent, commonly shipped to an audit log)
            import dataclasses as _dc

            self._event_log_lock = threading.Lock()

            def log_event(event: str, info, path=query_event_log):
                rec = {"event": event, "ts": time.time(),
                       **_dc.asdict(info)}
                line = json.dumps(rec, default=str)
                with self._event_log_lock:
                    with open(path, "a") as fh:
                        fh.write(line + "\n")

            self.query_manager.listeners.append(log_event)

        def _speculate(qe):
            # queue-wait precompile: farm-compile the statement's recorded
            # plans while the query waits for admission, spending (and
            # respecting) the group's compile budget
            group = qe.resource_group or ""
            user = qe.session.user
            _farm.speculate(
                qe.sql, self.catalog, qe.session.exec_config(),
                group=group, query_id=qe.query_id,
                charge_fn=lambda n: self.query_manager.resource_groups
                .charge_compiles(group, n, user),
                budget_fn=lambda: self.query_manager.resource_groups
                .compile_budget_remaining(group, user))

        self.query_manager.speculate_fn = _speculate
        # ahead-of-traffic farm boot: arm the program cache from the
        # persisted corpus BEFORE serving starts, so "coordinator ready"
        # means "known programs warm" (blocking by design; gated on
        # PRESTO_TPU_FARM=1 + PRESTO_TPU_CACHE_DIR, else a no-op)
        try:
            self._farm_armed = _farm.boot(self.catalog, self.config,
                                          block=True)
        except Exception:
            self._farm_armed = 0
        # bind the socket first (determines self.url), wire the protocol,
        # THEN start serving — no request can observe a half-built coordinator
        self._bind_http(port)
        self.protocol = StatementProtocol(
            self.query_manager, catalog, self.url,
            explain_fn=self._explain,
            authenticator=authenticator,
            session_property_manager=session_property_manager,
        )
        from presto_tpu.server.querymanager import batch_to_result as _btr

        self.protocol.execute_stmt_fn = (
            lambda session, stmt: _btr(self.run_batch(
                "", session.exec_config(), session, stmt=stmt)))
        threading.Thread(target=self._http.serve_forever, daemon=True,
                         name="coordinator-http").start()
        self.failure_detector.start()

    def _explain(self, sql: str, analyze: bool, session,
                 etype: Optional[str] = None) -> str:
        if etype not in (None, "distributed", "logical", "validate"):
            raise ValueError(
                f"unknown EXPLAIN type {etype!r} "
                "(supported: DISTRIBUTED, LOGICAL, VALIDATE)")
        if analyze:
            if etype not in (None, "distributed"):
                raise ValueError(
                    "EXPLAIN ANALYZE only supports TYPE DISTRIBUTED")
            return self.explain_analyze_distributed(sql, session)
        if etype == "validate":
            from presto_tpu.plan.builder import plan_query

            plan_query(sql, self.catalog)  # raises on invalid queries
            return "VALID"
        if etype == "logical":
            from presto_tpu.plan.builder import plan_query
            from presto_tpu.plan.nodes import plan_to_string
            from presto_tpu.plan.optimizer import optimize

            return plan_to_string(optimize(plan_query(sql, self.catalog), self.catalog).root)
        # default / TYPE DISTRIBUTED
        return self.plan_distributed(sql, session).to_string()

    def explain_analyze_distributed(self, sql: str, session=None) -> str:
        """Run the query on the cluster with per-operator accounting and
        render a per-fragment, per-task stats rollup (the QueryStats/
        OperatorStats view of the reference's EXPLAIN ANALYZE)."""
        import dataclasses as _dc

        dplan = self.plan_distributed(sql, session)
        cfg = _dc.replace(
            session.exec_config() if session else self.config,
            collect_stats=True)
        # result-cache header: what a NON-explain run of this statement
        # would see right now. peek() is non-mutating — rendering the
        # header neither counts a hit/miss nor refreshes the entry.
        rc_line = None
        rc_mode = (getattr(cfg, "result_cache", "off") or "off").lower()
        if rc_mode != "off":
            if dplan.__dict__.get("_rc_cacheable"):
                from presto_tpu.server import result_cache as _rc_mod2

                rc_key = _rc_mod2.query_key(
                    dplan, self.catalog,
                    getattr(session, "catalog", "") or "",
                    getattr(session, "schema", "") or "")
                rc_state = ("hit" if self.result_cache.peek(rc_key)
                            else "miss")
            else:
                rc_state = "bypass"
            rc_line = f"[cache: {rc_state}]"
        # farm header: would a first-seen run of this structure land on a
        # warm program cache? armed = boot pre-armed, live = queue-wait
        # speculation warmed it, miss = cold. Rendered only when the farm
        # is in play (process or session arming) — off stays bit-for-bit.
        farm_line = None
        if _farm.enabled(cfg):
            farm_line = ("[farm: "
                         + _farm.status_for(dplan.fragments[dplan.root_fid]
                                            .root) + "]")
        stats: list = []
        self.size_monitor.wait_for_minimum()
        qid = self.next_query_id()
        workers = self.node_manager.active_nodes()
        # lifecycle plane: EXPLAIN ANALYZE serves through a QueryExecution
        # (the _immediate path), so the session query id already has a
        # registered timeline when lifecycle=on
        session_qid = getattr(session, "query_id", "") or ""
        entry = _obs_lifecycle.get(session_qid) if session_qid else None
        if entry is not None:
            _obs_lifecycle.mark(session_qid, "compiling")
            _obs_lifecycle.alias(qid, entry.query_id)
        if session_qid and _obs_inflight.get(session_qid) is not None:
            # task publishers key by the scheduler attempt id; route them
            # to the session's inflight entry
            _obs_inflight.alias(qid, session_qid)
        tracer = _obs_trace.NOOP
        if getattr(cfg, "tracing", True):
            tracer = _obs_trace.Tracer(
                trace_id=getattr(session, "query_id", "") or None)
            self.trace_registry.register(tracer)
            self.trace_registry.alias(qid, tracer.trace_id)
        with _obs_trace.use(tracer), tracer.span("query", "query",
                                                 sql=sql[:200]):
            first = True
            for _ in self.scheduler.execute(qid, dplan, workers, cfg,
                                            stats_out=stats, tracer=tracer):
                if first and entry is not None:
                    _obs_lifecycle.mark(session_qid, "executing")
                    first = False
        lines = []
        if rc_line is not None:
            lines += [rc_line, ""]
        if farm_line is not None:
            lines += [farm_line, ""]
        if entry is not None:
            seg = entry.timeline.segments()
            lines += [
                "-- lifecycle --",
                "  " + "  ".join(
                    f"{k}={seg[k]:.3f}s"
                    for k in ("queue_wait", "plan", "compile", "exec",
                              "drain", "e2e")),
                "",
            ]
        try:
            # query doctor: ranked bottleneck attribution over lifecycle +
            # inflight telemetry (present only when a plane saw the query)
            tr_spans = (tracer.spans()
                        if tracer is not _obs_trace.NOOP else None)
            doctor = _obs_inflight.analyze(session_qid or qid,
                                           spans=tr_spans)
            if doctor is not None and doctor.get("verdict"):
                lines += ["-- doctor --", "  " + doctor["verdict"]]
                for c in doctor.get("causes", [])[1:3]:
                    lines.append(
                        f"    also: {c['cause']}"
                        f" ({c['score']:.0%}) {c.get('detail', '')}".rstrip())
                lines.append("")
        except Exception:
            pass
        lines += [dplan.to_string(), "", "-- task execution profile --"]
        by_fid: Dict[int, list] = {}
        for tid, fid, info in stats:
            by_fid.setdefault(fid, []).append((tid, info))
        for fid in sorted(by_fid):
            lines.append(f"fragment {fid}:")
            for tid, info in sorted(by_fid[fid]):
                lines.append(f"  task {tid} [{info.get('state')}]")
                for row in info.get("stats") or []:
                    line = (
                        f"    {row['node']:<16} rows={int(row['rows']):>12,}"
                        f" batches={int(row['batches']):>6}"
                        f" wall={row['wall_s']:.3f}s")
                    if row.get("bytes"):
                        line += f" bytes={int(row['bytes']):,}"
                    if "compiles" in row:
                        # compile wall comes out of the operator's measured
                        # wall: the split shows where the time actually went
                        cw = float(row.get("compile_wall_s") or 0.0)
                        line += (f" compiles={int(row['compiles'])}"
                                 f" compile={cw:.3f}s"
                                 f" execute="
                                 f"{max(0.0, row['wall_s'] - cw):.3f}s")
                    fl = float(row.get("flops") or 0.0)
                    ba = float(row.get("bytes_accessed") or 0.0)
                    pk = float(row.get("peak_bytes") or 0.0)
                    if fl or ba or pk:
                        # devprof plane: XLA's own cost/memory analysis of
                        # the operator's compiled programs (ai = flops per
                        # byte moved — the roofline x-axis)
                        parts = []
                        if pk:
                            parts.append(f"peak={int(pk):,}")
                        if fl:
                            parts.append(f"flops={fl:.4g}")
                        if ba:
                            parts.append(f"bytes={ba:.4g}")
                        if fl and ba:
                            parts.append(f"ai={fl / ba:.2f}")
                        line += " [" + " ".join(parts) + "]"
                    lines.append(line)
        return "\n".join(lines)

    # -- http -------------------------------------------------------------

    def _bind_http(self, port: int):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        coord = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _json(self, obj, code=200, extra_headers=None):
                data = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                for k, v in (extra_headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)

            def _text(self, body: str, content_type: str, code=200):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_POST(self):
                if self.path == "/v1/statement":
                    from presto_tpu.server.security import AuthenticationError

                    n = int(self.headers.get("Content-Length", 0))
                    sql = self.rfile.read(n).decode()
                    try:
                        out, extra = coord.protocol.create(sql, self.headers)
                        return self._json(out, extra_headers=extra)
                    except AuthenticationError as e:
                        return self._json(
                            {"error": {"message": str(e),
                                       "errorName": "AUTHENTICATION_FAILED",
                                       "errorType": "USER_ERROR"}},
                            code=401,
                            extra_headers={
                                "WWW-Authenticate": 'Basic realm="presto-tpu"'
                            })
                    except Exception as e:
                        return self._json(
                            {"error": {"message": str(e),
                                       "errorName": type(e).__name__,
                                       "errorType": "USER_ERROR"},
                             "id": "", "stats": {"state": "FAILED"}})
                self._json({"error": "not found"}, 404)

            def do_PUT(self):
                if self.path.startswith("/v1/announcement/"):
                    node_id = self.path.rsplit("/", 1)[1]
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n))
                    coord.node_manager.announce(
                        node_id, body["uri"], body.get("state", "active")
                    )
                    return self._json({"ok": True})
                self._json({"error": "not found"}, 404)

            def do_GET(self):
                m = re.match(r"^/v1/statement/([^/]+)/(\d+)$", self.path)
                if m:
                    try:
                        return self._json(
                            coord.protocol.poll(m.group(1), int(m.group(2)))
                        )
                    except KeyError:
                        return self._json({"error": "unknown query"}, 404)
                m = re.match(r"^/v1/query/([^/]+)/trace$", self.path)
                if m:
                    tr = coord.trace_registry.get(m.group(1))
                    if tr is None:
                        return self._json({"error": "no trace for query"},
                                          404)
                    return self._json(tr.to_json())
                m = re.match(r"^/v1/query/([^/]+)/progress$", self.path)
                if m:
                    qid = m.group(1)
                    state = None
                    try:
                        state = coord.query_manager.get(qid).state
                    except KeyError:
                        pass
                    doc = _obs_lifecycle.progress_doc(qid, state=state)
                    if doc is None:
                        return self._json(
                            {"error": "no lifecycle for query "
                                      "(unknown id or lifecycle=off)"}, 404)
                    return self._json(doc)
                m = re.match(r"^/v1/query/([^/]+)/inflight$", self.path)
                if m:
                    doc = _obs_inflight.snapshot_doc(m.group(1))
                    if doc is None:
                        return self._json(
                            {"error": "no inflight telemetry for query "
                                      "(unknown id or inflight=off)"}, 404)
                    return self._json(doc)
                m = re.match(r"^/v1/query/([^/]+)/doctor$", self.path)
                if m:
                    qid = m.group(1)
                    state = None
                    try:
                        state = coord.query_manager.get(qid).state
                    except KeyError:
                        pass
                    tr = coord.trace_registry.get(qid)
                    doc = _obs_inflight.analyze(
                        qid, spans=tr.spans() if tr is not None else None,
                        state=state)
                    if doc is None:
                        return self._json(
                            {"error": "no telemetry for query (unknown id "
                                      "or lifecycle+inflight off)"}, 404)
                    return self._json(doc)
                m = re.match(r"^/v1/events(?:\?(.*))?$", self.path)
                if m:
                    import urllib.parse as _up

                    q = _up.parse_qs(m.group(1) or "")

                    def _one(name, cast=str, default=None):
                        vals = q.get(name)
                        try:
                            return cast(vals[0]) if vals else default
                        except (TypeError, ValueError):
                            return default

                    return self._json({
                        "lastSeq": _obs_events.EVENTS.last_seq(),
                        "events": _obs_events.EVENTS.events(
                            since=_one("since", int, 0),
                            query_id=_one("queryId"),
                            kind=_one("kind"),
                            limit=_one("limit", int, 1000)),
                    })
                m = re.match(r"^/ui/query/([^/]+)$", self.path)
                if m:
                    from presto_tpu.server.metrics import render_query_page

                    page = render_query_page(coord, m.group(1))
                    if page is None:
                        return self._json({"error": "unknown query"}, 404)
                    return self._text(page, "text/html")
                m = re.match(r"^/v1/query/([^/]+)$", self.path)
                if m:
                    try:
                        qe = coord.query_manager.get(m.group(1))
                    except KeyError:
                        return self._json({"error": "unknown query"}, 404)
                    import dataclasses as _dc

                    return self._json(_dc.asdict(qe.info()))
                if self.path == "/v1/query":
                    import dataclasses as _dc

                    return self._json(
                        [_dc.asdict(i) for i in coord.query_manager.queries()]
                    )
                if self.path == "/v1/info":
                    return self._json({
                        "nodeId": "coordinator", "coordinator": True,
                        "uri": coord.url,
                    })
                if self.path == "/v1/node":
                    return self._json([
                        {"nodeId": n.node_id, "uri": n.uri,
                         "failureScore": n.failure_score, "state": n.state}
                        for n in coord.node_manager.nodes.values()
                    ])
                if self.path == "/v1/cluster":
                    qs = coord.query_manager.queries()
                    return self._json({
                        "activeWorkers": len(coord.node_manager.active_nodes()),
                        "runningQueries": sum(1 for q in qs if q.state == "RUNNING"),
                        "queuedQueries": sum(1 for q in qs if q.state == "QUEUED"),
                        "totalQueries": len(qs),
                        "memory": coord.cluster_memory.info(),
                    })
                if self.path == "/v1/memory":
                    # cluster memory rollup (MemoryPoolInfo REST analog):
                    # per-node reserved/peak/limit + device stats + the
                    # per-query slices the low-memory killer ranks on
                    return self._json(coord.cluster_memory.memory_rollup())
                if self.path == "/v1/metrics":
                    from presto_tpu.server.metrics import coordinator_metrics

                    return self._text(coordinator_metrics(coord),
                                      "text/plain; version=0.0.4")
                if self.path in ("/", "/ui", "/ui/"):
                    from presto_tpu.server.metrics import render_ui

                    return self._text(render_ui(coord), "text/html")
                self._json({"error": "not found"}, 404)

            def do_DELETE(self):
                m = re.match(r"^/v1/statement/([^/]+)(?:/\d+)?$", self.path)
                if m:
                    coord.protocol.cancel(m.group(1))
                    return self._json({"ok": True})
                if self.path == "/v1/cache":
                    # explicit operator flush of the semantic result cache
                    n = coord.result_cache.flush()
                    return self._json({"ok": True, "flushed": n})
                self._json({"error": "not found"}, 404)

        self._http = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        if self.tls is not None:
            from presto_tpu.server.tls import install_client_context, wrap_server

            wrap_server(self._http, self.tls)
            install_client_context(self.tls)
        self.port = self._http.server_address[1]
        scheme = "https" if self.tls is not None else "http"
        self.url = f"{scheme}://127.0.0.1:{self.port}"

    # -- queries ----------------------------------------------------------

    def next_query_id(self) -> str:
        with self._lock:
            self._query_seq += 1
            return f"q{self._query_seq}"

    def execute_distributed(self, dplan: DistributedPlan,
                            config: Optional[ExecConfig] = None,
                            tracer=None):
        self.size_monitor.wait_for_minimum()
        qid = self.next_query_id()
        workers = self.node_manager.active_nodes()
        if tracer is None:
            tracer = _obs_trace.current()
        if tracer.enabled:
            # task ids embed this scheduler attempt id — make it resolve
            # to the query's trace too
            self.trace_registry.alias(qid, tracer.trace_id)
            # ... and to the lifecycle progress entry (trace ids are
            # minted as the serving query id), so worker heartbeats keyed
            # by this attempt reach the right registry slot
            _obs_lifecycle.alias(qid, tracer.trace_id)
            # ... and to the inflight entry, so task publishers keyed by
            # this attempt heartbeat into the serving query's telemetry
            _obs_inflight.alias(qid, tracer.trace_id)
        entry = _obs_lifecycle.get(qid)
        if entry is None:
            yield from self.scheduler.execute(qid, dplan, workers, config,
                                              tracer=tracer)
            return
        # lifecycle plane: the first root-stream batch is the
        # compiling->executing boundary; every batch feeds the live
        # progress counts
        import numpy as _np
        first = True
        for b in self.scheduler.execute(qid, dplan, workers, config,
                                        tracer=tracer):
            if first:
                _obs_lifecycle.mark(entry.query_id, "executing")
                first = False
            entry.observe_batch(int(_np.asarray(b.live).sum()))
            yield b
        if first:
            # zero-batch stream (e.g. empty scan): still crossed into
            # execution before draining
            _obs_lifecycle.mark(entry.query_id, "executing")

    def _try_scaled_write(self, stmt, config, session) -> Optional[Batch]:
        """Scaled writers (SCALED_WRITER_DISTRIBUTION): CTAS into a
        connector that supports part tables plans the source query
        distributed and wraps each root task with a TableWriter — every
        task writes its own part concurrently; counts gather and sum; the
        staging directory commits atomically (TableFinish). Returns None
        when the statement doesn't qualify (the gathered single-writer
        path handles it)."""
        import uuid

        from presto_tpu.exec.runtime import _collect_concat
        from presto_tpu.plan.builder import plan_query
        from presto_tpu.plan.fragmenter import fragment_plan
        from presto_tpu.plan.nodes import Output, TableWriter
        from presto_tpu.plan.optimizer import optimize
        from presto_tpu.sql import ast as _ast

        if not isinstance(stmt, _ast.CreateTableAs):
            return None
        if stmt.properties:
            # partitioned CTAS groups rows by partition value — the
            # single-writer path owns that layout
            return None
        conn, tname = self.catalog.connector_for(stmt.name)
        if not getattr(conn, "supports_scaled_writes", lambda: False)():
            return None
        qp = optimize(plan_query(stmt.query, self.catalog), self.catalog)
        self._enforce_access([qp.root], session)
        if qp.scalar_subqueries:
            return None  # binding protocol stays on the gathered path
        write_id = uuid.uuid4().hex[:8]
        inner = qp.root.child
        writer = TableWriter(inner, conn.name, tname, write_id)
        qp.root = Output(writer, ["rows"], ["rows"])
        if not conn.begin_scaled_create(tname,
                                        if_not_exists=stmt.if_not_exists):
            return self._count_batch(0)
        try:
            dplan = fragment_plan(
                qp, self.catalog,
                broadcast_threshold_rows=self.broadcast_threshold_rows)
            # NO query-level retry here: a retry with a different worker
            # count would leave stale parts from the first attempt in the
            # staging dir (duplicated rows); failures abort the staging
            batches = list(self.execute_distributed(dplan, config))
            merged = _collect_concat(iter(batches))
            total = 0
            if merged is not None:
                rows = merged.to_pydict(decode_strings=False)["rows"]
                total = int(sum(int(v) for v in rows))
            conn.finish_scaled_create(tname)
        except BaseException:
            conn.abort_scaled_create(tname)
            raise
        return self._count_batch(total)

    @staticmethod
    def _count_batch(rows: int) -> Batch:
        import jax.numpy as jnp
        import numpy as np

        from presto_tpu.batch import Column
        from presto_tpu.types import BIGINT

        vals = np.zeros(128, np.int64)
        vals[0] = rows
        live = np.zeros(128, bool)
        live[0] = True
        return Batch(["rows"], [BIGINT],
                     [Column(jnp.asarray(vals), None)],
                     jnp.asarray(live), {})

    def _revoke_spillable_state(self) -> int:
        """POST /v1/memory/revoke on every active worker: spillable
        operator state (hybrid hash join builds, grace-agg accumulators)
        flags itself and spills at the next batch boundary. Returns how
        many revokers were signaled cluster-wide."""
        signaled = 0
        for n in self.node_manager.active_nodes():
            try:
                req = urllib.request.Request(
                    f"{n.uri}/v1/memory/revoke", data=b"{}", method="POST")
                if self._cluster_secret is not None:
                    req.add_header("X-Presto-Cluster-Secret",
                                   self._cluster_secret)
                with urllib.request.urlopen(req, timeout=3) as r:
                    doc = json.loads(r.read())
                signaled += int(doc.get("revokersSignaled") or 0)
            except Exception:
                continue
        return signaled

    def _revoke_partial_state(self) -> int:
        """POST /v1/memory/revoke {"partial": true} on every active
        worker: partition-granular owners (adaptive radix aggregations)
        shed only their LARGEST partitions at the next batch boundary.
        Returns partitions revoked cluster-wide — 0 means no partial
        owner anywhere, and the enforce ladder falls through to the
        whole-operator rung."""
        revoked = 0
        for n in self.node_manager.active_nodes():
            try:
                req = urllib.request.Request(
                    f"{n.uri}/v1/memory/revoke",
                    data=b'{"partial": true}', method="POST")
                if self._cluster_secret is not None:
                    req.add_header("X-Presto-Cluster-Secret",
                                   self._cluster_secret)
                with urllib.request.urlopen(req, timeout=3) as r:
                    doc = json.loads(r.read())
                revoked += int(doc.get("partitionsRevoked") or 0)
            except Exception:
                continue
        return revoked

    def _probe_and_exclude(self, n: NodeInfo):
        """One-node version of _reprobe_workers, called when task placement
        found the node dead: confirm with a direct probe and exclude it
        from rotation immediately if it really is gone."""
        try:
            with urllib.request.urlopen(f"{n.uri}/v1/status", timeout=3) as r:
                json.loads(r.read())
            n.record_success()
        except Exception:
            n.failure_score = 5.0  # past NodeInfo.failed threshold

    def _reprobe_workers(self):
        """Synchronous cluster probe before a retry: a node that fails its
        probe is excluded IMMEDIATELY (score jump past the threshold) —
        the background detector's decayed counter is deliberately slow for
        flaky networks, but a retry must not re-schedule onto a node that
        just killed the query."""
        def probe(n):
            try:
                with urllib.request.urlopen(f"{n.uri}/v1/status",
                                            timeout=3) as r:
                    json.loads(r.read())
                n.record_success()
            except Exception:
                n.failure_score = 5.0  # past NodeInfo.failed threshold

        threads = [threading.Thread(target=probe, args=(n,), daemon=True)
                   for n in list(self.node_manager.nodes.values())]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=4)

    def _execute_with_retry(self, dplan: DistributedPlan,
                            config: Optional[ExecConfig] = None) -> list:
        """Query-level elastic retry (reference: RetryPolicy.QUERY /
        recoverable execution's coarse form): any task failure or worker
        transport error re-probes the cluster and re-runs the whole query
        on the surviving nodes."""
        retries = (config or self.config).query_retry_count
        attempt = 0
        while True:
            try:
                return list(self.execute_distributed(dplan, config))
            except (QueryFailed, urllib.error.URLError, OSError) as e:
                # deterministic task errors re-fail identically: don't
                # burn a full re-execution on them
                retryable = (e.retryable if isinstance(e, QueryFailed)
                             else True)
                if attempt >= retries or not retryable:
                    raise (e if isinstance(e, QueryFailed)
                           else QueryFailed(str(e), retryable=True))
                attempt += 1
                self._reprobe_workers()
                if not self.node_manager.active_nodes():
                    raise QueryFailed(
                        "no active workers after failure probe") from e

    def plan_distributed(self, sql: str, session=None,
                         stmt=None) -> DistributedPlan:
        from presto_tpu.exec.runtime import ExecContext
        from presto_tpu.plan.builder import plan_query
        from presto_tpu.plan.fragmenter import fragment_plan
        from presto_tpu.plan.optimizer import optimize

        # session properties that change the PLAN feed the cache key
        # (join_distribution_type — SystemSessionProperties.java:59)
        jdt = (session.get("join_distribution_type") if session else "AUTOMATIC") or "AUTOMATIC"
        jdt = jdt.upper()
        threshold = {
            "BROADCAST": float("inf"),
            "PARTITIONED": 0.0,
        }.get(jdt, self.broadcast_threshold_rows)
        jm = ((session.get("join_mode") if session else None) or
              getattr(self.config, "join_mode", "auto")).lower()
        cache_key = (sql, jdt, jm)
        hit = self._dplan_cache.get(cache_key) if sql else None
        if hit is not None:
            return hit
        qp = optimize(plan_query(stmt if stmt is not None else sql,
                                 self.catalog), self.catalog)
        if jm != "off":
            from presto_tpu.plan.multiway import apply_join_mode

            cfg = session.exec_config() if session else self.config
            apply_join_mode(qp, self.catalog, cfg)
        cacheable = bool(sql) and not qp.scalar_subqueries and qp.cacheable
        if qp.scalar_subqueries:
            # bind uncorrelated scalar subqueries coordinator-side first
            # (the reference runs them as separate plan stages). They
            # EXECUTE here, before run_batch's fragment walk can see them —
            # authorize their scans now or a subquery smuggles denied data
            from presto_tpu.exec.runtime import bind_scalar_subqueries

            self._enforce_access(
                (s.root for s in qp.scalar_subqueries.values()), session)
            bind_scalar_subqueries(qp, ExecContext(self.catalog, self.config))
        dplan = fragment_plan(
            qp, self.catalog,
            broadcast_threshold_rows=threshold,
        )
        # result-cache eligibility rides on the plan object: only plans
        # with no scalar subqueries and a cacheable (deterministic) tree
        # may consult/populate the semantic result cache
        dplan.__dict__["_rc_cacheable"] = cacheable
        if cacheable:
            # concurrent submissions of the same sql both plan (the get
            # above is a lock-free fast path) but the insert keeps the
            # cache + membership set consistent; last writer wins with an
            # equivalent plan
            with self._lock:
                self._dplan_cache[cache_key] = dplan
                self._cached_sqls.add(sql)
        return dplan

    def _enforce_access(self, roots, session) -> None:
        """Column-level authorization over every table the (cached or
        fresh) plan touches — enforced per EXECUTION, so plan caching
        can't bypass a rule change (AccessControlManager.checkCanSelect
        FromColumns analog). `roots` is an iterable of plan roots."""
        if self.access_control is None:
            return
        from presto_tpu.plan.nodes import IndexJoin as _IdxJ
        from presto_tpu.plan.nodes import TableScan as _TS

        user = getattr(session, "user", None) or "user"

        def walk(n):
            if isinstance(n, _TS):
                self.access_control.check_can_select(
                    user, n.catalog, n.table,
                    set(n.assignments.values()) | set(n.constraints or ()))
            elif isinstance(n, _IdxJ):
                self.access_control.check_can_select(
                    user, n.catalog, n.table,
                    set(n.assignments.values()))
            for c in n.children():
                walk(c)

        for r in roots:
            walk(r)

    # -- result cache ------------------------------------------------------

    def _invalidate_result_cache(self):
        """Snapshot-token barrier after DDL/DML: reclaim every cached
        result whose token no longer matches the live catalog."""
        rc = self.result_cache
        if rc is None or not rc.armed():
            return
        try:
            from presto_tpu.obs.runstats import catalog_token

            rc.flush_stale(catalog_token(self.catalog))
        except Exception:
            pass

    def _rc_connector(self):
        """The private memory connector holding materialized subplan
        results. Underscore-prefixed, so `catalog_token` skips it — its
        churn must never invalidate the cache keyed on that token."""
        conn = self.catalog.connectors.get("_rc")
        if conn is None:
            from presto_tpu.catalog.memory import MemoryConnector

            conn = MemoryConnector("_rc")
            # direct registration (not Catalog.register): the splice
            # connector must never become the session default
            conn.name = "_rc"
            self.catalog.connectors["_rc"] = conn
        return conn

    @staticmethod
    def _rc_table_name(skey: str) -> str:
        """Splice table name for a subplan cache key — derived from the
        KEY (stable across plan objects and processes), never from plan
        node identity."""
        import hashlib as _hashlib

        return "rc_" + _hashlib.sha256(skey.encode()).hexdigest()[:16]

    def _materialize_subplan(self, node, skey, config):
        """Execute one breaker subtree as its own distributed query and
        land the result as a `_rc` memory table. Returns (table_name,
        batch, wall_s) or None on any failure (the caller falls back to
        executing the unspliced plan)."""
        from presto_tpu.exec.runtime import _JIT_COMPACT, _collect_concat
        from presto_tpu.plan.fragmenter import fragment_plan
        from presto_tpu.plan.nodes import Output, QueryPlan

        try:
            names = [s for s, _ in node.output]
            sub_qp = QueryPlan(Output(node, names, names))
            sub_dplan = fragment_plan(
                sub_qp, self.catalog,
                broadcast_threshold_rows=self.broadcast_threshold_rows)
            t0 = time.perf_counter()
            batches = list(self.execute_distributed(sub_dplan, config))
            merged = _collect_concat(iter(batches))
            if merged is None:
                return None
            merged = _JIT_COMPACT(merged)
            wall = time.perf_counter() - t0
            tname = self._rc_table_name(skey)
            conn = self._rc_connector()
            conn.drop_table(tname, if_exists=True)
            conn.create_table_from(tname, [merged])
            return tname, merged, wall
        except Exception:
            return None

    def _run_with_subplan_reuse(self, sql, stmt, config, session):
        """result_cache=subplan: replan FRESH (the shared-plan-cache copy
        must never be mutated), look up each topmost grouped-Aggregate
        breaker in the subplan cache, splice hits in as `_rc` table
        scans (materializing misses first), and execute the spliced
        plan. Returns the merged batch, or None when nothing spliced —
        the caller falls back to the normal path."""
        from presto_tpu.exec.runtime import _collect_concat
        from presto_tpu.plan.builder import plan_query
        from presto_tpu.plan.fragmenter import fragment_plan
        from presto_tpu.plan.nodes import TableScan
        from presto_tpu.plan.optimizer import optimize
        from presto_tpu.server import result_cache as _rc_mod

        try:
            qp = optimize(plan_query(
                stmt if stmt is not None else sql, self.catalog),
                self.catalog)
        except Exception:
            return None
        if qp.scalar_subqueries or not qp.cacheable:
            return None
        # authorization runs against the PRE-splice plan: splicing only
        # replaces subtrees the user was just cleared to read
        self._enforce_access([qp.root], session)
        candidates = _rc_mod.find_breaker_subplans(qp.root)
        if not candidates:
            return None
        spliced = 0
        for node in candidates:
            skey = _rc_mod.subplan_key(node, self.catalog)
            if skey is None:
                continue
            cached = self.result_cache.lookup(skey)
            if cached is None:
                made = self._materialize_subplan(node, skey, config)
                if made is None:
                    continue
                tname, batch, wall = made
                conn = self._rc_connector()
                if not self.result_cache.admit(
                        skey, "subplan", batch, wall_s=wall,
                        token=skey.rsplit("/", 2)[1],
                        on_evict=(lambda c=conn, t=tname:
                                  c.drop_table(t, if_exists=True))):
                    conn.drop_table(tname, if_exists=True)
                    continue
            else:
                # entry present ⇒ its backing table is still registered
                # (the entry's on_evict is what drops it)
                tname = self._rc_table_name(skey)
                if tname not in self._rc_connector().tables:
                    continue
            scan = TableScan(
                catalog="_rc", table=tname,
                assignments={s: s for s, _ in node.output},
                output=list(node.output))
            if _rc_mod.replace_child(qp.root, node, scan):
                spliced += 1
        if not spliced:
            return None
        dplan = fragment_plan(
            qp, self.catalog,
            broadcast_threshold_rows=self.broadcast_threshold_rows)
        batches = self._execute_with_retry(dplan, config)
        return _collect_concat(iter(batches))

    def _profile_capture(self, session):
        """Context manager for the `profile` session property: a
        jax.profiler trace per query under PRESTO_TPU_CACHE_DIR/profiles/
        <query_id>, surfaced as profileUri in the statement response.
        No-op with a warning when the profiler or cache dir is
        unavailable — the query still runs."""
        import contextlib
        import warnings

        qid = getattr(session, "query_id", "") or "adhoc"
        base = os.environ.get("PRESTO_TPU_CACHE_DIR")
        cm = None
        pdir = None
        if not base:
            warnings.warn("profile=true is a no-op: PRESTO_TPU_CACHE_DIR "
                          "is not set", stacklevel=3)
        else:
            try:
                import jax.profiler as _prof

                pdir = os.path.join(base, "profiles", qid)
                os.makedirs(pdir, exist_ok=True)
                cm = _prof.trace(pdir)
            except Exception as e:
                warnings.warn("profile=true is a no-op: jax profiler "
                              f"unavailable ({e})", stacklevel=3)
                cm = None

        @contextlib.contextmanager
        def run():
            if cm is None:
                yield
                return
            try:
                with cm:
                    yield
            finally:
                try:
                    from presto_tpu.obs import devprof as _devprof

                    _devprof.register_profile(qid, pdir)
                except Exception:
                    pass

        return run()

    def run_batch(self, sql: str, config: Optional[ExecConfig] = None,
                  session=None, stmt=None) -> Batch:
        """`stmt` overrides parsing — the bound AST of a prepared
        statement (EXECUTE path; no SQL re-rendering)."""
        cfg = config or self.config
        if getattr(cfg, "profile", False):
            with self._profile_capture(session):
                return self._run_batch_traced(sql, config, session, stmt)
        return self._run_batch_traced(sql, config, session, stmt)

    def _run_batch_traced(self, sql: str,
                          config: Optional[ExecConfig] = None,
                          session=None, stmt=None) -> Batch:
        cfg = config or self.config
        if not getattr(cfg, "tracing", True):
            return self._run_batch_inner(sql, config, session, stmt)
        # trace id = the session query id when there is one, so
        # /v1/query/{id}/trace resolves directly; the root span covers
        # planning + scheduling + result merge (≥95% of query wall)
        tracer = _obs_trace.Tracer(
            trace_id=getattr(session, "query_id", "") or None)
        self.trace_registry.register(tracer)
        with _obs_trace.use(tracer), tracer.span(
                "query", "query", sql=(sql or "")[:200],
                user=getattr(session, "user", None) or "user"):
            return self._run_batch_inner(sql, config, session, stmt)

    def _run_batch_inner(self, sql: str, config: Optional[ExecConfig] = None,
                         session=None, stmt=None) -> Batch:
        import jax.numpy as jnp

        from presto_tpu.batch import Column
        from presto_tpu.exec.runtime import _JIT_COMPACT, _collect_concat
        from presto_tpu.sql import ast as _ast
        from presto_tpu.sql.parser import parse_sql

        if stmt is None:
            # cached distributed plans are never DDL — skip the parse probe
            # (O(1) membership; the parsed stmt is reused by plan_distributed)
            cached = sql in self._cached_sqls
            stmt = None if cached else parse_sql(sql)
        from presto_tpu.exec.runner import is_ddl

        if stmt is not None and is_ddl(stmt):
            try:
                scaled = self._try_scaled_write(stmt, config, session)
                if scaled is not None:
                    return scaled
                # DDL/DML executes coordinator-side; the source query
                # still runs distributed (reference:
                # DataDefinitionExecution on the coordinator + a
                # distributed TableWriter source)
                from presto_tpu.exec.runner import execute_data_definition
                from presto_tpu.plan.builder import plan_query as _pq

                def run_query_fn(q):
                    from presto_tpu.plan.fragmenter import fragment_plan
                    from presto_tpu.plan.optimizer import optimize as _opt

                    qp = _opt(_pq(q, self.catalog), self.catalog)
                    self._enforce_access([qp.root], session)
                    d = fragment_plan(qp, self.catalog,
                                      broadcast_threshold_rows=self.broadcast_threshold_rows)
                    batches = list(self.execute_distributed(d, config))
                    merged = _collect_concat(iter(batches))
                    if merged is None:
                        root = d.fragments[d.root_fid].root
                        types = dict(root.output)
                        merged = Batch(
                            d.output_names,
                            [types[n] for n in d.output_names],
                            [Column(jnp.zeros(128, types[n].dtype), None)
                             for n in d.output_names],
                            jnp.zeros(128, bool), {},
                        )
                    return _JIT_COMPACT(merged)

                return execute_data_definition(stmt, self.catalog,
                                               run_query_fn)
            finally:
                # DDL/DML is the snapshot-token barrier: reclaim every
                # cached result whose token no longer matches (a no-op on
                # an unarmed cache — result_cache=off stays bit-for-bit)
                self._invalidate_result_cache()

        dplan = self.plan_distributed(sql, session, stmt=stmt)
        self._enforce_access(
            (f.root for f in dplan.fragments.values()), session)
        session_qid = getattr(session, "query_id", "") or ""
        lifecycle_on = bool(
            session_qid and _obs_lifecycle.get(session_qid) is not None)

        def _stamp_fingerprint():
            # stamp the structural fingerprint so progress gets its HBO
            # prediction and completion its regression baseline
            if not lifecycle_on:
                return
            try:
                from presto_tpu.obs import runstats as _runstats

                _obs_lifecycle.set_fingerprint(
                    session_qid, _runstats.node_fingerprint(
                        dplan.fragments[dplan.root_fid].root, self.catalog))
            except Exception:
                pass

        # result cache consult: after plan install + authorization,
        # BEFORE fragment scheduling. mode=off touches nothing (no key
        # computation, no arming — the pre-cache path bit-for-bit).
        cfg = config or self.config
        mode = (getattr(cfg, "result_cache", "off") or "off").lower()
        rc_key = rc_token = None
        if mode != "off" and dplan.__dict__.get("_rc_cacheable"):
            from presto_tpu.obs.runstats import catalog_token as _ctok
            from presto_tpu.server import result_cache as _rc_mod

            rc_token = _ctok(self.catalog)
            rc_key = _rc_mod.query_key(
                dplan, self.catalog,
                getattr(session, "catalog", "") or "",
                getattr(session, "schema", "") or "")
            if rc_key is not None:
                hit = self.result_cache.lookup(
                    rc_key, query_id=session_qid or None)
                if hit is not None:
                    # a hit short-circuits scheduling entirely: the
                    # timeline jumps straight to draining with a cache
                    # provenance mark (compile and exec segments resolve
                    # to exactly zero — segments() fills unstamped
                    # boundaries rightward)
                    _stamp_fingerprint()
                    if lifecycle_on:
                        _obs_lifecycle.mark(session_qid, "draining",
                                            provenance="cache")
                    _obs_lifecycle.note_cache(session_qid, {
                        "kind": "query", "key": rc_key[:24],
                        "bytes": _rc_mod.batch_nbytes(hit)})
                    return hit
        _stamp_fingerprint()
        if _farm.enabled(cfg):
            # corpus feed + status attribution: record this statement's
            # plans for future boots/speculation, and stamp whether THIS
            # run lands on a farm-warmed cache (armed/live) or cold (miss)
            try:
                froot = dplan.fragments[dplan.root_fid].root
                _farm.record_sql(
                    sql, [f.root for f in dplan.fragments.values()])
                fstatus = _farm.status_for(froot)
                if session_qid:
                    _obs_lifecycle.note_farm(session_qid, {
                        "status": fstatus})
                if fstatus != "miss":
                    _obs_events.EVENTS.emit(
                        "precompile_hit", query_id=session_qid or None,
                        status=fstatus)
            except Exception:
                pass
        if lifecycle_on:
            # lifecycle plane: plan ready = plan->compile boundary
            _obs_lifecycle.mark(session_qid, "compiling")
        t_exec0 = time.perf_counter()
        merged = None
        if mode == "subplan":
            merged = self._run_with_subplan_reuse(sql, stmt, config, session)
        if merged is None:
            batches = self._execute_with_retry(dplan, config)
            merged = _collect_concat(iter(batches))
        if merged is None:
            root = dplan.fragments[dplan.root_fid].root
            types = dict(root.output)
            merged = Batch(
                dplan.output_names,
                [types[n] for n in dplan.output_names],
                [Column(jnp.zeros(128, types[n].dtype), None)
                 for n in dplan.output_names],
                jnp.zeros(128, bool),
                {},
            )
        out = _JIT_COMPACT(merged)
        if rc_key is not None:
            # cost-aware admission: observed exec wall, floored by the
            # HBO baseline for this structure (a lucky fast run must not
            # undervalue a historically expensive query)
            wall = time.perf_counter() - t_exec0
            try:
                from presto_tpu.obs import runstats as _runstats

                ent = _runstats.lookup_node(
                    dplan.fragments[dplan.root_fid].root, self.catalog,
                    _runstats.QUERY_SITE)
                if ent and ent.get("wall_s"):
                    wall = max(wall, float(ent["wall_s"]))
            except Exception:
                pass
            self.result_cache.admit(rc_key, "query", out, wall_s=wall,
                                    token=rc_token,
                                    query_id=session_qid or None)
        return out

    def close(self):
        self.failure_detector.stop()
        self.query_manager.close()
        if self._http is not None:
            self._http.shutdown()
            self._http.server_close()


class DistributedRunner:
    """In-process cluster: coordinator + N workers over real localhost HTTP
    (DistributedQueryRunner.java:78 analog — multi-node without a cluster).

    Every worker shares the same Catalog object (connectors are
    deterministic; in a real deployment each worker constructs its own from
    catalog properties)."""

    def __init__(self, catalog: Catalog, n_workers: int = 2,
                 config: Optional[ExecConfig] = None,
                 broadcast_threshold_rows: float = 1_000_000,
                 access_control=None, tls=None,
                 coordinator_kwargs: Optional[dict] = None):
        import secrets as _secrets

        from presto_tpu.server.worker import Worker

        self.catalog = catalog
        self.config = config or ExecConfig()
        cluster_secret = _secrets.token_hex(16)
        self.coordinator = Coordinator(
            catalog, config=self.config, min_workers=n_workers,
            broadcast_threshold_rows=broadcast_threshold_rows,
            cluster_secret=cluster_secret,
            access_control=access_control, tls=tls,
            # extra Coordinator knobs (slow_query_log, events_log, ...)
            # without re-plumbing every parameter through the runner
            **(coordinator_kwargs or {}),
        )
        self.workers = [
            Worker(catalog, node_id=f"worker-{i}",
                   coordinator_url=self.coordinator.url,
                   memory_pool_bytes=self.config.memory_pool_bytes,
                   spill_dir=self.config.spill_dir,
                   revoke_threshold=self.config.memory_revoking_threshold,
                   revoke_target=self.config.memory_revoking_target,
                   cluster_secret=cluster_secret, tls=tls)
            for i in range(n_workers)
        ]

    def plan_distributed(self, sql: str) -> DistributedPlan:
        return self.coordinator.plan_distributed(sql)

    def explain_distributed(self, sql: str) -> str:
        return self.coordinator.plan_distributed(sql).to_string()

    def run_batch(self, sql: str) -> Batch:
        return self.coordinator.run_batch(sql)

    def run(self, sql: str):
        return self.run_batch(sql).to_pandas()

    def close(self):
        for w in self.workers:
            w.close()
        self.coordinator.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
