"""Exchange client — the consumer side of the HTTP pull shuffle.

Reference: operator/ExchangeClient.java:69 (addLocation:158, pollPage:250,
scheduleRequestIfNecessary:326) + HttpPageBufferClient.java:88: concurrent
page pulls from every upstream task's buffer, explicit token sequence
numbers, acknowledge-after-receive, bounded client-side buffer for
back-pressure.

Response wire format (mirrors PagesResponseWriter):
    <u32 header_len> <json header {next_token, complete, page_lens,
                                   task_state, error}> <pages bytes...>
"""

from __future__ import annotations

import json
import queue
import struct
import threading
import urllib.error
import urllib.request
from typing import Iterator, List, Optional

from presto_tpu.batch import Batch
from presto_tpu.serde import deserialize_batch


class ExchangeFailure(RuntimeError):
    """`task_error=True` means the REMOTE task reported a deterministic
    failure (its error message travels in the results header) — retrying
    the query would hit the same error. False means a transport-level
    failure (unreachable/partial producer), which IS worth a retry."""

    def __init__(self, msg: str, task_error: bool = False):
        super().__init__(msg)
        self.task_error = task_error
    pass


def parse_results_payload(data: bytes):
    (hlen,) = struct.unpack_from("<I", data, 0)
    header = json.loads(data[4:4 + hlen])
    pages = []
    off = 4 + hlen
    for n in header.get("page_lens", []):
        pages.append(data[off:off + n])
        off += n
    return header, pages


def encode_results_payload(header: dict, pages: List[bytes]) -> bytes:
    header = dict(header)
    header["page_lens"] = [len(p) for p in pages]
    hj = json.dumps(header, separators=(",", ":")).encode()
    return struct.pack("<I", len(hj)) + hj + b"".join(pages)


class _LocationPuller(threading.Thread):
    """One sequential token/ack pull loop per upstream location
    (HttpPageBufferClient analog)."""

    def __init__(self, location: str, out: "ExchangeClient"):
        super().__init__(daemon=True, name=f"exchange-{location}")
        self.location = location.rstrip("/")
        self.out = out

    def run(self):
        token = 0
        try:
            while not self.out.closed:
                url = f"{self.location}/{token}"
                try:
                    with urllib.request.urlopen(url, timeout=30) as r:
                        data = r.read()
                except urllib.error.HTTPError as e:
                    if e.code == 404:
                        # task not created yet — transient during scheduling
                        import time

                        time.sleep(0.05)
                        continue
                    raise
                header, pages = parse_results_payload(data)
                if header.get("error"):
                    raise ExchangeFailure(header["error"], task_error=True)
                for p in pages:
                    self.out._offer(p)
                next_token = header["next_token"]
                if pages:
                    # acknowledge so the producer can release the pages
                    urllib.request.urlopen(
                        f"{self.location}/{next_token}/ack", timeout=30
                    ).read()
                token = next_token
                if header.get("complete"):
                    break
        except Exception as e:  # propagate to the consuming iterator
            self.out._fail(f"{self.location}: {e}",
                           getattr(e, "task_error", False))
        finally:
            self.out._done()


class ExchangeClient:
    """Pulls pages from N upstream locations concurrently, yields Batches."""

    def __init__(self, locations: List[str], max_buffered_pages: int = 64):
        self.locations = list(locations)
        self._queue: queue.Queue = queue.Queue(maxsize=max_buffered_pages)
        self._remaining = len(self.locations)
        self._lock = threading.Lock()
        self._error: Optional[str] = None
        self.closed = False
        self._pullers = [_LocationPuller(loc, self) for loc in self.locations]
        for p in self._pullers:
            p.start()

    def _offer(self, page: bytes):
        while not self.closed:
            try:
                self._queue.put(page, timeout=0.5)
                return
            except queue.Full:
                continue

    def _fail(self, msg: str, task_error: bool = False):
        with self._lock:
            if self._error is None:
                self._error = msg
                self._error_is_task = task_error

    def _done(self):
        with self._lock:
            self._remaining -= 1
        self._queue.put(None)  # wake consumer

    def pages(self) -> Iterator[bytes]:
        done = 0
        while True:
            with self._lock:
                if self._error is not None:
                    self.closed = True
                    raise ExchangeFailure(
                        self._error,
                        task_error=getattr(self, "_error_is_task", False))
                if done >= len(self.locations) and self._queue.empty():
                    return
            try:
                item = self._queue.get(timeout=0.5)
            except queue.Empty:
                continue
            if item is None:
                done += 1
                continue
            yield item

    def _resolve_dict(self, digest: str) -> List[str]:
        """One-shot side-channel fetch for a by-ref dictionary. In-process
        deployments never get here (producer and consumer share the intern
        table); across processes, any upstream worker that shipped the ref
        has it interned, so try each distinct base once."""
        seen = set()
        for loc in self.locations:
            base = loc.split("/v1/")[0]
            if base in seen or not base.startswith("http"):
                continue
            seen.add(base)
            try:
                with urllib.request.urlopen(f"{base}/v1/dict/{digest}",
                                            timeout=30) as r:
                    return json.loads(r.read())
            except Exception:
                continue
        raise ExchangeFailure(
            f"dictionary {digest[:12]} unresolvable from any upstream",
            task_error=True)

    def batches(self) -> Iterator[Batch]:
        for page in self.pages():
            yield deserialize_batch(page, dict_resolver=self._resolve_dict)

    def close(self):
        self.closed = True
