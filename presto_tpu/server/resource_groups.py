"""Hierarchical resource groups — admission control for query execution.

Analog of execution/resourceGroups/InternalResourceGroup.java +
InternalResourceGroupManager and the file-based configuration manager
(presto-resource-group-managers FileResourceGroupConfigurationManager.java):
a tree of groups, each with concurrency/queue limits and a scheduling
policy; selectors route an incoming query (by user/source) to a leaf group;
queries queue when their group (or any ancestor) is at its hard concurrency
limit and start in policy order as slots free up.
"""

from __future__ import annotations

import dataclasses
import heapq
import itertools
import re
import threading
from typing import Callable, Dict, List, Optional


class QueryQueueFullError(RuntimeError):
    """Admission rejection; `group` carries the rejecting group id when
    the queue (rather than selector resolution) was the cause."""

    group: Optional[str] = None


@dataclasses.dataclass
class ResourceGroupSpec:
    """Config for one group (reference: ResourceGroupSpec in the file
    config manager; `${USER}` expansion as in `global.adhoc.${USER}`)."""

    name: str
    hard_concurrency_limit: int = 100
    max_queued: int = 1000
    scheduling_policy: str = "fair"  # fair | weighted_fair | query_priority
    scheduling_weight: int = 1
    soft_memory_limit_fraction: float = 1.0
    subgroups: List["ResourceGroupSpec"] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class SelectorSpec:
    """user/source regex → group id template (reference: SelectorSpec)."""

    group: str
    user_regex: Optional[str] = None
    source_regex: Optional[str] = None

    def matches(self, user: str, source: str) -> bool:
        if self.user_regex is not None and not re.search(self.user_regex, user or ""):
            return False
        if self.source_regex is not None and not re.search(
            self.source_regex, source or ""
        ):
            return False
        return True


class _Group:
    def __init__(self, spec: ResourceGroupSpec, parent: Optional["_Group"]):
        self.spec = spec
        self.parent = parent
        self.id = spec.name if parent is None else f"{parent.id}.{spec.name}"
        self.children: Dict[str, "_Group"] = {}
        self.running = 0
        self.queued: List = []  # heap of (sort_key, seq, entry)
        self._seq = itertools.count()
        for sub in spec.subgroups:
            self.children[sub.name] = _Group(sub, self)

    # -- capacity ----------------------------------------------------------

    def can_run(self) -> bool:
        g: Optional[_Group] = self
        while g is not None:
            if g.running >= g.spec.hard_concurrency_limit:
                return False
            g = g.parent
        return True

    def total_queued(self) -> int:
        return len(self.queued) + sum(c.total_queued() for c in self.children.values())

    # -- queue order -------------------------------------------------------

    def _sort_key(self, priority: int):
        if self.spec.scheduling_policy == "query_priority":
            return -priority
        if self.spec.scheduling_policy == "weighted_fair":
            # smaller running/weight ratio first — approximated at enqueue
            return self.running / max(1, self.spec.scheduling_weight)
        return 0  # fair = FIFO via seq tiebreak

    def enqueue(self, entry, priority: int):
        if len(self.queued) >= self.spec.max_queued:
            err = QueryQueueFullError(
                f"Too many queued queries for {self.id!r} "
                f"(max_queued={self.spec.max_queued})"
            )
            err.group = self.id
            raise err
        heapq.heappush(self.queued, (self._sort_key(priority), next(self._seq), entry))

    def dequeue(self):
        if not self.queued:
            return None
        return heapq.heappop(self.queued)[2]

    def start(self):
        g: Optional[_Group] = self
        while g is not None:
            g.running += 1
            g = g.parent

    def finish(self):
        g: Optional[_Group] = self
        while g is not None:
            g.running -= 1
            g = g.parent

    def walk(self):
        yield self
        for c in self.children.values():
            yield from c.walk()


class ResourceGroupManager:
    """Routes queries to groups and gates their start
    (InternalResourceGroupManager.submit → group.run or group.queue)."""

    def __init__(
        self,
        root: Optional[ResourceGroupSpec] = None,
        selectors: Optional[List[SelectorSpec]] = None,
    ):
        self._lock = threading.Lock()
        self.root = _Group(root or ResourceGroupSpec("global"), None)
        self.selectors = selectors or [SelectorSpec(group=self.root.id)]

    def _resolve(self, group_id: str, user: str) -> _Group:
        group_id = group_id.replace("${USER}", user)
        parts = group_id.split(".")
        if parts[0] != self.root.spec.name:
            raise KeyError(f"unknown resource group {group_id!r}")
        g = self.root
        for p in parts[1:]:
            if p not in g.children:
                # dynamic per-user leaf (the `${USER}` pattern): inherit limits
                g.children[p] = _Group(
                    dataclasses.replace(g.spec, name=p, subgroups=[]), g
                )
            g = g.children[p]
        return g

    def select(self, user: str, source: str) -> _Group:
        for sel in self.selectors:
            if sel.matches(user, source):
                return self._resolve(sel.group, user)
        raise QueryQueueFullError(
            f"no resource group matches user={user!r} source={source!r}"
        )

    def submit(self, user: str, source: str, priority: int,
               start_fn: Callable[[], None],
               on_group: Optional[Callable[[str], None]] = None,
               on_queued: Optional[Callable[[], None]] = None) -> str:
        """Admit (calls start_fn now) or queue (start_fn called later when a
        slot frees). `on_group` is invoked with the resolved group id BEFORE
        start_fn can run — callers that release the slot from a completion
        callback need the id recorded first. `on_queued` fires only when the
        query actually queues, still under the manager lock, so it is
        ordered strictly before any later dequeue can start the query (the
        lifecycle plane relies on queued-before-admitted event order).
        Raises QueryQueueFullError when the group's queue is full."""
        with self._lock:
            g = self.select(user, source)
            if on_group is not None:
                on_group(g.id)
            if g.can_run():
                g.start()
                run_now = True
            else:
                g.enqueue(start_fn, priority)
                run_now = False
                if on_queued is not None:
                    on_queued()
        if run_now:
            start_fn()
        return g.id

    def query_finished(self, group_id: str, user: str = ""):
        """Release the slot and start queued queries that now fit."""
        to_start = []
        with self._lock:
            g = self._resolve(group_id, user)
            g.finish()
            # drain eligible queued entries anywhere in the tree (a released
            # ancestor slot can unblock several leaves)
            for grp in self.root.walk():
                while grp.queued and grp.can_run():
                    entry = grp.dequeue()
                    grp.start()
                    to_start.append(entry)
        for fn in to_start:
            fn()

    def info(self) -> Dict:
        with self._lock:
            return {
                g.id: {
                    "running": g.running,
                    "queued": len(g.queued),
                    "hard_concurrency_limit": g.spec.hard_concurrency_limit,
                    "max_queued": g.spec.max_queued,
                    "policy": g.spec.scheduling_policy,
                }
                for g in self.root.walk()
            }
