"""Hierarchical resource groups — admission control for query execution.

Analog of execution/resourceGroups/InternalResourceGroup.java +
InternalResourceGroupManager and the file-based configuration manager
(presto-resource-group-managers FileResourceGroupConfigurationManager.java):
a tree of groups, each with concurrency/queue limits and a scheduling
policy; selectors route an incoming query (by user/source) to a leaf group;
queries queue when their group (or any ancestor) is at its hard concurrency
limit and start in policy order as slots free up.

Multi-tenant additions (the result-cache PR's admission side):

- ``weighted_fair`` is now a true dequeue-time discipline. Each child of a
  weighted_fair parent carries a virtual time advanced by ``1/weight`` per
  started query (stride scheduling / the reference's WeightedFairQueue
  counters); when slots free, the eligible group with the smallest
  root-to-leaf vtime path starts next, so siblings converge on their
  weight ratio regardless of arrival order. The old implementation froze
  ``running/weight`` into the ENQUEUE key, which is always 0 at
  concurrency 1 — i.e. no weighting at exactly the contention level where
  it matters.
- per-group compile budgets: ``compile_budget`` caps how many XLA
  trace+compile events (PR 5 compile counters, charged by the query
  manager at query completion) a group may consume per
  ``compile_budget_window_s`` rolling window; an exhausted group queues
  until the window rolls or ``replenish_compile_budgets`` runs. One
  tenant's cold compile storm cannot starve a sibling's cached hot path.
"""

from __future__ import annotations

import dataclasses
import heapq
import itertools
import re
import threading
import time
from typing import Callable, Dict, List, Optional


class QueryQueueFullError(RuntimeError):
    """Admission rejection; `group` carries the rejecting group id when
    the queue (rather than selector resolution) was the cause."""

    group: Optional[str] = None


@dataclasses.dataclass
class ResourceGroupSpec:
    """Config for one group (reference: ResourceGroupSpec in the file
    config manager; `${USER}` expansion as in `global.adhoc.${USER}`)."""

    name: str
    hard_concurrency_limit: int = 100
    max_queued: int = 1000
    scheduling_policy: str = "fair"  # fair | weighted_fair | query_priority
    scheduling_weight: int = 1
    soft_memory_limit_fraction: float = 1.0
    # compile-budget accounting: at most `compile_budget` XLA
    # trace+compile events per `compile_budget_window_s` rolling window
    # (0 = unlimited; window 0 = never auto-replenishes)
    compile_budget: int = 0
    compile_budget_window_s: float = 0.0
    subgroups: List["ResourceGroupSpec"] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class SelectorSpec:
    """user/source regex → group id template (reference: SelectorSpec)."""

    group: str
    user_regex: Optional[str] = None
    source_regex: Optional[str] = None

    def matches(self, user: str, source: str) -> bool:
        if self.user_regex is not None and not re.search(self.user_regex, user or ""):
            return False
        if self.source_regex is not None and not re.search(
            self.source_regex, source or ""
        ):
            return False
        return True


class _Group:
    def __init__(self, spec: ResourceGroupSpec, parent: Optional["_Group"]):
        self.spec = spec
        self.parent = parent
        self.id = spec.name if parent is None else f"{parent.id}.{spec.name}"
        self.children: Dict[str, "_Group"] = {}
        self.running = 0
        self.queued: List = []  # heap of (sort_key, seq, entry)
        self._seq = itertools.count()
        # stride-scheduling virtual time: advanced by 1/weight per started
        # query when the PARENT's policy is weighted_fair
        self.vtime = 0.0
        # compile events charged against this group's budget in the
        # current window
        self.compiles_used = 0
        self._window_start = time.monotonic()
        for sub in spec.subgroups:
            self.children[sub.name] = _Group(sub, self)

    # -- capacity ----------------------------------------------------------

    def _budget_ok(self, now: float) -> bool:
        b = self.spec.compile_budget
        if b <= 0:
            return True
        w = self.spec.compile_budget_window_s
        if w > 0 and (now - self._window_start) >= w:
            self._window_start = now
            self.compiles_used = 0
        return self.compiles_used < b

    def can_run(self) -> bool:
        now = time.monotonic()
        g: Optional[_Group] = self
        while g is not None:
            if g.running >= g.spec.hard_concurrency_limit:
                return False
            if not g._budget_ok(now):
                return False
            g = g.parent
        return True

    def total_queued(self) -> int:
        return len(self.queued) + sum(c.total_queued() for c in self.children.values())

    # -- queue order -------------------------------------------------------

    def _sort_key(self, priority: int):
        if self.spec.scheduling_policy == "query_priority":
            return -priority
        # fair AND weighted_fair queues are FIFO within the group (seq
        # tiebreak); weighted fairness is enforced ACROSS groups at
        # dequeue time by the manager's vtime-path selection
        return 0

    def enqueue(self, entry, priority: int):
        if len(self.queued) >= self.spec.max_queued:
            err = QueryQueueFullError(
                f"Too many queued queries for {self.id!r} "
                f"(max_queued={self.spec.max_queued})"
            )
            err.group = self.id
            raise err
        heapq.heappush(self.queued, (self._sort_key(priority), next(self._seq), entry))

    def dequeue(self):
        if not self.queued:
            return None
        return heapq.heappop(self.queued)[2]

    def start(self):
        g: Optional[_Group] = self
        while g is not None:
            g.running += 1
            if (g.parent is not None
                    and g.parent.spec.scheduling_policy == "weighted_fair"):
                g.vtime += 1.0 / max(1, g.spec.scheduling_weight)
            g = g.parent

    def finish(self):
        g: Optional[_Group] = self
        while g is not None:
            g.running -= 1
            g = g.parent

    def vtime_path(self) -> tuple:
        """Root-to-self vtimes under weighted_fair parents (0.0 under
        fair/priority parents, so mixed trees compare cleanly)."""
        path = []
        g: Optional[_Group] = self
        while g is not None and g.parent is not None:
            if g.parent.spec.scheduling_policy == "weighted_fair":
                path.append(g.vtime)
            else:
                path.append(0.0)
            g = g.parent
        path.reverse()
        return tuple(path)

    def walk(self):
        yield self
        for c in self.children.values():
            yield from c.walk()


class ResourceGroupManager:
    """Routes queries to groups and gates their start
    (InternalResourceGroupManager.submit → group.run or group.queue)."""

    def __init__(
        self,
        root: Optional[ResourceGroupSpec] = None,
        selectors: Optional[List[SelectorSpec]] = None,
    ):
        self._lock = threading.Lock()
        self.root = _Group(root or ResourceGroupSpec("global"), None)
        self.selectors = selectors or [SelectorSpec(group=self.root.id)]

    def _resolve(self, group_id: str, user: str) -> _Group:
        group_id = group_id.replace("${USER}", user)
        parts = group_id.split(".")
        if parts[0] != self.root.spec.name:
            raise KeyError(f"unknown resource group {group_id!r}")
        g = self.root
        for p in parts[1:]:
            if p not in g.children:
                # dynamic per-user leaf (the `${USER}` pattern): inherit
                # limits; a late joiner starts at the minimum sibling
                # vtime so it cannot burst ahead of established tenants
                child = _Group(
                    dataclasses.replace(g.spec, name=p, subgroups=[]), g
                )
                child.vtime = min(
                    (c.vtime for c in g.children.values()), default=0.0)
                g.children[p] = child
            g = g.children[p]
        return g

    def select(self, user: str, source: str) -> _Group:
        for sel in self.selectors:
            if sel.matches(user, source):
                return self._resolve(sel.group, user)
        raise QueryQueueFullError(
            f"no resource group matches user={user!r} source={source!r}"
        )

    def submit(self, user: str, source: str, priority: int,
               start_fn: Callable[[], None],
               on_group: Optional[Callable[[str], None]] = None,
               on_queued: Optional[Callable[[], None]] = None) -> str:
        """Admit (calls start_fn now) or queue (start_fn called later when a
        slot frees). `on_group` is invoked with the resolved group id BEFORE
        start_fn can run — callers that release the slot from a completion
        callback need the id recorded first. `on_queued` fires only when the
        query actually queues, still under the manager lock, so it is
        ordered strictly before any later dequeue can start the query (the
        lifecycle plane relies on queued-before-admitted event order).
        Raises QueryQueueFullError when the group's queue is full."""
        with self._lock:
            g = self.select(user, source)
            if on_group is not None:
                on_group(g.id)
            if g.can_run():
                g.start()
                run_now = True
            else:
                g.enqueue(start_fn, priority)
                run_now = False
                if on_queued is not None:
                    on_queued()
        if run_now:
            start_fn()
        return g.id

    # -- dequeue -----------------------------------------------------------

    def _drain_key(self, g: _Group) -> tuple:
        # (vtime path, queue-head seq): the lowest virtual time wins;
        # the enqueue sequence breaks exact ties FIFO. The path tuple is
        # compared FIRST as a unit, so mixed tree depths never compare a
        # sequence number against a vtime.
        head = g.queued[0]
        return (g.vtime_path(), (head[0], head[1]))

    def _drain_locked(self) -> List[Callable[[], None]]:
        to_start = []
        while True:
            eligible = [g for g in self.root.walk()
                        if g.queued and g.can_run()]
            if not eligible:
                return to_start
            g = min(eligible, key=self._drain_key)
            entry = g.dequeue()
            g.start()
            to_start.append(entry)

    def query_finished(self, group_id: str, user: str = ""):
        """Release the slot and start queued queries that now fit, in
        weighted-fair vtime order across sibling groups."""
        with self._lock:
            g = self._resolve(group_id, user)
            g.finish()
            to_start = self._drain_locked()
        for fn in to_start:
            fn()

    # -- compile budgets ---------------------------------------------------

    def charge_compiles(self, group_id: str, n: int, user: str = ""):
        """Charge `n` XLA compile events (PR 5 compile counters) against
        every budget-configured group on the path. Called by the query
        manager when a query completes."""
        if n <= 0:
            return
        with self._lock:
            try:
                g: Optional[_Group] = self._resolve(group_id, user)
            except KeyError:
                return
            while g is not None:
                if g.spec.compile_budget > 0:
                    g.compiles_used += int(n)
                g = g.parent

    def compile_budget_remaining(self, group_id: str,
                                 user: str = "") -> Optional[int]:
        """Tightest remaining compile headroom on the group's path for
        the current window (None = no budget configured anywhere on the
        path). The farm's speculative precompile consults this before
        spending a group's budget on warmth."""
        remaining: Optional[int] = None
        now = time.monotonic()
        with self._lock:
            try:
                g: Optional[_Group] = self._resolve(group_id, user)
            except KeyError:
                return None
            while g is not None:
                b = g.spec.compile_budget
                if b > 0:
                    g._budget_ok(now)  # roll the window first
                    left = max(0, b - g.compiles_used)
                    remaining = left if remaining is None \
                        else min(remaining, left)
                g = g.parent
        return remaining

    def replenish_compile_budgets(self):
        """Zero every group's window usage and drain newly-eligible
        queued queries (ops hook / tests; rolling windows replenish
        themselves via `compile_budget_window_s`)."""
        with self._lock:
            for g in self.root.walk():
                g.compiles_used = 0
                g._window_start = time.monotonic()
            to_start = self._drain_locked()
        for fn in to_start:
            fn()

    def info(self) -> Dict:
        with self._lock:
            return {
                g.id: {
                    "running": g.running,
                    "queued": len(g.queued),
                    "hard_concurrency_limit": g.spec.hard_concurrency_limit,
                    "max_queued": g.spec.max_queued,
                    "policy": g.spec.scheduling_policy,
                    "weight": g.spec.scheduling_weight,
                    "vtime": round(g.vtime, 6),
                    "compile_budget": g.spec.compile_budget,
                    "compiles_used": g.compiles_used,
                }
                for g in self.root.walk()
            }
